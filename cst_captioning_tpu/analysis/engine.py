"""The invariant engine: parse the package once, run pluggable
checkers, report ``file:line`` findings with rule IDs.

Seven PRs of perf work piled up correctness contracts that lived in
prose (docs/PARITY.md), two grep fingerprints, and reviewers' heads.
This subsystem machine-checks them:

* every checker is a function ``(modules, ctx) -> [Finding]`` registered
  in :data:`CHECKERS` under a rule-family name;
* findings carry a stable rule ID (catalogue in docs/ANALYSIS.md), the
  package-relative ``file:line``, and the enclosing symbol;
* one annotated suppression file (``suppressions.json``) silences known
  false positives — every entry REQUIRES a non-empty justification
  string, and stale (never-matched) entries are surfaced so the file
  cannot rot;
* ``python -m cst_captioning_tpu.analysis`` runs the pass standalone
  (pre-commit / bench preflight) and exits non-zero on any unsuppressed
  finding; tier-1 runs it in-process (tests/test_analysis.py) under the
  same < 30 s wall-clock budget discipline as ``TIER1_BUDGET_S``.

Everything here is stdlib-only and pure-AST — the pass reads source, it
never imports jax or the package under analysis, so it stays fast
enough for a preflight.
"""

from __future__ import annotations

import datetime as _dt
import json
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from cst_captioning_tpu.analysis.astutil import (
    ModuleInfo,
    PackageIndex,
    scan_package,
)

REPORT_VERSION = 1


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str        # e.g. "CST-DEC-001"
    file: str        # package-relative posix path
    line: int
    symbol: str      # enclosing qualname or logical symbol
    message: str

    def location(self) -> str:
        return f"{self.file}:{self.line}"

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} [{self.symbol}] {self.message}"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "file": self.file,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
        }


@dataclass
class CheckContext:
    """What checkers get besides the parsed modules."""

    index: PackageIndex
    package_root: Path
    docs_root: Optional[Path]    # repo docs/ dir (None when absent)


Checker = Callable[[List[ModuleInfo], CheckContext], List[Finding]]

# Rule-family name -> checker.  Populated by register_checker at import
# of the checker modules (see _load_checkers).
CHECKERS: Dict[str, Checker] = {}


def register_checker(name: str) -> Callable[[Checker], Checker]:
    def deco(fn: Checker) -> Checker:
        CHECKERS[name] = fn
        return fn

    return deco


def _load_checkers() -> None:
    # Import-for-side-effect: each module registers its rule family.
    from cst_captioning_tpu.analysis import (  # noqa: F401
        configflow,
        donation,
        dtypeflow,
        exceptions,
        jit_boundary,
        metrics_registry,
        observability,
        partitioning,
        resilience,
        rng,
        shapeflow,
        single_site,
        thread_safety,
    )


# ----------------------------------------------------------- suppressions

@dataclass(frozen=True)
class Suppression:
    """One annotated suppression: silences findings whose (rule, file,
    symbol) all match.  ``justification`` is REQUIRED non-empty prose —
    an unexplained suppression is itself a finding.  ``expires``
    (optional, ``"YYYY-MM-DD"``) dates the debt: past the date the
    entry fires CST-SUP-002, so a "temporary" suppression surfaces
    instead of rotting."""

    rule: str
    file: str
    symbol: str
    justification: str
    expires: Optional[str] = None

    def expired(self, today: Optional["_dt.date"] = None) -> bool:
        if not self.expires:
            return False
        today = today or _dt.date.today()
        return _dt.date.fromisoformat(self.expires) < today


def load_suppressions(
    path: Path,
) -> Tuple[List[Suppression], List[Finding]]:
    """Parse the suppression file; malformed entries come back as
    CST-SUP-001 findings instead of silently dropping rules."""
    entries: List[Suppression] = []
    problems: List[Finding] = []
    if not path.exists():
        return entries, problems
    try:
        data = json.loads(path.read_text())
    except json.JSONDecodeError as e:
        return entries, [
            Finding(
                "CST-SUP-001", path.name, 1, "<file>",
                f"suppression file is not valid JSON: {e}",
            )
        ]
    raw = data.get("entries") if isinstance(data, dict) else None
    if not isinstance(raw, list):
        return entries, [
            Finding(
                "CST-SUP-001", path.name, 1, "<file>",
                "suppression file must be {\"entries\": [...]}"
            )
        ]
    for i, e in enumerate(raw):
        where = f"entries[{i}]"
        if not isinstance(e, dict):
            problems.append(Finding(
                "CST-SUP-001", path.name, 1, where, "entry is not an object"
            ))
            continue
        missing = [
            k for k in ("rule", "file", "symbol", "justification")
            if not isinstance(e.get(k), str)
        ]
        if missing:
            problems.append(Finding(
                "CST-SUP-001", path.name, 1, where,
                f"entry missing string field(s) {missing}",
            ))
            continue
        if not e["justification"].strip():
            problems.append(Finding(
                "CST-SUP-001", path.name, 1, where,
                f"suppression of {e['rule']} at {e['file']} has an empty "
                "justification — every suppression must say WHY",
            ))
            continue
        expires = e.get("expires")
        if expires is not None:
            if not isinstance(expires, str):
                problems.append(Finding(
                    "CST-SUP-001", path.name, 1, where,
                    "'expires' must be a \"YYYY-MM-DD\" string",
                ))
                continue
            try:
                _dt.date.fromisoformat(expires)
            except ValueError:
                problems.append(Finding(
                    "CST-SUP-001", path.name, 1, where,
                    f"'expires' {expires!r} is not a valid "
                    "YYYY-MM-DD date",
                ))
                continue
        entries.append(Suppression(
            rule=e["rule"], file=e["file"], symbol=e["symbol"],
            justification=e["justification"], expires=expires,
        ))
    return entries, problems


def _matches(s: Suppression, f: Finding) -> bool:
    return s.rule == f.rule and s.file == f.file and s.symbol == f.symbol


# ----------------------------------------------------------------- report

@dataclass
class Report:
    findings: List[Finding]                    # unsuppressed
    suppressed: List[Tuple[Finding, Suppression]]
    unused_suppressions: List[Suppression]
    rules_run: List[str]
    files_scanned: int
    duration_s: float
    cache_hit_files: int = 0    # files served from the incremental cache

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": REPORT_VERSION,
            "clean": self.clean,
            "duration_s": round(self.duration_s, 3),
            "files_scanned": self.files_scanned,
            "cache_hit_files": self.cache_hit_files,
            "rules_run": list(self.rules_run),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [
                dict(f.to_dict(), justification=s.justification)
                for f, s in self.suppressed
            ],
            "unused_suppressions": [
                {"rule": s.rule, "file": s.file, "symbol": s.symbol}
                for s in self.unused_suppressions
            ],
        }

    def to_stable_dict(self) -> Dict[str, Any]:
        """The run-invariant payload: everything except the measured
        ``duration_s`` and the cache provenance — the byte-identical
        contract cold and warm cached runs are pinned against."""
        d = self.to_dict()
        d.pop("duration_s")
        d.pop("cache_hit_files")
        return d

    @classmethod
    def from_stable_dict(
        cls, d: Dict[str, Any], *, duration_s: float,
        cache_hit_files: int,
    ) -> "Report":
        """Rebuild a Report from a stored stable payload (the cache
        warm path)."""
        findings = [Finding(**f) for f in d["findings"]]
        suppressed = []
        for f in d["suppressed"]:
            just = f["justification"]
            core = {k: v for k, v in f.items() if k != "justification"}
            suppressed.append((
                Finding(**core),
                Suppression(
                    rule=core["rule"], file=core["file"],
                    symbol=core["symbol"], justification=just,
                ),
            ))
        unused = [
            Suppression(justification="", **u)
            for u in d["unused_suppressions"]
        ]
        return cls(
            findings=findings,
            suppressed=suppressed,
            unused_suppressions=unused,
            rules_run=list(d["rules_run"]),
            files_scanned=d["files_scanned"],
            duration_s=duration_s,
            cache_hit_files=cache_hit_files,
        )

    def render(self) -> str:
        lines = [f.render() for f in self.findings]
        lines.append(
            f"analysis: {len(self.findings)} finding(s), "
            f"{len(self.suppressed)} suppressed, "
            f"{self.files_scanned} files, {self.duration_s:.2f}s"
        )
        if self.unused_suppressions:
            lines.append(
                "stale suppressions (matched nothing): "
                + ", ".join(
                    f"{s.rule}@{s.file}[{s.symbol}]"
                    for s in self.unused_suppressions
                )
            )
        return "\n".join(lines)


def validate_report(rec: Any) -> Dict[str, Any]:
    """Schema-validate one ``--json`` analysis report (the same contract
    discipline as bench.py's ``validate_record``).  Returns the record
    or raises ValueError naming the violation."""

    def fail(msg: str) -> None:
        raise ValueError(f"malformed analysis report: {msg}")

    if not isinstance(rec, dict):
        fail("not a dict")
    for key in (
        "version", "clean", "duration_s", "files_scanned", "rules_run",
        "findings", "suppressed", "unused_suppressions",
    ):
        if key not in rec:
            fail(f"missing required key {key!r}")
    if rec["version"] != REPORT_VERSION:
        fail(f"unknown version {rec['version']!r}")
    if not isinstance(rec["clean"], bool):
        fail("'clean' must be a bool")
    if isinstance(rec["duration_s"], bool) or not isinstance(
        rec["duration_s"], (int, float)
    ):
        fail("'duration_s' must be a number")
    if isinstance(rec["files_scanned"], bool) or not isinstance(
        rec["files_scanned"], int
    ) or rec["files_scanned"] < 0:
        fail("'files_scanned' must be a non-negative int")
    if "cache_hit_files" in rec:
        v = rec["cache_hit_files"]
        if isinstance(v, bool) or not isinstance(v, int) or v < 0:
            fail("'cache_hit_files' must be a non-negative int")
        if v > rec["files_scanned"]:
            fail("'cache_hit_files' exceeds 'files_scanned'")
    if not (
        isinstance(rec["rules_run"], list)
        and all(isinstance(r, str) and r for r in rec["rules_run"])
    ):
        fail("'rules_run' must be a list of non-empty strings")
    for section in ("findings", "suppressed"):
        if not isinstance(rec[section], list):
            fail(f"'{section}' must be a list")
        for i, f in enumerate(rec[section]):
            if not isinstance(f, dict):
                fail(f"{section}[{i}] is not an object")
            for k in ("rule", "file", "symbol", "message"):
                if not (isinstance(f.get(k), str) and f[k]):
                    fail(f"{section}[{i}].{k} must be a non-empty string")
            if isinstance(f.get("line"), bool) or not isinstance(
                f.get("line"), int
            ) or f["line"] < 1:
                fail(f"{section}[{i}].line must be a positive int")
            if section == "suppressed" and not (
                isinstance(f.get("justification"), str)
                and f["justification"].strip()
            ):
                fail(
                    f"suppressed[{i}] lacks a non-empty justification"
                )
    if rec["clean"] != (len(rec["findings"]) == 0):
        fail("'clean' contradicts the findings list")
    return rec


# ------------------------------------------------------------------- run

def default_package_root() -> Path:
    return Path(__file__).resolve().parent.parent


def default_suppressions_path() -> Path:
    return Path(__file__).resolve().parent / "suppressions.json"


def run_analysis(
    package_root: Optional[Path] = None,
    *,
    rules: Optional[Sequence[str]] = None,
    suppressions_path: Optional[Path] = None,
    docs_root: Optional[Path] = None,
    cache_dir: Optional[Path] = None,
) -> Report:
    """Parse ``package_root`` once, run the requested rule families
    (default: all), apply suppressions, return the :class:`Report`.

    ``cache_dir`` enables the incremental cache (analysis/cache.py):
    when nothing that can change the report changed — sources,
    suppressions, docs, rule selection — the stored report is
    reconstructed without parsing or checking anything
    (``cache_hit_files`` reports the reuse; the stable payload is
    byte-identical to a cold run by construction)."""
    t0 = time.perf_counter()
    _load_checkers()
    root = Path(package_root) if package_root else default_package_root()
    if docs_root is None:
        cand = root.parent / "docs"
        docs_root = cand if cand.is_dir() else None
    names = list(rules) if rules else sorted(CHECKERS)
    spath_early = Path(suppressions_path or default_suppressions_path())
    cache_key = None
    cache_files = None
    if cache_dir is not None:
        from cst_captioning_tpu.analysis import cache as _cache

        cache_key, cache_files = _cache.compute_key(
            root,
            rules=names,
            suppressions_path=spath_early,
            docs_root=docs_root,
            report_version=REPORT_VERSION,
        )
        hit = _cache.load(Path(cache_dir), cache_key)
        if hit is not None:
            return Report.from_stable_dict(
                hit,
                duration_s=time.perf_counter() - t0,
                cache_hit_files=hit["files_scanned"],
            )
    modules = scan_package(root)
    # The analysis package audits the rest of the package; its own
    # sources (pattern tables, rule text) would trip the single-site
    # matchers on their own detection code.
    modules = [m for m in modules if not m.rel.startswith("analysis/")]
    ctx = CheckContext(
        index=PackageIndex(modules), package_root=root, docs_root=docs_root
    )
    unknown = [n for n in names if n not in CHECKERS]
    if unknown:
        raise ValueError(
            f"unknown rule families {unknown}; have {sorted(CHECKERS)}"
        )
    all_findings: List[Finding] = []
    for name in names:
        all_findings.extend(CHECKERS[name](modules, ctx))
    sups, sup_problems = load_suppressions(spath_early)
    all_findings.extend(sup_problems)
    # Dated debt surfaces (CST-SUP-002): an entry past its ``expires``
    # date keeps matching (so its target shows up exactly once, here)
    # but the expiry itself is an unsuppressable finding.
    for s in sups:
        if s.expired():
            all_findings.append(Finding(
                "CST-SUP-002", spath_early.name, 1,
                f"{s.rule}@{s.file}[{s.symbol}]",
                f"suppression of {s.rule} at {s.file} expired on "
                f"{s.expires} — the recorded debt "
                f"({s.justification!r:.120}) is due: fix the finding "
                "or re-justify with a new date",
            ))

    kept: List[Finding] = []
    suppressed: List[Tuple[Finding, Suppression]] = []
    used = set()
    for f in sorted(all_findings, key=lambda f: (f.file, f.line, f.rule)):
        hit = next((s for s in sups if _matches(s, f)), None)
        if hit is not None and not f.rule.startswith("CST-SUP-"):
            suppressed.append((f, hit))
            used.add((hit.rule, hit.file, hit.symbol))
        else:
            kept.append(f)
    unused = [
        s for s in sups if (s.rule, s.file, s.symbol) not in used
    ]
    report = Report(
        findings=kept,
        suppressed=suppressed,
        unused_suppressions=unused,
        rules_run=names,
        files_scanned=len(modules),
        duration_s=time.perf_counter() - t0,
    )
    if cache_dir is not None and cache_key is not None:
        from cst_captioning_tpu.analysis import cache as _cache

        _cache.store(
            Path(cache_dir), cache_key, report.to_stable_dict(),
            cache_files or {},
        )
    return report
