"""The dtype/shape abstract interpreter (ISSUE 15): the layer under
CST-DTY and CST-SHP.

The low-precision serving path the ROADMAP wants (bf16/int8 decode with
a bounded-divergence contract) is exactly the kind of change the PARITY
tiers cannot survive unaudited: one implicit upcast and the "token-exact"
tier silently becomes "close enough", one unregistered downcast and
nobody can say which tier a path is on.  Likewise the jit_registry
records *that* a site compiles but not *what shapes* it may see — the
pow2/admit-bucket shape discipline lives in prose.  This module turns
both contracts into dataflow facts:

* an :class:`AbstractValue` is a ``(dtype-lattice element, shape
  symbol tuple)`` pair.  The dtype lattice has JAX's weak types as
  first-class elements (a bare Python scalar is ``wi``/``wf``, which
  promotion DROPS against any concrete array dtype — the rule JAX
  implements and reviewers forget); ``any`` is top, so precision only
  ever errs toward silence, never toward false findings.
* :class:`TypeFlow` rides the PR-12 def-use chains
  (``analysis/dataflow.py``) and the CST-JIT traced-set closure: every
  function reachable from a registered jit root gets its expressions
  abstractly evaluated in lexical order — array creators
  (``jnp.zeros``/``arange``/``PRNGKey``/literals), dtype transformers
  (``astype``, ``convert_element_type``, ``.at[...]`` updates, binop
  promotion, matmul ``preferred_element_type``), and shape algebra
  over config-knob symbols (``self.S``, ``cfg.serving.num_slots``,
  ``V // M`` vocab tiles) — the same knob vocabulary CST-CFG resolves.
* interprocedural: a call into the package evaluates the callee's
  return expressions under the mapped argument values (memoized on the
  argument dtype signature, depth-bounded), so ``lstm_step``'s result
  dtype is known at its serving call sites.

Pure stdlib-``ast`` like the rest of the engine: reads source, never
imports jax or the package under analysis.  The checkers built on top
(``analysis/dtypeflow.py``, ``analysis/shapeflow.py``) consume the
facts; this module emits none itself.
"""

from __future__ import annotations

import ast
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple, Union

from cst_captioning_tpu.analysis.astutil import (
    FuncInfo,
    ModuleInfo,
    call_name,
    dotted,
    walk_body,
)
from cst_captioning_tpu.analysis.dataflow import DefUse

__all__ = [
    "AbstractValue",
    "TypeFlow",
    "build",
    "cast_sites",
    "site_key",
    "promote",
    "last_duration",
]

# --------------------------------------------------------- dtype lattice
#
# Elements: 'bottom' (never), concrete array dtypes, the two weak
# scalars ('wi' python int, 'wf' python float), 'key' (PRNG keys), and
# 'any' (top — unknown, e.g. a traced parameter).

FLOATS = ("f64", "f32", "bf16", "f16")
INTS = ("i64", "i32", "i16", "i8", "u64", "u32", "u16", "u8")
CONCRETE = FLOATS + INTS + ("bool", "key")

_FLOAT_RANK = {"f16": 1, "bf16": 1, "f32": 2, "f64": 3}
_INT_RANK = {
    "i8": 1, "u8": 1, "i16": 2, "u16": 2,
    "i32": 3, "u32": 3, "i64": 4, "u64": 4,
}

# dotted-name / string spellings -> lattice element
_DTYPE_NAMES = {
    "float64": "f64", "float32": "f32", "bfloat16": "bf16",
    "float16": "f16", "int64": "i64", "int32": "i32", "int16": "i16",
    "int8": "i8", "uint64": "u64", "uint32": "u32", "uint16": "u16",
    "uint8": "u8", "bool": "bool", "bool_": "bool", "float": "wf",
    "int": "wi",
}


def dtype_of_name(name: str) -> Optional[str]:
    """Lattice element for a dtype spelled as a (dotted) name or
    string literal (``jnp.float32``, ``"bfloat16"``, ``np.int32``)."""
    return _DTYPE_NAMES.get(name.rsplit(".", 1)[-1])


def is_float(dt: str) -> bool:
    return dt in _FLOAT_RANK


def is_int(dt: str) -> bool:
    return dt in _INT_RANK


def promote(a: str, b: str) -> str:
    """JAX-style binary promotion over the lattice, including the weak
    rules: a Python scalar (``wi``/``wf``) NEVER widens a concrete
    array dtype — ``bf16 * 0.5`` stays bf16 — but DOES float an int
    array (``i32 * 0.5`` -> the default float), which is the silent
    flip CST-DTY-002 exists to catch."""
    if a == b:
        return a
    if "any" in (a, b) or "bottom" in (a, b) or "key" in (a, b):
        return "any"
    # weak scalars
    if a in ("wi", "wf") and b in ("wi", "wf"):
        return "wf" if "wf" in (a, b) else "wi"
    for weak, strong in ((a, b), (b, a)):
        if weak == "wi" and strong in CONCRETE:
            return strong if strong != "bool" else "i32"
        if weak == "wf" and strong in CONCRETE:
            # weak float against an int/bool array floats it to the
            # DEFAULT float (f32 under the x64-off regime) — the
            # implicit upcast, not a width-preserving move.
            return strong if is_float(strong) else "f32"
    if is_float(a) and is_float(b):
        if {a, b} == {"bf16", "f16"}:
            return "f32"
        return a if _FLOAT_RANK[a] >= _FLOAT_RANK[b] else b
    if is_int(a) and is_int(b):
        return a if _INT_RANK[a] >= _INT_RANK[b] else b
    if "bool" in (a, b):
        other = b if a == "bool" else a
        return other
    # int x float -> the float side
    fl = a if is_float(a) else b
    return fl


# ---------------------------------------------------------- shape dims
#
# A dim is an int, a symbol string (config knob / attribute chain /
# derived expression), or a DATA-DEPENDENT symbol prefixed "?" — the
# taint CST-SHP-001 chases (a "?"-dim reaching a jit boundary without
# a ladder bucket in its derivation is a statically-visible recompile
# storm).

Dim = Union[int, str]


def dim_is_data_dependent(d: Dim) -> bool:
    return isinstance(d, str) and d.startswith("?")


def _dim_binop(op: ast.AST, a: Dim, b: Dim) -> Dim:
    if isinstance(a, int) and isinstance(b, int):
        try:
            if isinstance(op, ast.Add):
                return a + b
            if isinstance(op, ast.Sub):
                return a - b
            if isinstance(op, ast.Mult):
                return a * b
            if isinstance(op, ast.FloorDiv) and b:
                return a // b
        except Exception:
            pass
    sym = f"({a}{_OPS.get(type(op), '?')}{b})"
    if dim_is_data_dependent(a) or dim_is_data_dependent(b):
        return "?" + sym
    return sym


_OPS = {
    ast.Add: "+", ast.Sub: "-", ast.Mult: "*",
    ast.FloorDiv: "//", ast.Div: "/", ast.Mod: "%",
}


@dataclass(frozen=True)
class AbstractValue:
    """One ``(dtype, shape)`` lattice point.  ``shape`` is None when
    unknown; ``array`` is None when array-ness itself is unknown (a
    traced parameter), which the rules treat as "do not fire"."""

    dtype: str = "any"
    shape: Optional[Tuple[Dim, ...]] = None
    array: Optional[bool] = None

    def with_dtype(self, dt: str) -> "AbstractValue":
        return AbstractValue(dt, self.shape, self.array)


ANY = AbstractValue()
WEAK_INT = AbstractValue("wi", (), False)
WEAK_FLOAT = AbstractValue("wf", (), False)
BOOL_SCALAR = AbstractValue("bool", (), False)
PY = AbstractValue("any", None, False)        # non-numeric python value
KEY = AbstractValue("key", None, True)

# array creators: callee basename -> default dtype
_CREATORS = {
    "zeros": "f32", "ones": "f32", "empty": "f32", "full": "f32",
}
_LIKE_CREATORS = ("zeros_like", "ones_like", "full_like", "empty_like")
_MATMULS = ("dot_general", "dot", "matmul", "einsum", "tensordot")
_PASSTHROUGH = (
    "sum", "mean", "max", "min", "abs", "tanh", "exp", "log", "sqrt",
    "negative", "maximum", "minimum", "where", "squeeze", "reshape",
    "transpose", "swapaxes", "concatenate", "stack", "split",
    "expand_dims", "clip", "cumsum", "flip", "roll", "broadcast_to",
    "dynamic_slice", "dynamic_update_slice", "select", "tile",
)
_RANDOM_FLOAT = ("uniform", "normal", "gumbel", "truncated_normal")
_KEY_FNS = ("PRNGKey", "key", "split", "fold_in", "clone")
_ARG_FNS = ("argmax", "argmin", "argsort", "searchsorted")
_CAST_ATTRS = ("astype",)
_CONVERT_FNS = ("convert_element_type",)


def is_cast_call(node: ast.Call) -> Optional[str]:
    """``"astype"`` / ``"convert_element_type"`` when ``node`` is a
    dtype-cast application, else None."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _CAST_ATTRS:
        return f.attr
    name = (call_name(node) or "").rsplit(".", 1)[-1]
    if name in _CONVERT_FNS:
        return name
    return None


def site_key(mi: ModuleInfo, qualname: str) -> str:
    """Registry key for a cast site: ``<file>::<qualname>`` with
    ``<lambda#N>`` segments folded into their enclosing def (lambda
    sequence numbers are not stable under reformatting)."""
    parts = [
        p for p in qualname.split(".") if not p.startswith("<lambda")
    ]
    return f"{mi.rel}::{'.'.join(parts) or '<module>'}"


class _FnTypes:
    """Abstract values for one function's expressions, evaluated in
    lexical order over the def-use chains."""

    def __init__(self, tf: "TypeFlow", fn: FuncInfo):
        self.tf = tf
        self.fn = fn
        self.du = tf.defuse(fn)
        self._memo: Dict[int, AbstractValue] = {}

    def value_of(self, node: ast.AST, depth: int = 0) -> AbstractValue:
        key = id(node)
        if key in self._memo:
            return self._memo[key]
        if depth > 24:
            return ANY
        self._memo[key] = ANY           # cycle guard
        v = self._eval(node, depth)
        self._memo[key] = v
        return v

    # ------------------------------------------------------------ eval
    def _eval(self, node: ast.AST, depth: int) -> AbstractValue:
        if isinstance(node, ast.Constant):
            v = node.value
            if isinstance(v, bool):
                return BOOL_SCALAR
            if isinstance(v, int):
                return WEAK_INT
            if isinstance(v, float):
                return WEAK_FLOAT
            return PY
        if isinstance(node, ast.Name):
            b = self.du.reaching_def(node)
            if b is None or b.kind == "param":
                return self.tf.param_value(self.fn, node.id)
            if b.value is None:
                return ANY
            return self.value_of(b.value, depth + 1)
        if isinstance(node, ast.BinOp):
            a = self.value_of(node.left, depth + 1)
            b = self.value_of(node.right, depth + 1)
            arr = (
                True if a.array or b.array
                else (False if a.array is False and b.array is False
                      else None)
            )
            return AbstractValue(promote(a.dtype, b.dtype), None, arr)
        if isinstance(node, ast.UnaryOp):
            v = self.value_of(node.operand, depth + 1)
            if isinstance(node.op, ast.Not):
                return AbstractValue("bool", v.shape, v.array)
            return v
        if isinstance(node, ast.Compare):
            arr = any(
                self.value_of(s, depth + 1).array
                for s in [node.left, *node.comparators]
            )
            return AbstractValue("bool", None, True if arr else None)
        if isinstance(node, ast.BoolOp):
            return AbstractValue("bool", None, None)
        if isinstance(node, ast.IfExp):
            a = self.value_of(node.body, depth + 1)
            b = self.value_of(node.orelse, depth + 1)
            return AbstractValue(promote(a.dtype, b.dtype), None, a.array)
        if isinstance(node, ast.Call):
            return self._eval_call(node, depth)
        if isinstance(node, ast.Subscript):
            base = self.value_of(node.value, depth + 1)
            return AbstractValue(base.dtype, None, base.array)
        if isinstance(node, ast.Attribute):
            if node.attr in ("T", "real", "mT"):
                return self.value_of(node.value, depth + 1)
            return ANY
        if isinstance(node, (ast.Tuple, ast.List)):
            return PY
        return ANY

    def _dtype_arg(self, expr: ast.AST, depth: int) -> Optional[str]:
        """Lattice element for a dtype-position expression."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return dtype_of_name(expr.value)
        name = dotted(expr)
        if name:
            dt = dtype_of_name(name)
            if dt:
                return dt
            # ``x.dtype`` / ``self.compute_dtype`` style: the dtype OF
            # another abstract value when we know it
            if name.endswith(".dtype"):
                base = expr
                while isinstance(base, ast.Attribute):
                    base = base.value
                v = self.value_of(base, depth + 1)
                if isinstance(expr, ast.Attribute) and isinstance(
                    expr.value, ast.Name
                ):
                    v = self.value_of(expr.value, depth + 1)
                if v.dtype not in ("any", "bottom"):
                    return v.dtype
        if isinstance(expr, ast.Call):
            # jnp.dtype(X) wrapper
            if (call_name(expr) or "").rsplit(".", 1)[-1] == "dtype" and (
                expr.args
            ):
                return self._dtype_arg(expr.args[0], depth + 1)
        return None

    def _shape_arg(
        self, expr: ast.AST, depth: int
    ) -> Optional[Tuple[Dim, ...]]:
        if isinstance(expr, (ast.Tuple, ast.List)):
            return tuple(
                self.dim_of(e, depth + 1) for e in expr.elts
            )
        d = self.dim_of(expr, depth + 1)
        return (d,)

    def dim_of(self, expr: ast.AST, depth: int = 0) -> Dim:
        """Symbolic value of one shape-dimension expression: ints fold,
        attribute chains become knob symbols, ``len(...)`` taints the
        dim data-dependent, a registered ladder-bucket call launders
        the taint (the shape is laddered by construction)."""
        if depth > 24:
            return "?"
        if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
            return expr.value
        if isinstance(expr, ast.Name):
            b = self.du.reaching_def(expr)
            if b is None or b.kind == "param":
                return expr.id       # symbol: parameter / free name
            if b.value is None or b.kind == "for":
                # loop targets and valueless bindings stay plain
                # symbols: unknown is NOT data-dependent — the taint
                # below is reserved for PROVEN len() derivations.
                return expr.id
            return self.dim_of(b.value, depth + 1)
        if isinstance(expr, ast.Attribute):
            return dotted(expr) or expr.attr
        if isinstance(expr, ast.BinOp):
            return _dim_binop(
                expr.op,
                self.dim_of(expr.left, depth + 1),
                self.dim_of(expr.right, depth + 1),
            )
        if isinstance(expr, ast.Call):
            name = (call_name(expr) or "").rsplit(".", 1)[-1]
            if name == "len":
                return "?len"
            if name in self.tf.bucket_fn_names:
                return f"bucket:{name}"
            if name in ("min", "max") and expr.args:
                dims = [self.dim_of(a, depth + 1) for a in expr.args]
                if any(dim_is_data_dependent(d) for d in dims):
                    # min(len(x), cap) is still data-dependent unless a
                    # bucket call quantizes it afterwards
                    return "?" + f"{name}({','.join(map(str, dims))})"
                return f"{name}({','.join(map(str, dims))})"
            if name == "int":
                return self.dim_of(expr.args[0], depth + 1) if (
                    expr.args
                ) else "?"
            return f"{name}()"
        if isinstance(expr, ast.Subscript):
            base = expr.value
            if isinstance(base, ast.Attribute) and base.attr == "shape":
                owner = dotted(base.value) or "x"
                return f"{owner}.shape[…]"
        return "unknown"

    def _eval_call(self, node: ast.Call, depth: int) -> AbstractValue:
        cast = is_cast_call(node)
        if cast is not None:
            if cast in _CAST_ATTRS:
                operand = node.func.value          # type: ignore[union-attr]
                dt_expr = node.args[0] if node.args else None
            else:
                operand = node.args[0] if node.args else None
                dt_expr = node.args[1] if len(node.args) > 1 else next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "new_dtype"), None
                )
            base = self.value_of(operand, depth + 1) if (
                operand is not None
            ) else ANY
            dt = self._dtype_arg(dt_expr, depth) if (
                dt_expr is not None
            ) else None
            return AbstractValue(dt or "any", base.shape, True)
        name = call_name(node) or ""
        base_name = name.rsplit(".", 1)[-1]
        if base_name in _CREATORS:
            dt = None
            for kw in node.keywords:
                if kw.arg == "dtype":
                    dt = self._dtype_arg(kw.value, depth)
            # jnp.full's positional dtype sits at arg 2; zeros/ones at 1
            pos = 2 if base_name == "full" else 1
            if dt is None and len(node.args) > pos:
                dt = self._dtype_arg(node.args[pos], depth)
            shape = self._shape_arg(node.args[0], depth) if (
                node.args
            ) else None
            return AbstractValue(dt or _CREATORS[base_name], shape, True)
        if base_name in _LIKE_CREATORS:
            v = self.value_of(node.args[0], depth + 1) if (
                node.args
            ) else ANY
            dt = None
            for kw in node.keywords:
                if kw.arg == "dtype":
                    dt = self._dtype_arg(kw.value, depth)
            return AbstractValue(dt or v.dtype, v.shape, True)
        if base_name == "arange":
            dt = None
            for kw in node.keywords:
                if kw.arg == "dtype":
                    dt = self._dtype_arg(kw.value, depth)
            if dt is None and any(
                isinstance(a, ast.Constant) and isinstance(a.value, float)
                for a in node.args
            ):
                dt = "f32"
            return AbstractValue(dt or "i32", None, True)
        if base_name == "iota":
            return AbstractValue("i32", None, True)
        if base_name in _KEY_FNS and name.split(".")[0] in (
            "jax", "random", "jr",
        ) or (base_name in _KEY_FNS and "random" in name):
            return KEY
        if base_name in _RANDOM_FLOAT and "random" in name:
            dt = None
            for kw in node.keywords:
                if kw.arg == "dtype":
                    dt = self._dtype_arg(kw.value, depth)
            return AbstractValue(dt or "f32", None, True)
        if base_name == "categorical" and "random" in name:
            return AbstractValue("i32", None, True)
        if base_name == "bernoulli" and "random" in name:
            return AbstractValue("bool", None, True)
        if base_name in _ARG_FNS:
            return AbstractValue("i32", None, True)
        if base_name == "one_hot":
            return AbstractValue("f32", None, True)
        if base_name in _MATMULS:
            for kw in node.keywords:
                if kw.arg == "preferred_element_type":
                    dt = self._dtype_arg(kw.value, depth)
                    return AbstractValue(dt or "any", None, True)
            ops = [
                self.value_of(a, depth + 1)
                for a in node.args
                if not (
                    isinstance(a, ast.Constant)
                    and isinstance(a.value, str)
                )
            ]
            dt = "bottom"
            for v in ops:
                dt = promote(dt, v.dtype) if dt != "bottom" else v.dtype
            return AbstractValue(dt or "any", None, True)
        if base_name in _PASSTHROUGH and node.args:
            v = self.value_of(node.args[0], depth + 1)
            if base_name == "where" and len(node.args) >= 3:
                a = self.value_of(node.args[1], depth + 1)
                b = self.value_of(node.args[2], depth + 1)
                return AbstractValue(
                    promote(a.dtype, b.dtype), None, True
                )
            return AbstractValue(v.dtype, None, v.array)
        # ``.at[...].set/add/...(v)`` functional update: dtype of the
        # base array (JAX casts the update operand INTO the buffer)
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(
            f.value, ast.Subscript
        ):
            sub = f.value.value
            if isinstance(sub, ast.Attribute) and sub.attr == "at":
                return self.value_of(sub.value, depth + 1)
        # package call: evaluate the callee's returns interprocedurally
        resolved = self.tf.resolve(self.fn.module, self.fn, node)
        if resolved:
            return self.tf.return_value(resolved[0], node, self, depth)
        return ANY


class TypeFlow:
    """Package-wide driver: the traced set plus lazy per-function
    :class:`_FnTypes` environments."""

    def __init__(self, modules: List[ModuleInfo], ctx):
        t0 = time.perf_counter()
        from cst_captioning_tpu.analysis import jit_boundary as jb

        self.modules = modules
        self.ctx = ctx
        self.by_rel = {m.rel: m for m in modules}
        traced = jb._TracedSet()
        jb._collect_roots(modules, traced)
        jb._expand(modules, ctx, traced)
        self.traced = traced
        self._du: Dict[Tuple[str, str], DefUse] = {}
        self._fn_types: Dict[Tuple[str, str], _FnTypes] = {}
        self._ret_memo: Dict[Tuple[str, str, Tuple[str, ...]], str] = {}
        self.bucket_fn_names = self._bucket_fn_names()
        self.duration_s = time.perf_counter() - t0

    @staticmethod
    def _bucket_fn_names() -> frozenset:
        from cst_captioning_tpu.analysis import jit_registry

        names = set()
        for entry in jit_registry.SHAPE_LADDER_REGISTRY.values():
            for fq in entry.bucket_fns:
                names.add(fq.split("::")[-1].rsplit(".", 1)[-1])
        return frozenset(names)

    # --------------------------------------------------------- plumbing
    def key(self, fn: FuncInfo) -> Tuple[str, str]:
        return (fn.module.rel, fn.qualname)

    def defuse(self, fn: FuncInfo) -> DefUse:
        k = self.key(fn)
        if k not in self._du:
            self._du[k] = DefUse(fn)
        return self._du[k]

    def types_of(self, fn: FuncInfo) -> _FnTypes:
        k = self.key(fn)
        if k not in self._fn_types:
            self._fn_types[k] = _FnTypes(self, fn)
        return self._fn_types[k]

    def resolve(self, mi: ModuleInfo, fn: FuncInfo, call: ast.Call):
        return self.ctx.index.resolve_call(mi, fn, call)

    def traced_functions(self) -> List[FuncInfo]:
        out = []
        for (rel, qn) in sorted(self.traced.static):
            mi = self.by_rel.get(rel)
            if mi is not None and qn in mi.functions:
                out.append(mi.functions[qn])
        return out

    def param_value(self, fn: FuncInfo, name: str) -> AbstractValue:
        """Traced parameters are TOP (unknown array) by construction —
        a rule fires only on facts the flow actually proves."""
        return ANY

    # ------------------------------------------- interprocedural return
    def return_value(
        self, callee: FuncInfo, call: ast.Call,
        caller_types: _FnTypes, depth: int,
    ) -> AbstractValue:
        if depth > 8:
            return ANY
        node = callee.node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return ANY
        # argument dtype signature for memoization
        sig = tuple(
            caller_types.value_of(a, depth + 1).dtype for a in call.args
        )
        mk = (callee.module.rel, callee.qualname, sig)
        if mk in self._ret_memo:
            dt = self._ret_memo[mk]
            return AbstractValue(dt, None, None if dt == "any" else True)
        self._ret_memo[mk] = "any"          # recursion guard
        callee_types = _CalleeTypes(self, callee, call, caller_types)
        dt = "bottom"
        for n in walk_body(callee):
            if isinstance(n, ast.Return) and n.value is not None:
                v = callee_types.value_of(n.value, depth + 1)
                dt = v.dtype if dt == "bottom" else promote(dt, v.dtype)
        if dt == "bottom":
            dt = "any"
        self._ret_memo[mk] = dt
        return AbstractValue(dt, None, None if dt == "any" else True)


class _CalleeTypes(_FnTypes):
    """A callee evaluated under the caller's argument values: positional
    and keyword args map onto parameters; everything else stays TOP."""

    def __init__(
        self, tf: TypeFlow, fn: FuncInfo, call: ast.Call,
        caller: _FnTypes,
    ):
        super().__init__(tf, fn)
        self._args: Dict[str, AbstractValue] = {}
        params = [p for p in fn.params if p not in ("self", "cls")]
        for p, a in zip(params, call.args):
            self._args[p] = caller.value_of(a, 1)
        for kw in call.keywords:
            if kw.arg:
                self._args[kw.arg] = caller.value_of(kw.value, 1)

    def value_of(self, node: ast.AST, depth: int = 0) -> AbstractValue:
        if isinstance(node, ast.Name) and node.id in self._args:
            b = self.du.reaching_def(node)
            if b is None or b.kind == "param":
                return self._args[node.id]
        return super().value_of(node, depth)


# --------------------------------------------------------- cast surface

def cast_sites(
    modules: List[ModuleInfo], tf: TypeFlow
) -> List[Tuple[str, ModuleInfo, FuncInfo, ast.Call, str]]:
    """Every dtype-cast application inside the traced set, as
    ``(registry_key, module, function, call, kind)`` — the surface
    CST-DTY-001 audits against ``CAST_REGISTRY``."""
    out = []
    for fn in tf.traced_functions():
        mi = fn.module
        for node in walk_body(fn):
            if isinstance(node, ast.Call):
                kind = is_cast_call(node)
                if kind is not None:
                    out.append(
                        (site_key(mi, fn.qualname), mi, fn, node, kind)
                    )
    return out


# ------------------------------------------------------------ lifecycle

_CACHE: List[Tuple[object, TypeFlow]] = []
_LAST_DURATION = 0.0


def build(modules: List[ModuleInfo], ctx) -> TypeFlow:
    """Build (or reuse — both CST-DTY and CST-SHP ride one flow per
    engine run) the TypeFlow for a scanned module list."""
    global _LAST_DURATION
    for obj, tf in _CACHE:
        if obj is modules:
            return tf
    tf = TypeFlow(modules, ctx)
    _CACHE.clear()
    _CACHE.append((modules, tf))
    _LAST_DURATION = tf.duration_s
    return tf


def note_duration(seconds: float) -> None:
    """Accumulate checker wall time onto the current flow's total (the
    interpretation itself is lazy, so the build alone undercounts)."""
    global _LAST_DURATION
    _LAST_DURATION += seconds


def last_duration() -> float:
    """Wall seconds the most recent typeflow pass took — traced-set
    build plus the CST-DTY/CST-SHP interpretation on top (0.0 when the
    engine served a cache hit and no flow ran) — the bench preflight
    records this as ``analysis_typeflow_duration_s``."""
    return _LAST_DURATION
