"""CST-DEC: single-definition-site rules for the decode recurrence.

The repo's hardest-won invariant (PR 6) is that the per-step decode
recurrence exists exactly once — ``decoding/core.py::decode_step`` —
and (PR 7) that admission paths never re-grow the K× replicated
``DecodeCache`` layout the dedup removed.  Both used to be guarded by
regex fingerprints over comment-stripped source
(tests/test_decode_core.py); these AST rules replace them and survive
reformatting, aliasing (``from jax.lax import top_k``), and line
wrapping.

Rules (allowlists are CONSCIOUS extension points — the fused Pallas
kernel bodies and their bit-exact XLA twins keep in-kernel recurrences
by necessity):

* CST-DEC-001 — a ``top_k`` call (the beam-selection recurrence)
  outside :data:`TOP_K_ALLOWED`.
* CST-DEC-002 — the finish update ``(tok == EOS_ID) | (tok == PAD_ID)``
  outside :data:`FINISH_ALLOWED`.
* CST-DEC-003 — the PAD→EOS feed ``where(x == PAD_ID, EOS_ID, ...)``
  outside :data:`FEED_ALLOWED`.
* CST-DEC-004 — ``jnp.repeat``-style cache replication outside
  :data:`REPEAT_ALLOWED` (the PR-7 K× decode-state memory regression).
"""

from __future__ import annotations

import ast
from typing import List

from cst_captioning_tpu.analysis.astutil import ModuleInfo, dotted
from cst_captioning_tpu.analysis.engine import (
    CheckContext,
    Finding,
    register_checker,
)

# Files allowed to contain each pattern.  Removing an entry that still
# holds the pattern makes the pass fail at the exact file:line —
# pinned by tests/test_analysis.py.
TOP_K_ALLOWED = frozenset({
    "decoding/core.py",
    "ops/pallas_beam.py",
    # The shard_map port of the fused kernels (ISSUE 14): per-shard
    # vocab-tile top-K feeding the cross-shard candidate merge — the
    # same conscious kernel-twin exemption as the Pallas files.
    "ops/shard_decode.py",
})
FINISH_ALLOWED = frozenset({
    "decoding/core.py",
    "ops/pallas_beam.py",
    "ops/pallas_sampler.py",
    "ops/shard_decode.py",
})
# training/cst.py: the PG update's input shift, not a decode loop.
FEED_ALLOWED = frozenset({
    "decoding/core.py",
    "ops/pallas_beam.py",
    "ops/pallas_sampler.py",
    "ops/shard_decode.py",
    "training/cst.py",
})
# Allowed jnp.repeat fan-outs: the offline beam expansion (beam.py),
# the seq_per_img rollout fan-out (captioner.py), the fused kernels'
# twins, the CST reward broadcast (cst.py), and slots.py's flag-gated
# legacy replicated layout (serving.dedup_cache=false).
REPEAT_ALLOWED = frozenset({
    "decoding/beam.py",
    "models/captioner.py",
    "ops/pallas_beam.py",
    "ops/shard_decode.py",
    "training/cst.py",
    "serving/slots.py",
})

_EOS_NAMES = {"EOS_ID"}
_PAD_NAMES = {"PAD_ID"}


def _cmp_against(node: ast.AST, names: frozenset) -> bool:
    """True for ``X == NAME`` / ``NAME == X`` Compare nodes."""
    if not (isinstance(node, ast.Compare) and len(node.ops) == 1):
        return False
    if not isinstance(node.ops[0], ast.Eq):
        return False
    sides = [node.left, node.comparators[0]]
    return any(
        isinstance(s, ast.Name) and s.id in names for s in sides
    )


def _finish_update(node: ast.AST) -> bool:
    """``(x == EOS_ID) | (y == PAD_ID)`` in either order, possibly
    nested in a wider BitOr chain, or the bool-op spelling."""
    terms: List[ast.AST] = []

    def flatten(n: ast.AST) -> None:
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.BitOr):
            flatten(n.left)
            flatten(n.right)
        elif isinstance(n, ast.BoolOp) and isinstance(n.op, ast.Or):
            for v in n.values:
                flatten(v)
        else:
            terms.append(n)

    if not (
        (isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr))
        or (isinstance(node, ast.BoolOp) and isinstance(node.op, ast.Or))
    ):
        return False
    flatten(node)
    has_eos = any(_cmp_against(t, frozenset(_EOS_NAMES)) for t in terms)
    has_pad = any(_cmp_against(t, frozenset(_PAD_NAMES)) for t in terms)
    return has_eos and has_pad


def _pad_eos_feed(node: ast.Call) -> bool:
    """``where(x == PAD_ID, EOS_ID, ...)`` — any where-flavored callee
    (jnp.where, np.where, bare where)."""
    callee = dotted(node.func)
    if not callee.split(".")[-1] == "where":
        return False
    if len(node.args) < 2:
        return False
    cond, then = node.args[0], node.args[1]
    return (
        _cmp_against(cond, frozenset(_PAD_NAMES))
        and isinstance(then, ast.Name)
        and then.id in _EOS_NAMES
    )


def _resolved_callee(mi: ModuleInfo, node: ast.Call) -> str:
    """Dotted callee with its head resolved through the module's import
    map, so ``from jax.lax import top_k as tk; tk(...)`` still names
    ``jax.lax.top_k``."""
    callee = dotted(node.func)
    head, dot, rest = callee.partition(".")
    target = mi.imports.get(head)
    if target:
        return target + (("." + rest) if rest else "")
    return callee


def _is_top_k(mi: ModuleInfo, node: ast.Call) -> bool:
    callee = _resolved_callee(mi, node)
    return bool(callee) and callee.split(".")[-1] == "top_k"


def _is_repeat(node: ast.Call) -> bool:
    """``jnp.repeat`` / ``np.repeat`` / aliased ``repeat`` imported from
    a numpy-flavored module — NOT ``str.repeat``-style methods on
    arbitrary objects (``x.repeat(...)`` with a non-module receiver is
    torch idiom that doesn't occur here; a bare attribute ``.repeat``
    on a Name receiver counts only for the known array-module aliases)."""
    callee = dotted(node.func)
    if callee in ("jnp.repeat", "np.repeat", "numpy.repeat", "repeat"):
        return True
    return callee.endswith(".repeat") and callee.split(".")[0] in (
        "jnp", "np", "jax", "numpy",
    )


@register_checker("single_site")
def check(modules: List[ModuleInfo], ctx: CheckContext) -> List[Finding]:
    out: List[Finding] = []
    for mi in modules:
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Call):
                if _is_top_k(mi, node) and mi.rel not in TOP_K_ALLOWED:
                    out.append(Finding(
                        "CST-DEC-001", mi.rel, node.lineno,
                        mi.qualname_of(node),
                        "beam-selection recurrence (top_k) outside "
                        "decoding/core.py — import "
                        "decoding.core.decode_step instead (kernel "
                        "bodies: extend TOP_K_ALLOWED consciously)",
                    ))
                if _pad_eos_feed(node) and mi.rel not in FEED_ALLOWED:
                    out.append(Finding(
                        "CST-DEC-003", mi.rel, node.lineno,
                        mi.qualname_of(node),
                        "PAD→EOS feed of finished rows re-implemented "
                        "outside decoding/core.py",
                    ))
                if _is_repeat(node) and mi.rel not in REPEAT_ALLOWED:
                    out.append(Finding(
                        "CST-DEC-004", mi.rel, node.lineno,
                        mi.qualname_of(node),
                        "jnp.repeat-style replication outside the "
                        "allowlist — replicating cached decode state "
                        "at admission is the K× memory regression the "
                        "deduped slot layout removed (PR 7); read the "
                        "shared row via row//K instead",
                    ))
            elif (
                _finish_update(node)
                and mi.rel not in FINISH_ALLOWED
                # only the OUTERMOST node of an |-chain fires (a nested
                # sub-chain would double-report one expression)
                and not (
                    (p := mi.parent.get(node)) is not None
                    and (
                        (isinstance(p, ast.BinOp)
                         and isinstance(p.op, ast.BitOr))
                        or (isinstance(p, ast.BoolOp)
                            and isinstance(p.op, ast.Or))
                    )
                )
            ):
                out.append(Finding(
                    "CST-DEC-002", mi.rel, node.lineno,
                    mi.qualname_of(node),
                    "EOS/PAD finish update re-implemented outside "
                    "decoding/core.py",
                ))
    return out
