"""CST-THR: static thread-safety pass over the serving layer.

The serving stack is ~20 lock/thread sites across 9 files: HTTP handler
threads (one per in-flight request), one or N scheduler threads, control
callers (``stop``/``shutdown``/``kill_replica``), all sharing batcher
queues, replica tables, metrics, and caches.  Nothing checked ordering
or guarding until now.  Two rules:

* CST-THR-001 — **lock-order inversion**: build the static
  lock-acquisition graph — which locks are HELD (``with lock:`` /
  ``.acquire()`` AST shapes, propagated through the intra-serving call
  graph) when other locks are acquired — and flag any cycle.  Two locks
  ever taken in both orders on different paths is a latent deadlock
  regardless of how rarely the paths race.  The dynamic twin
  (``analysis/lockwatch.py``) asserts the same acyclicity on the REAL
  acquisition order under stub traffic in tier-1.
* CST-THR-002 — **unguarded shared-state mutation**: an instance
  attribute written with NO lock held in a method reachable from a
  concurrent entry point (HTTP handlers, ``submit``, multi-instance
  worker threads, external control calls) — or from two different
  entry points — is a data race unless the owning object is
  single-owner by contract.  Classes may declare that contract in
  source (``_analysis_single_owner = True``), which both silences the
  rule for their attributes and documents the ownership model where
  the next reader needs it.

Entry-point model: a function passed to ``threading.Thread(target=…)``
is a worker root — MULTI when the Thread is constructed inside a loop
(one thread per replica), SINGLE otherwise; ``do_GET``/``do_POST`` and
the public submit/control surface are MULTI (any number of caller
threads).  Reachability propagates the set of held locks along call
edges, so a write inside a method only ever called under ``self._cond``
is correctly seen as guarded.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from cst_captioning_tpu.analysis.astutil import (
    FuncInfo,
    ModuleInfo,
    call_name,
    dotted,
    walk_body,
)
from cst_captioning_tpu.analysis.engine import (
    CheckContext,
    Finding,
    register_checker,
)

# Files the pass covers (training/rewards.py is a PROCESS pool —
# apply_async + get, no shared-memory threading — and stays out).
SCOPE_PREFIXES = ("serving/",)

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}

# Public surface callable from arbitrary threads.  Method name -> why.
EXTERNAL_ROOTS: Dict[str, str] = {
    "submit": "HTTP handler threads (one per in-flight request)",
    "stop": "external control callers",
    "shutdown": "SIGTERM thread / context exits / serve_forever finally",
    "begin_drain": "external control callers",
    "kill_replica": "operational control callers",
}
_HANDLER_ROOTS = {"do_GET", "do_POST"}


@dataclass
class MethodFacts:
    fn: FuncInfo
    cls: str
    # (lock_id, line, locks-held-at-acquisition-site)
    acquisitions: List[Tuple[str, int, FrozenSet[str]]] = field(
        default_factory=list
    )
    # (owner_class, attr, line, locks-held)
    writes: List[Tuple[str, str, int, FrozenSet[str]]] = field(
        default_factory=list
    )
    # (callee FuncInfo, locks-held-at-call)
    calls: List[Tuple[FuncInfo, FrozenSet[str]]] = field(
        default_factory=list
    )


class _World:
    """Everything the two rules need, extracted in one pass."""

    def __init__(self, modules: List[ModuleInfo], ctx: CheckContext):
        self.modules = [
            m for m in modules if m.rel.startswith(SCOPE_PREFIXES)
        ]
        self.ctx = ctx
        # "Class.attr" lock ids, from self.<attr> = threading.Lock()
        self.locks: Set[str] = set()
        # attr name -> owning classes (from __init__/__slots__ writes)
        self.attr_owner: Dict[str, Set[str]] = {}
        # attr of a class -> inferred class of the attribute value
        # (self.router = Router(...) -> {"ReplicaSet.router": "Router"})
        self.attr_class: Dict[str, str] = {}
        self.single_owner: Set[str] = set()
        self.class_bases: Dict[str, List[str]] = {}
        self.methods: Dict[Tuple[str, str], MethodFacts] = {}
        # (class name, method name) -> FuncInfo, for receiver-typed
        # call resolution (self.metrics.replica -> ServingMetrics.replica)
        self.cls_methods: Dict[Tuple[str, str], FuncInfo] = {}
        for mi in self.modules:
            for qn, fn in mi.functions.items():
                if fn.cls is not None:
                    self.cls_methods[(fn.cls, fn.name)] = fn
        self._collect_classes()
        self._collect_methods()

    # ------------------------------------------------------------ classes
    def _collect_classes(self) -> None:
        for mi in self.modules:
            for cname, cnode in mi.classes.items():
                self.class_bases[cname] = [
                    dotted(b).split(".")[-1] for b in cnode.bases
                ]
                for stmt in cnode.body:
                    # _analysis_single_owner = True marker
                    if (
                        isinstance(stmt, ast.Assign)
                        and any(
                            isinstance(t, ast.Name)
                            and t.id == "_analysis_single_owner"
                            for t in stmt.targets
                        )
                        and isinstance(stmt.value, ast.Constant)
                        and stmt.value.value is True
                    ):
                        self.single_owner.add(cname)
                    # __slots__ attribute ownership
                    if (
                        isinstance(stmt, ast.Assign)
                        and any(
                            isinstance(t, ast.Name) and t.id == "__slots__"
                            for t in stmt.targets
                        )
                        and isinstance(stmt.value, (ast.Tuple, ast.List))
                    ):
                        for el in stmt.value.elts:
                            if isinstance(el, ast.Constant):
                                self.attr_owner.setdefault(
                                    str(el.value), set()
                                ).add(cname)
                init = mi.functions.get(f"{cname}.__init__")
                if init is None:
                    continue
                for node in walk_body(init):
                    if not isinstance(node, ast.Assign):
                        continue
                    for t in node.targets:
                        if not (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            continue
                        self.attr_owner.setdefault(t.attr, set()).add(cname)
                        v = node.value
                        vname = (
                            call_name(v) if isinstance(v, ast.Call) else ""
                        )
                        if vname in _LOCK_CTORS:
                            self.locks.add(f"{cname}.{t.attr}")
                        # self.x = C(...) / self.x = y or C(...)
                        ctor = ""
                        if isinstance(v, ast.Call):
                            ctor = vname.split(".")[-1]
                        elif isinstance(v, ast.BoolOp) and isinstance(
                            v.op, ast.Or
                        ):
                            for alt in v.values:
                                if isinstance(alt, ast.Call):
                                    ctor = call_name(alt).split(".")[-1]
                        if ctor and ctor.lstrip("_")[:1].isupper():
                            self.attr_class[f"{cname}.{t.attr}"] = ctor

        # inherited locks/attrs: subclasses own their bases' locks
        for cname, bases in self.class_bases.items():
            for b in bases:
                for lock in list(self.locks):
                    owner, attr = lock.split(".", 1)
                    if owner == b:
                        self.locks.add(f"{cname}.{attr}")
                for attr, owners in self.attr_owner.items():
                    if b in owners:
                        owners.add(cname)

    def lock_id(self, cls: Optional[str], attr: str) -> Optional[str]:
        """Canonical lock id for self.<attr> in class ``cls`` — bases'
        locks canonicalize to the BASE class (one shared graph node for
        _BatcherBase._cond across its subclasses)."""
        if cls is None:
            return None
        seen, stack = set(), [cls]
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            stack.extend(self.class_bases.get(c, []))
        # the DEFINING base wins so one graph node covers the lock
        # across subclasses (_BatcherBase._cond, not MicroBatcher._cond)
        owners = [
            c for c in sorted(seen) if f"{c}.{attr}" in self.locks
        ]
        if not owners:
            return None
        base_cands = [
            c for c in owners
            if any(c in self.class_bases.get(c2, ())
                   for c2 in owners if c2 != c)
        ]
        pick = sorted(base_cands or owners)[0]
        return f"{pick}.{attr}"

    # ------------------------------------------------------------ methods
    def _collect_methods(self) -> None:
        for mi in self.modules:
            for qn, fn in mi.functions.items():
                if fn.cls is None:
                    continue
                mf = MethodFacts(fn=fn, cls=fn.cls)
                self.methods[(mi.rel, qn)] = mf
                self._walk_method(mi, fn, mf)

    def _self_lock(self, mf: MethodFacts, expr: ast.AST) -> Optional[str]:
        """self.<attr> (or bare name aliasing a self lock attr is not
        tracked) resolving to a known lock id."""
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            return self.lock_id(mf.cls, expr.attr)
        # obj.attr where obj's class was inferred from __init__
        if isinstance(expr, ast.Attribute):
            recv_cls = self._recv_class(mf, expr.value)
            if recv_cls is not None:
                return self.lock_id(recv_cls, expr.attr)
        return None

    def _attr_class_mro(self, cls: str, attr: str) -> Optional[str]:
        stack, seen = [cls], set()
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            got = self.attr_class.get(f"{c}.{attr}")
            if got:
                return got
            stack.extend(self.class_bases.get(c, []))
        return None

    def _recv_class(self, mf: MethodFacts, recv: ast.AST) -> Optional[str]:
        """Inferred class of a receiver expression, recursively through
        attribute chains: ``self.metrics`` -> ServingMetrics,
        ``self.metrics.requests_total`` -> Counter (via the __init__
        constructor map)."""
        if isinstance(recv, ast.Name):
            return None  # locals are untyped; the unique-attr fallback
        if isinstance(recv, ast.Attribute):
            if (
                isinstance(recv.value, ast.Name)
                and recv.value.id == "self"
            ):
                return self._attr_class_mro(mf.cls, recv.attr)
            inner = self._recv_class(mf, recv.value)
            if inner is not None:
                return self._attr_class_mro(inner, recv.attr)
        return None

    def _walk_method(
        self, mi: ModuleInfo, fn: FuncInfo, mf: MethodFacts
    ) -> None:
        def walk(node: ast.AST, held: FrozenSet[str]) -> None:
            if isinstance(node, ast.With):
                inner = held
                for item in node.items:
                    lid = self._self_lock(mf, item.context_expr)
                    if lid is not None:
                        mf.acquisitions.append(
                            (lid, item.context_expr.lineno, inner)
                        )
                        inner = inner | {lid}
                for stmt in node.body:
                    walk(stmt, inner)
                return
            if isinstance(node, ast.Call):
                if (
                    call_name(node).endswith(".acquire")
                    and isinstance(node.func, ast.Attribute)
                ):
                    lid = self._self_lock(mf, node.func.value)
                    if lid is not None:
                        mf.acquisitions.append((lid, node.lineno, held))
                callees = self.ctx.index.resolve_call(mi, fn, node)
                if not callees and isinstance(node.func, ast.Attribute):
                    # receiver-typed resolution: obj.m() where obj's
                    # class is inferable from the __init__ ctor map
                    recv_cls = self._recv_class(mf, node.func.value)
                    if recv_cls is not None:
                        got = self.cls_methods.get(
                            (recv_cls, node.func.attr)
                        )
                        if got is not None:
                            callees = [got]
                for callee in callees:
                    mf.calls.append((callee, held))
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    base = t
                    # unwrap subscript stores: self.d[k] = v mutates d
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if not isinstance(base, ast.Attribute):
                        continue
                    owner: Optional[str] = None
                    if (
                        isinstance(base.value, ast.Name)
                        and base.value.id == "self"
                    ):
                        owner = mf.cls
                    else:
                        owner = self._recv_class(mf, base.value)
                        if owner is None and isinstance(
                            base.value, ast.Name
                        ):
                            # unique-attr fallback: rep.healthy ->
                            # Replica when exactly one class owns it
                            owners = self.attr_owner.get(base.attr, set())
                            if len(owners) == 1:
                                owner = next(iter(owners))
                    if owner is not None:
                        mf.writes.append(
                            (owner, base.attr, base.lineno, held)
                        )
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        if isinstance(fn.node, ast.Lambda):
            return
        for stmt in fn.node.body:
            walk(stmt, frozenset())


# ------------------------------------------------------------ entry roots

def _collect_roots(world: _World) -> Dict[Tuple[str, str], Tuple[str, str]]:
    """(module rel, qualname) -> (kind, why).  kind: "multi" | "single"."""
    roots: Dict[Tuple[str, str], Tuple[str, str]] = {}
    for mi in world.modules:
        for qn, fn in mi.functions.items():
            if fn.cls is not None and fn.name in _HANDLER_ROOTS:
                roots[(mi.rel, qn)] = (
                    "multi", "HTTP handler (thread per request)"
                )
            if fn.cls is not None and fn.name in EXTERNAL_ROOTS:
                roots[(mi.rel, qn)] = ("multi", EXTERNAL_ROOTS[fn.name])
        for node in ast.walk(mi.tree):
            if not (
                isinstance(node, ast.Call)
                and call_name(node) in (
                    "threading.Thread", "Thread",
                )
            ):
                continue
            target = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
            if target is None:
                continue
            tname = dotted(target)
            if not tname.startswith("self."):
                continue
            # worker multiplicity: Thread() constructed inside a loop
            # => one thread per item => MULTI entry
            multi = False
            cur = mi.parent.get(node)
            while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                if isinstance(cur, (ast.For, ast.While)):
                    multi = True
                cur = mi.parent.get(cur)
            encl = mi.qualname_of(node)
            cls = encl.split(".")[0] if "." in encl else None
            if cls is None:
                continue
            mname = tname.split(".", 1)[1]
            qn = f"{cls}.{mname}"
            if qn in mi.functions:
                kind = "multi" if multi else "single"
                # never downgrade an already-multi root (a method can be
                # both a thread target and public control surface)
                if roots.get((mi.rel, qn), ("", ""))[0] != "multi":
                    roots[(mi.rel, qn)] = (
                        kind,
                        "thread target "
                        + ("(per-replica workers)" if multi
                           else "(scheduler thread)"),
                    )
    return roots


# ------------------------------------------------------------------ rules

def _reachability(
    world: _World,
    roots: Dict[Tuple[str, str], Tuple[str, str]],
):
    """BFS over (method, held-locks) states from every root.

    Returns (write_roots, edges):
    * write_roots: (owner_cls, attr) -> {root_key: (file, line, qualname)}
      for writes seen with NO lock held;
    * edges: lock digraph {(A, B): (file, line, qualname)} — B acquired
      while A held.
    """
    write_roots: Dict[Tuple[str, str], Dict] = {}
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    for root_key, _ in roots.items():
        seen: Set[Tuple[str, str, FrozenSet[str]]] = set()
        stack: List[Tuple[Tuple[str, str], FrozenSet[str]]] = [
            (root_key, frozenset())
        ]
        while stack:
            (rel, qn), held = stack.pop()
            state = (rel, qn, held)
            if state in seen:
                continue
            seen.add(state)
            mf = world.methods.get((rel, qn))
            if mf is None:
                continue
            for lid, line, local_held in mf.acquisitions:
                for a in held | local_held:
                    if a != lid:
                        edges.setdefault((a, lid), (rel, line, qn))
            for owner, attr, line, local_held in mf.writes:
                if qn.endswith("__init__"):
                    continue
                if owner in world.single_owner:
                    continue
                if not (held | local_held):
                    write_roots.setdefault((owner, attr), {})[root_key] = (
                        rel, line, qn
                    )
            for callee, local_held in mf.calls:
                stack.append((
                    (callee.module.rel, callee.qualname),
                    held | local_held,
                ))
    return write_roots, edges


def _find_cycles(
    edges: Dict[Tuple[str, str], Tuple[str, int, str]]
) -> List[List[str]]:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles: List[List[str]] = []
    color: Dict[str, int] = {}
    path: List[str] = []

    def dfs(n: str) -> None:
        color[n] = 1
        path.append(n)
        for m in sorted(graph[n]):
            if color.get(m, 0) == 0:
                dfs(m)
            elif color.get(m) == 1:
                cyc = path[path.index(m):] + [m]
                if not any(set(cyc) == set(c) for c in cycles):
                    cycles.append(cyc)
        path.pop()
        color[n] = 2

    for n in sorted(graph):
        if color.get(n, 0) == 0:
            dfs(n)
    return cycles


@register_checker("thread_safety")
def check(modules: List[ModuleInfo], ctx: CheckContext) -> List[Finding]:
    world = _World(modules, ctx)
    roots = _collect_roots(world)
    write_roots, edges = _reachability(world, roots)
    out: List[Finding] = []

    for cyc in _find_cycles(edges):
        pairs = list(zip(cyc, cyc[1:]))
        sites = "; ".join(
            f"{a}->{b} at {edges[(a, b)][0]}:{edges[(a, b)][1]}"
            for a, b in pairs
            if (a, b) in edges
        )
        rel, line, qn = edges[pairs[0]] if pairs[0] in edges else (
            "serving/", 1, "<graph>"
        )
        out.append(Finding(
            "CST-THR-001", rel, line, qn,
            "lock-order inversion: cycle "
            + " -> ".join(cyc) + f" ({sites}) — two locks taken in "
            "both orders on different paths is a latent deadlock; "
            "pick one global order",
        ))

    for (owner, attr), by_root in sorted(write_roots.items()):
        kinds = {roots[rk][0] for rk in by_root}
        if "multi" in kinds or len(by_root) >= 2:
            rel, line, qn = sorted(by_root.values())[0]
            whys = sorted(
                f"{rk[1]} [{roots[rk][0]}]" for rk in by_root
            )
            out.append(Finding(
                "CST-THR-002", rel, line, qn,
                f"`{owner}.{attr}` is mutated with no lock held, "
                f"reachable from concurrent entry point(s): "
                f"{', '.join(whys)} — guard the write, or declare the "
                "owning class `_analysis_single_owner = True` if one "
                "thread owns it by contract",
            ))
    return out
