"""Shared def-use dataflow layer for the invariant engine.

The PR-8 checkers each re-derived the slice of flow information they
needed — the jit auditor closed a traced set over the call graph, the
thread pass propagated held locks, the resilience pass re-ran the jit
closure.  The three ISSUE-12 families (CST-RNG key discipline,
CST-CFG knob lifecycle, CST-EXC silent-exception audit) all need the
same two primitives, so they live here once:

* :class:`DefUse` — per-function def-use chains in LEXICAL event
  order: every binding (parameter, assignment, walrus, loop target,
  ``with``-as, ``except``-as) and every ``Name`` read, with
  ``reaching_def`` resolving a read to the latest earlier binding of
  that name.  Lexical order is a conscious approximation of control
  flow (a textually-later def inside a loop is treated as not
  reaching an earlier read); the checkers built on top are tuned so
  the approximation only ever costs recall, never package-clean
  precision.
* :func:`provenance_chain` — the taint API: walk a value expression
  backwards through the chains (``k = fold_in(rng, i)`` →
  ``rng`` → parameter) until it bottoms out at a parameter, an
  enclosing-scope binding, an attribute read, a constant, or a call,
  classifying the origin.  CST-RNG keys, CST-CFG section aliases
  (``sv = cfg.serving``) and any future taint rule ride this walk.
* :func:`expand_call_closure` — the interprocedural closure the
  CST-JIT traced-set machinery now delegates to (jit_boundary,
  resilience and observability all close seed sets over nested defs
  plus ``PackageIndex.resolve_call``); CST-EXC reuses it for
  thread-root reachability.

Pure stdlib-``ast`` like the rest of the engine: reads source, never
imports the package under analysis.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional

from cst_captioning_tpu.analysis.astutil import (
    FuncInfo,
    ModuleInfo,
    walk_body,
)

__all__ = [
    "Binding",
    "DefUse",
    "Origin",
    "provenance_chain",
    "expand_call_closure",
]


@dataclass(frozen=True)
class Binding:
    """One name binding inside a function body."""

    name: str
    index: int                    # lexical event index (params = -1)
    kind: str                     # param | assign | aug | walrus | for
    #                               | with | except | comp
    value: Optional[ast.AST]      # RHS expression bound to the name
    #                               (None for params / loop targets)
    stmt: Optional[ast.AST]       # the binding statement/handler node

    @property
    def line(self) -> int:
        if self.stmt is not None and hasattr(self.stmt, "lineno"):
            return self.stmt.lineno
        return 0


def _ordered_children(node: ast.AST) -> Iterator[ast.AST]:
    """Children of ``node`` in EVALUATION order (values before the
    targets they bind — ``x = f(x)`` reads the old ``x`` first)."""
    if isinstance(node, ast.Assign):
        yield node.value
        for t in node.targets:
            yield t
    elif isinstance(node, ast.AnnAssign):
        if node.value is not None:
            yield node.value
        yield node.target
    elif isinstance(node, ast.AugAssign):
        yield node.value
        yield node.target
    elif isinstance(node, ast.NamedExpr):
        yield node.value
        yield node.target
    elif isinstance(node, (ast.For, ast.AsyncFor)):
        yield node.iter
        yield node.target
        for s in node.body + node.orelse:
            yield s
    elif isinstance(node, ast.comprehension):
        yield node.iter
        yield node.target
        for c in node.ifs:
            yield c
    else:
        yield from ast.iter_child_nodes(node)


class DefUse:
    """Lexical def-use chains for one function body.

    ``events`` interleaves bindings and reads in source-evaluation
    order; ``reaching_def(name_node)`` resolves a ``Name`` read to the
    latest earlier :class:`Binding` of that name (or None — a free
    variable: parameter of an enclosing scope, module global, or
    builtin).  Nested ``def``/``lambda`` bodies are NOT walked (they
    are their own :class:`FuncInfo`/``DefUse``); reads inside them see
    this function's bindings through :func:`free_names`.
    """

    def __init__(self, fn: FuncInfo):
        self.fn = fn
        self.bindings: List[Binding] = []
        self._by_name: Dict[str, List[Binding]] = {}
        self._use_index: Dict[int, int] = {}     # id(Name node) -> index
        self.uses: List[ast.Name] = []
        for p in fn.params:
            self._record(Binding(p, -1, "param", None, fn.node))
        self._walk(fn.node)

    # ------------------------------------------------------------ build
    def _record(self, b: Binding) -> None:
        self.bindings.append(b)
        self._by_name.setdefault(b.name, []).append(b)

    def _bind_target(
        self, target: ast.AST, index: int, kind: str,
        value: Optional[ast.AST], stmt: ast.AST,
    ) -> None:
        """Bind an assignment target, pairing tuple targets with tuple
        values element-wise (``m, d = cfg.model, cfg.data``)."""
        if isinstance(target, ast.Name):
            self._record(Binding(target.id, index, kind, value, stmt))
        elif isinstance(target, (ast.Tuple, ast.List)):
            vals: List[Optional[ast.AST]] = [None] * len(target.elts)
            if isinstance(value, (ast.Tuple, ast.List)) and len(
                value.elts
            ) == len(target.elts):
                vals = list(value.elts)
            elif isinstance(value, ast.Call):
                # ``k_w, k_b = split(rng)``: every element is a
                # projection of the one call — keep the derivation.
                vals = [value] * len(target.elts)
            for t, v in zip(target.elts, vals):
                self._bind_target(t, index, kind, v, stmt)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, index, kind, None, stmt)
        # Attribute / Subscript stores bind no local name.

    def _walk(self, root: ast.AST) -> None:
        index = 0

        def visit(node: ast.AST, stmt: ast.AST) -> None:
            nonlocal index
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ) and node is not root:
                # nested scope: its def-name still binds here
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    index += 1
                    self._record(
                        Binding(node.name, index, "assign", node, node)
                    )
                return
            if isinstance(node, ast.Name):
                index += 1
                if isinstance(node.ctx, ast.Load):
                    self._use_index[id(node)] = index
                    self.uses.append(node)
                return
            if isinstance(node, ast.Assign):
                visit(node.value, stmt)
                index += 1
                for t in node.targets:
                    self._bind_target(t, index, "assign", node.value, node)
                return
            if isinstance(node, ast.AnnAssign):
                if node.value is not None:
                    visit(node.value, stmt)
                index += 1
                self._bind_target(
                    node.target, index, "assign", node.value, node
                )
                return
            if isinstance(node, ast.AugAssign):
                visit(node.value, stmt)
                if isinstance(node.target, ast.Name):
                    # aug reads the old binding then rebinds
                    index += 1
                    self._use_index[id(node.target)] = index
                    index += 1
                    self._record(Binding(
                        node.target.id, index, "aug", node.value, node
                    ))
                else:
                    visit(node.target, stmt)
                return
            if isinstance(node, ast.NamedExpr):
                visit(node.value, stmt)
                index += 1
                self._bind_target(
                    node.target, index, "walrus", node.value, node
                )
                return
            if isinstance(node, (ast.For, ast.AsyncFor)):
                visit(node.iter, stmt)
                index += 1
                self._bind_target(node.target, index, "for", node.iter, node)
                for s in node.body + node.orelse:
                    visit(s, s)
                return
            if isinstance(node, ast.comprehension):
                visit(node.iter, stmt)
                index += 1
                self._bind_target(node.target, index, "comp", node.iter, node)
                for c in node.ifs:
                    visit(c, stmt)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    visit(item.context_expr, stmt)
                    if item.optional_vars is not None:
                        index += 1
                        self._bind_target(
                            item.optional_vars, index, "with",
                            item.context_expr, node,
                        )
                for s in node.body:
                    visit(s, s)
                return
            if isinstance(node, ast.ExceptHandler):
                if node.name:
                    index += 1
                    self._record(
                        Binding(node.name, index, "except", node.type, node)
                    )
                for s in node.body:
                    visit(s, s)
                return
            for child in _ordered_children(node):
                visit(child, stmt if not isinstance(
                    child, ast.stmt
                ) else child)

        body = getattr(root, "body", [])
        if isinstance(body, list):
            for s in body:
                visit(s, s)
        else:                     # Lambda
            visit(body, root)

    # ---------------------------------------------------------- queries
    def reaching_def(self, use: ast.Name) -> Optional[Binding]:
        """Latest binding of ``use.id`` strictly before the read, or
        None for free variables."""
        at = self._use_index.get(id(use))
        if at is None:
            return None
        best = None
        for b in self._by_name.get(use.id, ()):
            if b.index < at and (best is None or b.index > best.index):
                best = b
        return best

    def bindings_of(self, name: str) -> List[Binding]:
        return list(self._by_name.get(name, ()))

    def is_local(self, name: str) -> bool:
        return name in self._by_name


# ----------------------------------------------------------- provenance

@dataclass(frozen=True)
class Origin:
    """Where a value expression bottoms out after chasing bindings.

    ``kind``:
      * ``"param"``      — a parameter of the function itself;
      * ``"enclosing"``  — bound in an enclosing function scope
        (closure read);
      * ``"attribute"``  — an attribute chain (``self._base_rng``);
      * ``"constant"``   — a literal;
      * ``"call"``       — a call expression (``node`` is the Call);
      * ``"free"``       — unresolvable free name (module global /
        builtin / truly undefined);
      * ``"opaque"``     — anything else (subscript, binop, …).
    """

    kind: str
    node: ast.AST
    name: str = ""


def _enclosing_scopes(fn: FuncInfo) -> List[FuncInfo]:
    """Enclosing FuncInfos, innermost first, by qualname prefix."""
    out: List[FuncInfo] = []
    qn = fn.qualname
    while "." in qn:
        qn = qn.rsplit(".", 1)[0]
        parent = fn.module.functions.get(qn)
        if parent is not None:
            out.append(parent)
    return out


def provenance_chain(
    fn: FuncInfo,
    du: DefUse,
    expr: ast.AST,
    *,
    through: Callable[[ast.Call], Optional[ast.AST]] = lambda c: None,
    _depth: int = 0,
) -> Origin:
    """Chase ``expr`` backwards through the def-use chains to its
    origin.  ``through(call)`` lets the caller declare derivation
    calls transparent — return the operand expression to keep chasing
    (``fold_in(rng, i)`` → ``rng``), or None to stop at the call.
    """
    if _depth > 32:
        return Origin("opaque", expr)
    if isinstance(expr, ast.Name):
        b = du.reaching_def(expr)
        if b is None:
            if not du.is_local(expr.id):
                for enc in _enclosing_scopes(fn):
                    enc_du = DefUse(enc)
                    if enc_du.is_local(expr.id):
                        return Origin("enclosing", expr, expr.id)
            return Origin("free", expr, expr.id)
        if b.kind == "param":
            return Origin("param", expr, expr.id)
        if b.value is None:
            return Origin("opaque", expr, expr.id)
        return provenance_chain(
            fn, du, b.value, through=through, _depth=_depth + 1
        )
    if isinstance(expr, ast.Call):
        onward = through(expr)
        if onward is not None:
            return provenance_chain(
                fn, du, onward, through=through, _depth=_depth + 1
            )
        return Origin("call", expr)
    if isinstance(expr, ast.Attribute):
        return Origin("attribute", expr)
    if isinstance(expr, ast.Constant):
        return Origin("constant", expr)
    return Origin("opaque", expr)


# -------------------------------------------------- call-graph closure

def expand_call_closure(
    modules: List[ModuleInfo],
    ctx,  # CheckContext (duck-typed: only ctx.index.resolve_call used)
    seeds: List[FuncInfo],
    add: Callable[[FuncInfo, str], bool],
) -> None:
    """Close a seed set over nested defs + the intra-package call
    graph.  ``add(fn, reason)`` must return True exactly when ``fn``
    was newly admitted (drives the worklist); reasons follow the
    CST-JIT wording so existing finding text is unchanged:
    ``"nested in traced <qualname>"`` /
    ``"called from traced <rel>::<qualname>"``.
    """
    work = list(seeds)
    while work:
        fn = work.pop()
        mi = fn.module
        prefix = fn.qualname + "."
        for qn, sub in mi.functions.items():
            if qn.startswith(prefix) and add(
                sub, f"nested in traced {fn.qualname}"
            ):
                work.append(sub)
        for call in (
            n for n in walk_body(fn) if isinstance(n, ast.Call)
        ):
            for callee in ctx.index.resolve_call(mi, fn, call):
                if add(
                    callee,
                    f"called from traced {mi.rel}::{fn.qualname}",
                ):
                    work.append(callee)
