"""Incremental analysis cache: content-hash-keyed report reuse.

The pass is a preflight — it runs before every bench row and inside
tier-1 — so the common case is re-running it over an UNCHANGED tree.
Parsing ~70 files and walking every checker costs a few seconds; the
cache makes the warm case cost only the hashing:

* the cache KEY digests everything that can change the report: every
  ``.py`` under the package root (the analysis package's own sources
  included — a rule edit invalidates), the suppression file, the docs
  the doc-coverage rules read, the requested rule families, and the
  report schema version.  Suppression files that carry ``expires``
  dates additionally fold in today's date, so an entry expiring
  overnight cannot hide behind a stale hit.
* a HIT reconstructs the full :class:`~.engine.Report` from the
  stored payload — byte-identical findings (pinned by
  ``Report.to_stable_dict`` in tests) with ``cache_hit_files`` set to
  the file count; only ``duration_s`` is re-measured (it reports THIS
  run).
* the store also records the per-file digest map, which powers the
  CLI ``--changed-only`` mode: report only findings in files whose
  content changed since the last stored run.

Storage is a single JSON file under ``--cache-dir`` (default
``.analysis_cache/``); stdlib-only like the rest of the engine.
"""

from __future__ import annotations

import datetime
import hashlib
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

CACHE_VERSION = 1
_STORE_NAME = "analysis_report.json"


def _digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def file_digests(root: Path) -> Dict[str, str]:
    """``{package-relative posix path: sha256}`` for every ``.py``
    under ``root`` (reads bytes, never parses — the warm-path cost)."""
    out: Dict[str, str] = {}
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        out[path.relative_to(root).as_posix()] = _digest(
            path.read_bytes()
        )
    return out


def compute_key(
    root: Path,
    *,
    rules: Sequence[str],
    suppressions_path: Path,
    docs_root: Optional[Path],
    report_version: int,
    files: Optional[Dict[str, str]] = None,
) -> Tuple[str, Dict[str, str]]:
    """The cache key + the per-file digest map it was computed from."""
    files = files if files is not None else file_digests(root)
    h = hashlib.sha256()
    h.update(f"cache-v{CACHE_VERSION}/report-v{report_version}".encode())
    for rel, dig in sorted(files.items()):
        h.update(f"\x00{rel}\x01{dig}".encode())
    h.update(b"\x02rules" + ",".join(rules).encode())
    sup = b""
    if suppressions_path.exists():
        sup = suppressions_path.read_bytes()
    h.update(b"\x02sup" + _digest(sup).encode())
    if b"expires" in sup:
        # date-dependent semantics: an entry can expire overnight
        h.update(datetime.date.today().isoformat().encode())
    if docs_root is not None and docs_root.is_dir():
        for doc in sorted(docs_root.glob("*.md")):
            h.update(
                f"\x02doc{doc.name}\x01".encode()
                + _digest(doc.read_bytes()).encode()
            )
    return h.hexdigest(), files


def store_path(cache_dir: Path) -> Path:
    return Path(cache_dir) / _STORE_NAME


def load(cache_dir: Path, key: str) -> Optional[dict]:
    """The stored report payload when the key matches, else None."""
    p = store_path(cache_dir)
    if not p.exists():
        return None
    try:
        data = json.loads(p.read_text())
    except (json.JSONDecodeError, OSError):
        return None
    if not isinstance(data, dict) or data.get("key") != key:
        return None
    rep = data.get("report")
    return rep if isinstance(rep, dict) else None


def last_files(cache_dir: Path) -> Dict[str, str]:
    """The per-file digest map of the last stored run (empty when no
    store exists) — the ``--changed-only`` baseline."""
    p = store_path(cache_dir)
    if not p.exists():
        return {}
    try:
        data = json.loads(p.read_text())
    except (json.JSONDecodeError, OSError):
        return {}
    files = data.get("files")
    return files if isinstance(files, dict) else {}


def store(
    cache_dir: Path, key: str, report: dict, files: Dict[str, str]
) -> None:
    cache_dir = Path(cache_dir)
    cache_dir.mkdir(parents=True, exist_ok=True)
    tmp = store_path(cache_dir).with_suffix(".tmp")
    tmp.write_text(json.dumps({
        "version": CACHE_VERSION,
        "key": key,
        "files": files,
        "report": report,
    }, indent=1, sort_keys=True))
    tmp.replace(store_path(cache_dir))


def changed_files(
    cache_dir: Path, files: Dict[str, str]
) -> Optional[List[str]]:
    """Files whose digest differs from (or is absent in) the last
    stored run; None when no baseline exists (everything is
    "changed")."""
    base = last_files(cache_dir)
    if not base:
        return None
    return sorted(
        rel for rel, dig in files.items() if base.get(rel) != dig
    )
