"""CST-OBS: observability-layer invariants (span tracing / flight
recorder — ``cst_captioning_tpu/observability/``).

The tracing layer is only trustworthy if three things hold everywhere,
forever — so they are rules, not prose:

* CST-OBS-001 — no wall-clock ``time.time()`` on a span path: anywhere
  inside ``observability/``, or in any function that emits spans or
  flight events.  Wall clocks step under NTP; a span that goes
  backwards poisons every duration computed from it.  Span paths use
  ``time.monotonic()`` (the tracer's shared base).
* CST-OBS-002 — every span/event name emitted as a literal anywhere in
  the package must match a family registered in
  ``observability/trace.py::SPAN_CATALOGUE`` / ``EVENT_CATALOGUE``
  (f-string placeholders normalize to ``*``), and every registered
  family must be documented in docs/OBSERVABILITY.md — the
  ``METRIC_FAMILIES`` discipline applied to spans.
* CST-OBS-003 — no tracer/flight call reachable from a jit-traced root
  (the CST-JIT traced-set machinery, including the intra-package call
  graph): a span inside traced code records trace time once and
  nothing thereafter, while looking instrumented.

Emission sites are recognized structurally: a ``.record`` /
``.start_span`` / ``.span`` call on a receiver whose final name is
``tracer``-like, or an ``.event`` call on a ``flight``/``recorder``
receiver — the naming convention the serving/training call sites follow
(and docs/OBSERVABILITY.md documents).  ``observability/trace.py`` is
stdlib-only by design, so importing the catalogue here keeps the pass
jax-free (the ``metrics_registry`` precedent).
"""

from __future__ import annotations

import ast
from fnmatch import fnmatchcase
from typing import List, Optional, Tuple

from cst_captioning_tpu.analysis.astutil import (
    ModuleInfo,
    call_name,
    dotted,
    walk_body,
)
from cst_captioning_tpu.analysis.engine import (
    CheckContext,
    Finding,
    register_checker,
)

OBS_SCOPE = "observability/"
REGISTRY_FILE = "observability/trace.py"
DOC_FILE = "OBSERVABILITY.md"

# The emission-surface convention (documented in docs/OBSERVABILITY.md):
# span emitters are methods named here, called on a receiver whose final
# identifier names a tracer / flight recorder.
_SPAN_ATTRS = {"record", "start_span", "span"}
_EVENT_ATTRS = {"event"}
_FLIGHT_HINTS = {"flight", "recorder"}


def _load_patterns() -> List[str]:
    from cst_captioning_tpu.observability.trace import (
        EVENT_CATALOGUE,
        SPAN_CATALOGUE,
    )

    return [p for p, _, _ in SPAN_CATALOGUE + EVENT_CATALOGUE]


def _emission_call(node: ast.Call) -> bool:
    """Whether this Call is a span/event emission per the receiver-name
    convention (``tracer.record(…)``, ``self.tracer.span(…)``,
    ``rep.flight.event(…)``, …)."""
    if not isinstance(node.func, ast.Attribute):
        return False
    base = dotted(node.func.value)
    if not base:
        return False
    last = base.split(".")[-1].lstrip("_").lower()
    attr = node.func.attr
    if attr in _SPAN_ATTRS and "tracer" in last:
        return True
    if attr in _EVENT_ATTRS and last in _FLIGHT_HINTS:
        return True
    return False


def _literal_name(node: ast.Call) -> Optional[Tuple[str, int]]:
    """The emitted name when the first argument is a (possibly
    formatted) string literal — FormattedValues normalize to ``*``,
    the metrics_registry convention.  Non-literal names are skipped
    (the runtime catalogue check still refuses them)."""
    if not node.args:
        return None
    a = node.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        return a.value, a.lineno
    if isinstance(a, ast.JoinedStr):
        parts = []
        for v in a.values:
            parts.append(str(v.value) if isinstance(v, ast.Constant) else "*")
        return "".join(parts), a.lineno
    return None


def emission_sites(
    modules: List[ModuleInfo],
) -> List[Tuple[ModuleInfo, ast.Call]]:
    """Every recognized span/event emission call in the package (the
    vacuous-green guard in tests asserts this finds the real serving
    and training sites)."""
    out = []
    for mi in modules:
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Call) and _emission_call(node):
                out.append((mi, node))
    return out


@register_checker("observability")
def check(modules: List[ModuleInfo], ctx: CheckContext) -> List[Finding]:
    out: List[Finding] = []
    patterns = _load_patterns()

    # ---- OBS-001: wall clock on a span path -------------------------
    # (a) anywhere inside the observability package itself;
    for mi in modules:
        if not mi.rel.startswith(OBS_SCOPE):
            continue
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Call) and call_name(node) == "time.time":
                out.append(Finding(
                    "CST-OBS-001", mi.rel, node.lineno,
                    mi.qualname_of(node),
                    "wall-clock `time.time()` inside the observability "
                    "layer — span paths must use the monotonic base "
                    "(`time.monotonic()`); wall clocks step under NTP",
                ))
    # (b) any function elsewhere that both emits spans/events and reads
    # the wall clock.
    for mi in modules:
        if mi.rel.startswith(OBS_SCOPE):
            continue
        for qn, fn in mi.functions.items():
            body = list(walk_body(fn))
            if not any(
                isinstance(n, ast.Call) and _emission_call(n) for n in body
            ):
                continue
            for n in body:
                if isinstance(n, ast.Call) and call_name(n) == "time.time":
                    out.append(Finding(
                        "CST-OBS-001", mi.rel, n.lineno, qn,
                        "`time.time()` in a function that emits spans — "
                        "span timestamps share one monotonic base; use "
                        "`time.monotonic()` here",
                    ))

    # ---- OBS-002: every emitted name registered + documented --------
    for mi, node in emission_sites(modules):
        lit = _literal_name(node)
        if lit is None:
            continue
        name, line = lit
        if not any(fnmatchcase(name, p) or name == p for p in patterns):
            out.append(Finding(
                "CST-OBS-002", mi.rel, line, name,
                f"emitted span/event name `{name}` matches no family in "
                "observability/trace.py::SPAN_CATALOGUE / "
                "EVENT_CATALOGUE — register it and document it in "
                f"docs/{DOC_FILE}",
            ))
    if ctx.docs_root is not None:
        doc_path = ctx.docs_root / DOC_FILE
        doc_text = doc_path.read_text() if doc_path.exists() else ""
        for pattern in patterns:
            if pattern not in doc_text:
                out.append(Finding(
                    "CST-OBS-002", REGISTRY_FILE, 1, pattern,
                    f"registered span/event family `{pattern}` is not "
                    f"documented in docs/{DOC_FILE} — operators discover "
                    "the timeline vocabulary there; add it to the "
                    "catalogue table",
                ))

    # ---- OBS-003: no tracer calls reachable from jit roots ----------
    from cst_captioning_tpu.analysis import jit_boundary as jb

    traced = jb._TracedSet()
    jb._collect_roots(modules, traced)
    jb._expand(modules, ctx, traced)
    by_mod = {m.rel: m for m in modules}
    for (rel, qn) in sorted(traced.static):
        mi = by_mod.get(rel)
        if mi is None:
            continue
        fn = mi.functions[qn]
        for node in walk_body(fn):
            if not isinstance(node, ast.Call):
                continue
            if _emission_call(node):
                out.append(Finding(
                    "CST-OBS-003", rel, node.lineno, qn,
                    "tracer/flight call inside traced code "
                    f"({traced.reason[(rel, qn)]}) — it would record "
                    "trace time once and nothing thereafter; record "
                    "around the host-side dispatch instead",
                ))
                continue
            for callee in ctx.index.resolve_call(mi, fn, node):
                if callee.module.rel.startswith(OBS_SCOPE):
                    out.append(Finding(
                        "CST-OBS-003", rel, node.lineno, qn,
                        f"call into {callee.module.rel} from traced "
                        f"code ({traced.reason[(rel, qn)]}) — the "
                        "observability layer is host-side only",
                    ))
    return out
