"""CST-JIT: host-state and control-flow audit of traced code.

A ``jax.jit``/``pjit``/``shard_map``-traced function runs ONCE at trace
time; host-state calls inside it (clocks, host RNG, printing, ``.item()``
syncs) silently bake a single value into the compiled graph or defeat
the dispatch pipelining the serving/training layers were built around,
and a Python ``if`` on a traced value is a TracerBoolConversionError at
best and a shape-specialized silent miscompile at worst.  This checker:

1. collects every traced ROOT — functions decorated with a jit-flavored
   transform (``@jax.jit``, ``@functools.partial(jax.jit, ...)``,
   ``@pjit``, ``shard_map``) or passed by name to one
   (``jax.jit(train_step, ...)``), plus lambdas jitted inline;
2. expands the traced set over the intra-package call graph (including
   flax ``.apply(..., method=...)`` indirection and defs nested inside
   traced bodies);
3. inside traced code flags:

   * CST-JIT-001 — host-state calls: ``time.*``, ``np.random.*`` /
     stdlib ``random.*``, ``print``, ``.item()`` / ``.tolist()``;
   * CST-JIT-002 — a Python ``if``/``while``/ternary whose test reads a
     likely-traced parameter (not declared static via
     ``static_argnums``/``static_argnames``, and not an obviously
     host-static test — ``is None``, ``isinstance``, ``.shape``/
     ``.ndim``/``.dtype`` reads, string-constant comparisons, ``self``
     config reads);
   * CST-JIT-003 — iteration over a ``set`` (the one builtin whose
     iteration order is hash-seed dependent — a nondeterministic trace).

CST-JIT-002 is a heuristic by construction (tracedness is a runtime
property); false positives go in the suppression file WITH justification
— that annotation is the documentation the invariant wants.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from cst_captioning_tpu.analysis.astutil import (
    FuncInfo,
    ModuleInfo,
    call_name,
    dotted,
    walk_body,
)
from cst_captioning_tpu.analysis.engine import (
    CheckContext,
    Finding,
    register_checker,
)

# Callees that trace their function argument.
_JIT_NAMES = {
    "jax.jit", "jit", "pjit", "jax.experimental.pjit.pjit",
}
_TRACING_WRAPPERS = _JIT_NAMES | {
    "shard_map", "jax.shard_map", "jax.experimental.shard_map.shard_map",
}
_PARTIAL_NAMES = {"functools.partial", "partial"}

_HOST_CALL_PREFIXES = ("time.", "np.random.", "numpy.random.")
_HOST_SYNC_ATTRS = {"item", "tolist"}

# Test shapes that are host-static even when they mention a parameter.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_STATIC_CALLS = {"isinstance", "len", "hasattr", "getattr", "callable"}


def _jit_call_static(node: ast.Call) -> Tuple[Set[int], Set[str]]:
    """static_argnums / static_argnames of a jit(...) call."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in node.keywords:
        v = kw.value
        vals: List = []
        if isinstance(v, (ast.Tuple, ast.List)):
            vals = [
                e.value for e in v.elts if isinstance(e, ast.Constant)
            ]
        elif isinstance(v, ast.Constant):
            vals = [v.value]
        if kw.arg == "static_argnums":
            nums.update(x for x in vals if isinstance(x, int))
        elif kw.arg == "static_argnames":
            names.update(x for x in vals if isinstance(x, str))
    return nums, names


def _jit_flavor(node: ast.AST) -> Optional[ast.Call]:
    """If ``node`` (a decorator or callee expression) is a jit-flavored
    transform application, return the Call carrying its kwargs (or a
    bare marker Call-less None handled by caller)."""
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name in _TRACING_WRAPPERS:
            return node
        if name in _PARTIAL_NAMES and node.args:
            if dotted(node.args[0]) in _TRACING_WRAPPERS:
                return node
    return None


class _TracedSet:
    """Traced functions + the static params known per function."""

    def __init__(self) -> None:
        self.static: Dict[Tuple[str, str], Set[str]] = {}
        self.reason: Dict[Tuple[str, str], str] = {}
        # jit ROOTS: the function IS the jit boundary, so every
        # non-static parameter is traced by construction (CST-JIT-002
        # applies only here — a transitive callee's params are usually
        # closure-static python config, not tracers)
        self.roots: Set[Tuple[str, str]] = set()

    def key(self, fn: FuncInfo) -> Tuple[str, str]:
        return (fn.module.rel, fn.qualname)

    def add(
        self, fn: FuncInfo, reason: str,
        static_names: Optional[Set[str]] = None,
        *, root: bool = False,
    ) -> bool:
        k = self.key(fn)
        if root:
            self.roots.add(k)
        if k in self.static:
            if static_names:
                self.static[k] |= static_names
            return False
        self.static[k] = set(static_names or ())
        self.reason[k] = reason
        return True

    def __contains__(self, fn: FuncInfo) -> bool:
        return self.key(fn) in self.static


def _collect_roots(modules: List[ModuleInfo], traced: _TracedSet) -> None:
    for mi in modules:
        for qn, fn in mi.functions.items():
            node = fn.node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if dotted(dec) in _TRACING_WRAPPERS:
                        traced.add(fn, f"@{dotted(dec)}", root=True)
                        continue
                    call = _jit_flavor(dec)
                    if call is not None:
                        nums, names = _jit_call_static(call)
                        params = fn.params
                        for i in nums:
                            if i < len(params):
                                names.add(params[i])
                        traced.add(fn, f"@{call_name(call)}", names, root=True)
        # jitted-by-call: jax.jit(fn_name, ...) / shard_map(fn, ...)
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name not in _TRACING_WRAPPERS:
                continue
            if not node.args:
                continue
            target = node.args[0]
            nums, names = _jit_call_static(node)
            if isinstance(target, ast.Name):
                scope = mi.qualname_of(node)
                cands = []
                if scope != "<module>":
                    cands.append(f"{scope}.{target.id}")
                    # enclosing chain
                    parts = scope.split(".")
                    for i in range(len(parts) - 1, 0, -1):
                        cands.append(
                            ".".join(parts[:i]) + f".{target.id}"
                        )
                cands.append(target.id)
                for qn in cands:
                    fn = mi.functions.get(qn)
                    if fn is not None:
                        params = fn.params
                        for i in nums:
                            if i < len(params):
                                names.add(params[i])
                        traced.add(fn, f"{name}(…) call", names, root=True)
                        break
            elif isinstance(target, ast.Lambda):
                for qn, fn in mi.functions.items():
                    if fn.node is target:
                        traced.add(fn, f"{name}(lambda)", root=True)
                        break


def _expand(
    modules: List[ModuleInfo], ctx: CheckContext, traced: _TracedSet
) -> None:
    """Close the traced set over nested defs + the package call graph
    (the shared :func:`dataflow.expand_call_closure` worklist — the
    resilience/observability passes ride the same machinery)."""
    from cst_captioning_tpu.analysis.dataflow import expand_call_closure

    by_mod = {m.rel: m for m in modules}
    seeds = [
        by_mod[rel].functions[qn]
        for (rel, qn) in list(traced.static)
        if rel in by_mod
    ]

    def admit(fn: FuncInfo, reason: str) -> bool:
        if fn in traced:
            return False
        traced.add(fn, reason)
        return True

    expand_call_closure(modules, ctx, seeds, admit)


def _test_is_static(test: ast.AST) -> bool:
    """Host-static test shapes: shape/dtype reads, None checks,
    isinstance/len, string-constant comparisons, self/config reads
    only."""
    for node in ast.walk(test):
        if isinstance(node, ast.Compare):
            if any(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in node.ops
            ):
                return True
            sides = [node.left, *node.comparators]
            if any(
                isinstance(s, ast.Constant) and isinstance(s.value, str)
                for s in sides
            ):
                return True
            if any(
                isinstance(s, (ast.Tuple, ast.List))
                and all(isinstance(e, ast.Constant) for e in s.elts)
                for s in sides
            ):
                return True
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return True
        if isinstance(node, ast.Call):
            if call_name(node) in _STATIC_CALLS:
                return True
    return False


def _traced_param_in_test(
    test: ast.AST, fn: FuncInfo, static_names: Set[str]
) -> Optional[str]:
    params = {
        p for p in fn.params
        if p not in ("self", "cls") and p not in static_names
    }
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in params:
            return node.id
    return None


@register_checker("jit_boundary")
def check(modules: List[ModuleInfo], ctx: CheckContext) -> List[Finding]:
    traced = _TracedSet()
    _collect_roots(modules, traced)
    _expand(modules, ctx, traced)

    out: List[Finding] = []
    by_mod = {m.rel: m for m in modules}
    for (rel, qn), static_names in sorted(traced.static.items()):
        mi = by_mod.get(rel)
        if mi is None:
            continue
        fn = mi.functions[qn]
        for node in walk_body(fn):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name == "print" or name.startswith(_HOST_CALL_PREFIXES):
                    out.append(Finding(
                        "CST-JIT-001", rel, node.lineno, qn,
                        f"host-state call `{name}(…)` inside traced "
                        f"code ({traced.reason[(rel, qn)]}) — the value "
                        "is baked in at trace time; hoist it out of "
                        "the jit boundary or thread it as an argument",
                    ))
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _HOST_SYNC_ATTRS
                    and not node.args
                ):
                    out.append(Finding(
                        "CST-JIT-001", rel, node.lineno, qn,
                        f"`.{node.func.attr}()` inside traced code — "
                        "a device sync cannot execute under trace; "
                        "return the array and read it on the host",
                    ))
            if isinstance(node, (ast.If, ast.While, ast.IfExp)) and (
                (rel, qn) in traced.roots
            ):
                test = node.test
                if _test_is_static(test):
                    continue
                p = _traced_param_in_test(test, fn, static_names)
                if p is not None:
                    out.append(Finding(
                        "CST-JIT-002", rel, test.lineno, qn,
                        f"Python `{type(node).__name__.lower()}` on "
                        f"parameter `{p}` inside traced code — a "
                        "traced value cannot branch host control flow; "
                        "use lax.cond/jnp.where, or declare the "
                        "argument static",
                    ))
            if isinstance(node, (ast.For, ast.comprehension)):
                it = node.iter
                if isinstance(it, ast.Set) or (
                    isinstance(it, ast.Call) and call_name(it) == "set"
                ):
                    out.append(Finding(
                        "CST-JIT-003", rel, it.lineno, qn,
                        "iteration over a set inside traced code — "
                        "set order is hash-seed dependent, so the "
                        "traced graph is nondeterministic; sort it",
                    ))
    return out
