"""The jit call-site registry: every jit application in the package,
keyed stably, with its expected retrace budget.

``budget`` is reviewer-facing prose answering ONE question: what bounds
recompiles at this site?  (A fixed shape ladder, a pre-warmed bank
ladder, a handful of static values, a once-per-process probe…)  The
CST-DON-002 rule fails the analysis pass on any unregistered site, and
CST-DON-003 on stale entries, so this file tracks the code by
construction.  ``update_step=True`` marks TrainState update steps that
MUST donate their state (CST-DON-001, paired with the
``tf.aliasing_output`` pin in tests/test_training.py);
``donates=True`` acknowledges donation at non-update sites.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple


class JitSite(NamedTuple):
    budget: str
    update_step: bool = False
    donates: bool = False


JIT_SITE_REGISTRY: Dict[str, JitSite] = {
    # ---------------------------------------------------------- decoding
    "decoding/beam.py::make_beam_search_fn::fn": JitSite(
        "one compile per (B, K, L) decode shape; offline eval uses one "
        "shape, serving dispatches through the engine's fixed batch "
        "ladder (warmup pre-compiles every rung)"
    ),
    # ------------------------------------------------------ fused kernels
    "ops/pallas_beam.py::attlstm_beam": JitSite(
        "static (beam_size, max_len, suppress_unk) + input shapes: one "
        "compile per eval/bench configuration, reused for the whole run"
    ),
    "ops/pallas_beam.py::lstm_beam": JitSite(
        "same static-knob discipline as attlstm_beam (meanpool fusion "
        "variant)"
    ),
    "ops/pallas_sampler.py::attlstm_sample": JitSite(
        "static (max_len, greedy, suppress_unk) + shapes; temperature "
        "is an SMEM scalar by design (ADVICE r5 #1) so distinct "
        "temperatures share ONE compiled kernel"
    ),
    "ops/pallas_sampler.py::lstm_sample": JitSite(
        "same discipline as attlstm_sample (meanpool fusion variant)"
    ),
    # ----------------------------------------------------------- serving
    "serving/engine.py::InferenceEngine._encode_fn.encode": JitSite(
        "one compile per ladder bucket B, all built at warmup(); the "
        "coalescer never builds a batch outside the ladder"
    ),
    "serving/engine.py::InferenceEngine._state_fn.from_state": JitSite(
        "one compile per ladder bucket B (tier-2 fast path), built at "
        "warmup()"
    ),
    "serving/slots.py::SlotDecoder._tick_fn.tick": JitSite(
        "one compile per (bank size S, admit bucket A) pair; warmup() "
        "builds every variant and SlotDecoder.compile_count pins that "
        "post-warmup traffic builds ZERO new ones (tier-1)"
    ),
    "serving/slots.py::SlotDecoder._tick_fn.tick_spec": JitSite(
        "speculative twin of tick: the SAME (bank S, admit bucket A) "
        "grid — draft_k is fixed per decoder config so it never splits "
        "the key; warmup() builds every variant and compile_count pins "
        "zero post-warmup builds (the AOT key carries the :k suffix so "
        "a foreign-k artifact is refused at install)"
    ),
    "serving/slots.py::SlotDecoder._free_fn.free_rows": JitSite(
        "one compile per bank size, warmup-built, compile_count-pinned"
    ),
    "serving/slots.py::SlotDecoder._resize_fn.resize": JitSite(
        "one compile per bank-ladder transition (grow+shrink), "
        "warmup-built, compile_count-pinned"
    ),
    # --------------------------------------------------- speculative decode
    "decoding/speculative.py::_greedy_spec_runner.round_fn": JitSite(
        "offline spec parity backend: one compile at the harness's "
        "fixed (B, k, L) shape per test run (the shared-harness "
        "token-exact pin reuses it for every video)"
    ),
    # ---------------------------------------------------------- training
    "training/steps.py::make_xe_train_step::train_step": JitSite(
        "one compile per distinct static ss_prob value (the scheduled-"
        "sampling schedule steps a handful of times per run) at the "
        "fixed train batch shape",
        update_step=True,
    ),
    "training/steps.py::make_greedy_sample_fn::sample": JitSite(
        "one compile at the fixed validation batch shape"
    ),
    "training/cst.py::dispatch_latency_ms::<lambda>": JitSite(
        "one trivial probe compile per process (dispatch-latency "
        "measurement)"
    ),
    "training/cst.py::io_callback_supported::<lambda>": JitSite(
        "one capability-probe compile per process"
    ),
    "training/cst.py::_make_one_graph_step::train_step": JitSite(
        "one compile at the fixed CST batch shape",
        update_step=True,
    ),
    "training/cst.py::_make_pipelined_step::_rollout": JitSite(
        "one compile at the fixed rollout batch shape (pipelined "
        "layout's first dispatch)"
    ),
    "training/cst.py::_make_pipelined_step.update_and_rollout": JitSite(
        "one compile at the fixed CST batch shape (steady-state "
        "pipelined step)",
        update_step=True,
    ),
    "training/cst.py::_make_pipelined_step.update_only": JitSite(
        "one compile at the fixed CST batch shape (pipeline flush)",
        update_step=True,
    ),
    "training/cst.py::_make_split_step.rollout_chunk": JitSite(
        "one compile per rollout chunk shape (fixed chunking of the "
        "fixed batch)"
    ),
    "training/cst.py::_make_split_step.rollout_fused": JitSite(
        "one compile at the fixed batch shape (fused-sampler variant)"
    ),
    "training/cst.py::_make_split_step.greedy_chunk": JitSite(
        "one compile at the fixed greedy-baseline batch shape"
    ),
    "training/cst.py::_make_split_step.update_fn": JitSite(
        "one compile per power-of-two trimmed PG length bucket at the "
        "fixed batch shape",
        update_step=True,
    ),
    "training/cst.py::SlotRollout.__init__::prepare": JitSite(
        "static (repeat, need_greedy): one compile per rollout "
        "configuration at the fixed batch shape"
    ),
    "training/cst.py::SlotRollout._tick_fn.tick": JitSite(
        "one compile per slot-rollout geometry (n_slots, block) — a "
        "single full-width admission bucket, fixed per run"
    ),
    "training/cst.py::_make_slot_step.update_fn": JitSite(
        "one compile per power-of-two trimmed PG length bucket "
        "(identical trim to the padded layout)",
        update_step=True,
    ),
    # --------------------------------------------------------------- cli
    "cli/distill_draft.py::_make_update.update": JitSite(
        "offline draft distillation: one compile at the fixed "
        "(batch, max_len) distillation shape per CLI invocation",
        update_step=True,
    ),
    # ------------------------------------------------------------- tools
    "tools/overlap_sim.py::simulate::<lambda>": JitSite(
        "bench-only overlap simulator: one compile per simulated shape "
        "per bench invocation"
    ),
}


# Every AOT compile/install site in the package (PR 13): the
# ``.lower(...).compile(...)`` chain compiles OUTSIDE the jit dispatch
# path and ``deserialize_and_load`` installs an executable compiled in
# ANOTHER process — both bypass the runtime retrace guards above, so
# CST-DON-004 requires each such site (keyed ``<file>::<qualname>``) to
# state what enumerates its variants and what refuses a stale or
# foreign executable; CST-DON-005 flags stale entries.
AOT_SITE_REGISTRY: Dict[str, str] = {
    "serving/artifact.py::build_artifact": (
        "artifact builder: compiles exactly the variants "
        "SlotDecoder.aot_lower / InferenceEngine.aot_lower_encode "
        "enumerate (the same ladder code warmup walks), through the "
        "persistent compilation cache pointed into the artifact; the "
        "manifest records a sha256 HLO key per variant"
    ),
    "serving/artifact.py::load_engine": (
        "artifact loader: deserializes only after the manifest's "
        "schema/jax/jaxlib/device/version fields AND the re-derived "
        "variant key set match the live environment exactly "
        "(ArtifactMismatchError otherwise — refusal, never a silent "
        "retrace); installed via SlotDecoder.aot_install with "
        "compile_count == 0 pinned in tier-1"
    ),
    "serving/slots.py::_slot_runner": (
        "shared parity harness's artifact-boot backend: compiles a "
        "builder decoder's aot_lower variants and installs them into a "
        "fresh decoder, pinning compile_count == 0 plus token-exactness "
        "vs the scan reference (tests/test_decode_core.py)"
    ),
}


# Every ``shard_map`` call site in the package (raw jax API, the
# ``parallel/mesh.py`` version-compat wrapper, or its resolved
# ``_shard_map_impl``), keyed ``<file>::<enclosing qualname>`` —
# CST-SHD-004 fails the pass on any unregistered site and on stale
# entries.  The value is reviewer-facing prose: the COLLECTIVE LAYOUT
# the manual specs buy (which per-step gather they avoid) and what
# bounds the site's recompiles.  A shard_map with no story is usually
# a partitioner workaround nobody can maintain.
SHARD_MAP_REGISTRY: Dict[str, str] = {
    "parallel/mesh.py::shard_map": (
        "the version-portability wrapper every package shard_map routes "
        "through (jax.experimental vs top-level spelling, check_rep vs "
        "check_vma) — the one raw-impl call site, no collective layout "
        "of its own"
    ),
    "parallel/ring.py::ring_attention": (
        "ring attention: frame-axis K/V shards rotate via "
        "collective_permute so each device scores S/M frames per hop "
        "instead of all-gathering the full frame axis; one compile per "
        "(mesh, block shape)"
    ),
    "parallel/ring.py::sharded_context_attention": (
        "single-query Bahdanau fusion with frames sharded over `model`: "
        "local score + one psum of the (B, E) context instead of every "
        "device holding all frames; one compile per (mesh, shape)"
    ),
    "training/cst.py::_make_one_graph_step.score": (
        "per-shard CST reward io_callback: each shard scores its own "
        "rollout rows host-side — the replicated-global fallback would "
        "funnel every row through device 0; one compile per CST batch "
        "shape"
    ),
    "decoding/core.py::make_tp_beam_topk.topk": (
        "the ISSUE-14 cross-shard beam top-K: per-shard vocab-tile "
        "candidates + one O(shards*K) all-gather replace the O(V) "
        "full-vocab gather the SPMD partitioner inserts for the inline "
        "lax.top_k over model-sharded logits; compiled inside the "
        "warmup-bounded slot tick variants"
    ),
    "decoding/core.py::make_tp_row_pick.pick": (
        "the greedy twin of make_tp_beam_topk: per-shard argmax "
        "(value, global id) pairs merged by one tiny all-gather instead "
        "of gathering the (rows, V) logits; compiled inside the "
        "warmup-bounded slot tick variants"
    ),
    "ops/shard_decode.py::_sharded_beam_impl": (
        "the shard_map port of the fused beam kernel: vocab-over-model "
        "in_specs keep each shard on its (H, V/M) w_out tile, the "
        "per-step candidate all-gather is O(shards*K) bytes vs the "
        "forbidden O(V) gather, and the embedding feed is a masked "
        "lookup + (rows, E) psum; one compile per (mesh, beam, L) "
        "decode configuration like the kernel it ports"
    ),
    "ops/shard_decode.py::_sharded_sample_impl": (
        "the shard_map port of the fused sampler: same tile layout as "
        "the beam port with per-shard Gumbel-max winners (global-id "
        "counters keep the hash stream shard-invariant) merged by one "
        "tiny all-gather; one compile per (mesh, T, greedy) "
        "configuration"
    ),
}


# Every ``with_sharding_constraint`` site in the package (and every call
# through ``parallel/partition.py::constrain``), keyed
# ``<file>::<enclosing qualname>`` — CST-SHD-002 fails the pass on any
# unregistered site and on stale entries.  The value is reviewer-facing
# prose: WHAT the pin buys (which all-gather it prevents, which SPMD
# partitioner cliff it avoids).  A constraint with no story is usually a
# constraint papering over a placement bug.
# Every dtype-cast site reachable from a registered jit root (ISSUE
# 15), keyed ``<file>::<qualname>`` with ``<lambda#N>`` segments folded
# into the enclosing def — CST-DTY-001 fails the pass on any
# unregistered traced cast site and on stale entries.  ``tier`` names
# the docs/PARITY.md tier the casts at this site preserve;
# ``justification`` is reviewer-facing prose saying WHY (what the casts
# are for, why the tier survives them).  ``low_precision=True`` marks
# the paths that compute in a configurable dtype (``compute_dtype`` /
# ``cdt``) — the surface the bf16/int8 serving PR will ride — and
# subjects every matmul inside them to the CST-DTY-003
# preferred_element_type accumulation pin.
class CastSite(NamedTuple):
    tier: str                      # a PARITY_TIERS member (docs/PARITY.md)
    justification: str
    low_precision: bool = False


# The LEGAL parity-tier vocabulary (ISSUE 16): every CAST_REGISTRY entry
# must name one of these — CST-DTY-001 flags an entry carrying a tier
# outside the set, so a typo'd or invented tier can never silently claim
# a parity guarantee docs/PARITY.md doesn't define.  The tiers, strongest
# first (docs/PARITY.md r17):
#   bit-exact       same bits as the reference path
#   token-exact     same decoded tokens (float association may differ)
#   relaxed-rtol    training-loss tier: scalar agreement within rtol
#   relaxed-serving low-precision serving (serving.dtype=bf16/int8w):
#                   decoded tokens MAY move; the machine-checked bound is
#                   caption-match rate vs f32 >= RELAXED_SERVING_MATCH_FLOOR
#                   and per-caption score gap <= RELAXED_SERVING_SCORE_RTOL
#                   on a fixed eval set (tests/test_quant.py + the
#                   lowprec_* bench rows assert BEFORE recording).
PARITY_TIERS = frozenset({
    "bit-exact",
    "token-exact",
    "relaxed-rtol",
    "relaxed-serving",
})

# Pinned relaxed-serving bounds — THE constants the tests and the bench
# enforce (single definition site; docs/PARITY.md r17 quotes them).
# Floor 0.75: on the pinned synthetic eval set the bf16 tick path moves
# at most 2/8 captions of a random-init model (measured; a trained
# checkpoint is far tighter) — deterministic per platform, so the floor
# is a regression tripwire, not a statistical hope.  Rtol 0.02: measured
# per-caption beam-score gaps sit near 4e-4; 0.02 leaves real headroom
# while still failing on any structural scoring change.
RELAXED_SERVING_MATCH_FLOOR = 0.75
RELAXED_SERVING_SCORE_RTOL = 0.02


CAST_REGISTRY: Dict[str, CastSite] = {
    # ---------------------------------------------------------- decoding
    "decoding/beam.py::finalize_beams": CastSite(
        "bit-exact",
        "length-normalize divides f32 scores by an i32 length cast to "
        "f32 — an explicit widening of exact small ints, shared by "
        "every beam consumer (the one finalize epilogue)",
    ),
    "decoding/core.py::init_core": CastSite(
        "bit-exact",
        "seeds the carry: i32 token/finished rows and the f32 score "
        "matrix are CREATED at their contract dtypes (no value ever "
        "changes width)",
    ),
    "decoding/core.py::decode_step": CastSite(
        "token-exact",
        "the per-step recurrence: i32 parent/token extraction from "
        "flat top-K keys and bool→f32 finished-mask widening — index "
        "and mask arithmetic on exactly-representable values, "
        "identical in every registered backend (the shared-harness "
        "token-exact pin)",
    ),
    "decoding/core.py::make_tp_row_pick.pick.body": CastSite(
        "token-exact",
        "the TP greedy merge casts the per-shard argmax winner's "
        "global vocab id to i32 — integer id plumbing, value-exact",
    ),
    "decoding/core.py::row_sample_fn.fn": CastSite(
        "token-exact",
        "row-keyed sampling casts the categorical draw to the carry's "
        "i32 token dtype — id plumbing on the PARITY-r10 row-keyed "
        "stream",
    ),
    "decoding/speculative.py::draft_step": CastSite(
        "token-exact",
        "draft proposal: all-f32 compute around ops/rnn.py::lstm_step "
        "(whose casts are registered at the cell), with the argmax "
        "winner cast to the carry's i32 token dtype — id plumbing; the "
        "draft NEVER emits tokens, verify-side acceptance is what the "
        "token-exact tier pins",
    ),
    "decoding/speculative.py::spec_round": CastSite(
        "token-exact",
        "the accept/emit core: bool proposal-vs-verified equality mask "
        "-> i32 for the cumprod prefix-match count, i32 next-token "
        "plumbing, and {0,1}/count widening to f32 for the acceptance "
        "stats — integer/mask arithmetic on exactly-representable "
        "values; the tier is MACHINE-pinned by the shared harness "
        "(greedy_spec_offline + slot_decoder_greedy_spec vs "
        "scan_greedy) and the bench's spec_token_mismatches==0 assert",
    ),
    # ------------------------------------------------------------ model
    "models/captioner.py::CaptionModel._encode": CastSite(
        "token-exact",
        "THE compute-dtype boundary: features/projections enter at "
        "`model.compute_dtype` (cdt), masked mean-pool accumulates "
        "f32; under the default f32 config every cast is identity — "
        "the bf16 serving PR changes cdt HERE and nowhere else",
        low_precision=True,
    ),
    "models/captioner.py::CaptionModel._context": CastSite(
        "token-exact",
        "attention query/scores in cdt with the score softmax pinned "
        "f32 (kernel twins mirror this exactly); identity under f32",
        low_precision=True,
    ),
    "models/captioner.py::CaptionModel._step": CastSite(
        "token-exact",
        "embedding/carry rows enter the LSTM stack at cdt; identity "
        "under f32, the kernels' cdt contract otherwise",
        low_precision=True,
    ),
    "models/captioner.py::CaptionModel._logits": CastSite(
        "token-exact",
        "the vocab matmul runs in cdt and the logits EXIT f32 — the "
        "one place decode scores are widened; every consumer "
        "(beam top-K, sampler, losses) sees f32 logits by contract",
        low_precision=True,
    ),
    "models/captioner.py::CaptionModel._sample_from_cache": CastSite(
        "token-exact",
        "bool finished-mask → f32 for the carry update — mask algebra "
        "on {0,1}, exact in any float width",
    ),
    "models/captioner.py::CaptionModel._fused_gx_static": CastSite(
        "token-exact",
        "pre-computed gate inputs for the fused kernels at cdt with "
        "f32 accumulation pinned at the matmul (preferred_element_type)",
        low_precision=True,
    ),
    "models/captioner.py::CaptionModel.fused_beam": CastSite(
        "token-exact",
        "kernel operand staging: weights/activations to cdt, masks to "
        "f32, tokens i32 — the fused-kernel calling convention whose "
        "token-exactness vs the scan path tier-1 pins",
        low_precision=True,
    ),
    "models/captioner.py::CaptionModel._fused_sample": CastSite(
        "token-exact",
        "sampler-kernel staging twin of fused_beam (same convention, "
        "same pins) plus u32 seed-word extraction from the PRNG key",
        low_precision=True,
    ),
    # ----------------------------------------------------------- losses
    "ops/losses.py::_token_logprobs": CastSite(
        "relaxed-rtol",
        "one-hot gather of f32 log-probs casts the i32 token ids into "
        "the take_along_axis index dtype — index plumbing",
    ),
    "ops/losses.py::weighted_cross_entropy": CastSite(
        "relaxed-rtol",
        "XE loss: i32 targets → one-hot f32, bool mask → f32 weights; "
        "loss accumulation stays f32 (the training tier is rtol, not "
        "bitwise — docs/PARITY.md r12)",
    ),
    "ops/losses.py::reward_criterion": CastSite(
        "relaxed-rtol",
        "PG loss twin of weighted_cross_entropy: mask/advantage "
        "widening to f32 around f32 log-probs",
    ),
    # ------------------------------------------------- fused kernels/XLA
    "ops/pallas_attention.py::dense_context_attention": CastSite(
        "bit-exact",
        "the dense reference the attention kernel diffs against: "
        "scores f32, context mix f32-accumulated then rounded back to "
        "the value dtype — the kernel's own cast structure, kept "
        "textually parallel so the parity argument stays readable",
        low_precision=True,
    ),
    "ops/pallas_attention.py::_fused_fwd_call": CastSite(
        "bit-exact",
        "kernel operands: mask → f32 at the pallas_call boundary "
        "(Mosaic wants float mask lanes); values pass through at their "
        "own dtype",
    ),
    "ops/pallas_beam.py::_select_beams": CastSite(
        "token-exact",
        "flat top-K key → (parent, token) i32 extraction — exact "
        "integer arithmetic on flat indices",
    ),
    "ops/pallas_beam.py::_onehot_parent": CastSite(
        "token-exact",
        "parent-id equality mask → f32 one-hot for the beam-reorder "
        "matmul — {0,1} exact in f32",
    ),
    "ops/pallas_beam.py::_make_beam_kernel.kernel": CastSite(
        "token-exact",
        "the in-kernel cdt/f32 discipline docs/PARITY.md r6 "
        "specifies: gates and logits accumulate f32 "
        "(preferred_element_type), activations round to cdt, "
        "seq/token scratch lives f32-encoded and exits i32 — every "
        "cast is part of the pinned bit-exact-vs-twin contract",
        low_precision=True,
    ),
    "ops/pallas_beam.py::_make_beam_kernel.kernel.vloop": CastSite(
        "token-exact",
        "per-V-tile logits: cdt matmul with f32 accumulation then f32 "
        "candidate scores — the streamed top-K operates on f32 only; "
        "int8w mode dequantizes the streamed code tile in-kernel "
        "(codes cast losslessly to cdt, per-logit scale applied to the "
        "f32 accumulator, f32 bias, no cdt rounding — quant_matmul "
        "semantics, relaxed-serving bounded vs unfused int8w)",
        low_precision=True,
    ),
    "ops/pallas_beam.py::_beam_impl": CastSite(
        "token-exact",
        "kernel staging: att mask → f32 replication before the grid "
        "launch (same convention as _fused_fwd_call)",
    ),
    "ops/pallas_sampler.py::_gumbel_from_counter": CastSite(
        "token-exact",
        "hash-Gumbel stream: u32 counter/seed arithmetic then u32 → "
        "f32 mantissa bits — the bit-exact pinned sampler stream "
        "(PARITY r7); every cast is integer/bit manipulation",
    ),
    "ops/pallas_sampler.py::_decode_bias": CastSite(
        "token-exact",
        "decode-policy bias staging (shared by the float and int8 "
        "vocab paddings): b_out widened to f32 before the NEG_INF "
        "masking — exact widening, no rounding",
    ),
    "ops/pallas_sampler.py::_masked_vocab_q": CastSite(
        "relaxed-serving",
        "int8 vocab-tile staging: per-logit scales widened to f32 with "
        "unit scales + zero codes in the padded tail (0 * scale + "
        "NEG_INF bias keeps padding inert in max/LSE exactly like the "
        "float padding); the in-kernel dequant these scales feed is "
        "quant_matmul semantics, bounded by "
        "RELAXED_SERVING_MATCH_FLOOR / _SCORE_RTOL",
        low_precision=True,
    ),
    "ops/pallas_sampler.py::_make_sample_kernel.kernel": CastSite(
        "token-exact",
        "sampler twin of the beam kernel's cdt/f32 discipline: gates "
        "f32-accumulated, tokens i32, Gumbel race in f32",
        low_precision=True,
    ),
    "ops/pallas_sampler.py::_make_sample_kernel.kernel.vloop": CastSite(
        "token-exact",
        "per-V-tile logits + Gumbel keys in f32 over cdt matmul tiles; "
        "int8w mode dequantizes the streamed code tile in-kernel "
        "(scale after the f32 accumulation, f32 bias, no cdt rounding "
        "— quant_matmul semantics, relaxed-serving bounded vs unfused "
        "int8w)",
        low_precision=True,
    ),
    "ops/pallas_sampler.py::_sample_impl": CastSite(
        "token-exact",
        "kernel staging: mask → f32, PRNG key words → u32 seed scalars "
        "(both words — the 64-bit seed space fix, ADVICE r5 #2)",
    ),
    # ------------------------------------------------------------- quant
    "ops/quant.py::quant_matmul": CastSite(
        "relaxed-serving",
        "int8 weight-only GEMM (serving.dtype=int8w): codes cast to the "
        "activation dtype (lossless — int8 magnitudes are exact in "
        "bf16), accumulation pinned f32, per-channel scale applied "
        "AFTER accumulation in f32 — logits exit f32 like the float "
        "path, but the one quantization round can move tokens; bounded "
        "by RELAXED_SERVING_MATCH_FLOOR / _SCORE_RTOL",
        low_precision=True,
    ),
    "ops/quant.py::dequant_rows": CastSite(
        "relaxed-serving",
        "quantized embedding gather: int8 rows reconstructed in f32 "
        "(code x per-row scale) then rounded ONCE to cdt — the same "
        "single f32->cdt rounding as the float path's astype(cdt) "
        "gather, on top of the quantization round the tier bounds",
        low_precision=True,
    ),
    # -------------------------------------------------------------- rnn
    "ops/rnn.py::lstm_step": CastSite(
        "token-exact",
        "THE cell-dtype contract (docstring): activations/weights at "
        "compute_dtype, gates + cell state ALWAYS f32 — c is the "
        "additive recurrence that cannot survive bf16 accumulation; "
        "identity under the default f32 config",
        low_precision=True,
    ),
    # ----------------------------------------------------- shard_decode
    "ops/shard_decode.py::_emb_psum": CastSite(
        "relaxed-serving",
        "sharded int8w embedding gather: the shard's gathered int8 "
        "rows reconstruct in f32 (code x per-row scale slice) then "
        "round ONCE to cdt BEFORE the mask + psum — dequant_rows "
        "semantics per shard, and the psum only adds exact zeros from "
        "non-owner shards; float mode has no cast here",
        low_precision=True,
    ),
    "ops/shard_decode.py::_attention_ctx": CastSite(
        "token-exact",
        "shard_map port of the attention helper: same cdt/f32 "
        "structure as the kernel it ports (scores f32, context mix "
        "f32-accumulated)",
        low_precision=True,
    ),
    "ops/shard_decode.py::_gates": CastSite(
        "token-exact",
        "gate GEMMs at cdt with f32 accumulation pinned — mirrors the "
        "fused kernel's association exactly (the bitwise-twin "
        "contract, PARITY r15)",
        low_precision=True,
    ),
    "ops/shard_decode.py::_local_logits": CastSite(
        "token-exact",
        "per-shard vocab-tile logits: cdt matmul, f32 accumulation, "
        "f32 exit — the candidate merge consumes f32 only",
        low_precision=True,
    ),
    "ops/shard_decode.py::_sharded_beam_impl.body.step": CastSite(
        "token-exact",
        "bool finished → f32 freeze mask inside the sharded "
        "recurrence — mask algebra, exact",
    ),
    "ops/shard_decode.py::_sharded_sample_impl.body.step": CastSite(
        "token-exact",
        "u32 hash-counter arithmetic keyed on GLOBAL vocab position "
        "(the shard-invariant sampler stream) plus i32 id plumbing",
    ),
    # ---------------------------------------------------------- serving
    "serving/slots.py::SlotDecoder._tick_fn.admit_one": CastSite(
        "token-exact",
        "admission scatter casts the incoming cache rows to the "
        "resident slot leaves' dtypes — same-dtype by construction "
        "(one engine produced both); the cast is a pytree-uniformity "
        "guard, not a precision change",
    ),
    "serving/slots.py::SlotDecoder._tick_fn.admit_all": CastSite(
        "token-exact",
        "bool admit/free masks → f32 for the select over slot rows — "
        "{0,1} exact; the staggered-admission row-exact pin covers it "
        "(shared by the plain and speculative tick variants)",
    ),
    # --------------------------------------------------------- training
    "training/cst.py::SlotRollout._tick_fn.tick": CastSite(
        "relaxed-rtol",
        "rollout-slot admission mirrors the serving tick's mask "
        "widening (the shared machinery, PARITY r10 slot-rollout "
        "invariance)",
    ),
    "training/cst.py::_make_slot_step.update_fn": CastSite(
        "relaxed-rtol",
        "PG update widens the bool PAD mask to f32 loss weights over "
        "the pow2-trimmed token matrix — zero-loss columns stay "
        "exactly zero",
    ),
    "training/steps.py::make_xe_train_step.train_step": CastSite(
        "relaxed-rtol",
        "scheduled-sampling mix casts the bernoulli draw mask to the "
        "token dtype — {0,1} integer select between teacher and "
        "model tokens",
    ),
}


# Every jit site's shape contract (ISSUE 15), keyed EXACTLY like
# JIT_SITE_REGISTRY — CST-SHP-001 fails the pass on a jit site with no
# ladder entry (at the site's file:line), on stale entries, and on
# declared bucket functions that no longer resolve to a live def.
#
#   kind = "fixed":      the site only ever sees one shape tuple per
#                        process/config — no quantizer needed.
#   kind = "enumerated": runtime counts are quantized onto a finite
#                        pre-compiled ladder; ``bucket_fns`` MUST name
#                        the ``<file>::<qualname>`` quantizers (the
#                        pow2/admit-bucket/bank-ladder code) so the
#                        dataflow half can recognize laddered dims and
#                        rot is detectable.
#   kind = "probe":      a once-per-process capability/latency probe.
class ShapeLadder(NamedTuple):
    kind: str                      # fixed | enumerated | probe
    ladder: str                    # reviewer-facing prose: the family
    bucket_fns: Tuple[str, ...] = ()


SHAPE_LADDER_REGISTRY: Dict[str, ShapeLadder] = {
    # ---------------------------------------------------------- decoding
    "decoding/beam.py::make_beam_search_fn::fn": ShapeLadder(
        "enumerated",
        "offline eval runs ONE (B, K, L) shape; serving reaches this "
        "only through the engine's pow2 batch ladder (every rung "
        "warmup-compiled)",
        ("serving/engine.py::InferenceEngine.bucket",
         "serving/engine.py::_default_ladder"),
    ),
    # ------------------------------------------------------ fused kernels
    "ops/pallas_beam.py::attlstm_beam": ShapeLadder(
        "fixed",
        "one (B, K, L, V) configuration per eval/bench run; serving "
        "dispatch arrives pre-bucketed by the engine ladder",
    ),
    "ops/pallas_beam.py::lstm_beam": ShapeLadder(
        "fixed", "meanpool twin of attlstm_beam — same one-shape-per-run "
        "discipline",
    ),
    "ops/pallas_sampler.py::attlstm_sample": ShapeLadder(
        "fixed",
        "one (B, T, V) rollout shape per run; temperature is an SMEM "
        "scalar so it never splits the shape key",
    ),
    "ops/pallas_sampler.py::lstm_sample": ShapeLadder(
        "fixed", "meanpool twin of attlstm_sample",
    ),
    # ----------------------------------------------------------- serving
    "serving/engine.py::InferenceEngine._encode_fn.encode": ShapeLadder(
        "enumerated",
        "the pow2 batch ladder: every served batch pads up to "
        "bucket(n); warmup compiles every rung, the coalescer never "
        "builds an off-ladder batch",
        ("serving/engine.py::InferenceEngine.bucket",
         "serving/engine.py::_default_ladder"),
    ),
    "serving/engine.py::InferenceEngine._state_fn.from_state": ShapeLadder(
        "enumerated",
        "tier-2 fast path rides the SAME batch ladder as encode",
        ("serving/engine.py::InferenceEngine.bucket",
         "serving/engine.py::_default_ladder"),
    ),
    "serving/slots.py::SlotDecoder._tick_fn.tick": ShapeLadder(
        "enumerated",
        "(bank S, admit bucket A) grid: S walks the doubling bank "
        "ladder, A the padded admit buckets; warmup compiles every "
        "variant and compile_count pins zero post-warmup builds",
        ("serving/slots.py::SlotDecoder._pad_bucket",
         "serving/slots.py::_buckets",
         "serving/slots.py::_bank_ladder",
         "serving/slots.py::SlotDecoder.warm_admit_counts"),
    ),
    "serving/slots.py::SlotDecoder._tick_fn.tick_spec": ShapeLadder(
        "enumerated",
        "the SAME (bank S, admit bucket A) grid as tick — draft_k and "
        "draft_hidden are per-decoder constants (config-fixed), so the "
        "spec variant family is exactly the tick family's size; warmup "
        "compiles every variant, compile_count pins zero post-warmup "
        "builds, and the aot key's :k<draft_k> suffix refuses a "
        "foreign-k executable at install",
        ("serving/slots.py::SlotDecoder._pad_bucket",
         "serving/slots.py::_buckets",
         "serving/slots.py::_bank_ladder",
         "serving/slots.py::SlotDecoder.warm_admit_counts"),
    ),
    "serving/slots.py::SlotDecoder._free_fn.free_rows": ShapeLadder(
        "enumerated",
        "one variant per bank size on the doubling ladder",
        ("serving/slots.py::_bank_ladder",),
    ),
    "serving/slots.py::SlotDecoder._resize_fn.resize": ShapeLadder(
        "enumerated",
        "one variant per adjacent bank transition, both directions, "
        "all warmup-compiled",
        ("serving/slots.py::_bank_ladder",),
    ),
    # --------------------------------------------------- speculative decode
    "decoding/speculative.py::_greedy_spec_runner.round_fn": ShapeLadder(
        "fixed",
        "offline parity backend: one (B, k, L) shape per harness run "
        "(k and L are harness constants)",
    ),
    # ---------------------------------------------------------- training
    "training/steps.py::make_xe_train_step::train_step": ShapeLadder(
        "fixed",
        "the fixed (B, L) train batch; ss_prob splits the cache as a "
        "STATIC value, not a shape",
    ),
    "training/steps.py::make_greedy_sample_fn::sample": ShapeLadder(
        "fixed", "the fixed validation batch shape",
    ),
    "training/cst.py::dispatch_latency_ms::<lambda>": ShapeLadder(
        "probe", "once-per-process dispatch-latency probe on a scalar",
    ),
    "training/cst.py::io_callback_supported::<lambda>": ShapeLadder(
        "probe", "once-per-process capability probe on a scalar",
    ),
    "training/cst.py::_make_one_graph_step::train_step": ShapeLadder(
        "fixed", "the fixed CST batch shape",
    ),
    "training/cst.py::_make_pipelined_step::_rollout": ShapeLadder(
        "fixed", "the fixed rollout batch shape (pipeline head)",
    ),
    "training/cst.py::_make_pipelined_step.update_and_rollout": ShapeLadder(
        "fixed", "the fixed CST batch shape (pipeline steady state)",
    ),
    "training/cst.py::_make_pipelined_step.update_only": ShapeLadder(
        "fixed", "the fixed CST batch shape (pipeline flush)",
    ),
    "training/cst.py::_make_split_step.rollout_chunk": ShapeLadder(
        "fixed",
        "fixed chunking of the fixed batch — the chunk grid is decided "
        "once per run from config",
    ),
    "training/cst.py::_make_split_step.rollout_fused": ShapeLadder(
        "fixed", "the fixed batch shape (fused-sampler rollout)",
    ),
    "training/cst.py::_make_split_step.greedy_chunk": ShapeLadder(
        "fixed", "the fixed greedy-baseline batch shape",
    ),
    "training/cst.py::_make_split_step.update_fn": ShapeLadder(
        "enumerated",
        "pow2-trimmed PG length buckets at the fixed batch shape — "
        "both CST layouts trim from the same token matrix through the "
        "same bucket helper",
        ("training/cst.py::_make_slot_step._trim_len",),
    ),
    "training/cst.py::SlotRollout.__init__::prepare": ShapeLadder(
        "fixed",
        "static (repeat, need_greedy) at the fixed batch shape",
    ),
    "training/cst.py::SlotRollout._tick_fn.tick": ShapeLadder(
        "fixed",
        "one slot-rollout geometry (n_slots, block) per run — a "
        "single full-width admission bucket by construction",
    ),
    "training/cst.py::_make_slot_step.update_fn": ShapeLadder(
        "enumerated",
        "the same pow2 length-trim buckets as the split-step update",
        ("training/cst.py::_make_slot_step._trim_len",),
    ),
    # --------------------------------------------------------------- cli
    "cli/distill_draft.py::_make_update.update": ShapeLadder(
        "fixed",
        "one (batch, max_len) distillation shape per CLI invocation "
        "(both are argparse constants)",
    ),
    # ------------------------------------------------------------- tools
    "tools/overlap_sim.py::simulate::<lambda>": ShapeLadder(
        "fixed",
        "bench-only simulator: one shape per simulated configuration "
        "per bench invocation",
    ),
}


SHARDING_CONSTRAINT_REGISTRY: Dict[str, str] = {
    "parallel/partition.py::constrain": (
        "the one raw-constraint helper every boundary pin can route "
        "through; degrades to identity off-mesh so call sites stay "
        "unconditional"
    ),
    "training/steps.py::make_xe_train_step.train_step.loss_fn": (
        "pins the (rows, T, V) XE logits rows-over-data x "
        "vocab-over-model before the loss so XLA keeps the dominant "
        "vocab matmul sharded instead of all-gathering the logits into "
        "every device (docs/PERF.md r12 comm arithmetic)"
    ),
    "training/cst.py::_pg_update.loss_fn": (
        "pins the PG logits before log_softmax: without it the SPMD "
        "partitioner flattens the softmax reductions onto all devices "
        "and hits the involuntary-full-remat cliff the dryrun tripwire "
        "fails on (see _pg_update docstring)"
    ),
    "training/cst.py::_make_one_graph_step.score": (
        "replicates the tiny (B*S,) reward-callback operands/result on "
        "old-shard_map meshes so the device-0 io_callback crossing is a "
        "plain broadcast, not a full repartition of sharded activations"
    ),
    "serving/slots.py::SlotDecoder._build_step.step_once.step_logits": (
        "model-sharded serving: keeps the (rows, V) decode-step logits "
        "vocab-over-model through the step so the logit matmul stays "
        "sharded up to the top-K/argmax instead of all-gathering every "
        "step (docs/PERF.md r12)"
    ),
    "serving/slots.py::SlotDecoder._build_step.spec_once.verify_fn": (
        "speculative verify: pins the batched (k*rows, V) verify "
        "logits vocab-over-model — the ONE big GEMM the spec round "
        "amortizes its k steps into — so the tp_row_pick merge sees "
        "sharded tiles instead of an all-gathered (k*rows, V) logits "
        "block every round (the step_logits pin's k-row twin)"
    ),
}
