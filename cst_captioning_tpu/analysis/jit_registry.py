"""The jit call-site registry: every jit application in the package,
keyed stably, with its expected retrace budget.

``budget`` is reviewer-facing prose answering ONE question: what bounds
recompiles at this site?  (A fixed shape ladder, a pre-warmed bank
ladder, a handful of static values, a once-per-process probe…)  The
CST-DON-002 rule fails the analysis pass on any unregistered site, and
CST-DON-003 on stale entries, so this file tracks the code by
construction.  ``update_step=True`` marks TrainState update steps that
MUST donate their state (CST-DON-001, paired with the
``tf.aliasing_output`` pin in tests/test_training.py);
``donates=True`` acknowledges donation at non-update sites.
"""

from __future__ import annotations

from typing import Dict, NamedTuple


class JitSite(NamedTuple):
    budget: str
    update_step: bool = False
    donates: bool = False


JIT_SITE_REGISTRY: Dict[str, JitSite] = {
    # ---------------------------------------------------------- decoding
    "decoding/beam.py::make_beam_search_fn::fn": JitSite(
        "one compile per (B, K, L) decode shape; offline eval uses one "
        "shape, serving dispatches through the engine's fixed batch "
        "ladder (warmup pre-compiles every rung)"
    ),
    # ------------------------------------------------------ fused kernels
    "ops/pallas_beam.py::attlstm_beam": JitSite(
        "static (beam_size, max_len, suppress_unk) + input shapes: one "
        "compile per eval/bench configuration, reused for the whole run"
    ),
    "ops/pallas_beam.py::lstm_beam": JitSite(
        "same static-knob discipline as attlstm_beam (meanpool fusion "
        "variant)"
    ),
    "ops/pallas_sampler.py::attlstm_sample": JitSite(
        "static (max_len, greedy, suppress_unk) + shapes; temperature "
        "is an SMEM scalar by design (ADVICE r5 #1) so distinct "
        "temperatures share ONE compiled kernel"
    ),
    "ops/pallas_sampler.py::lstm_sample": JitSite(
        "same discipline as attlstm_sample (meanpool fusion variant)"
    ),
    # ----------------------------------------------------------- serving
    "serving/engine.py::InferenceEngine._encode_fn.encode": JitSite(
        "one compile per ladder bucket B, all built at warmup(); the "
        "coalescer never builds a batch outside the ladder"
    ),
    "serving/engine.py::InferenceEngine._state_fn.from_state": JitSite(
        "one compile per ladder bucket B (tier-2 fast path), built at "
        "warmup()"
    ),
    "serving/slots.py::SlotDecoder._tick_fn.tick": JitSite(
        "one compile per (bank size S, admit bucket A) pair; warmup() "
        "builds every variant and SlotDecoder.compile_count pins that "
        "post-warmup traffic builds ZERO new ones (tier-1)"
    ),
    "serving/slots.py::SlotDecoder._free_fn.free_rows": JitSite(
        "one compile per bank size, warmup-built, compile_count-pinned"
    ),
    "serving/slots.py::SlotDecoder._resize_fn.resize": JitSite(
        "one compile per bank-ladder transition (grow+shrink), "
        "warmup-built, compile_count-pinned"
    ),
    # ---------------------------------------------------------- training
    "training/steps.py::make_xe_train_step::train_step": JitSite(
        "one compile per distinct static ss_prob value (the scheduled-"
        "sampling schedule steps a handful of times per run) at the "
        "fixed train batch shape",
        update_step=True,
    ),
    "training/steps.py::make_greedy_sample_fn::sample": JitSite(
        "one compile at the fixed validation batch shape"
    ),
    "training/cst.py::dispatch_latency_ms::<lambda>": JitSite(
        "one trivial probe compile per process (dispatch-latency "
        "measurement)"
    ),
    "training/cst.py::io_callback_supported::<lambda>": JitSite(
        "one capability-probe compile per process"
    ),
    "training/cst.py::_make_one_graph_step::train_step": JitSite(
        "one compile at the fixed CST batch shape",
        update_step=True,
    ),
    "training/cst.py::_make_pipelined_step::_rollout": JitSite(
        "one compile at the fixed rollout batch shape (pipelined "
        "layout's first dispatch)"
    ),
    "training/cst.py::_make_pipelined_step.update_and_rollout": JitSite(
        "one compile at the fixed CST batch shape (steady-state "
        "pipelined step)",
        update_step=True,
    ),
    "training/cst.py::_make_pipelined_step.update_only": JitSite(
        "one compile at the fixed CST batch shape (pipeline flush)",
        update_step=True,
    ),
    "training/cst.py::_make_split_step.rollout_chunk": JitSite(
        "one compile per rollout chunk shape (fixed chunking of the "
        "fixed batch)"
    ),
    "training/cst.py::_make_split_step.rollout_fused": JitSite(
        "one compile at the fixed batch shape (fused-sampler variant)"
    ),
    "training/cst.py::_make_split_step.greedy_chunk": JitSite(
        "one compile at the fixed greedy-baseline batch shape"
    ),
    "training/cst.py::_make_split_step.update_fn": JitSite(
        "one compile per power-of-two trimmed PG length bucket at the "
        "fixed batch shape",
        update_step=True,
    ),
    "training/cst.py::SlotRollout.__init__::prepare": JitSite(
        "static (repeat, need_greedy): one compile per rollout "
        "configuration at the fixed batch shape"
    ),
    "training/cst.py::SlotRollout._tick_fn.tick": JitSite(
        "one compile per slot-rollout geometry (n_slots, block) — a "
        "single full-width admission bucket, fixed per run"
    ),
    "training/cst.py::_make_slot_step.update_fn": JitSite(
        "one compile per power-of-two trimmed PG length bucket "
        "(identical trim to the padded layout)",
        update_step=True,
    ),
    # ------------------------------------------------------------- tools
    "tools/overlap_sim.py::simulate::<lambda>": JitSite(
        "bench-only overlap simulator: one compile per simulated shape "
        "per bench invocation"
    ),
}


# Every AOT compile/install site in the package (PR 13): the
# ``.lower(...).compile(...)`` chain compiles OUTSIDE the jit dispatch
# path and ``deserialize_and_load`` installs an executable compiled in
# ANOTHER process — both bypass the runtime retrace guards above, so
# CST-DON-004 requires each such site (keyed ``<file>::<qualname>``) to
# state what enumerates its variants and what refuses a stale or
# foreign executable; CST-DON-005 flags stale entries.
AOT_SITE_REGISTRY: Dict[str, str] = {
    "serving/artifact.py::build_artifact": (
        "artifact builder: compiles exactly the variants "
        "SlotDecoder.aot_lower / InferenceEngine.aot_lower_encode "
        "enumerate (the same ladder code warmup walks), through the "
        "persistent compilation cache pointed into the artifact; the "
        "manifest records a sha256 HLO key per variant"
    ),
    "serving/artifact.py::load_engine": (
        "artifact loader: deserializes only after the manifest's "
        "schema/jax/jaxlib/device/version fields AND the re-derived "
        "variant key set match the live environment exactly "
        "(ArtifactMismatchError otherwise — refusal, never a silent "
        "retrace); installed via SlotDecoder.aot_install with "
        "compile_count == 0 pinned in tier-1"
    ),
    "serving/slots.py::_slot_runner": (
        "shared parity harness's artifact-boot backend: compiles a "
        "builder decoder's aot_lower variants and installs them into a "
        "fresh decoder, pinning compile_count == 0 plus token-exactness "
        "vs the scan reference (tests/test_decode_core.py)"
    ),
}


# Every ``shard_map`` call site in the package (raw jax API, the
# ``parallel/mesh.py`` version-compat wrapper, or its resolved
# ``_shard_map_impl``), keyed ``<file>::<enclosing qualname>`` —
# CST-SHD-004 fails the pass on any unregistered site and on stale
# entries.  The value is reviewer-facing prose: the COLLECTIVE LAYOUT
# the manual specs buy (which per-step gather they avoid) and what
# bounds the site's recompiles.  A shard_map with no story is usually
# a partitioner workaround nobody can maintain.
SHARD_MAP_REGISTRY: Dict[str, str] = {
    "parallel/mesh.py::shard_map": (
        "the version-portability wrapper every package shard_map routes "
        "through (jax.experimental vs top-level spelling, check_rep vs "
        "check_vma) — the one raw-impl call site, no collective layout "
        "of its own"
    ),
    "parallel/ring.py::ring_attention": (
        "ring attention: frame-axis K/V shards rotate via "
        "collective_permute so each device scores S/M frames per hop "
        "instead of all-gathering the full frame axis; one compile per "
        "(mesh, block shape)"
    ),
    "parallel/ring.py::sharded_context_attention": (
        "single-query Bahdanau fusion with frames sharded over `model`: "
        "local score + one psum of the (B, E) context instead of every "
        "device holding all frames; one compile per (mesh, shape)"
    ),
    "training/cst.py::_make_one_graph_step.score": (
        "per-shard CST reward io_callback: each shard scores its own "
        "rollout rows host-side — the replicated-global fallback would "
        "funnel every row through device 0; one compile per CST batch "
        "shape"
    ),
    "decoding/core.py::make_tp_beam_topk.topk": (
        "the ISSUE-14 cross-shard beam top-K: per-shard vocab-tile "
        "candidates + one O(shards*K) all-gather replace the O(V) "
        "full-vocab gather the SPMD partitioner inserts for the inline "
        "lax.top_k over model-sharded logits; compiled inside the "
        "warmup-bounded slot tick variants"
    ),
    "decoding/core.py::make_tp_row_pick.pick": (
        "the greedy twin of make_tp_beam_topk: per-shard argmax "
        "(value, global id) pairs merged by one tiny all-gather instead "
        "of gathering the (rows, V) logits; compiled inside the "
        "warmup-bounded slot tick variants"
    ),
    "ops/shard_decode.py::_sharded_beam_impl": (
        "the shard_map port of the fused beam kernel: vocab-over-model "
        "in_specs keep each shard on its (H, V/M) w_out tile, the "
        "per-step candidate all-gather is O(shards*K) bytes vs the "
        "forbidden O(V) gather, and the embedding feed is a masked "
        "lookup + (rows, E) psum; one compile per (mesh, beam, L) "
        "decode configuration like the kernel it ports"
    ),
    "ops/shard_decode.py::_sharded_sample_impl": (
        "the shard_map port of the fused sampler: same tile layout as "
        "the beam port with per-shard Gumbel-max winners (global-id "
        "counters keep the hash stream shard-invariant) merged by one "
        "tiny all-gather; one compile per (mesh, T, greedy) "
        "configuration"
    ),
}


# Every ``with_sharding_constraint`` site in the package (and every call
# through ``parallel/partition.py::constrain``), keyed
# ``<file>::<enclosing qualname>`` — CST-SHD-002 fails the pass on any
# unregistered site and on stale entries.  The value is reviewer-facing
# prose: WHAT the pin buys (which all-gather it prevents, which SPMD
# partitioner cliff it avoids).  A constraint with no story is usually a
# constraint papering over a placement bug.
SHARDING_CONSTRAINT_REGISTRY: Dict[str, str] = {
    "parallel/partition.py::constrain": (
        "the one raw-constraint helper every boundary pin can route "
        "through; degrades to identity off-mesh so call sites stay "
        "unconditional"
    ),
    "training/steps.py::make_xe_train_step.train_step.loss_fn": (
        "pins the (rows, T, V) XE logits rows-over-data x "
        "vocab-over-model before the loss so XLA keeps the dominant "
        "vocab matmul sharded instead of all-gathering the logits into "
        "every device (docs/PERF.md r12 comm arithmetic)"
    ),
    "training/cst.py::_pg_update.loss_fn": (
        "pins the PG logits before log_softmax: without it the SPMD "
        "partitioner flattens the softmax reductions onto all devices "
        "and hits the involuntary-full-remat cliff the dryrun tripwire "
        "fails on (see _pg_update docstring)"
    ),
    "training/cst.py::_make_one_graph_step.score": (
        "replicates the tiny (B*S,) reward-callback operands/result on "
        "old-shard_map meshes so the device-0 io_callback crossing is a "
        "plain broadcast, not a full repartition of sharded activations"
    ),
    "serving/slots.py::SlotDecoder._build_step.step_once.step_logits": (
        "model-sharded serving: keeps the (rows, V) decode-step logits "
        "vocab-over-model through the step so the logit matmul stays "
        "sharded up to the top-K/argmax instead of all-gathering every "
        "step (docs/PERF.md r12)"
    ),
}
