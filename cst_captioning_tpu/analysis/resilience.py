"""CST-RES: fault-injection invariants (chaos engine —
``serving/chaos.py``).

A fault injector is only trustworthy if it provably cannot change the
serving path when it is off, and provably covers the failure modes it
claims to — so those are rules, not prose:

* CST-RES-001 — every ``chaos.fire("<site>")`` literal anywhere in the
  package must name a site registered in
  ``serving/chaos.py::FAULT_SITES`` (the ``METRIC_FAMILIES`` discipline
  applied to injection points); on a full-package scan, every registered
  site must also have at least one live call site (a site that is never
  injected reads as chaos coverage that isn't there) and be documented
  in docs/SERVING.md's failure-modes table.
* CST-RES-002 — every ``chaos.fire`` call site must be guarded so
  chaos-off costs NOTHING: the call must sit under an ``is not None`` /
  truthiness check of a chaos-named expression (``if self.chaos is not
  None and self.chaos.fire(...)`` counts — the guard is the left
  operand).  On a full-package scan the ``ServingConfig.chaos`` field
  must also default to an EMPTY dict, so chaos is off unless explicitly
  configured (the byte-identical-serving contract the no-chaos parity
  test pins at runtime).
* CST-RES-003 — no ``chaos.fire`` call (or any call resolving into
  ``serving/chaos.py``) reachable from a jit-traced root, via the
  CST-JIT traced-set machinery: a fault decision inside traced code
  would be baked in at trace time and replayed forever, which is the
  opposite of a schedule-driven injection.

Emission sites are recognized structurally: a ``.fire`` call on a
receiver whose final name contains ``chaos`` — the naming convention the
serving call sites follow.  ``serving/chaos.py`` is stdlib-only by
design, so importing the catalogue here keeps the pass jax-free (the
``metrics_registry`` / ``observability`` precedent); the registry file
itself is excluded from site checks (its own machinery is not an
injection point).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from cst_captioning_tpu.analysis.astutil import (
    ModuleInfo,
    dotted,
    walk_body,
)
from cst_captioning_tpu.analysis.engine import (
    CheckContext,
    Finding,
    register_checker,
)

REGISTRY_FILE = "serving/chaos.py"
CONFIG_FILE = "config.py"
DOC_FILE = "SERVING.md"


def _load_sites() -> List[Tuple[str, str, str]]:
    from cst_captioning_tpu.serving.chaos import FAULT_SITES

    return list(FAULT_SITES)


def _chaos_name(node: ast.AST) -> bool:
    """Whether ``node`` is a Name/Attribute chain whose final identifier
    names a chaos engine (``chaos``, ``self.chaos``, ``self._chaos``)."""
    base = dotted(node)
    if not base:
        return False
    return "chaos" in base.split(".")[-1].lstrip("_").lower()


def _fire_call(node: ast.Call) -> bool:
    return (
        isinstance(node.func, ast.Attribute)
        and node.func.attr == "fire"
        and _chaos_name(node.func.value)
    )


def _site_literal(node: ast.Call) -> Optional[Tuple[str, int]]:
    if not node.args:
        return None
    a = node.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        return a.value, a.lineno
    return None


def _guard_expr(e: ast.AST) -> bool:
    """Whether an expression reads as a chaos-off guard: any chaos-named
    Name/Attribute inside it (covers ``x is not None``, bare truthiness,
    and boolean combinations thereof)."""
    return any(
        isinstance(n, (ast.Name, ast.Attribute)) and _chaos_name(n)
        for n in ast.walk(e)
    )


def _is_guarded(mi: ModuleInfo, call: ast.Call) -> bool:
    """Whether a ``chaos.fire`` call is dominated by a chaos-off guard:
    an enclosing ``if``/ternary whose test mentions the chaos engine, or
    an ``and`` chain whose EARLIER operand does (short-circuit guard)."""
    child: ast.AST = call
    cur = mi.parent.get(call)
    while cur is not None:
        if isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return False
        if isinstance(cur, (ast.If, ast.While, ast.IfExp)):
            if cur.test is not child and _guard_expr(cur.test):
                return True
        if isinstance(cur, ast.BoolOp) and isinstance(cur.op, ast.And):
            for v in cur.values:
                if v is child or any(
                    v is n for n in ast.walk(child)
                ):
                    break
                if _guard_expr(v):
                    return True
        child = cur
        cur = mi.parent.get(cur)
    return False


def fire_sites(
    modules: List[ModuleInfo],
) -> List[Tuple[ModuleInfo, ast.Call, Optional[str]]]:
    """Every recognized ``chaos.fire`` call site in the package with its
    literal site name when the first argument is a string constant (the
    vacuous-green guard in tests asserts this finds the real serving
    injection points)."""
    out = []
    for mi in modules:
        if mi.rel == REGISTRY_FILE:
            continue
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Call) and _fire_call(node):
                lit = _site_literal(node)
                out.append((mi, node, lit[0] if lit else None))
    return out


def _config_default_off(mi: ModuleInfo) -> Optional[int]:
    """Return the line of the ``ServingConfig.chaos`` field when its
    default is NOT an empty-dict factory (None = compliant or absent).
    Compliant shape: ``chaos: ... = field(default_factory=dict)``."""
    cls = mi.classes.get("ServingConfig")
    if cls is None:
        return None
    for node in cls.body:
        if not (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
            and node.target.id == "chaos"
        ):
            continue
        v = node.value
        if (
            isinstance(v, ast.Call)
            and dotted(v.func).endswith("field")
            and any(
                kw.arg == "default_factory"
                and isinstance(kw.value, ast.Name)
                and kw.value.id == "dict"
                for kw in v.keywords
            )
        ):
            return None
        return node.lineno
    return None


@register_checker("resilience")
def check(modules: List[ModuleInfo], ctx: CheckContext) -> List[Finding]:
    out: List[Finding] = []
    sites = _load_sites()
    names = {s for s, _, _ in sites}
    full_scan = any(m.rel == REGISTRY_FILE for m in modules)

    # ---- RES-001: every fired site registered; registry covered ------
    seen_names = set()
    for mi, node, name in fire_sites(modules):
        if name is None:
            continue
        seen_names.add(name)
        if name not in names:
            out.append(Finding(
                "CST-RES-001", mi.rel, node.lineno,
                mi.qualname_of(node),
                f"chaos site `{name}` matches no entry in "
                "serving/chaos.py::FAULT_SITES — register it and "
                f"document it in docs/{DOC_FILE} before injecting",
            ))
        # ---- RES-002: the site must be guarded (chaos-off is free) ---
        if not _is_guarded(mi, node):
            out.append(Finding(
                "CST-RES-002", mi.rel, node.lineno,
                mi.qualname_of(node),
                "unguarded `chaos.fire` call — every injection point "
                "must sit behind an `is not None`/truthiness check of "
                "the chaos engine so the default (chaos-off) serving "
                "path is byte-identical and pays nothing",
            ))
    if full_scan:
        for name in sorted(names - seen_names):
            out.append(Finding(
                "CST-RES-001", REGISTRY_FILE, 1, name,
                f"registered fault site `{name}` has no live "
                "`chaos.fire` call site — chaos coverage that is "
                "registered but never injected reads as survival "
                "certification that isn't there",
            ))
        if ctx.docs_root is not None:
            doc_path = ctx.docs_root / DOC_FILE
            doc_text = doc_path.read_text() if doc_path.exists() else ""
            for name in sorted(names):
                if name not in doc_text:
                    out.append(Finding(
                        "CST-RES-001", REGISTRY_FILE, 1, name,
                        f"registered fault site `{name}` is not "
                        f"documented in docs/{DOC_FILE} — operators "
                        "discover the failure-mode vocabulary in the "
                        "degradation-ladder table; add it",
                    ))
        # ---- RES-002(b): config defaults chaos OFF -------------------
        cfg_mi = next(
            (m for m in modules if m.rel == CONFIG_FILE), None
        )
        if cfg_mi is not None:
            bad_line = _config_default_off(cfg_mi)
            if bad_line is not None:
                out.append(Finding(
                    "CST-RES-002", CONFIG_FILE, bad_line,
                    "ServingConfig.chaos",
                    "serving.chaos must default to an EMPTY dict "
                    "(field(default_factory=dict)) — chaos is opt-in; "
                    "a non-empty default would inject faults into "
                    "every serving process",
                ))

    # ---- RES-003: no chaos decision reachable from jit-traced code ---
    from cst_captioning_tpu.analysis import jit_boundary as jb

    traced = jb._TracedSet()
    jb._collect_roots(modules, traced)
    jb._expand(modules, ctx, traced)
    by_mod = {m.rel: m for m in modules}
    for (rel, qn) in sorted(traced.static):
        mi = by_mod.get(rel)
        if mi is None or mi.rel == REGISTRY_FILE:
            continue
        fn = mi.functions[qn]
        for node in walk_body(fn):
            if not isinstance(node, ast.Call):
                continue
            if _fire_call(node):
                out.append(Finding(
                    "CST-RES-003", rel, node.lineno, qn,
                    "chaos.fire inside traced code "
                    f"({traced.reason[(rel, qn)]}) — the fault decision "
                    "would be baked in at trace time and replayed "
                    "forever; inject at the host-side tick boundary "
                    "instead",
                ))
                continue
            for callee in ctx.index.resolve_call(mi, fn, node):
                if callee.module.rel == REGISTRY_FILE:
                    out.append(Finding(
                        "CST-RES-003", rel, node.lineno, qn,
                        f"call into {REGISTRY_FILE} from traced code "
                        f"({traced.reason[(rel, qn)]}) — the chaos "
                        "layer is host-side only",
                    ))
    return out
