// Native CIDEr-D scorer — the CST reward hot path (SURVEY.md §3 hot loop
// #2: in-loop consensus scoring must stay far cheaper than the device
// step).  Drop-in twin of the Python scorer in
// cst_captioning_tpu/metrics/cider.py + training/rewards.py: identical
// math (tf-idf over n=1..4 id n-grams, count-clipped cosine, Gaussian
// length penalty, x10 scale), corpus-mode document frequencies.
//
// The reference implements this in Python (cider/pyciderevalcap/ciderD,
// SURVEY.md §2); a C++ scorer is the TPU-native framework's equivalent of
// the reference's native eval components, keeping the io_callback latency
// per CST step in the tens of microseconds instead of milliseconds.
//
// Design notes:
// * Token ids are < 2^15 (vocab ~10-20k; the Python wrapper enforces the
//   bound and falls back otherwise), so an n-gram (n<=4) packs exactly
//   into a uint64 key: 15 bits per token (60) + 2 bits n-gram order —
//   exact, no hash collisions.  Word ids start at 4 (0=PAD, 1=BOS,
//   2=EOS, 3=UNK), so a zero slot is unambiguous.
// * Per-video reference vectors are cooked once at finalize(); scoring a
//   candidate is one pass to count its n-grams plus one hash lookup per
//   (candidate n-gram, reference).
// * C ABI for ctypes — no pybind11 in this environment.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kNGrams = 4;
constexpr double kSigma = 6.0;
constexpr int kPad = 0, kBos = 1, kEos = 2;

using Counts = std::unordered_map<uint64_t, float>;

struct RefVec {
  // tf-idf weights per n-gram order, L2 norm per order, unigram length.
  Counts vec[kNGrams];
  double norm[kNGrams];
  long length;
};

struct Video {
  std::vector<std::vector<int>> refs;   // token ids per reference
  std::vector<RefVec> ref_vecs;         // cooked at finalize()
  std::vector<float> weights;           // per-ref consensus weights
                                        // (empty = uniform)
  // Merged scoring structure: one hash lookup per CANDIDATE n-gram
  // instead of one per (n-gram, reference).  merged[key][r] = ref r's
  // tf-idf weight for that n-gram (0 when absent); norms/lengths are the
  // per-ref per-order L2 norms and unigram lengths.
  std::unordered_map<uint64_t, std::vector<float>> merged;
  std::vector<double> ref_norms;        // nref * kNGrams
  std::vector<long> ref_lengths;        // nref
};

struct Scorer {
  std::vector<Video> videos;
  std::unordered_map<uint64_t, float> doc_freq;  // over videos (corpus mode)
  double log_ref_len = 0.0;
  bool finalized = false;
};

inline uint64_t pack(const int* toks, int n) {
  uint64_t key = 0;
  for (int i = 0; i < n; ++i) {
    key = (key << 15) | static_cast<uint64_t>(toks[i] & 0x7fff);
  }
  // Disambiguate orders so ("a") and ("\0","a") can't collide: bits
  // 60-61 hold (n-1).
  return key | (static_cast<uint64_t>(n - 1) << 60);
}

void precook(const std::vector<int>& toks, Counts out[kNGrams]) {
  const int len = static_cast<int>(toks.size());
  for (int n = 1; n <= kNGrams; ++n) {
    for (int i = 0; i + n <= len; ++i) {
      out[n - 1][pack(toks.data() + i, n)] += 1.0f;
    }
  }
}

void counts_to_vec(const Counts cnts[kNGrams],
                   const std::unordered_map<uint64_t, float>& df,
                   double log_ref_len, RefVec* rv) {
  rv->length = 0;
  for (int n = 0; n < kNGrams; ++n) {
    rv->norm[n] = 0.0;
    rv->vec[n].clear();
    for (const auto& kv : cnts[n]) {
      auto it = df.find(kv.first);
      double d = it == df.end() ? 0.0 : it->second;
      double idf = log_ref_len - std::log(std::max(1.0, d));
      double w = static_cast<double>(kv.second) * idf;
      rv->vec[n][kv.first] = static_cast<float>(w);
      rv->norm[n] += w * w;
      if (n == 0) rv->length += static_cast<long>(kv.second);
    }
    rv->norm[n] = std::sqrt(rv->norm[n]);
  }
}

// Build the merged per-video scoring structure from cooked ref_vecs and
// release the per-ref maps (scoring never touches them again).
void build_merged(Video* v) {
  const size_t nref = v->ref_vecs.size();
  v->merged.clear();
  v->ref_norms.assign(nref * kNGrams, 0.0);
  v->ref_lengths.assign(nref, 0);
  for (size_t r = 0; r < nref; ++r) {
    const RefVec& rv = v->ref_vecs[r];
    v->ref_lengths[r] = rv.length;
    for (int n = 0; n < kNGrams; ++n) {
      v->ref_norms[r * kNGrams + n] = rv.norm[n];
      for (const auto& kv : rv.vec[n]) {
        auto& slot = v->merged[kv.first];
        if (slot.empty()) slot.assign(nref, 0.0f);
        slot[r] = kv.second;
      }
    }
  }
  v->ref_vecs.clear();
  v->ref_vecs.shrink_to_fit();
}

// CIDEr-D of one cooked hypothesis against every reference of `v` at
// once: one merged-map lookup per hypothesis n-gram, then per-ref
// normalization + Gaussian length penalty.  out_sims[r] = sim_d(hyp, r).
void sim_d_all(const RefVec& hyp, const Video& v, double* out_sims) {
  const size_t nref = v.ref_lengths.size();
  std::vector<double> acc(nref * kNGrams, 0.0);
  for (int n = 0; n < kNGrams; ++n) {
    for (const auto& kv : hyp.vec[n]) {
      auto it = v.merged.find(kv.first);
      if (it == v.merged.end()) continue;
      const float* m = it->second.data();
      const float wh = kv.second;
      double* a = acc.data() + n;  // stride kNGrams per ref
      for (size_t r = 0; r < nref; ++r) {
        a[r * kNGrams] += static_cast<double>(std::min(wh, m[r])) *
                          static_cast<double>(m[r]);
      }
    }
  }
  for (size_t r = 0; r < nref; ++r) {
    const double delta = static_cast<double>(hyp.length - v.ref_lengths[r]);
    const double penalty =
        std::exp(-(delta * delta) / (2.0 * kSigma * kSigma));
    double total = 0.0;
    for (int n = 0; n < kNGrams; ++n) {
      double val = acc[r * kNGrams + n];
      const double nr = v.ref_norms[r * kNGrams + n];
      if (hyp.norm[n] != 0.0 && nr != 0.0) val /= hyp.norm[n] * nr;
      total += val * penalty;
    }
    out_sims[r] = total;
  }
}

}  // namespace

extern "C" {

void* ciderd_new() { return new Scorer(); }

void ciderd_free(void* h) { delete static_cast<Scorer*>(h); }

// Add one video's references: `tokens` is the concatenation of all refs'
// ids, `ref_lens[i]` the length of ref i.  Call in dataset index order.
void ciderd_add_video(void* h, const int* tokens, const int* ref_lens,
                      int num_refs) {
  auto* s = static_cast<Scorer*>(h);
  Video v;
  int off = 0;
  for (int r = 0; r < num_refs; ++r) {
    v.refs.emplace_back(tokens + off, tokens + off + ref_lens[r]);
    off += ref_lens[r];
  }
  s->videos.push_back(std::move(v));
}

// Optional per-reference consensus weights for the most recently added
// video (the paper's weighted-consensus reward).  Normalized at score
// time; call after ciderd_add_video.
void ciderd_set_video_weights(void* h, int video, const float* w, int n) {
  auto* s = static_cast<Scorer*>(h);
  if (video < 0 || video >= static_cast<int>(s->videos.size())) return;
  s->videos[video].weights.assign(w, w + n);
}

// Corpus-mode finalize: df[ngram] = number of videos whose ref set
// contains it; log_ref_len = log(max(#videos, 2)); cook every ref.
void ciderd_finalize(void* h) {
  auto* s = static_cast<Scorer*>(h);
  s->doc_freq.clear();
  for (auto& v : s->videos) {
    std::unordered_map<uint64_t, char> seen;
    for (auto& ref : v.refs) {
      Counts cnts[kNGrams];
      precook(ref, cnts);
      for (int n = 0; n < kNGrams; ++n)
        for (const auto& kv : cnts[n]) seen.emplace(kv.first, 1);
    }
    for (const auto& kv : seen) s->doc_freq[kv.first] += 1.0f;
  }
  s->log_ref_len =
      std::log(std::max(static_cast<double>(s->videos.size()), 2.0));
  for (auto& v : s->videos) {
    v.ref_vecs.clear();
    for (auto& ref : v.refs) {
      Counts cnts[kNGrams];
      precook(ref, cnts);
      RefVec rv;
      counts_to_vec(cnts, s->doc_freq, s->log_ref_len, &rv);
      v.ref_vecs.push_back(std::move(rv));
    }
    build_merged(&v);
  }
  s->finalized = true;
}

// Externally-supplied document frequencies (idf-table mode).  Entries:
// flat_ngrams = concatenated ids, ngram_lens[i] in [1,4], dfs[i] raw df.
// Must be followed by ciderd_finalize_with_df(log_ref_len).
void ciderd_set_df(void* h, const int* flat_ngrams, const int* ngram_lens,
                   const float* dfs, int count) {
  auto* s = static_cast<Scorer*>(h);
  s->doc_freq.clear();
  int off = 0;
  for (int i = 0; i < count; ++i) {
    uint64_t key = pack(flat_ngrams + off, ngram_lens[i]);
    auto it = s->doc_freq.find(key);
    // UNK-collapse collisions keep the max df (conservative idf) —
    // matches rewards.py's re-keying rule.
    if (it == s->doc_freq.end() || it->second < dfs[i]) s->doc_freq[key] = dfs[i];
    off += ngram_lens[i];
  }
}

void ciderd_finalize_with_df(void* h, double log_ref_len) {
  auto* s = static_cast<Scorer*>(h);
  s->log_ref_len = log_ref_len;
  for (auto& v : s->videos) {
    v.ref_vecs.clear();
    for (auto& ref : v.refs) {
      Counts cnts[kNGrams];
      precook(ref, cnts);
      RefVec rv;
      counts_to_vec(cnts, s->doc_freq, s->log_ref_len, &rv);
      v.ref_vecs.push_back(std::move(rv));
    }
    build_merged(&v);
  }
  s->finalized = true;
}

int ciderd_num_videos(void* h) {
  return static_cast<int>(static_cast<Scorer*>(h)->videos.size());
}

// Score a batch: tokens (batch x max_len) int32 rows — candidate stops at
// the first PAD/EOS, BOS skipped; video_idx (batch,) dataset indices.
// out (batch,) float32 CIDEr-D x10.
// Returns 0 on success, -1 if any video_idx is out of range (the Python
// wrapper raises IndexError — matching the Python scorer — instead of UB).
namespace {

void score_rows(const Scorer* s, const int* video_idx, const int* tokens,
                int max_len, float* out, int begin, int end) {
  for (int b = begin; b < end; ++b) {
    const int* row = tokens + static_cast<long>(b) * max_len;
    std::vector<int> cand;
    cand.reserve(max_len);
    for (int i = 0; i < max_len; ++i) {
      int t = row[i];
      if (t == kPad || t == kEos) break;
      if (t == kBos) continue;
      cand.push_back(t);
    }
    Counts cnts[kNGrams];
    precook(cand, cnts);
    RefVec hyp;
    counts_to_vec(cnts, s->doc_freq, s->log_ref_len, &hyp);
    const Video& v = s->videos[video_idx[b]];
    const size_t nref = v.ref_lengths.size();
    if (nref == 0) {  // reference-less video: reward 0, not NaN
      out[b] = 0.0f;
      continue;
    }
    std::vector<double> sims(nref);
    sim_d_all(hyp, v, sims.data());
    double total = 0.0;
    if (v.weights.size() == nref) {
      double wsum = 0.0;
      for (float w : v.weights) wsum += w;
      const bool degenerate = wsum <= 1e-12;
      for (size_t r = 0; r < nref; ++r) {
        const double w =
            degenerate ? 1.0 / nref : v.weights[r] / wsum;
        total += w * sims[r];
      }
      out[b] = static_cast<float>(total / kNGrams * 10.0);
    } else {
      for (size_t r = 0; r < nref; ++r) total += sims[r];
      out[b] = static_cast<float>(
          total / kNGrams / static_cast<double>(nref) * 10.0);
    }
  }
}

}  // namespace

namespace {

// Leave-one-out consensus of one video: ref j scored (as a hypothesis)
// against its siblings, mean over j.  Twin of
// rewards.CiderDRewarder.gt_consensus()'s per-video body — same df
// table, same optional per-ref weights renormalized over the siblings.
void gt_consensus_rows(const Scorer* s, float* out, int begin, int end) {
  for (int i = begin; i < end; ++i) {
    const Video& v = s->videos[i];
    const size_t nref = v.ref_lengths.size();
    if (nref < 2) {  // matches the Python early-continue (score 0)
      out[i] = 0.0f;
      continue;
    }
    const bool weighted = v.weights.size() == nref;
    double mean = 0.0;
    std::vector<double> sims(nref);
    for (size_t j = 0; j < nref; ++j) {
      Counts cnts[kNGrams];
      precook(v.refs[j], cnts);
      RefVec hyp;
      counts_to_vec(cnts, s->doc_freq, s->log_ref_len, &hyp);
      sim_d_all(hyp, v, sims.data());
      double total = 0.0;
      if (weighted) {
        double wsum = 0.0;
        for (size_t r = 0; r < nref; ++r) {
          if (r != j) wsum += v.weights[r];
        }
        const bool degenerate = wsum <= 1e-12;
        for (size_t r = 0; r < nref; ++r) {
          if (r == j) continue;
          const double w = degenerate
                               ? 1.0 / static_cast<double>(nref - 1)
                               : v.weights[r] / wsum;
          total += w * sims[r];
        }
        mean += total / kNGrams * 10.0;
      } else {
        for (size_t r = 0; r < nref; ++r) {
          if (r != j) total += sims[r];
        }
        mean += total / kNGrams / static_cast<double>(nref - 1) * 10.0;
      }
    }
    out[i] = static_cast<float>(mean / static_cast<double>(nref));
  }
}

}  // namespace

// Leave-one-out GT consensus for every video -> out (num_videos,)
// float32, CIDEr-D x10 units (same scale as ciderd_score rewards).  One
// call at CST startup for cst_baseline='gt_consensus'; threaded — at
// MSR-VTT scale this is ~200k scorings (ADVICE r4 #3).
void ciderd_gt_consensus(void* h, float* out) {
  auto* s = static_cast<Scorer*>(h);
  const int n = static_cast<int>(s->videos.size());
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  int workers = std::max(1, std::min({hw, n / 16, 16}));
  if (workers <= 1) {
    gt_consensus_rows(s, out, 0, n);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(workers);
  const int chunk = (n + workers - 1) / workers;
  for (int w = 0; w < workers; ++w) {
    const int begin = w * chunk;
    const int end = std::min(n, begin + chunk);
    if (begin >= end) break;
    pool.emplace_back(gt_consensus_rows, s, out, begin, end);
  }
  for (auto& t : pool) t.join();
}

int ciderd_score(void* h, const int* video_idx, const int* tokens, int batch,
                 int max_len, float* out) {
  auto* s = static_cast<Scorer*>(h);
  const int n = static_cast<int>(s->videos.size());
  for (int b = 0; b < batch; ++b) {
    if (video_idx[b] < 0 || video_idx[b] >= n) return -1;
  }
  // Rows are independent over a read-only scorer — fan out across cores.
  // A CST step scores B*S (e.g. 1280) rollouts; single-threaded this is
  // the dominant host cost (SURVEY.md hard part #1).  Threads are
  // spawned per call (~0.3 ms for 16) — noise against the >=64-rows-per-
  // worker scoring time that gates spawning; small batches stay inline.
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  int workers = std::max(1, std::min({hw, batch / 64, 16}));
  if (workers <= 1) {
    score_rows(s, video_idx, tokens, max_len, out, 0, batch);
    return 0;
  }
  std::vector<std::thread> pool;
  pool.reserve(workers);
  const int chunk = (batch + workers - 1) / workers;
  for (int w = 0; w < workers; ++w) {
    const int begin = w * chunk;
    const int end = std::min(batch, begin + chunk);
    if (begin >= end) break;
    pool.emplace_back(score_rows, s, video_idx, tokens, max_len, out,
                      begin, end);
  }
  for (auto& t : pool) t.join();
  return 0;
}

}  // extern "C"
