"""Native (C++) components and their ctypes bindings.

``ciderd.cpp`` is the CST reward scorer's fast path; ``build_ciderd()``
compiles it on first use with g++ (no pybind11 in this environment — the
binding is a plain C ABI via ctypes) and caches the .so next to the
source.  ``NativeCiderD`` mirrors the scoring core of
``training/rewards.CiderDRewarder`` exactly; parity is tested in
``tests/test_native_ciderd.py``.
"""

from __future__ import annotations

import ctypes
import logging
import math
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

log = logging.getLogger("cst_captioning_tpu.native")

_SRC = os.path.join(os.path.dirname(__file__), "ciderd.cpp")
_LIB = os.path.join(os.path.dirname(__file__), "_ciderd.so")
_BUILD_LOCK = threading.Lock()
_LIB_HANDLE: Optional[ctypes.CDLL] = None

MAX_TOKEN_ID = 1 << 15  # packing bound in ciderd.cpp


class NativeUnavailable(RuntimeError):
    pass


def build_ciderd(force: bool = False) -> str:
    """Compile ciderd.cpp -> _ciderd.so (cached; rebuilt when stale)."""
    with _BUILD_LOCK:
        if (
            not force
            and os.path.exists(_LIB)
            and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)
        ):
            return _LIB
        # Compile to a process-unique temp path and atomically rename so
        # concurrent builders (multi-host shared filesystem) never load a
        # half-written .so.
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
            _SRC, "-o", tmp,
        ]
        try:
            subprocess.run(
                cmd, check=True, capture_output=True, text=True, timeout=120
            )
            os.replace(tmp, _LIB)
        except (OSError, subprocess.SubprocessError) as e:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            detail = getattr(e, "stderr", "") or str(e)
            raise NativeUnavailable(f"g++ build failed: {detail}") from e
        return _LIB


def _load() -> ctypes.CDLL:
    global _LIB_HANDLE
    if _LIB_HANDLE is not None:
        return _LIB_HANDLE
    lib = ctypes.CDLL(build_ciderd())
    lib.ciderd_new.restype = ctypes.c_void_p
    lib.ciderd_free.argtypes = [ctypes.c_void_p]
    lib.ciderd_add_video.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
        ctypes.c_int,
    ]
    lib.ciderd_set_video_weights.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int,
    ]
    lib.ciderd_finalize.argtypes = [ctypes.c_void_p]
    lib.ciderd_set_df.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int,
    ]
    lib.ciderd_finalize_with_df.argtypes = [ctypes.c_void_p, ctypes.c_double]
    lib.ciderd_num_videos.argtypes = [ctypes.c_void_p]
    lib.ciderd_num_videos.restype = ctypes.c_int
    lib.ciderd_score.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
        ctypes.c_int,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_float),
    ]
    lib.ciderd_score.restype = ctypes.c_int
    lib.ciderd_gt_consensus.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_float),
    ]
    _LIB_HANDLE = lib
    return lib


def _int_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int))


class NativeCiderD:
    """C++ CIDEr-D scorer over token-id sequences.

    ``refs_per_video``: list (dataset order) of lists of id sequences
    (word ids only — no BOS/EOS/PAD).  ``df`` optional {ngram tuple: raw
    df} with ``log_ref_len`` for idf-table mode; corpus mode otherwise.
    ``ref_weights``: optional per-video (num_refs,) consensus weights
    (None entries = uniform) — the paper's weighted consensus reward.
    """

    def __init__(
        self,
        refs_per_video: List[List[Sequence[int]]],
        df=None,
        log_ref_len: Optional[float] = None,
        vocab_size: Optional[int] = None,
        ref_weights: Optional[List[Optional[np.ndarray]]] = None,
    ):
        # The packing bound must hold for anything a CANDIDATE can contain
        # (sampled rollouts range over the whole vocab), not just the refs.
        if vocab_size is not None and vocab_size > MAX_TOKEN_ID:
            raise NativeUnavailable(
                f"vocab_size {vocab_size} exceeds the native packing bound "
                f"({MAX_TOKEN_ID})"
            )
        if ref_weights is not None and len(ref_weights) != len(
            refs_per_video
        ):
            raise ValueError(
                f"ref_weights has {len(ref_weights)} entries for "
                f"{len(refs_per_video)} videos"
            )
        lib = _load()
        self._lib = lib
        self._handle = ctypes.c_void_p(lib.ciderd_new())
        for i, refs in enumerate(refs_per_video):
            for r in refs:
                if any(t >= MAX_TOKEN_ID for t in r):
                    raise NativeUnavailable(
                        f"token id >= {MAX_TOKEN_ID} exceeds the native "
                        "packing bound"
                    )
            flat = np.asarray(
                [t for r in refs for t in r], dtype=np.int32
            )
            lens = np.asarray([len(r) for r in refs], dtype=np.int32)
            if flat.size == 0:
                flat = np.zeros(1, np.int32)  # valid pointer, lens all 0
            lib.ciderd_add_video(
                self._handle, _int_ptr(flat), _int_ptr(lens), len(refs)
            )
            w = None if ref_weights is None else ref_weights[i]
            if w is not None:
                w = np.ascontiguousarray(w, dtype=np.float32)
                if w.shape != (len(refs),):
                    raise ValueError(
                        f"video {i}: {w.shape[0]} weights for "
                        f"{len(refs)} references"
                    )
                lib.ciderd_set_video_weights(
                    self._handle,
                    i,
                    w.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    len(refs),
                )
        if df is None:
            lib.ciderd_finalize(self._handle)
        else:
            ngrams = list(df.items())
            flat = np.asarray(
                [t for ng, _ in ngrams for t in ng], dtype=np.int32
            )
            lens = np.asarray([len(ng) for ng, _ in ngrams], dtype=np.int32)
            vals = np.asarray([v for _, v in ngrams], dtype=np.float32)
            if flat.size == 0:
                flat = np.zeros(1, np.int32)
            lib.ciderd_set_df(
                self._handle,
                _int_ptr(flat),
                _int_ptr(lens),
                vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                len(ngrams),
            )
            if log_ref_len is None:
                log_ref_len = math.log(max(len(refs_per_video), 2))
            lib.ciderd_finalize_with_df(
                self._handle, ctypes.c_double(log_ref_len)
            )

    def __del__(self):
        try:
            self._lib.ciderd_free(self._handle)
        except Exception:
            pass

    def gt_consensus(self) -> np.ndarray:
        """(num_videos,) leave-one-out GT consensus, threaded in C++ —
        same math and units as the Python
        ``CiderDRewarder.gt_consensus`` (parity-tested); at MSR-VTT scale
        (~10k videos x 20 refs) this replaces ~200k Python scorings at
        CST startup (ADVICE r4 #3)."""
        n = self._lib.ciderd_num_videos(self._handle)
        out = np.zeros((n,), np.float32)
        self._lib.ciderd_gt_consensus(
            self._handle,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return out

    def score_ids(
        self, video_idx: np.ndarray, token_ids: np.ndarray
    ) -> np.ndarray:
        vidx = np.ascontiguousarray(video_idx, dtype=np.int32)
        toks = np.ascontiguousarray(token_ids, dtype=np.int32)
        B, L = toks.shape
        out = np.zeros((B,), np.float32)
        rc = self._lib.ciderd_score(
            self._handle,
            _int_ptr(vidx),
            _int_ptr(toks),
            B,
            L,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        if rc != 0:
            n = self._lib.ciderd_num_videos(self._handle)
            raise IndexError(
                f"video_idx out of range [0, {n}) — rewarder built on a "
                "different split?"
            )
        return out
