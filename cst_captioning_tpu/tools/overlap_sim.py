"""Simulated-latency demonstration of the chunked-scoring overlap.

VERDICT r3 weak #2: the K-chunk split CST step
(``training/cst.py::_make_split_step``) pipelines host CIDEr-D scoring
against device compute, but the latency gate disables chunking on the
tunneled runtime this repo benches on — so the machinery shipped in the
default config (``cst_score_chunks: 4``) had never been MEASURED
delivering a win under the conditions it targets (a low-dispatch-latency
TPU-VM host with a scorer that costs real time).

This tool manufactures those conditions on the in-process CPU backend
(per-dispatch latency ~0.1 ms) by wrapping the rewarder with a
configurable sleep — a stand-in for real scoring cost that, like the
real scorer's numpy/C++ loop, does not contend for the accelerator —
then measures steady-state step time at K=1 vs K=N on the same batch.

Theory: with per-chunk device compute D/K and per-chunk scoring S/K, the
K=1 layout serializes D + S while K chunks hide min(S·(K-1)/K, device
tail) of the scoring, so the recoverable stall is ~S·(K-1)/K.  The tool
prints one JSON line with the measured recovery fraction; ``bench.py``
runs it in a subprocess (the main bench process holds the TPU) and
records the numbers under ``cst_overlap_sim_*``.

Run standalone:

    python -m cst_captioning_tpu.tools.overlap_sim [--sleep-ms 60]
        [--chunks 4] [--steps 5]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def credibility(rec_per_rep_ms, recoverable_ms: float):
    """Driver-channel credibility reduction over per-rep recovered-stall
    samples (VERDICT r5 #5: BENCH_r05 recorded ``recovered_frac 1.144 ±
    0.301`` — >100% recovery — with nothing flagging it).  Returns
    ``(recovered_ms, frac, frac_raw, noisy)``: the headline fraction is
    clamped to [0, 1] (outside is a measurement artifact — the K=1
    baseline moved under load — never a real recovery), and ``noisy``
    is set when the spread swamps the signal (sd/|mean| > 0.3) or the
    raw fraction fell outside [0, 1]."""
    import numpy as np

    pp = np.asarray(rec_per_rep_ms, dtype=float)
    recovered = float(pp.mean())
    frac_raw = (
        recovered / recoverable_ms if recoverable_ms > 0 else 0.0
    )
    frac = min(max(frac_raw, 0.0), 1.0)
    spread_bad = len(pp) >= 2 and float(pp.std(ddof=1)) > 0.3 * max(
        abs(recovered), 1e-9
    )
    noisy = bool(spread_bad or frac_raw > 1.0 or frac_raw < 0.0)
    return recovered, frac, frac_raw, noisy


def simulate(sleep_ms: float = 0.0, chunks: int = 4, steps: int = 5,
             batch: int = 48, rollouts: int = 8, reps: int = 1) -> dict:
    """``sleep_ms=0`` auto-sizes the injected scorer to the measured
    rollout compute — the MSR-VTT bench's regime (~40 ms scoring vs
    ~38 ms rollout compute).  Scoring can only overlap rollout chunks
    still computing, so the recoverable stall is bounded by both the
    scorer cost and the rollout tail; the workload is sized large enough
    (rnn 512, batch*rollouts rows) that the CPU backend's fixed per-chunk
    dispatch overhead stays a realistic fraction of the rollout, as it is
    on the TPU shapes the chunked layout targets."""
    import jax

    # The session may register an accelerator platform via sitecustomize;
    # this sim must run on the in-process CPU backend (dispatch ~0.1 ms).
    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.data import BatchIterator, make_synthetic_dataset
    from cst_captioning_tpu.models import model_from_config
    from cst_captioning_tpu.training import cst as cst_mod
    from cst_captioning_tpu.training.rewards import CiderDRewarder
    from cst_captioning_tpu.training.steps import (
        create_train_state,
        make_optimizer,
    )

    ds, _ = make_synthetic_dataset(
        num_videos=batch * 2, max_frames=6, max_words=10, seed=11
    )
    cfg = get_preset("synthetic_smoke")
    cfg.data.batch_size = batch
    cfg.data.seq_per_img = 2
    cfg.data.max_frames = 6
    cfg.data.max_seq_len = 10
    cfg.train.train_mode = "cst"
    cfg.train.cst_baseline = "scb"
    cfg.train.cst_num_samples = rollouts
    # Big enough that the rollout has real compute to overlap against.
    cfg.model.rnn_size = 512
    cfg.model.vocab_size = len(ds.vocab)
    model = model_from_config(cfg)
    it = BatchIterator(ds, batch_size=batch, seq_per_img=2, max_frames=6,
                       shuffle=False)
    b = next(iter(it.epoch(0)))
    tx = make_optimizer(cfg.train, 10)

    total_rows = batch * rollouts

    # Measure the rollout-only compute the scorer can hide behind.
    import jax.numpy as jnp

    params = model.init(
        jax.random.PRNGKey(0), b.feats, b.feat_masks,
        jnp.ones((batch, 2), jnp.int32),
    )
    roll = jax.jit(lambda p, r: model.apply(
        p, b.feats, b.feat_masks, rng=r, max_len=cfg.data.max_seq_len,
        greedy=False, method="sample", repeat=rollouts,
    ).tokens)
    import numpy as np_mod
    np_mod.asarray(roll(params, jax.random.PRNGKey(1)))
    t0 = time.perf_counter()
    for i in range(3):
        np_mod.asarray(roll(params, jax.random.PRNGKey(2 + i)))
    rollout_ms = (time.perf_counter() - t0) / 3 * 1e3
    if sleep_ms <= 0:
        sleep_ms = round(rollout_ms, 1)

    class SleepyRewarder(CiderDRewarder):
        """Real scorer plus an injected per-row sleep totalling
        ``sleep_ms`` per full-batch scoring pass.  sleep() releases the
        GIL and burns no CPU — like a scorer running in the C++ backend's
        threads, it leaves the device pipeline free."""

        def score_ids(self, video_idx, token_ids):
            time.sleep(sleep_ms / 1e3 * token_ids.shape[0] / total_rows)
            return super().score_ids(video_idx, token_ids)

    rewarder = SleepyRewarder(ds)

    def build(k: int):
        cfg_k = cfg.replace(**{"train.cst_score_chunks": k})
        step = cst_mod._make_split_step(model, cfg_k, rewarder)
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, b._asdict()
        )
        rng = jax.random.PRNGKey(5)
        state, m = step(state, b.feats, b.feat_masks, b.captions,
                        b.weights, None, b.video_idx, rng, 0.0)
        float(m["loss"])  # compile/warm
        return step, [state]

    def sweep(step, box, rep: int) -> float:
        rng = jax.random.fold_in(jax.random.PRNGKey(5), rep)
        times = []
        for i in range(steps):
            k2 = jax.random.fold_in(rng, i)
            t0 = time.perf_counter()
            box[0], m = step(box[0], b.feats, b.feat_masks, b.captions,
                             b.weights, None, b.video_idx, k2, 0.0)
            float(m["loss"])
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2]

    lat = cst_mod.dispatch_latency_ms()
    step1, box1 = build(1)
    stepk, boxk = build(chunks)
    # INTERLEAVED repetitions (VERDICT r4 #8: a single quiet-window run
    # has no spread statement, and CPU co-tenancy noise drifts over
    # time): each rep measures K=1 then K=N back-to-back so a load shift
    # hits both layouts, and mean±sd across reps is recorded.
    t1s, tks = [], []

    def one_rep():
        r = len(t1s)
        t1s.append(sweep(step1, box1, r))
        tks.append(sweep(stepk, boxk, r))

    for _ in range(max(1, reps)):
        one_rep()
    # The rollout is scored (B*S rows) and SCB needs no greedy scoring;
    # K=1 serializes the full sleep, K chunks can hide ~ (K-1)/K of it.
    recoverable = sleep_ms * (chunks - 1) / chunks

    def rec_per_rep():
        return (np.asarray(t1s) - np.asarray(tks)) * 1e3

    # Auto-escalate reps while the spread swamps the signal (sd/|mean|
    # > 0.3, the BENCH_r05 failure mode): co-tenant noise averages out,
    # and if it doesn't, the record says so via ``noisy`` below.
    max_reps = int(os.environ.get(
        "CST_OVERLAP_SIM_MAX_REPS", str(max(9, 3 * max(1, reps)))
    ))
    while (
        len(t1s) > 1 and len(t1s) < max_reps
        and credibility(rec_per_rep(), recoverable)[3]
    ):
        one_rep()

    pp = rec_per_rep()
    t1 = float(np.asarray(t1s).mean())
    tk = float(np.asarray(tks).mean())
    recovered, frac, frac_raw, noisy = credibility(pp, recoverable)
    out = {
        "cst_overlap_sim_dispatch_latency_ms": round(lat, 3),
        "cst_overlap_sim_rollout_compute_ms": round(rollout_ms, 2),
        "cst_overlap_sim_injected_scorer_ms": sleep_ms,
        "cst_overlap_sim_k1_step_ms": round(t1 * 1e3, 2),
        f"cst_overlap_sim_k{chunks}_step_ms": round(tk * 1e3, 2),
        "cst_overlap_sim_recovered_ms": round(recovered, 2),
        "cst_overlap_sim_recoverable_ms": round(recoverable, 2),
        "cst_overlap_sim_recovered_frac": round(frac, 3),
        "cst_overlap_sim_reps": len(t1s),
        # Credibility marker for the driver channel: true when the
        # spread still swamps the signal after rep escalation, or the
        # raw fraction fell outside [0, 1].
        "cst_overlap_sim_noisy": noisy,
    }
    if round(frac_raw, 3) != round(frac, 3):
        out["cst_overlap_sim_recovered_frac_raw"] = round(frac_raw, 3)
    if len(t1s) > 1:
        out["cst_overlap_sim_recovered_ms_sd"] = round(
            float(pp.std(ddof=1)), 2
        )
        out["cst_overlap_sim_recovered_frac_sd"] = round(
            float(pp.std(ddof=1) / recoverable), 3
        ) if recoverable > 0 else 0.0
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser("overlap_sim")
    p.add_argument("--sleep-ms", type=float, default=0.0,
                   help="injected scorer cost per full batch; 0 = "
                        "auto-size to the measured rollout compute")
    p.add_argument("--chunks", type=int, default=4)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--reps", type=int, default=3,
                   help="interleaved K=1/K=N measurement repetitions; "
                        "mean±sd recorded (VERDICT r4 #8)")
    a = p.parse_args(argv)
    print(json.dumps(simulate(a.sleep_ms, a.chunks, a.steps,
                              reps=a.reps)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
