"""Convert per-video feature h5s into the packed contiguous layout.

Reference equivalent: none — the reference reads per-video h5 datasets
every step (SURVEY.md §3 hot loop #3).  This one-shot converter produces
``data/packed.py``'s streaming layout; point ``data.feature_files`` at
the output directory afterwards.

Run::

    python -m cst_captioning_tpu.tools.pack_features \
        --label-file data/msrvtt/labels_train.h5 \
        --features resnet=feats/resnet.h5 c3d=feats/c3d.h5 \
        --out-dir data/msrvtt/packed_train \
        --max-frames 28 --dtype float16

``--max-frames`` should equal the training ``data.max_frames`` — frames
are uniformly subsampled at pack time with the exact loader semantics
(``subsample_frames``), so training batches are bit-identical to the
per-video path.
"""

from __future__ import annotations

import argparse
from typing import Dict

from cst_captioning_tpu.data.packed import pack_modality


def pack_from_h5(
    label_file: str,
    feature_files: Dict[str, str],
    out_dir: str,
    max_frames: int,
    dtype: str = "float32",
) -> Dict[str, str]:
    """Pack every modality, in the label file's video order (so packed
    indices equal dataset indices — no remap needed at load time)."""
    import h5py

    with h5py.File(label_file, "r") as lab:
        vids = [
            v.decode() if isinstance(v, bytes) else str(v)
            for v in lab["video_ids"][()]
        ]
    paths = {}
    for m, p in feature_files.items():
        with h5py.File(p, "r") as f:
            missing = [v for v in vids if v not in f]
            if missing:
                raise ValueError(
                    f"feature h5 {p} is missing {len(missing)} videos "
                    f"(first: {missing[:3]})"
                )
            dim = int(f[vids[0]].shape[-1])
            paths[m] = pack_modality(
                out_dir,
                m,
                vids,
                (f[v][()] for v in vids),
                max_frames,
                dim,
                dtype=dtype,
            )
    return paths


def main(argv=None):
    p = argparse.ArgumentParser("pack_features")
    p.add_argument("--label-file", required=True)
    p.add_argument(
        "--features",
        required=True,
        nargs="+",
        help="modality=path.h5 pairs",
    )
    p.add_argument("--out-dir", required=True)
    p.add_argument("--max-frames", type=int, default=28)
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "float16"])
    a = p.parse_args(argv)
    feature_files = dict(kv.split("=", 1) for kv in a.features)
    paths = pack_from_h5(
        a.label_file, feature_files, a.out_dir, a.max_frames, a.dtype
    )
    for m, path in sorted(paths.items()):
        print(f"{m}: {path}")


if __name__ == "__main__":
    main()
