"""METEOR jar-vs-lite parity harness — one command when a JRE appears.

The reference scores METEOR through ``meteor-1.5.jar`` (SURVEY.md §2
"coco-caption": Java subprocess).  This environment has no JRE and no
jar, so empirical jar-vs-lite numbers have been impossible for four
rounds (VERDICT r3/r4 "METEOR empirical parity").  This harness makes
the measurement a ONE-COMMAND affair the moment both appear:

    METEOR_JAR=/path/to/meteor-1.5.jar \
    python -m cst_captioning_tpu.tools.meteor_jar_diff [preds.json refs.json]

With no arguments it runs a built-in battery of caption-like segment
pairs spanning the matcher stages (exact, stem, synonym, function-word
weighting, fragmentation) plus degenerate cases; with two JSON files
({video_id: caption} and {video_id: [refs...]}) it diffs a real
prediction set.  Output: one JSON line with corpus scores from both
backends, per-segment |delta| stats, and the worst offenders — the
number VERDICT asks for is ``corpus_abs_delta``.

Exit codes: 0 = diff computed; 2 = blocked (no JRE or no jar), with the
blocked reason printed so automation can tell "parity unmeasured" from
"parity failed".
"""

from __future__ import annotations

import json
import shutil
import sys

import numpy as np

from cst_captioning_tpu.metrics.meteor import (
    METEOR_JAR_ENV,
    MeteorJava,
    MeteorLite,
    _find_jar,
)

# Caption-like battery: (hypothesis, [references]).  Cases target the
# matcher stages where lite-vs-jar drift is plausible: stemming, the
# vendored synonym subset vs WordNet, function-word delta weighting,
# chunk fragmentation, and length extremes.
BATTERY = [
    ("a man is playing a guitar", ["a man plays the guitar"]),
    ("a woman is slicing vegetables",
     ["a lady cuts vegetables", "a woman is cutting some vegetables"]),
    ("kids are running in the park",
     ["children run through a park", "young children are jogging outside"]),
    ("a cat sits on the sofa", ["a kitten is sitting on a couch"]),
    ("someone is cooking food in a kitchen",
     ["a person prepares a meal", "a chef cooks food"]),
    ("the quick brown fox", ["the quick brown fox"]),
    ("completely unrelated words here", ["a man is swimming in a pool"]),
    ("a a a a a", ["a man is talking"]),
    ("man guitar", ["a man is playing a guitar loudly on a stage"]),
    ("a man is playing a guitar loudly on a stage at night",
     ["man guitar"]),
    ("a group of people are dancing", ["people dance together"]),
    ("a car is driving down the road fast",
     ["an automobile speeds down a street"]),
]


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    jar = _find_jar()
    if jar is None:
        reason = (
            "no JRE on PATH" if shutil.which("java") is None
            else f"no jar (set {METEOR_JAR_ENV})"
        )
        print(json.dumps({"blocked": reason}))
        return 2

    if len(argv) == 2:
        with open(argv[0]) as f:
            preds = json.load(f)
        with open(argv[1]) as f:
            refs = json.load(f)
        gts = {k: list(refs[k]) for k in preds}
        res = {k: [preds[k]] for k in preds}
    else:
        gts = {f"seg{i}": r for i, (_, r) in enumerate(BATTERY)}
        res = {f"seg{i}": [h] for i, (h, _) in enumerate(BATTERY)}

    java = MeteorJava(jar)
    try:
        corpus_j, seg_j = java.compute_score(gts, res)
    finally:
        java.close()
    lite = MeteorLite.meteor15_en()
    corpus_l, seg_l = lite.compute_score(gts, res)

    delta = np.abs(seg_j - seg_l)
    keys = sorted(gts.keys(), key=str)
    worst = sorted(zip(delta, keys), reverse=True)[:5]
    print(json.dumps({
        "jar": jar,
        "segments": len(keys),
        "corpus_java": round(float(corpus_j), 6),
        "corpus_lite": round(float(corpus_l), 6),
        "corpus_abs_delta": round(abs(float(corpus_j - corpus_l)), 6),
        "seg_abs_delta_mean": round(float(delta.mean()), 6),
        "seg_abs_delta_max": round(float(delta.max()), 6),
        "worst_segments": [
            {"id": k, "delta": round(float(d), 6),
             "hyp": res[k][0], "refs": gts[k]}
            for d, k in worst
        ],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
