"""Offline data-prep CLIs (SURVEY.md §3.4 / L- layer)."""
