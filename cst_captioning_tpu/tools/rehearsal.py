"""Reference-shaped end-to-end rehearsal: the full MSR-VTT pipeline on a
fabricated corpus with the REAL file formats at (scaled) real shapes.

No MSR-VTT/MSVD data exists in this environment (VERDICT r1 missing #2),
so this tool is the closest honest substitute for a real-data run — and
the exact command sequence a real run uses.  It exercises every
production surface end-to-end:

  1. fabricate ``videodatainfo.json`` (msrvtt annotation format: splits,
     categories, 20 captions/video) + one per-video feature h5 per
     modality (resnet-2048, c3d-4096; topic-structured so the captions
     are learnable and CST has real signal);
  2. ``tools/prepare_data``  -> vocab, label h5s, cocofmt GT jsons,
     CIDEr idf table, consensus weights json;
  3. ``tools/pack_features`` -> packed contiguous feature store;
  4. ``cli/pipeline``         -> staged XE -> WXE -> CST_MS (SCB baseline,
     weighted consensus reward) with warm-start chaining;
  5. beam-search eval on the test split against the cocofmt GT.

Swap step 1's fabricated files for the real MSR-VTT bundle and the
remaining steps are unchanged — that IS the real-data recipe.

Run (scaled default: ~2 min on one chip):

    python -m cst_captioning_tpu.tools.rehearsal --out-dir /tmp/rehearsal
        [--videos 200] [--epochs 3] [--feature-dims resnet=2048,c3d=4096]

Prints one JSON line: per-stage best val CIDEr + final test metrics.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, List

import numpy as np

_NOUNS = [
    "cat", "dog", "man", "woman", "car", "ball", "bird", "horse", "child",
    "robot", "chef", "dancer", "player", "singer", "train", "boat",
    "monkey", "girl", "boy", "band",
]
_VERBS = [
    "runs", "jumps", "sings", "drives", "cooks", "plays", "walks", "flies",
    "dances", "sleeps", "swims", "talks", "rides", "draws",
]
_ADVS = ["quickly", "slowly", "happily", "loudly", "quietly", "gracefully",
         "outside", "indoors"]
_PLACES = ["park", "street", "kitchen", "stage", "field", "river", "room",
           "garden"]


def fabricate(
    out_dir: str,
    num_videos: int,
    feature_dims: Dict[str, int],
    caps_per_video: int = 20,
    max_frames_range=(24, 32),
    noise: float = 0.15,
    seed: int = 0,
) -> Dict[str, str]:
    """Write msrvtt-format annotations + per-video feature h5s."""
    import h5py

    os.makedirs(out_dir, exist_ok=True)
    rng = np.random.RandomState(seed)
    n_train = int(num_videos * 0.65)
    n_val = max(1, int(num_videos * 0.1))
    videos, sentences = [], []
    topics: List[tuple] = []
    for i in range(num_videos):
        split = (
            "train" if i < n_train
            else "val" if i < n_train + n_val
            else "test"
        )
        t = (rng.randint(len(_NOUNS)), rng.randint(len(_VERBS)),
             rng.randint(len(_PLACES)))
        topics.append(t)
        videos.append({
            "video_id": f"video{i}",
            "split": split,
            "category": int(t[0] % 20),
        })
        n_i, v_i, p_i = t
        for c in range(caps_per_video):
            words = ["a", _NOUNS[n_i], _VERBS[v_i]]
            if c % 2:
                words.append(_ADVS[(n_i + v_i + c) % len(_ADVS)])
            if c % 3 == 0:
                words += ["in", "the", _PLACES[p_i]]
            sentences.append(
                {"video_id": f"video{i}", "caption": " ".join(words)}
            )
    ann_path = os.path.join(out_dir, "videodatainfo.json")
    with open(ann_path, "w") as f:
        json.dump({"videos": videos, "sentences": sentences}, f)

    # Topic embeddings at real dims (seed-independent so features cluster
    # identically across runs), noisy per-frame copies.
    topic_rng = np.random.RandomState(20260730)
    n_topics = len(_NOUNS) * len(_VERBS) * len(_PLACES)
    feats = {}
    for m, d in feature_dims.items():
        path = os.path.join(out_dir, f"{m}.h5")
        embed = topic_rng.randn(n_topics, d).astype(np.float32)
        with h5py.File(path, "w") as f:
            for i, (n_i, v_i, p_i) in enumerate(topics):
                t = (n_i * len(_VERBS) + v_i) * len(_PLACES) + p_i
                nf = rng.randint(*max_frames_range)
                frames = embed[t][None, :] + noise * rng.randn(nf, d).astype(
                    np.float32
                )
                f.create_dataset(f"video{i}", data=frames.astype(np.float32))
        feats[m] = path
    return {"annotations": ann_path, **feats}


def run(args) -> Dict:
    from cst_captioning_tpu.cli.pipeline import run_pipeline
    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.tools.prepare_data import prepare

    out = args.out_dir
    dims = dict(
        kv.split("=") for kv in args.feature_dims.split(",")
    )
    dims = {m: int(d) for m, d in dims.items()}

    raw = fabricate(os.path.join(out, "raw"), args.videos, dims,
                    seed=args.seed)
    prep = prepare(
        raw["annotations"], "msrvtt", os.path.join(out, "prep"),
        min_freq=1, max_words=args.max_words,
    )
    # ONE packed store over every video: all three splits' datasets share
    # cfg.data.feature_files, and H5Dataset remaps split -> packed indices
    # by video id.
    import h5py

    packed_dir = os.path.join(out, "packed")
    from cst_captioning_tpu.data.packed import pack_modality

    vids_all = [f"video{i}" for i in range(args.videos)]
    for m in dims:
        with h5py.File(raw[m], "r") as f:
            pack_modality(
                packed_dir, m, vids_all, (f[v][()] for v in vids_all),
                args.max_frames, dims[m], dtype="float16",
            )

    cfg = get_preset("msrvtt_resnet_c3d_xe")
    cfg.name = "rehearsal"
    cfg.data.feature_modalities = list(dims)
    cfg.data.feature_dims = dims
    cfg.data.label_file = os.path.join(out, "prep", "labels_{split}.h5")
    cfg.data.vocab_file = prep["vocab"]
    cfg.data.idf_file = prep["idf"]
    cfg.data.consensus_file = os.path.join(
        out, "prep", "consensus_{split}.json"
    )
    cfg.data.cocofmt_files = {
        s: prep[f"cocofmt_{s}"] for s in ("train", "val", "test")
    }
    cfg.data.feature_files = {m: packed_dir for m in dims}
    cfg.data.batch_size = args.batch_size
    cfg.data.max_frames = args.max_frames
    cfg.data.max_seq_len = args.max_words
    cfg.train.checkpoint_dir = os.path.join(out, "checkpoints")
    cfg.train.max_epochs = args.epochs
    cfg.train.max_patience = 0
    cfg.train.cst_num_samples = args.cst_samples
    cfg.train.cst_weighted_reward = True      # driver config 4 regime
    cfg.train.log_every = 50
    cfg.eval.beam_size = args.beam_size
    cfg.eval.max_decode_len = args.max_words
    cfg.eval.metrics = ["Bleu_4", "METEOR", "ROUGE_L", "CIDEr"]
    if args.use_pallas:
        cfg.model.use_pallas_lstm = True

    results = run_pipeline(
        cfg, ["xe", "wxe", "cst"], eval_split="test"
    )
    summary = {
        "videos": args.videos,
        "feature_dims": dims,
        "stages": {},
        "test_scores": results.get("eval", {}).get("scores", {}),
    }
    for stage in ("xe", "wxe", "cst"):
        hist = results.get(stage, {})
        cider = [
            e["val"]["CIDEr"] for e in hist.values()
            if isinstance(e, dict) and "val" in e and "CIDEr" in e["val"]
        ]
        rewards = [
            e["reward"] for e in hist.values()
            if isinstance(e, dict) and "reward" in e
        ]
        summary["stages"][stage] = {
            "best_val_cider": max(cider) if cider else None,
            "final_reward": rewards[-1] if rewards else None,
        }
    return summary


def main(argv=None) -> int:
    p = argparse.ArgumentParser("rehearsal")
    p.add_argument("--out-dir", required=True)
    p.add_argument("--videos", type=int, default=200)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--max-frames", type=int, default=28)
    p.add_argument("--max-words", type=int, default=12)
    p.add_argument("--beam-size", type=int, default=5)
    p.add_argument("--cst-samples", type=int, default=5)
    p.add_argument("--feature-dims", default="resnet=2048,c3d=4096")
    p.add_argument("--use-pallas", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    a = p.parse_args(argv)
    summary = run(a)
    print(json.dumps(summary, default=str))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
