"""Reference-shaped end-to-end rehearsal: the full MSR-VTT pipeline on a
fabricated corpus with the REAL file formats at (scaled) real shapes.

No MSR-VTT/MSVD data exists in this environment (VERDICT r1 missing #2),
so this tool is the closest honest substitute for a real-data run — and
the exact command sequence a real run uses.  It exercises every
production surface end-to-end:

  1. fabricate ``videodatainfo.json`` (msrvtt annotation format: splits,
     categories, 20 captions/video) + one per-video feature h5 per
     modality (resnet-2048, c3d-4096; topic-structured so the captions
     are learnable and CST has real signal);
  2. ``tools/prepare_data``  -> vocab, label h5s, cocofmt GT jsons,
     CIDEr idf table, consensus weights json;
  3. ``tools/pack_features`` -> packed contiguous feature store;
  4. ``cli/pipeline``         -> staged XE -> WXE -> CST_MS (SCB baseline,
     weighted consensus reward) with warm-start chaining;
  5. beam-search eval on the test split against the cocofmt GT.

Swap step 1's fabricated files for the real MSR-VTT bundle and the
remaining steps are unchanged — that IS the real-data recipe.

Run (scaled default: ~2 min on one chip):

    python -m cst_captioning_tpu.tools.rehearsal --out-dir /tmp/rehearsal
        [--videos 200] [--epochs 3] [--feature-dims resnet=2048,c3d=4096]

Prints one JSON line: per-stage best val CIDEr + final test metrics.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, List

import numpy as np

_NOUNS = [
    "cat", "dog", "man", "woman", "car", "ball", "bird", "horse", "child",
    "robot", "chef", "dancer", "player", "singer", "train", "boat",
    "monkey", "girl", "boy", "band",
]
_VERBS = [
    "runs", "jumps", "sings", "drives", "cooks", "plays", "walks", "flies",
    "dances", "sleeps", "swims", "talks", "rides", "draws",
]
_ADVS = ["quickly", "slowly", "happily", "loudly", "quietly", "gracefully",
         "outside", "indoors"]
_PLACES = ["park", "street", "kitchen", "stage", "field", "river", "room",
           "garden"]


# The corpus-wide generic caption: every video carries `generic_refs`
# copies, so MLE's modal decode is this sentence — whose n-grams appear in
# EVERY video's reference set (df = N, idf ~ 0) and therefore score ~0
# CIDEr-D.  This engineers, at rehearsal scale, exactly the failure mode
# the CST paper targets (generic MLE captions vs consensus-scoring
# specific ones): XE gravitates to it, consensus weighting (WXE)
# de-emphasizes it, and the CST reward must escape it entirely.
_GENERIC = ["a", "person", "is", "doing", "something"]

# Branch-trap corpus (VERDICT r3 #1: "build ONE corpus where MLE provably
# cannot reach the ceiling").  Three reference blocks per video:
#
# * 9x GENERIC   "someone is doing something" — corpus-wide (idf ~ 0,
#   consensus weight ~ 0) but the unweighted mode: plain XE decodes it
#   and scores ~0.
# * 8x DECOY     "the NOUN VERBS ADV j1..j8" — a shared VIDEO-SPECIFIC
#   4-word prefix (the adverb is a per-video function of noun+verb), then
#   eight junk words drawn per REFERENCE from a 200-word junk vocabulary.
# * 3x TARGET    "a NOUN VERBS in the PLACE" — identical copies: the
#   highest-scoring decodable caption, reachable from the WXE policy by
#   first-token exploration.
#
# Why the trap holds, with sim_d = decoy-decoy CIDEr, cross =
# decoy-target CIDEr, D/T = decoy/target counts: the weighted first-token
# mass prefers the decoy branch iff D(D-1)·sim_d > T(T-1)·10 (identical
# targets score 10 with each other), while the best decodable decoy-branch
# caption — the infinite-capacity conditional greedy-decodes ONE decoy
# verbatim, since each junk tail uniquely identifies its reference — loses
# to the target iff (D-1)·sim_d < (T-1)·10 + (D-T)·cross.  Both hold for
# (D-1)·sim_d in an open window that D=8, T=3 with a 4-content-word,
# 8-junk-word decoy places sim_d comfortably inside; the corpus-wide junk
# vocabulary keeps junk idf low so the window does not drift with corpus
# size.
#
# The trap is verified ANALYTICALLY per corpus by analyze_mle_optimum():
# the exact per-video conditional of the reference distribution (the
# optimum any MLE stage can converge to, at any capacity) is greedy-
# decoded with and without consensus weights and scored — establishing
# score(XE*) < score(WXE*) < score(target) before any training runs.
_BT_GENERIC = ["someone", "is", "doing", "something"]
_BT_JUNK_VOCAB = 200
_BT_GENERIC_REFS = 9
_BT_DECOY_REFS = 8
_BT_JUNK_LEN = 8


def fabricate(
    out_dir: str,
    num_videos: int,
    feature_dims: Dict[str, int],
    caps_per_video: int = 20,
    max_frames_range=(24, 32),
    noise: float = 0.15,
    seed: int = 0,
    generic_refs: int = 8,
    scene_mix: float = 0.0,
    corpus_kind: str = "v2",
) -> Dict[str, str]:
    """Write msrvtt-format annotations + per-video feature h5s.

    Features are COMPOSITIONAL: each modality's dim is split into three
    slices holding a per-noun / per-verb / per-place embedding, so a
    model can generalize to (noun, verb, place) combinations never seen
    in training — like real ResNet/C3D features and unlike a lookup
    table of independent per-topic vectors (which made val topics
    unlearnable and capped every stage's val CIDEr; round-2 rehearsal).

    References per video: ``generic_refs`` copies of the corpus-wide
    generic caption (modal but consensus-worthless, see ``_GENERIC``)
    plus specific variants ("a NOUN VERBS [ADV] [in the PLACE]"), each
    variant rarer than the generic block.

    ``scene_mix`` > 0 makes videos TWO-scene: each video draws a
    distractor place and a mix fraction ~ U(0, min(scene_mix, 0.5)),
    and that fraction of its frames carries the distractor place's
    embedding slice; captions always name the majority place.  Videos
    with a mix near 0.5 are genuinely ambiguous (frame-averaged place
    evidence is a near-even blend of two centroids), so no MLE stage
    can saturate the val metric and expected-reward optimization (CST)
    has a real include-the-place-clause-or-not decision to make.  The
    scene draws use a SEPARATE rng stream, so scene_mix=0 reproduces
    the unmixed corpus bit-for-bit.
    """
    import h5py

    os.makedirs(out_dir, exist_ok=True)
    rng = np.random.RandomState(seed)
    n_train = int(num_videos * 0.65)
    n_val = max(1, int(num_videos * 0.1))
    videos, sentences = [], []
    topics: List[tuple] = []
    for i in range(num_videos):
        split = (
            "train" if i < n_train
            else "val" if i < n_train + n_val
            else "test"
        )
        t = (rng.randint(len(_NOUNS)), rng.randint(len(_VERBS)),
             rng.randint(len(_PLACES)))
        topics.append(t)
        videos.append({
            "video_id": f"video{i}",
            "split": split,
            "category": int(t[0] % 20),
        })
        n_i, v_i, p_i = t
        for c in range(caps_per_video):
            if corpus_kind == "branch_trap":
                words = _branch_trap_ref(rng, c, n_i, v_i, p_i)
            elif c < generic_refs:
                words = list(_GENERIC)
            else:
                words = ["a", _NOUNS[n_i], _VERBS[v_i]]
                if c % 2:
                    words.append(_ADVS[(n_i + v_i + c) % len(_ADVS)])
                if c % 3 == 0:
                    words += ["in", "the", _PLACES[p_i]]
            sentences.append(
                {"video_id": f"video{i}", "caption": " ".join(words)}
            )
    ann_path = os.path.join(out_dir, "videodatainfo.json")
    with open(ann_path, "w") as f:
        json.dump({"videos": videos, "sentences": sentences}, f)

    # Two-scene plan (distractor place + mix fraction per video), from a
    # separate stream so scene_mix=0 corpora are bit-identical to the
    # unmixed generator.  frac <= 0.5 keeps the captioned place the
    # majority scene.
    scene_plan = []
    if scene_mix > 0.0:
        rng_scene = np.random.RandomState(seed + 77)
        cap = min(float(scene_mix), 0.5)
        for i, (n_i, v_i, p_i) in enumerate(topics):
            q_i = (p_i + 1 + rng_scene.randint(len(_PLACES) - 1)) % len(
                _PLACES
            )
            scene_plan.append((q_i, float(rng_scene.uniform(0.0, cap))))

    # Compositional atom embeddings at real dims (seed-independent so
    # features cluster identically across runs), noisy per-frame copies.
    atom_rng = np.random.RandomState(20260730)
    feats = {}
    for m, d in feature_dims.items():
        path = os.path.join(out_dir, f"{m}.h5")
        dn = dv = d // 3
        dp = d - dn - dv
        noun_emb = atom_rng.randn(len(_NOUNS), dn).astype(np.float32)
        verb_emb = atom_rng.randn(len(_VERBS), dv).astype(np.float32)
        place_emb = atom_rng.randn(len(_PLACES), dp).astype(np.float32)
        with h5py.File(path, "w") as f:
            for i, (n_i, v_i, p_i) in enumerate(topics):
                base = np.concatenate(
                    [noun_emb[n_i], verb_emb[v_i], place_emb[p_i]]
                )
                nf = rng.randint(*max_frames_range)
                frames = base[None, :] + noise * rng.randn(nf, d).astype(
                    np.float32
                )
                if scene_mix > 0.0:
                    # All scene-mix randomness (frame choice AND the
                    # distractor frames' noise) comes from the per-video
                    # scene rng: the main stream is untouched, so mixing
                    # perturbs ONLY place slices vs the unmixed corpus.
                    q_i, frac = scene_plan[i]
                    k = int(round(frac * nf))
                    srng = _scene_rng(seed, i)
                    which = srng.permutation(nf)[:k]
                    frames[which, dn + dv:] = (
                        place_emb[q_i][None, :]
                        + noise * srng.randn(k, dp).astype(np.float32)
                    )
                f.create_dataset(f"video{i}", data=frames.astype(np.float32))
        feats[m] = path
    return {"annotations": ann_path, **feats}


def _scene_rng(seed: int, video: int):
    """Per-video rng for scene-mix frame choices — deterministic and
    identical across modalities so resnet and c3d tell one story."""
    return np.random.RandomState((seed * 1_000_003 + video * 7 + 1)
                                 % (2**31 - 1))


def _branch_trap_ref(rng, c: int, n_i: int, v_i: int, p_i: int):
    """Reference ``c`` of a branch-trap video (see _BT_* block comment)."""
    if c < _BT_GENERIC_REFS:
        return list(_BT_GENERIC)
    if c < _BT_GENERIC_REFS + _BT_DECOY_REFS:
        junk = [
            f"zz{rng.randint(_BT_JUNK_VOCAB)}" for _ in range(_BT_JUNK_LEN)
        ]
        adv = _ADVS[(n_i + v_i) % len(_ADVS)]
        return ["the", _NOUNS[n_i], _VERBS[v_i], adv] + junk
    return ["a", _NOUNS[n_i], _VERBS[v_i], "in", "the", _PLACES[p_i]]


def analyze_mle_optimum(ann_path: str, consensus_path: str,
                        split: str = "val") -> Dict:
    """Exact infinite-capacity MLE analysis of a fabricated corpus.

    The optimum ANY cross-entropy stage can converge to — at any model
    capacity, any epoch budget — is the true conditional of the
    per-video reference distribution (token-level MLE's global optimum).
    That conditional is computable exactly from the corpus: P(tok |
    video, prefix) is the (weighted) frequency of ``tok`` among the
    video's references extending ``prefix``.  Greedy-decoding it gives
    the best caption XE (uniform weights) or WXE (consensus weights)
    greedy decoding can EVER emit; scoring those decodes against the
    split's references with corpus-df CIDEr-D bounds every MLE stage
    from above, before any training runs.

    Returns mean scores for the XE optimum, the WXE optimum, and the
    per-video target caption ("a NOUN VERBS in the PLACE" — the known
    high-consensus candidate a reward-optimizing stage can reach).
    """
    from cst_captioning_tpu.metrics.cider import CiderD

    with open(ann_path) as f:
        ann = json.load(f)
    split_vids = [v["video_id"] for v in ann["videos"]
                  if v["split"] == split]
    refs: Dict[str, List[str]] = {v: [] for v in split_vids}
    for s in ann["sentences"]:
        if s["video_id"] in refs:
            refs[s["video_id"]].append(s["caption"])
    weights: Dict[str, List[float]] = {}
    if os.path.exists(consensus_path):
        with open(consensus_path) as f:
            weights = json.load(f)

    def greedy_conditional(caps: List[str], w: List[float]) -> str:
        """Greedy decode of the exact weighted conditional, max 20 toks."""
        out: List[str] = []
        for _ in range(20):
            mass: Dict[str, float] = {}
            for cap, cw in zip(caps, w):
                toks = cap.split()
                if toks[: len(out)] == out:
                    nxt = toks[len(out)] if len(toks) > len(out) else "</s>"
                    mass[nxt] = mass.get(nxt, 0.0) + cw
            if not mass:
                break
            # Deterministic tie-break (alphabetical) like argmax over a
            # fixed vocab order.
            best = max(sorted(mass), key=lambda k: mass[k])
            if best == "</s>":
                break
            out.append(best)
        return " ".join(out)

    gts = {v: refs[v] for v in split_vids}
    cands = {}
    for kind in ("xe", "wxe", "target"):
        per_video = {}
        for v in split_vids:
            caps = refs[v]
            if kind == "xe":
                per_video[v] = [greedy_conditional(caps, [1.0] * len(caps))]
            elif kind == "wxe":
                w = weights.get(v, [1.0] * len(caps))
                per_video[v] = [greedy_conditional(caps, list(w))]
            else:
                # The identical-copies block is the known high-consensus
                # candidate; recover it as the modal non-generic,
                # non-decoy reference (it appears ``caps_per_video -
                # generic - decoy`` times verbatim).
                from collections import Counter

                filtered = [
                    c for c in caps
                    if not c.startswith("the ") and "someone" not in c
                ] or caps
                per_video[v] = [Counter(filtered).most_common(1)[0][0]]
        cands[kind] = per_video

    scorer = CiderD(df_mode="corpus")
    out = {}
    for kind, per_video in cands.items():
        mean, _ = scorer.compute_score(gts, per_video)
        out[f"{kind}_greedy_optimum_cider"] = round(float(mean), 4)
        out[f"{kind}_example"] = per_video[split_vids[0]][0]
    return out


def run(args) -> Dict:
    from cst_captioning_tpu.cli.pipeline import run_pipeline
    from cst_captioning_tpu.config import get_preset
    from cst_captioning_tpu.tools.prepare_data import prepare

    out = args.out_dir
    dims = dict(
        kv.split("=") for kv in args.feature_dims.split(",")
    )
    dims = {m: int(d) for m, d in dims.items()}

    packed_dir = os.path.join(out, "packed")
    manifest_path = os.path.join(out, "prep", "manifest.json")
    # Everything that shapes the corpus: a --reuse-data arm must match the
    # cached corpus on ALL of these or it would silently sweep over the
    # wrong data while its summary records the new flags.
    corpus_kind = args.corpus.replace("-", "_")
    corpus_args = {
        "videos": args.videos,
        "seed": args.seed,
        "generic_refs": args.generic_refs,
        "scene_mix": args.scene_mix,
        "feature_dims": dims,
        "max_frames": args.max_frames,
        "max_words": args.max_words,
        "corpus_kind": corpus_kind,
    }
    if args.reuse_data and os.path.exists(manifest_path):
        # Hyperparameter-sweep mode: the fabricate/prepare/pack steps are
        # deterministic in the corpus args, so arms sharing an --out-dir
        # reuse the corpus and only retrain their stage(s).
        with open(manifest_path) as f:
            manifest = json.load(f)
        # Manifests written before newer corpus knobs existed imply those
        # knobs' no-op defaults (documented bit-identical corpora).
        manifest["corpus_args"].setdefault("scene_mix", 0.0)
        manifest["corpus_args"].setdefault("corpus_kind", "v2")
        if manifest["corpus_args"] != corpus_args:
            raise ValueError(
                "--reuse-data: cached corpus was built with "
                f"{manifest['corpus_args']}, this run asks for "
                f"{corpus_args} — use a fresh --out-dir"
            )
        prep = manifest["prep"]
    elif args.reuse_data:
        raise FileNotFoundError(
            f"--reuse-data: no corpus manifest at {manifest_path} — run "
            "once without --reuse-data first"
        )
    else:
        raw = fabricate(os.path.join(out, "raw"), args.videos, dims,
                        seed=args.seed, generic_refs=args.generic_refs,
                        scene_mix=args.scene_mix, corpus_kind=corpus_kind)
        prep = prepare(
            raw["annotations"], "msrvtt", os.path.join(out, "prep"),
            min_freq=1, max_words=args.max_words,
        )
        # ONE packed store over every video: all three splits' datasets
        # share cfg.data.feature_files, and H5Dataset remaps split ->
        # packed indices by video id.
        import h5py

        from cst_captioning_tpu.data.packed import pack_modality

        vids_all = [f"video{i}" for i in range(args.videos)]
        for m in dims:
            with h5py.File(raw[m], "r") as f:
                pack_modality(
                    packed_dir, m, vids_all, (f[v][()] for v in vids_all),
                    args.max_frames, dims[m], dtype="float16",
                )
        # Written LAST: its presence certifies prepare+pack completed.
        with open(manifest_path, "w") as f:
            json.dump({"corpus_args": corpus_args, "prep": prep}, f)

    cfg = get_preset("msrvtt_resnet_c3d_xe")
    cfg.name = args.run_name
    if args.train_seed is not None:
        cfg.train.seed = args.train_seed
    cfg.data.feature_modalities = list(dims)
    cfg.data.feature_dims = dims
    cfg.data.label_file = os.path.join(out, "prep", "labels_{split}.h5")
    cfg.data.vocab_file = prep["vocab"]
    cfg.data.idf_file = prep["idf"]
    cfg.train.start_from = args.start_from
    cfg.data.consensus_file = os.path.join(
        out, "prep", "consensus_{split}.json"
    )
    cfg.data.cocofmt_files = {
        s: prep[f"cocofmt_{s}"] for s in ("train", "val", "test")
    }
    cfg.data.feature_files = {m: packed_dir for m in dims}
    cfg.data.batch_size = args.batch_size
    cfg.data.max_frames = args.max_frames
    cfg.data.max_seq_len = args.max_words
    cfg.train.checkpoint_dir = os.path.join(out, "checkpoints")
    cfg.train.max_epochs = args.epochs
    cfg.train.max_patience = 0
    cfg.train.cst_num_samples = args.cst_samples
    cfg.train.cst_weighted_reward = True      # driver config 4 regime
    cfg.train.log_every = 50
    cfg.eval.beam_size = args.beam_size
    cfg.eval.max_decode_len = args.max_words
    cfg.eval.metrics = ["Bleu_4", "METEOR", "ROUGE_L", "CIDEr"]
    if args.use_pallas:
        cfg.model.use_pallas_lstm = True
    if args.fusion:
        cfg.model.feature_fusion = args.fusion
    if args.att_hidden:
        cfg.model.att_hidden_size = args.att_hidden

    stages = [s.strip() for s in args.stages.split(",") if s.strip()]
    # CST sweep knobs (VERDICT r2 #1): override the cst/cst_greedy stage
    # recipe without touching the shared STAGE_RECIPES.
    cst_over = {}
    if args.cst_lr is not None:
        cst_over["train.learning_rate"] = args.cst_lr
    if args.cst_baseline is not None:
        cst_over["train.cst_baseline"] = args.cst_baseline
    if args.cst_temperature is not None:
        cst_over["train.sample_temperature"] = args.cst_temperature
    if args.cst_lr_decay_every is not None:
        cst_over["train.lr_decay_every"] = args.cst_lr_decay_every
    overrides = {s: dict(cst_over) for s in ("cst", "cst_greedy")}

    results = run_pipeline(
        cfg, stages, eval_split="test", stage_overrides=overrides
    )
    summary = {
        "videos": args.videos,
        "feature_dims": dims,
        "run_name": args.run_name,
        "corpus_kind": corpus_kind,
        "train_seed": (
            args.train_seed if args.train_seed is not None
            else cfg.train.seed
        ),
        "cst_overrides": cst_over,
        "model_overrides": {
            k: v for k, v in (
                ("feature_fusion", args.fusion),
                ("att_hidden_size", args.att_hidden),
            ) if v
        },
        "scene_mix": args.scene_mix,
        "stages": {},
        "test_scores": results.get("eval", {}).get("scores", {}),
    }
    if corpus_kind == "branch_trap":
        # The analytic MLE bound (see analyze_mle_optimum): computed per
        # run so the trained stages can be read against the exact optimum
        # any XE/WXE stage could ever reach on this corpus.
        for split in ("val", "test"):
            summary[f"mle_optimum_{split}"] = analyze_mle_optimum(
                os.path.join(out, "raw", "videodatainfo.json"),
                os.path.join(out, "prep", f"consensus_{split}.json"),
                split=split,
            )
    for stage in stages:
        hist = results.get(stage, {})
        cider = [
            e["val"]["CIDEr"] for e in hist.values()
            if isinstance(e, dict) and "val" in e and "CIDEr" in e["val"]
        ]
        rewards = [
            e["reward"] for e in hist.values()
            if isinstance(e, dict) and "reward" in e
        ]
        summary["stages"][stage] = {
            "best_val_cider": max(cider) if cider else None,
            "final_reward": rewards[-1] if rewards else None,
        }
    return summary


def main(argv=None) -> int:
    p = argparse.ArgumentParser("rehearsal")
    p.add_argument("--out-dir", required=True)
    p.add_argument("--videos", type=int, default=200)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--max-frames", type=int, default=28)
    p.add_argument("--max-words", type=int, default=12)
    p.add_argument("--beam-size", type=int, default=5)
    p.add_argument("--cst-samples", type=int, default=5)
    p.add_argument("--feature-dims", default="resnet=2048,c3d=4096")
    p.add_argument("--use-pallas", action="store_true")
    p.add_argument("--fusion", default=None,
                   choices=["meanpool", "attention"],
                   help="override model.feature_fusion")
    p.add_argument("--att-hidden", type=int, default=None,
                   help="override model.att_hidden_size (A-width sweeps)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--train-seed", type=int, default=None,
                   help="training seed (init/shuffle/sampling rng) — "
                        "multi-seed sweeps vary this while --seed keeps "
                        "the corpus fixed")
    p.add_argument("--corpus", default="v2",
                   choices=["v2", "branch-trap"],
                   help="corpus generator: v2 (compositional + generic "
                        "trap) or branch-trap (weighted-MLE provably "
                        "cannot reach the ceiling; see module docs)")
    p.add_argument("--generic-refs", type=int, default=8,
                   help="per-video copies of the corpus-wide generic "
                        "caption (0 = round-2 style corpus)")
    p.add_argument("--scene-mix", type=float, default=0.0,
                   help="fraction of frames showing a distractor place "
                        "(two-scene videos; captions name the majority "
                        "place)")
    # Sweep mode (VERDICT r2 #1): reuse the corpus, train a stage subset,
    # warm-start from an existing checkpoint, tune the CST recipe.
    p.add_argument("--stages", default="xe,wxe,cst",
                   help="comma list from {xe,wxe,cst,cst_greedy}")
    p.add_argument("--run-name", default="rehearsal",
                   help="checkpoint namespace (sweep arms must differ)")
    p.add_argument("--reuse-data", action="store_true",
                   help="reuse out-dir's prep/packed corpus if present")
    p.add_argument("--start-from", default="",
                   help="warm-start checkpoint for the first stage")
    p.add_argument("--cst-lr", type=float, default=None)
    p.add_argument("--cst-baseline", default=None,
                   choices=[None, "greedy", "scb", "gt_consensus", "none"])
    p.add_argument("--cst-temperature", type=float, default=None)
    p.add_argument("--cst-lr-decay-every", type=int, default=None,
                   help="epochs between CST lr decays (0 = constant lr)")
    a = p.parse_args(argv)
    summary = run(a)
    print(json.dumps(summary, default=str))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
