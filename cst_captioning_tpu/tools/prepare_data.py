"""Offline data prep: raw annotations -> vocab, label h5, cocofmt GT,
CIDEr idf table, WXE consensus weights.

Reference equivalent (SURVEY.md §2 "Offline prep", §3.4): the reference's
prep scripts / author-distributed bundles produce, per dataset:
  1. vocab json (frequency threshold, UNK replacement);
  2. per-split label h5 (encoded caption id matrix + per-video index);
  3. per-split cocofmt GT jsons for coco-caption scoring;
  4. CIDEr document-frequency pickle for idf-mode reward scoring;
  5. per-caption consensus CIDEr weights for WXE (each GT caption scored
     with CIDEr-D against its sibling references).

Input formats:
  * ``msrvtt``: the MSR-VTT ``videodatainfo.json`` layout —
    {"videos": [{"video_id", "split", "category"...}],
     "sentences": [{"video_id", "caption"}]}.
  * ``simple``: {"splits": {split: [video_id...]},
     "captions": {video_id: [caption...]},
     "categories": {video_id: int}  (optional)} — covers MSVD/yt2t given
    any csv->json conversion.

Run: ``python -m cst_captioning_tpu.tools.prepare_data --input X.json
--format msrvtt --out-dir data/msrvtt [--min-freq 3] [--max-words 30]``.
Feature h5s are produced by the author-distributed extractors and are
consumed as-is (H5Dataset schema: one (F, D) dataset per video id).
"""

from __future__ import annotations

import argparse
import json
import math
import os
from collections import defaultdict
from typing import Dict, List, Sequence, Tuple

import numpy as np

from cst_captioning_tpu.data.vocab import Vocabulary
from cst_captioning_tpu.metrics.cider import (
    ciderd_score_cooked,
    compute_doc_freq,
    precook,
    save_df,
)
from cst_captioning_tpu.metrics.tokenizer import ptb_tokenize


def load_annotations(path: str, fmt: str) -> Tuple[
    Dict[str, List[str]], Dict[str, List[str]], Dict[str, int]
]:
    """-> (splits: split->video ids, captions: vid->raw strings,
    categories: vid->int)."""
    with open(path) as f:
        raw = json.load(f)
    if fmt == "msrvtt":
        splits: Dict[str, List[str]] = defaultdict(list)
        categories: Dict[str, int] = {}
        for v in raw["videos"]:
            # The real videodatainfo.json names the split "validate";
            # the framework's canonical name is "val" (label/cocofmt
            # file templates, pipeline best-checkpoint selection).
            split = v.get("split", "train")
            split = {"validate": "val"}.get(split, split)
            splits[split].append(v["video_id"])
            categories[v["video_id"]] = int(v.get("category", 0))
        captions: Dict[str, List[str]] = defaultdict(list)
        for s in raw["sentences"]:
            captions[s["video_id"]].append(s["caption"])
        return dict(splits), dict(captions), categories
    if fmt == "simple":
        return (
            raw["splits"],
            raw["captions"],
            {k: int(v) for k, v in raw.get("categories", {}).items()},
        )
    raise ValueError(f"unknown format {fmt!r}")


def consensus_weights(
    tokenized: Sequence[Sequence[str]],
    df=None,
    log_ref_len: float = None,
    normalize: bool = True,
) -> np.ndarray:
    """CIDEr-D of each caption vs its siblings (leave-one-out), the paper's
    WXE consensus score.  ``normalize`` rescales to mean 1.0 per video so
    WXE keeps the same overall loss scale as XE.

    ``df``/``log_ref_len``: CORPUS-level document frequencies (one
    document per video's reference set), as standard CIDEr uses and the
    reference's precomputed df pickle implies.  Falling back to
    per-video df (each sibling its own document) when omitted is kept
    for lone-video corpora only — per-video df INVERTS the weighting on
    corpora with a corpus-wide generic caption: within one video the
    generic block's n-grams look rare-ish (df = #generic of 20) and its
    members validate each other, so the generic refs get the HIGHEST
    weight, while under corpus df (df = every video) they get ~0.  The
    round-3 rehearsal corpus demonstrated exactly this failure: WXE
    with per-video-df weights collapsed val CIDEr to 0 by amplifying
    the generic caption it is meant to suppress.
    """
    # ``tokenized`` may be pre-cooked n-gram counters (from a caller that
    # already cooked the split for its df table) or raw token lists.
    cooked = [
        t if isinstance(t, dict) else precook(t) for t in tokenized
    ]
    n = len(cooked)
    if n < 2:
        return np.ones((n,), np.float32)
    if df is None:
        df = compute_doc_freq([[c] for c in cooked])
        log_ref_len = math.log(max(float(n), 2.0))
    w = np.array(
        [
            ciderd_score_cooked(
                cooked[i], cooked[:i] + cooked[i + 1 :], df, log_ref_len
            )
            for i in range(n)
        ],
        np.float32,
    )
    if normalize:
        mean = float(w.mean())
        w = w / mean if mean > 1e-8 else np.ones_like(w)
    return w


def write_label_h5(
    path: str,
    video_ids: List[str],
    encoded: Dict[str, np.ndarray],
    weights: Dict[str, np.ndarray],
    refs: Dict[str, List[str]],
    categories: Dict[str, int],
) -> None:
    import h5py

    caps, starts, ends, wts = [], [], [], []
    pos = 0
    for vid in video_ids:
        e = encoded[vid]
        caps.append(e)
        starts.append(pos)
        pos += e.shape[0]
        ends.append(pos)
        wts.append(weights[vid])
    with h5py.File(path, "w") as f:
        f.create_dataset("captions", data=np.concatenate(caps, axis=0))
        f.create_dataset("cap_start", data=np.asarray(starts, np.int64))
        f.create_dataset("cap_end", data=np.asarray(ends, np.int64))
        f.create_dataset("weights", data=np.concatenate(wts, axis=0))
        f.create_dataset(
            "category",
            data=np.asarray([categories.get(v, 0) for v in video_ids], np.int32),
        )
        f.create_dataset(
            "video_ids",
            data=np.asarray([v.encode() for v in video_ids]),
        )
        g = f.create_group("refs")
        for vid in video_ids:
            g.create_dataset(
                vid, data=np.asarray([r.encode() for r in refs[vid]])
            )


def write_cocofmt(path: str, video_ids: List[str],
                  refs: Dict[str, List[str]]) -> None:
    """coco-caption ground-truth json (reference "cocofmt" files)."""
    images = [{"id": vid} for vid in video_ids]
    annotations = []
    k = 0
    for vid in video_ids:
        for cap in refs[vid]:
            annotations.append({"image_id": vid, "caption": cap, "id": k})
            k += 1
    with open(path, "w") as f:
        json.dump(
            {
                "images": images,
                "annotations": annotations,
                "type": "captions",
                "info": {"description": "cst_captioning_tpu prep"},
                "licenses": [],
            },
            f,
        )


def prepare(
    input_path: str,
    fmt: str,
    out_dir: str,
    min_freq: int = 1,
    max_words: int = 30,
) -> Dict[str, str]:
    """Run the full prep pipeline; returns the paths written."""
    os.makedirs(out_dir, exist_ok=True)
    splits, captions, categories = load_annotations(input_path, fmt)
    missing = [
        vid
        for vids in splits.values()
        for vid in vids
        if not captions.get(vid)
    ]
    if missing:
        raise ValueError(
            f"{len(missing)} video(s) in the split lists have no captions "
            f"(first few: {missing[:5]}) — fix the annotations before prep"
        )

    tokenized: Dict[str, List[List[str]]] = {
        vid: [ptb_tokenize(c) for c in caps]
        for vid, caps in captions.items()
    }
    train_vids = splits.get("train", [])
    vocab = Vocabulary.build(
        (t for vid in train_vids for t in tokenized[vid]), min_freq=min_freq
    )
    paths = {"vocab": os.path.join(out_dir, "vocab.json")}
    vocab.save(paths["vocab"])

    # CIDEr idf table from the training references (reference idf pickle).
    train_gts = {
        vid: [" ".join(t) for t in tokenized[vid]] for vid in train_vids
    }
    paths["idf"] = os.path.join(out_dir, "cider_idf.pkl")
    save_df(train_gts, paths["idf"])

    for split, vids in splits.items():
        encoded = {
            vid: np.stack(
                [vocab.encode(t, max_words) for t in tokenized[vid]]
            )
            for vid in vids
        }
        # Consensus weights under the SPLIT's corpus document
        # frequencies (one document per video's reference set) — the
        # standard-CIDEr df the paper's consensus score implies.  For
        # the train split this is the same corpus as the idf table.
        # Cook each split once; consensus_weights accepts the cooked
        # counters directly.
        split_cooked = {
            vid: [precook(t) for t in tokenized[vid]] for vid in vids
        }
        split_df = compute_doc_freq(list(split_cooked.values()))
        split_log_ref = math.log(max(float(len(vids)), 2.0))
        weights = {
            vid: consensus_weights(
                split_cooked[vid], df=split_df,
                log_ref_len=split_log_ref,
            )
            for vid in vids
        }
        refs = {vid: captions[vid] for vid in vids}
        lab = os.path.join(out_dir, f"labels_{split}.h5")
        coco = os.path.join(out_dir, f"cocofmt_{split}.json")
        cons = os.path.join(out_dir, f"consensus_{split}.json")
        write_label_h5(lab, list(vids), encoded, weights, refs, categories)
        write_cocofmt(coco, list(vids), refs)
        # Standalone consensus-weight artifact (reference: precomputed WXE
        # CIDEr scores distributed separately) — consumable via
        # ``data.consensus_file`` without re-reading the label h5.
        with open(cons, "w") as f:
            json.dump({v: weights[v].tolist() for v in vids}, f)
        paths[f"labels_{split}"] = lab
        paths[f"cocofmt_{split}"] = coco
        paths[f"consensus_{split}"] = cons
    return paths


def main(argv=None):
    p = argparse.ArgumentParser("prepare_data")
    p.add_argument("--input", required=True)
    p.add_argument("--format", default="msrvtt", choices=["msrvtt", "simple"])
    p.add_argument("--out-dir", required=True)
    p.add_argument("--min-freq", type=int, default=1)
    p.add_argument("--max-words", type=int, default=30)
    a = p.parse_args(argv)
    paths = prepare(a.input, a.format, a.out_dir, a.min_freq, a.max_words)
    for k, v in sorted(paths.items()):
        print(f"{k}: {v}")


if __name__ == "__main__":
    main()
