"""Torch-checkpoint import for parity debugging (SURVEY.md §5
"torch->flax weight-import tool optional for parity debugging" and §7.8).

Maps a PyTorch ``state_dict`` of the reference-style captioner (embedding
+ per-modality linear projections + LSTMCell stack + vocab head +
optional Bahdanau attention MLP) onto this framework's parameter pytree.

Expected torch key layout (the reference's ``model.py`` modules map onto
these; rename keys with ``key_map`` for other layouts):

  embed.weight                  (V, E)        -> word_embed
  feat_proj.<mod>.weight        (E, D_mod)    -> proj_<mod>_w (transposed)
  feat_proj.<mod>.bias          (E,)          -> proj_<mod>_b
  lstm.<l>.weight_ih            (4H, D_in)    -> lstm<l>_w rows [:D_in]
  lstm.<l>.weight_hh            (4H, H)       -> lstm<l>_w rows [D_in:]
  lstm.<l>.bias_ih / bias_hh    (4H,)         -> lstm<l>_b (summed)
  logit.weight                  (V, H)        -> logit_w (transposed)
  logit.bias                    (V,)          -> logit_b
  att_wf.weight / att_wh.weight / att_b / att_v.weight   (attention MLP)
  cat_embed.weight              (C, Ce)       -> cat_embed

Gate order is torch's i|f|g|o — identical to ``ops/rnn.py``, so kernels
import without reordering.  Run:
  python -m cst_captioning_tpu.tools.import_torch --torch ckpt.pth \\
      --config cfg.json --out params/
"""

from __future__ import annotations

import argparse
from typing import Callable, Dict, Optional

import numpy as np


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, np.float32)


def import_torch_state_dict(
    state_dict: Dict[str, object],
    modalities,
    num_layers: int,
    key_map: Optional[Callable[[str], str]] = None,
) -> Dict[str, Dict[str, np.ndarray]]:
    """-> flax-style ``{"params": {...}}`` pytree (numpy leaves)."""
    sd = {
        (key_map(k) if key_map else k): v for k, v in state_dict.items()
    }

    def need(key: str) -> np.ndarray:
        if key not in sd:
            raise KeyError(
                f"torch state_dict missing {key!r}; have "
                f"{sorted(sd)[:10]}..."
            )
        return _np(sd[key])

    p: Dict[str, np.ndarray] = {}
    p["word_embed"] = need("embed.weight")
    for m in modalities:
        p[f"proj_{m}_w"] = need(f"feat_proj.{m}.weight").T
        p[f"proj_{m}_b"] = need(f"feat_proj.{m}.bias")
    for layer in range(num_layers):
        w_ih = need(f"lstm.{layer}.weight_ih")  # (4H, D_in)
        w_hh = need(f"lstm.{layer}.weight_hh")  # (4H, H)
        p[f"lstm{layer}_w"] = np.concatenate([w_ih.T, w_hh.T], axis=0)
        b = need(f"lstm.{layer}.bias_ih") + need(f"lstm.{layer}.bias_hh")
        p[f"lstm{layer}_b"] = b
    p["logit_w"] = need("logit.weight").T
    p["logit_b"] = need("logit.bias")
    if "att_wf.weight" in sd:
        p["att_wf"] = need("att_wf.weight").T
        p["att_wh"] = need("att_wh.weight").T
        p["att_b"] = need("att_b")
        p["att_v"] = need("att_v.weight").T
    if "cat_embed.weight" in sd:
        p["cat_embed"] = need("cat_embed.weight")
    return {"params": p}


def validate_against_model(params, model, sample_inputs) -> None:
    """Shape-check the imported tree against ``model.init``'s structure."""
    import jax

    template = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), *sample_inputs)
    )
    timported = {k: v.shape for k, v in params["params"].items()}
    texpected = {
        k: tuple(v.shape) for k, v in template["params"].items()
    }
    if set(timported) != set(texpected):
        raise ValueError(
            f"param name mismatch: imported-only "
            f"{sorted(set(timported) - set(texpected))}, missing "
            f"{sorted(set(texpected) - set(timported))}"
        )
    bad = {
        k: (timported[k], texpected[k])
        for k in texpected
        if tuple(timported[k]) != texpected[k]
    }
    if bad:
        raise ValueError(f"shape mismatches (imported, expected): {bad}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("import_torch")
    ap.add_argument("--torch", required=True, help="torch .pth checkpoint")
    ap.add_argument("--config", required=True, help="framework config json")
    ap.add_argument("--out", required=True, help="orbax output dir")
    a = ap.parse_args(argv)

    import torch

    from cst_captioning_tpu.config import Config
    from cst_captioning_tpu.models.captioner import model_from_config

    cfg = Config.from_json(a.config)
    sd = torch.load(a.torch, map_location="cpu")
    if isinstance(sd, dict) and "state_dict" in sd:
        sd = sd["state_dict"]
    params = import_torch_state_dict(
        sd, cfg.data.feature_modalities, cfg.model.num_layers
    )
    model = model_from_config(cfg)

    import orbax.checkpoint as ocp
    import os

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(
        os.path.join(os.path.abspath(a.out), "params"), params, force=True
    )
    ckptr.wait_until_finished()
    print(f"imported {len(params['params'])} tensors -> {a.out}")
    return 0


if __name__ == "__main__":
    main()
