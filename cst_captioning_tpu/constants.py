"""Framework-wide token-id convention: 0=PAD, 1=BOS, 2=EOS, 3=UNK, real
words from 4.  PAD and EOS both terminate a sequence when sampled; the end
token slot is included in loss masks, padding after it is not.

Lives in its own dependency-free module so the host-only data layer and the
jax model layer can share it without importing each other.
"""

PAD_ID = 0
BOS_ID = 1
EOS_ID = 2
UNK_ID = 3
NUM_SPECIAL_TOKENS = 4
