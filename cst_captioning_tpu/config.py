"""Typed configuration system.

Replaces the reference's ``opts.py`` (argparse, ~200-400 LoC of flags) and the
``Makefile`` variable layering (dataset / feature set / training stage).  Every
reference flag has a field here; ``docs/PARITY.md`` holds the flag-for-flag
table.  Presets 1-5 mirror the driver acceptance configs (BASELINE.json:6-12).

Knob lifecycle is machine-checked (ISSUE 12, ``analysis/configflow.py``):
every dotted read anywhere in the package must name a field declared
here (CST-CFG-001 — ``Config.from_dict`` validates writes from JSON,
the analysis pass validates reads), every declared field must be read
somewhere (CST-CFG-002) and listed in the docs/ANALYSIS.md knob
catalogue (CST-CFG-003), and presets may only assign declared fields
(CST-CFG-004).  Adding a field means wiring it AND adding its
catalogue row, or the pass goes red.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence


@dataclass
class DataConfig:
    """Data paths and batching — reference ``opts.py`` data flags + ``dataloader.py``.

    The reference opens N feature h5 files (one per modality), a label h5
    (encoded captions + per-video start/end index) and "cocofmt" ground-truth
    JSONs per split.
    """

    dataset: str = "msvd"  # msvd (yt2t) | msrvtt | synthetic
    # One h5 (or .npz shard dir) per feature modality, keyed by modality name.
    feature_files: Dict[str, str] = field(default_factory=dict)
    # Modalities actually fed to the model, in fusion order.
    feature_modalities: List[str] = field(default_factory=lambda: ["resnet"])
    label_file: str = ""          # encoded captions + per-video index
    vocab_file: str = ""          # id -> word json
    cocofmt_files: Dict[str, str] = field(default_factory=dict)  # split -> GT json
    idf_file: str = ""            # CIDEr document-frequency pickle/json
    consensus_file: str = ""      # per-caption WXE consensus CIDEr weights (npy/json)

    batch_size: int = 64          # videos per batch
    seq_per_img: int = 17         # captions sampled per video (20 msrvtt, 17 msvd)
    max_seq_len: int = 30         # tokens incl. BOS/EOS padding target
    max_frames: int = 28          # temporal length features are padded/pooled to
    feature_dims: Dict[str, int] = field(default_factory=lambda: {"resnet": 2048})
    num_categories: int = 20      # MSR-VTT category vocabulary (0 disables)
    shuffle: bool = True
    drop_last: bool = True


@dataclass
class ModelConfig:
    """Decoder architecture — reference ``model.py`` flags in ``opts.py``."""

    vocab_size: int = 0           # filled from vocab at build time
    rnn_size: int = 512           # LSTM hidden size
    num_layers: int = 1           # 1-2 layer LSTM
    input_encoding_size: int = 512  # word/feature embedding dim
    feature_fusion: str = "meanpool"  # meanpool | attention
    att_hidden_size: int = 512    # temporal-attention MLP width
    drop_prob: float = 0.5        # dropout on LM input/output
    scheduled_sampling_start: int = -1   # epoch to start ss (-1 = off)
    scheduled_sampling_increase_every: int = 5
    scheduled_sampling_increase_prob: float = 0.05
    scheduled_sampling_max_prob: float = 0.25
    use_category: bool = False    # MSR-VTT category embedding as extra modality
    category_embed_size: int = 64
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"   # MXU-friendly activations
    use_pallas_lstm: bool = False     # fused Pallas LSTM cell fast path
    # Fused Pallas Bahdanau attention step (attention fusion only) —
    # independent of the LSTM kernel; exact vs the dense math, falls back
    # off-TPU / on untileable batches (ops/pallas_attention.py).
    use_pallas_attention: bool = False
    # Whole-recurrence fused SAMPLER (ops/pallas_sampler.py): the CST
    # rollout / greedy-baseline decode as ONE kernel.  Greedy tokens are
    # bit-identical to the scan path; multinomial draws from the same
    # softmax(logits/T) distribution via a hash-Gumbel stream that
    # differs from the scan path's threefry stream (docs/PARITY.md).
    # model_from_config additionally gates this on a real TPU backend
    # (interpret mode would crawl) and single-device meshes.
    use_pallas_sampler: bool = False
    # Whole-recurrence fused BEAM-SEARCH kernel (ops/pallas_beam.py): the
    # eval/validation beam decode as ONE kernel — attention tensors
    # VMEM-resident across steps, vocab projection streamed in V-tiles
    # with an online per-beam top-K (no (B*K, V) logits array), beam
    # reorder in-kernel.  Token-exact vs decoding/beam.py at float32;
    # tie-order contract in docs/PARITY.md.  model_from_config gates it
    # on a real TPU backend and single-device meshes like the sampler.
    use_pallas_beam: bool = False
    # Bar UNK from the decode policy (sampling, beam search, and the CST
    # PG likelihood).  False = reference parity: the reference sampler can
    # emit UNK, and since both sides vocab-encode references with
    # OOV -> UNK, sampled UNKs can harvest in-loop reward from UNK-encoded
    # reference n-grams (docs/PARITY.md; pinned by
    # tests/test_cst.py::test_unk_reward_channel).
    decode_suppress_unk: bool = False
    # Shard the attention-fusion frame axis over the mesh "model" axis
    # (sequence/context parallelism for long feature streams; requires
    # feature_fusion="attention" and a multi-device mesh).
    shard_frames: bool = False


@dataclass
class TrainConfig:
    """Optimization + regime staging — reference ``train.py`` / ``opts.py``."""

    train_mode: str = "xe"        # xe | wxe | cst
    # CST sub-switches (reference CST_* Makefile targets):
    # greedy (SCST/CST_MS_Greedy) | scb (CST_MS_SCB: leave-one-out rollout
    # mean) | gt_consensus (SURVEY §3.2's alternative SCB reading: the
    # video's mean GT-caption consensus CIDEr-D — docs/PARITY.md) | none
    cst_baseline: str = "greedy"
    cst_num_samples: int = 20     # multinomial rollouts per video (CST_MS)
    # CST_GT_None: the "samples" are the GT captions themselves, weighted by
    # consensus — mathematically the WXE regime; train_mode="cst" with this
    # flag dispatches to the weighted-XE step (trainer._build_steps).
    cst_use_gt: bool = False
    # Weight each reference's CIDEr-D contribution to the CST reward by its
    # consensus weight (driver config 4: "20-ref weighted CIDEr").
    cst_weighted_reward: bool = False
    sample_temperature: float = 1.0
    # Split-step scoring pipeline (backends without io_callback): the
    # rollout is dispatched in this many equal batch chunks, all enqueued
    # on the device back-to-back, and the host CIDEr-D scorer consumes
    # chunk i while chunks i+1..K still compute — device idle shrinks to
    # ~1/K of the scoring time with identical math (every chunk samples
    # from the same params).  1 = unchunked (bit-matches the one-graph
    # rollout stream for a given rng).  Values that don't divide the
    # batch fall back to the largest divisor.
    cst_score_chunks: int = 4
    # Split-step dispatch layout (backends without io_callback):
    #   auto     — probe per-dispatch latency once; high-latency (tunneled)
    #              runtimes take the software-pipelined layout, low-latency
    #              hosts the chunked-scoring layout above.
    #   pipeline — force the pipelined layout: each call dispatches ONE
    #              graph holding [previous step's PG update + this step's
    #              rollout], so a step pays one dispatch round-trip instead
    #              of two (identical math, update boundaries moved; the
    #              trainer flushes the final pending update at epoch ends).
    #   chunked  — force the chunked/two-dispatch layout.
    cst_split_layout: str = "auto"
    # Parallel CIDEr-D reward pool (training/rewards.py::RewardPool):
    # rollout rows shard across this many persistent worker processes,
    # with the corpus document-frequency and cooked-reference tables
    # pickled to the workers once at pool start.  Scores are
    # BIT-IDENTICAL to serial scoring (rows are independent; shards
    # concatenate in order — docs/PARITY.md).  0/1 = serial in-process
    # scoring; ignored when the native C++ scorer (already threaded) is
    # built.
    reward_workers: int = 0
    # CST rollout decode layout:
    #   scan   — the classic fused-scan rollout (model.sample): every
    #            row pads to max_seq_len inside one jitted graph.
    #   slot   — the serving slot machinery reused in training
    #            (training/cst.py::SlotRollout via decoding/core.py):
    #            sampled-rollout and greedy-baseline rows occupy
    #            persistent device slots, exit on EOS, and stream to
    #            the reward scorer as they are harvested — total decode
    #            cost ~ sum(row lengths) instead of rows x L
    #            (docs/PERF.md r10).  Sampling is row-keyed
    #            (fold_in(fold_in(rng, row_id), t)), so slot position /
    #            admission order cannot change any sampled token.
    #   padded — the slot path's bit-twin with every row resident for
    #            the full L steps (bench baseline; same row-keyed
    #            stream, bit-identical losses/params to "slot").
    # NOTE: "scan" and the slot layouts draw from different PRNG
    # streams (batch-threefry vs row-keyed) — same policy distribution,
    # different trajectories (docs/PARITY.md).
    cst_rollout: str = "scan"
    # Decode slots for cst_rollout="slot" (rows, 1 row/slot).  0 = a
    # quarter of the rollout rows (>= 8), so freed slots keep refilling
    # while stragglers run.
    cst_slot_count: int = 0
    # Device decode steps per jitted slot-rollout call (>= 1) — the
    # serving slot_block_steps knob: amortizes dispatch overhead at the
    # price of harvest granularity (frozen rows ride at zero cost).
    cst_slot_block_steps: int = 1
    # Overlapped reward scheduling in the split CST step: feed rollout
    # chunks to the scorer the moment their tokens are fetched (scoring
    # proceeds in pool workers while the greedy-baseline decode still
    # runs on device) and block only at the PG-update dispatch — step
    # time approaches max(t_device, t_score) + t_update instead of the
    # serial sum (docs/PERF.md).  Scheduling only: rewards and updates
    # are bit-identical with this on or off.
    overlap_rewards: bool = True

    optimizer: str = "adam"
    learning_rate: float = 2e-4
    lr_decay: float = 0.5         # multiplicative decay factor
    lr_decay_every: int = 3       # epochs between decays (0 = off)
    grad_clip: float = 10.0       # global-norm clip (0 = off)
    weight_decay: float = 0.0
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    # PRNG implementation for all training randomness.  "rbg" rides the
    # TPU's hardware generator and partitions cleanly under SPMD —
    # threefry2x32 costs ~10ms/step generating the (B,S,T,H) dropout mask
    # alone at MSR-VTT shape (docs/PERF.md) and dominates rollout
    # sampling.  Streams are deterministic per impl but differ across
    # impls; set "threefry2x32" to reproduce older runs bit-for-bit.
    rng_impl: str = "rbg"

    max_epochs: int = 50
    max_patience: int = 5         # early stop on val CIDEr
    eval_every: int = 1           # epochs between val language evals
    save_checkpoint_every: int = 1
    checkpoint_dir: str = "checkpoints"
    start_from: str = ""          # warm-start checkpoint (XE -> WXE -> CST staging)
    resume: bool = False          # continue from <workdir>/last (preemption)
    seed: int = 213

    # Parallelism over the device mesh (reference: .cuda()/DataParallel only).
    mesh_shape: Dict[str, int] = field(default_factory=lambda: {"data": -1, "model": 1})
    remat: bool = False           # jax.checkpoint the decoder scan
    nan_check: bool = False       # debug nan-guard on losses/grads
    profile_dir: str = ""         # jax.profiler trace output ("" = off)
    # Steps the profiler window stays open (trace covers steps
    # 1..1+window of epoch 0) — the trainer-side twin of the serving
    # /debug/profile?ms=N knob.
    profile_window_steps: int = 10
    # Write the span tracer's Chrome-trace JSON here at the end of fit()
    # ("" = off).  PhaseClock phases are spans in the same format the
    # serving /debug/trace export uses, so a CST step and a served
    # request render in one Perfetto timeline.
    trace_file: str = ""
    tensorboard_dir: str = ""     # tf.summary event files ("" = off)
    log_every: int = 20           # steps between loss log lines
    history_file: str = "history.json"


@dataclass
class EvalConfig:
    """Decoding + metric suite — reference ``sample.py`` / ``test.py``."""

    beam_size: int = 5
    max_decode_len: int = 30
    length_normalize: bool = True   # divide beam logprob by length at finalize
    metrics: List[str] = field(
        default_factory=lambda: ["Bleu_4", "METEOR", "ROUGE_L", "CIDEr"]
    )
    eval_split: str = "test"
    out_dir: str = "eval_out"


@dataclass
class ServingConfig:
    """Online caption-serving subsystem (``cst_captioning_tpu/serving/``):
    warm-engine shape ladder, micro-batching scheduler, caches, HTTP
    front end.  No reference equivalent — the reference is batch-only."""

    host: str = "127.0.0.1"
    port: int = 8000              # 0 = ephemeral (tests)
    # Decode backend for served requests: "beam" matches the offline
    # eval path token-exactly (the serving parity contract); "greedy"
    # is the cheaper validation-style decode.
    decode_mode: str = "beam"
    # Continuous in-flight batching (serving/slots.py): a persistent
    # matrix of decode slots stepped one decode step at a time — slots
    # free as soon as their caption hits EOS (short captions exit in
    # ~length steps instead of max_decode_len) and new requests are
    # admitted at the next step boundary instead of the next batch
    # boundary.  False = the PR-2 batch-at-a-time shape ladder.
    continuous: bool = True
    # Decode slots for continuous mode (greedy: 1 row/slot; beam: K
    # contiguous rows/slot).  0 = max_batch_size.  With an elastic bank
    # ladder (slot_bank_min > 0) this is the TOP bank.
    num_slots: int = 0
    # Beam-deduplicated decode-state cache (serving/slots.py): store the
    # read-only projected encoder DecodeCache ONCE per slot ((S, ...)
    # leaves) instead of once per beam row ((S*K, ...)); the jitted step
    # reads the shared copy via the row->slot index.  Cuts decode-state
    # HBM per in-flight beam request ~K x with token-exact output (the
    # replicated rows were identical copies).  False keeps the legacy
    # replicated layout (paired bench rows / regression escape hatch).
    dedup_cache: bool = True
    # Elastic slot-bank ladder: 0 = one fixed bank of num_slots (the
    # PR-3 behavior).  > 0 pages the slot matrix through a pre-jitted
    # doubling ladder [min, 2*min, ..., num_slots]; the decoder grows
    # banks under queue pressure and shrinks after
    # slot_shrink_idle_ticks consecutive underfull ticks — capacity
    # follows traffic with no cold-retrace stall (every transition is
    # compiled at warmup).
    slot_bank_min: int = 0
    # Consecutive underfull ticks (occupancy + queue fits the next bank
    # down) before an elastic shrink; hysteresis against thrash.
    slot_shrink_idle_ticks: int = 8
    # Zero freed/evicted slots' cache + carry rows at free time (one
    # fused mask-select per harvest batch) so the live decode-state
    # byte gauges report resident state honestly, not stale rows.
    zero_freed_slots: bool = True
    # Data-parallel engine replicas (serving/replicas.py): one warm
    # engine + slot decoder per replica, weights device_put once per
    # replica, a least-loaded router in front.  1 = the single-replica
    # scheduler (ContinuousBatcher); 0 = one replica per local device;
    # N > len(devices) wraps round-robin onto the same devices.
    replicas: int = 1
    # Model-sharded serving: ONE logical replica spans this many
    # devices on a (data=1, model=N) mesh — vocab-sized params shard
    # per parallel/partition.py, decode-step logits carry a
    # with_sharding_constraint over the model axis, slot/decode state
    # stays replicated across the shard group (the data axis is 1).
    # 1 = today's per-device replica scaling, byte-identical to the
    # pre-TP engine; > 1 composes with `replicas` into an (R data-
    # parallel replicas) x (M model shards) serving grid: each replica
    # is a model-sharded engine on its own deterministic (1, M)
    # submesh of id-sorted local devices, and R*M must fit the local
    # device count (replicas=0 means one sharded replica per M
    # devices).  Decoded tokens are exact vs model_shards=1: the
    # column-sharded vocab matmul computes each logit column with the
    # same reduction order as the replicated layout (docs/PARITY.md
    # r12/r15).
    model_shards: int = 1
    # Cross-shard fused top-K for the model-sharded slot decode
    # (decoding/core.py::make_tp_beam_topk / make_tp_row_pick): each
    # shard top-Ks its own vocab tile and one O(shards*K) candidate
    # all-gather merges them — instead of the O(V) full-vocab gather
    # XLA inserts for the inline top-K over sharded logits.  Token-
    # exact incl. tie order (docs/PARITY.md r15; the tp2_fused
    # backends pin it in the shared harness).  Requires the vocab to
    # divide model_shards — uneven tiles log a warning and keep the
    # gather path.  False = the PR-9 gather path (paired bench rows).
    shard_fused_decode: bool = True
    # Router policy across replica admission queues: "least_loaded"
    # (most free slots minus queued work wins, round-robin tiebreak) or
    # "round_robin".
    router: str = "least_loaded"
    # Double-buffered tick dispatch in each replica worker: dispatch
    # tick t+1 before harvesting tick t, overlapping host-side
    # harvest/detokenize/admission with device compute.  Costs one
    # extra (frozen, parity-neutral) tick block of latency per caption
    # tail; False = the synchronous one-sync-per-tick loop.
    double_buffer: bool = True
    # Device decode steps per jitted slot-loop call (>=1).  Raising it
    # amortizes per-call dispatch + host-sync overhead at the price of
    # admission/exit granularity (a finished slot rides frozen for up
    # to N-1 extra steps — parity-neutral, the freeze is a no-op).
    slot_block_steps: int = 1
    # Graceful-shutdown drain budget: on SIGTERM/shutdown the server
    # stops admissions (503), lets in-flight work finish for up to this
    # many seconds, then exits.
    drain_timeout_s: float = 30.0
    # Fixed batch shapes the engine pre-jits (ascending).  Empty = a
    # power-of-two ladder 1, 2, 4, ... up to max_batch_size.  Every
    # served batch is padded up to the smallest ladder shape that fits,
    # so the jit cache never grows past the ladder.
    batch_shapes: List[int] = field(default_factory=list)
    max_batch_size: int = 8       # coalescing target (ladder top)
    max_wait_ms: float = 5.0      # micro-batch coalescing window
    queue_depth: int = 256        # bounded request queue (backpressure)
    default_deadline_ms: float = 10_000.0  # per-request deadline
    retry_after_s: float = 0.25   # hint returned on queue-full rejects
    caption_cache_size: int = 4096   # tier-1: content hash -> caption
    feature_cache_size: int = 512    # tier-2: feature id -> encoder state
    # Span tracing (observability/trace.py): host-side spans over the
    # whole request path (request/queue/admit/tick/harvest/detok),
    # exported as Chrome-trace JSON at GET /debug/trace and stamped as
    # exemplar trace_ids on /stats.  Off = every tracer handle is the
    # disabled no-op tracer (the paired trace_overhead_* bench rows
    # measure the difference).
    tracing: bool = True
    # Per-thread finished-span ring size (bounded memory; the export
    # window an operator sees at /debug/trace).
    trace_buffer_spans: int = 4096
    # Flight recorder (observability/flight.py): per-replica ring of
    # recent tick/lifecycle events, live at GET /debug/flight.  Ring
    # length in events:
    flight_events: int = 256
    # Directory flight dumps are written to on worker death,
    # kill_replica, watchdog/drain timeout, and SIGTERM drain.  "" =
    # in-memory ring only (no disk writes — the test/dev default).
    flight_dir: str = ""
    # jax.profiler device-trace output dir for the opt-in
    # GET /debug/profile?ms=N window.  "" disables the endpoint.
    profile_dir: str = ""
    # Request hedging (serving/replicas.py): when a submitted request
    # has produced no result after max(hedge_ms, measured p99 of the
    # total-latency histogram) milliseconds, dispatch a duplicate onto a
    # second healthy replica — first result wins, the losing copy is
    # cancelled at admission (queued) or discarded at harvest
    # (in-flight).  Token-exact by construction: every replica holds
    # byte-identical weights, so either copy decodes the same caption
    # (pinned in tests/test_replicas.py).  0 = hedging off (default; the
    # serve path is byte-identical to the pre-hedging scheduler).
    hedge_ms: float = 0.0
    # Server-side retry budget: how many times a request may be requeued
    # onto survivors across replica deaths before it fails outright —
    # caps the requeue storm a flapping fleet could otherwise amplify
    # (`caption_requeue_overflow_total` counts the cap firing).
    requeue_budget: int = 3
    # Deterministic fault injection (serving/chaos.py).  Empty dict =
    # chaos fully OFF: no ChaosEngine is constructed and the serving
    # path is byte-identical to a chaos-free build (pinned by the
    # no-chaos parity test).  Keys: "seed" (int), "schedule" (list of
    # entries {"site": <FAULT_SITES name>, "at"|"every"|"p": trigger,
    # "replica": optional id, "value": site-specific payload}).  Every
    # site is catalogued in serving/chaos.py::FAULT_SITES and documented
    # in docs/SERVING.md; the CST-RES analysis rules machine-check the
    # call sites.
    chaos: Dict[str, Any] = field(default_factory=dict)
    # Tier-2 byte budget (0 = entry-count bound only).  Projected
    # DecodeCache rows are the largest cached objects — bound the tier
    # by what it actually holds, not how many entries it has; evictions
    # are counted and exported on /metrics.
    feature_cache_bytes: int = 0
    # Elastic autoscaler (serving/autoscaler.py).  Empty dict = OFF: no
    # control loop is constructed and the fleet is statically sized (the
    # pre-PR-13 behavior, byte-identical).  Keys (all optional, see
    # AutoscaleConfig for defaults/semantics): "min_replicas",
    # "max_replicas", "window_ticks", "scale_up_queue_depth",
    # "scale_up_shed", "scale_up_wait_p99_ms", "scale_down_occupancy",
    # "cooldown_ticks", "interval_s".  Decisions are a deterministic
    # function of the observed signal window (pinned by the PR-11
    # virtual-time replay tests); each applied decision lands as an
    # `autoscale` flight event and on the caption_autoscale_* metric
    # families.
    autoscale: Dict[str, Any] = field(default_factory=dict)
    # AOT serving artifacts (serving/artifact.py): how many artifact
    # VERSIONS the loader keeps on disk per artifact root.  Loading an
    # artifact garbage-collects older version directories beyond this
    # count — the ACTIVE (just-loaded) version is never collected.
    artifact_keep: int = 2
    # Low-precision serving fast path (ops/quant.py; docs/SERVING.md
    # "Low-precision serving").  "f32" = byte-identical to the engine
    # before this knob existed (the model's own compute_dtype rules).
    # "bf16" forces bfloat16 activations (f32 accumulation stays pinned
    # per CST-DTY-003; decode decisions stay f32).  "int8w" additionally
    # quantizes the big GEMM weights — vocab projection, embedding rows,
    # LSTM kernels, attention MLP — to int8 with per-channel f32 scales,
    # computed ONCE at engine boot or AOT artifact build; activations
    # run bf16 and every decode DECISION (top-K keys, argmax, Gumbel
    # race) still consumes f32 logits.  Rounding can move tokens: the
    # parity contract is the `relaxed-serving` tier (caption-match rate
    # vs f32 >= the pinned floor, per-caption score gap <= the pinned
    # rtol — analysis/jit_registry.py constants, docs/PARITY.md r17).
    # Serving-only: the trainer never reads this knob.
    dtype: str = "f32"
    # int8w weight calibration (ops/quant.py::quantize_per_channel):
    # "absmax" = per-channel abs-max scaling (the PR-16 behavior,
    # byte-identical); "percentile" clips each channel at its 99.9th
    # |w| percentile before rounding — outlier channels trade a little
    # clipping error for finer resolution on the bulk of the weights.
    # Read once at quantize time (engine boot / artifact build); the
    # chosen scales travel with the quantized tree, so replicas and
    # AOT loads never re-calibrate.
    quant_calibration: str = "absmax"
    # Speculative decode on the slot runtime (decoding/speculative.py;
    # docs/SERVING.md "Speculative decode").  Empty dict = OFF: no
    # draft model is built and the slot decoder is byte-identical to a
    # speculation-free build.  Keys: "draft_k" (proposals per tick,
    # >= 2), "draft_hidden" (draft LSTM width, < model.rnn_size;
    # default 128), "draft_params" (optional .npz from
    # cli/distill_draft.py — absent means truncation-init from the
    # full checkpoint).  Greedy-only; the rejection rule keeps the
    # emitted stream token-exact vs non-speculative greedy
    # (docs/PARITY.md r18), so the knob can only change throughput,
    # never captions.
    speculative: Dict[str, Any] = field(default_factory=dict)
    warmup: bool = True           # pre-jit the whole ladder at startup


@dataclass
class Config:
    data: DataConfig = field(default_factory=DataConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    eval: EvalConfig = field(default_factory=EvalConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)
    name: str = "default"

    # ------------------------------------------------------------------ io
    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Config":
        def build(tp, sub):
            fields = {f.name: f for f in dataclasses.fields(tp)}
            kwargs = {}
            for k, v in sub.items():
                if k not in fields:
                    raise KeyError(f"unknown config key {tp.__name__}.{k}")
                kwargs[k] = v
            return tp(**kwargs)

        return cls(
            data=build(DataConfig, d.get("data", {})),
            model=build(ModelConfig, d.get("model", {})),
            train=build(TrainConfig, d.get("train", {})),
            eval=build(EvalConfig, d.get("eval", {})),
            serving=build(ServingConfig, d.get("serving", {})),
            name=d.get("name", "default"),
        )

    @classmethod
    def from_json(cls, path: str) -> "Config":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def replace(self, **kv) -> "Config":
        """dotted-path override: replace(**{"train.learning_rate": 1e-4})."""
        d = self.to_dict()
        for k, v in kv.items():
            cur = d
            parts = k.split(".")
            for p in parts[:-1]:
                cur = cur[p]
            if parts[-1] not in cur:
                raise KeyError(f"unknown config key {k}")
            cur[parts[-1]] = v
        d["name"] = d.get("name", self.name)
        return Config.from_dict(d)


# --------------------------------------------------------------------------
# Presets — the five driver acceptance configs (BASELINE.json:6-12), plus a
# CPU-runnable synthetic smoke config used by tests and CI.
# --------------------------------------------------------------------------

def _preset_msvd_xe() -> Config:
    """1) MSVD, ResNet-152 feats only, XE loss, 1-layer LSTM-512 (tiny)."""
    c = Config(name="msvd_resnet_xe")
    c.data.dataset = "msvd"
    c.data.feature_modalities = ["resnet"]
    c.data.feature_dims = {"resnet": 2048}
    c.data.seq_per_img = 17
    c.model.num_layers = 1
    c.model.rnn_size = 512
    c.train.train_mode = "xe"
    return c


def _preset_msrvtt_xe() -> Config:
    """2) MSR-VTT, ResNet-152 + C3D feats, XE-loss pretrain."""
    c = Config(name="msrvtt_resnet_c3d_xe")
    c.data.dataset = "msrvtt"
    c.data.feature_modalities = ["resnet", "c3d"]
    c.data.feature_dims = {"resnet": 2048, "c3d": 4096}
    c.data.seq_per_img = 20
    c.train.train_mode = "xe"
    # TPU fast paths on by default for the production presets.  The
    # kernels step aside automatically on untileable shapes and on
    # multi-device meshes (model_from_config); off-TPU, however, they run
    # in Pallas INTERPRET mode — numerically equivalent but orders of
    # magnitude slower than the scan path, acceptable only for tests
    # (ADVICE r4 #4).  Any CPU run of these presets should set
    # use_pallas_lstm = use_pallas_attention = False.  The global
    # ModelConfig defaults stay False so plain CPU tests never pay for
    # interpret-mode kernels by accident.
    c.model.use_pallas_lstm = True
    c.model.use_pallas_attention = True
    c.model.use_pallas_sampler = True
    c.model.use_pallas_beam = True
    return c


def _preset_msrvtt_wxe_cst_gt() -> Config:
    """3) MSR-VTT, WXE warm-start -> CST_GT_None (GT samples, consensus weights)."""
    c = _preset_msrvtt_xe()
    c.name = "msrvtt_wxe_cst_gt_none"
    c.train.train_mode = "wxe"
    c.train.cst_baseline = "none"
    c.train.cst_use_gt = True
    c.train.learning_rate = 1e-4
    c.train.start_from = "checkpoints/msrvtt_resnet_c3d_xe/best"
    return c


def _preset_msrvtt_cst_ms() -> Config:
    """4) MSR-VTT, CST_MS multi-sample consensus (20-ref weighted CIDEr)."""
    c = _preset_msrvtt_xe()
    c.name = "msrvtt_cst_ms_scb"
    c.train.train_mode = "cst"
    c.train.cst_baseline = "scb"
    c.train.cst_num_samples = 20
    c.train.cst_weighted_reward = True  # 20-ref weighted CIDEr reward
    c.train.learning_rate = 1e-4
    c.train.start_from = "checkpoints/msrvtt_wxe_cst_gt_none/best"
    # TPU-VM hosts have many idle cores during CST; shard the in-loop
    # CIDEr-D scorer across 8 worker processes (bit-identical scores)
    # so host scoring stays well under device decode time.  No-op when
    # the native C++ scorer is built (it is already threaded).
    c.train.reward_workers = 8
    return c


def _preset_msrvtt_eval() -> Config:
    """5) MSR-VTT test eval, beam=5, full BLEU/METEOR/ROUGE/CIDEr suite."""
    c = _preset_msrvtt_xe()
    c.name = "msrvtt_eval_beam5"
    c.eval.beam_size = 5
    c.eval.eval_split = "test"
    return c


def _preset_msrvtt_serve() -> Config:
    """Online serving: MSR-VTT checkpoint behind the micro-batching HTTP
    front end (cli/serve.py), beam-5 decode for offline parity.  The
    64-wide ladder top matches the training batch so the fused beam
    kernel's shape gate sees the shapes it was calibrated for."""
    c = _preset_msrvtt_eval()
    c.name = "msrvtt_serve_beam5"
    c.serving.max_batch_size = 64
    c.serving.batch_shapes = [8, 16, 32, 64]
    c.serving.max_wait_ms = 8.0
    c.serving.queue_depth = 1024
    c.serving.caption_cache_size = 65536
    c.serving.feature_cache_size = 4096
    # ~64KB/row projected f32 DecodeCache at MSR-VTT shape; cap the tier
    # at 256MiB of host RAM regardless of entry count.
    c.serving.feature_cache_bytes = 256 * 1024 * 1024
    c.serving.num_slots = 64
    # Elastic decode-state capacity: page the slot matrix through the
    # pre-jitted 8 -> 16 -> 32 -> 64 bank ladder so quiet replicas hold
    # an 8-slot bank's worth of decode-state HBM, not 64 slots' worth.
    c.serving.slot_bank_min = 8
    # Production default: replicate the engine over every local chip
    # (serving/replicas.py) with double-buffered dispatch.
    c.serving.replicas = 0
    # Observability: flight dumps land next to the checkpoints on
    # worker death / kill / SIGTERM drain; /debug/profile is live.
    c.serving.flight_dir = "flight_dumps"
    c.serving.profile_dir = "profiles"
    return c


def _preset_msrvtt_xe_2d() -> Config:
    """MSR-VTT XE pretrain on a REAL 2D (data x model) mesh: vocab-sized
    params + Adam moments shard over a model axis of 2, batch over the
    remaining devices (parallel/partition.py rules; update steps are
    NamedSharding-in/out jits).  The 10,496-token vocab divides every
    power-of-two model axis, so the dominant logit/embedding matmuls
    actually shard instead of falling back to replication.  The fused
    Pallas decode kernels step aside on multi-device meshes
    (model_from_config gate) — docs/PERF.md r12 has the comm-volume
    arithmetic for when the trade wins."""
    c = _preset_msrvtt_xe()
    c.name = "msrvtt_xe_2d"
    c.train.mesh_shape = {"data": -1, "model": 2}
    # Vocab padded at build time stays a multiple of 256 (bench shape);
    # any preset vocab must divide the model axis for the sharding to
    # engage (shard_params falls back to replication otherwise).
    return c


def _preset_msrvtt_serve_tp() -> Config:
    """Model-sharded serving: one logical replica spanning 2 devices on
    a (data=1, model=2) mesh instead of two independent clones — halves
    the per-device vocab-param footprint, serves bigger decoders than
    one device holds.  Token-exact vs the replicated engine
    (docs/PARITY.md r12); the slot decode's per-step top-K runs the
    cross-shard fused candidate merge (shard_fused_decode, PARITY
    r15)."""
    c = _preset_msrvtt_serve()
    c.name = "msrvtt_serve_tp2"
    c.serving.replicas = 1
    c.serving.model_shards = 2
    return c


def _preset_msrvtt_serve_grid() -> Config:
    """Replica x shard serving grid: R=2 data-parallel replicas OF
    M=2-way model-sharded engines — one config, four devices, both
    axes (ISSUE 14).  Each replica lives on its own deterministic
    (1, 2) submesh of the id-sorted local devices; the router,
    hedging, requeue, and autoscaling machinery see ordinary replicas
    whose insides happen to be sharded."""
    c = _preset_msrvtt_serve_tp()
    c.name = "msrvtt_serve_r2xtp2"
    c.serving.replicas = 2
    return c


def _preset_msrvtt_serve_int8w() -> Config:
    """Low-precision serving: the TP2 grid with int8 weight-only
    quantization of the vocab/embedding/LSTM/attention GEMM weights
    (serving.dtype=int8w, ops/quant.py).  Per-device vocab-tile weight
    bytes drop to ~0.25x the f32 TP2 engine (int8 codes; the per-channel
    f32 scales shard with their columns), activations run bf16, decode
    decisions stay f32.  Parity is the `relaxed-serving` tier: caption-
    match floor + score-gap rtol vs f32 on the fixed eval set
    (docs/PARITY.md r17; the lowprec_* bench rows assert it before
    recording)."""
    c = _preset_msrvtt_serve_tp()
    c.name = "msrvtt_serve_int8w_tp2"
    c.serving.dtype = "int8w"
    return c


def _preset_synthetic_smoke() -> Config:
    """CPU-runnable synthetic tiny config (tests / CI / integration)."""
    c = Config(name="synthetic_smoke")
    c.data.dataset = "synthetic"
    c.data.feature_modalities = ["resnet"]
    c.data.feature_dims = {"resnet": 64}
    c.data.batch_size = 8
    c.data.seq_per_img = 3
    c.data.max_seq_len = 12
    c.data.max_frames = 6
    c.model.rnn_size = 32
    c.model.input_encoding_size = 32
    c.model.att_hidden_size = 32
    c.model.drop_prob = 0.0
    c.model.compute_dtype = "float32"
    c.train.max_epochs = 3
    c.train.log_every = 5
    c.eval.beam_size = 3
    c.eval.max_decode_len = 12
    c.serving.max_batch_size = 8
    c.serving.batch_shapes = [2, 4, 8]
    c.serving.max_wait_ms = 20.0
    c.serving.queue_depth = 32
    c.serving.caption_cache_size = 64
    c.serving.feature_cache_size = 16
    c.serving.feature_cache_bytes = 1024 * 1024
    c.serving.num_slots = 4
    # Block of 2 decode steps per slot-loop call: exercises the
    # frozen-ride parity path in tier-1 and halves per-call overhead.
    c.serving.slot_block_steps = 2
    c.serving.drain_timeout_s = 60.0
    return c


PRESETS = {
    "msvd_resnet_xe": _preset_msvd_xe,
    "msrvtt_resnet_c3d_xe": _preset_msrvtt_xe,
    "msrvtt_wxe_cst_gt_none": _preset_msrvtt_wxe_cst_gt,
    "msrvtt_cst_ms_scb": _preset_msrvtt_cst_ms,
    "msrvtt_eval_beam5": _preset_msrvtt_eval,
    "msrvtt_serve_beam5": _preset_msrvtt_serve,
    "msrvtt_xe_2d": _preset_msrvtt_xe_2d,
    "msrvtt_serve_tp2": _preset_msrvtt_serve_tp,
    "msrvtt_serve_r2xtp2": _preset_msrvtt_serve_grid,
    "msrvtt_serve_int8w_tp2": _preset_msrvtt_serve_int8w,
    "synthetic_smoke": _preset_synthetic_smoke,
}


def get_preset(name: str) -> Config:
    if name not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
    return PRESETS[name]()


# --------------------------------------------------------------------------
# argparse bridge — CLI parity with the reference's `python train.py <flags>`.
# Any dataclass field is addressable as --section.field (e.g. --train.learning_rate).
# --------------------------------------------------------------------------

def _add_section(parser: argparse.ArgumentParser, section: str, tp) -> None:
    for f in dataclasses.fields(tp):
        flag = f"--{section}.{f.name}"
        if f.type in ("bool", bool):
            parser.add_argument(flag, type=lambda s: s.lower() in ("1", "true", "yes"),
                                default=None)
        elif f.type in ("int", int):
            parser.add_argument(flag, type=int, default=None)
        elif f.type in ("float", float):
            parser.add_argument(flag, type=float, default=None)
        elif f.type in ("str", str):
            parser.add_argument(flag, type=str, default=None)
        else:  # dict/list fields take JSON literals
            parser.add_argument(flag, type=json.loads, default=None)


def parse_cli(argv: Optional[Sequence[str]] = None) -> Config:
    """Build a Config from `--preset NAME` / `--config FILE` plus overrides."""
    parser = argparse.ArgumentParser("cst_captioning_tpu")
    parser.add_argument("--preset", type=str, default=None)
    parser.add_argument("--config", type=str, default=None, help="JSON config file")
    for section, tp in (("data", DataConfig), ("model", ModelConfig),
                        ("train", TrainConfig), ("eval", EvalConfig),
                        ("serving", ServingConfig)):
        _add_section(parser, section, tp)
    args = parser.parse_args(argv)

    if args.config:
        cfg = Config.from_json(args.config)
    elif args.preset:
        cfg = get_preset(args.preset)
    else:
        cfg = Config()

    overrides = {
        k: v for k, v in vars(args).items()
        if v is not None and k not in ("preset", "config")
    }
    if overrides:
        cfg = cfg.replace(**overrides)
    return cfg
