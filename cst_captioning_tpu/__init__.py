"""cst_captioning_tpu — a TPU-native video-captioning framework.

A from-scratch JAX/XLA/Flax re-design of the capabilities of the reference
``xiadingZ/cst_captioning`` PyTorch codebase (BMVC 2017, "Consensus-based
Sequence Training for Video Captioning", arXiv:1712.09532):

* LSTM caption decoder over pre-extracted video features (ResNet-152, C3D,
  MFCC audio, category embeddings) — reference ``model.py``.
* Three training regimes — reference ``train.py``:
  XE (teacher forcing), WXE / CST_GT_None (consensus-weighted XE), and
  CST_MS (consensus-based self-critical REINFORCE with in-loop CIDEr-D).
* Greedy / multinomial sampling and fixed-shape beam search under ``jit`` —
  reference ``sample.py`` / ``model.py``.
* Vendored pure-Python metric suite (PTB tokenization, BLEU, ROUGE-L,
  CIDEr-D, METEOR) — reference ``coco-caption`` / ``cider`` submodules.
* Data-parallel + tensor-parallel execution over a ``jax.sharding.Mesh``
  (the reference's ``.cuda()`` / ``nn.DataParallel``, rebuilt on ICI
  collectives).

NOTE: at build time ``/root/reference`` was an empty directory (see
SURVEY.md header), so docstring citations refer to the reference's public
layout (file names per SURVEY.md §2) rather than file:line into the mount.
"""

__version__ = "0.1.0"

from cst_captioning_tpu.config import (  # noqa: F401
    Config,
    DataConfig,
    ModelConfig,
    TrainConfig,
    EvalConfig,
    get_preset,
    PRESETS,
)
