"""Crash flight recorder: a bounded in-memory ring of recent events per
replica/scheduler, dumped to disk as JSON at the moments that matter.

Aggregate metrics say a replica died; they cannot say what its last
twenty ticks looked like.  Each serving worker owns one
:class:`FlightRecorder`: every tick appends a tiny event (admits / done
/ occupancy / tick seq), lifecycle transitions append theirs (drain
start, requeues, kill, worker death), and on a trigger — worker death,
``kill_replica``, the drain/watchdog deadline, SIGTERM drain — the ring
is written to ``serving.flight_dir`` together with the tracer's recent
spans for that replica, so an operator can reconstruct the final
seconds after the process is gone.  The live rings are also readable at
``GET /debug/flight`` while the server is up.

Event names are registered in
``observability/trace.py::EVENT_CATALOGUE`` (the span-name discipline;
CST-OBS-002 checks the call sites).  Timestamps are monotonic seconds
on the tracer's base — the one wall-clock reading is the dump-file
header (``wall_time_utc``), taken at dump time so the monotonic
timeline can be anchored to the outside world without any wall-clock
read on the event path (CST-OBS-001).

Thread-safety: ``event`` appends under the recorder's lock (events come
from the owning worker AND from control threads — ``kill_replica``,
``stop``); ``snapshot``/``dump`` take the same lock.  Dumping never
raises into the caller: a flight dump rides failure paths, and a
recorder that cannot write disk must not turn a drain into a crash.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from cst_captioning_tpu.observability.trace import Tracer, registered

_log = logging.getLogger("cst_captioning_tpu.observability")

FLIGHT_SCHEMA_VERSION = 1


class FlightRecorder:
    """See module doc.  One per replica worker / scheduler thread."""

    def __init__(
        self,
        name: str,
        max_events: int = 256,
        out_dir: str = "",
        tracer: Optional[Tracer] = None,
        tags: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.out_dir = out_dir
        self.tracer = tracer
        self.tags = dict(tags or ())
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=int(max_events))
        self._dumps = 0

    # ------------------------------------------------------------- record
    def event(self, name: str, **tags: Any) -> None:
        """Append one event to the ring (monotonic-stamped)."""
        if not registered(name):
            raise ValueError(
                f"flight event name {name!r} is not registered in "
                "observability/trace.py::EVENT_CATALOGUE"
            )
        with self._lock:
            self._events.append((time.monotonic(), name, tags or None))

    # ------------------------------------------------------------- read
    def snapshot(self) -> Dict[str, Any]:
        """The ring as a JSON-ready dict (live ``/debug/flight`` view)."""
        with self._lock:
            events = list(self._events)
            dumps = self._dumps
        return {
            "version": FLIGHT_SCHEMA_VERSION,
            "name": self.name,
            "tags": dict(self.tags),
            "dumps_written": dumps,
            "events": [
                {"t_s": round(t, 6), "event": n, **({"tags": g} if g else {})}
                for t, n, g in events
            ],
        }

    def _recent_spans(self) -> List[Dict[str, Any]]:
        """The tracer's buffered spans belonging to this recorder's
        replica (matched on the recorder's tags, e.g. ``replica``)."""
        if self.tracer is None or not self.tracer.enabled:
            return []
        want = self.tags.get("replica")
        out = []
        for s in self.tracer.spans():
            if want is not None and s["tags"].get("replica") != want:
                continue
            out.append(s)
        return out

    # ------------------------------------------------------------- dump
    def dump(self, reason: str) -> Optional[str]:
        """Write the ring (+ recent spans) to
        ``<out_dir>/flight-<name>-<seq>-<reason>.json``.  No-op when no
        ``out_dir`` is configured; never raises (failure paths call
        this)."""
        if not self.out_dir:
            return None
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            with self._lock:
                self._dumps += 1
                seq = self._dumps
            body = self.snapshot()
            body["reason"] = reason
            body["pid"] = os.getpid()
            # The single wall-clock anchor: lets an operator line the
            # monotonic timeline up with external logs.  Taken HERE (at
            # dump time), never on the event path.
            body["wall_time_utc"] = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            )
            body["monotonic_now_s"] = round(time.monotonic(), 6)
            body["spans"] = self._recent_spans()
            safe = "".join(
                c if c.isalnum() or c in "-_" else "_" for c in reason
            )
            path = os.path.join(
                self.out_dir, f"flight-{self.name}-{seq:03d}-{safe}.json"
            )
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(body, f, indent=1)
            os.replace(tmp, path)
            self.event("dump", reason=reason, path=path)
            _log.warning("flight dump (%s): %s", reason, path)
            return path
        except Exception:  # noqa: BLE001 — dumps ride failure paths
            _log.exception("flight dump failed (%s)", reason)
            return None


def validate_flight_dump(rec: Any) -> Dict[str, Any]:
    """Schema-check one flight dump / snapshot (tests + tooling).
    Returns the record or raises ValueError naming the violation."""

    def fail(msg: str) -> None:
        raise ValueError(f"malformed flight dump: {msg}")

    if not isinstance(rec, dict):
        fail("not a dict")
    for k in ("version", "name", "events"):
        if k not in rec:
            fail(f"missing required key {k!r}")
    if rec["version"] != FLIGHT_SCHEMA_VERSION:
        fail(f"unknown version {rec['version']!r}")
    if not isinstance(rec["events"], list):
        fail("'events' must be a list")
    last_t = None
    for i, ev in enumerate(rec["events"]):
        if not isinstance(ev, dict):
            fail(f"events[{i}] is not an object")
        t = ev.get("t_s")
        if isinstance(t, bool) or not isinstance(t, (int, float)):
            fail(f"events[{i}].t_s must be a number")
        if last_t is not None and t < last_t:
            fail(f"events[{i}] goes backwards in time")
        last_t = t
        if not (isinstance(ev.get("event"), str) and ev["event"]):
            fail(f"events[{i}].event must be a non-empty string")
        if not registered(ev["event"]):
            fail(f"events[{i}].event {ev['event']!r} unregistered")
    if "spans" in rec and not isinstance(rec["spans"], list):
        fail("'spans' must be a list")
    return rec
