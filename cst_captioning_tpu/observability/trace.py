"""Stdlib-only span tracer: one request's (or one train step's) journey
as a tree of timed spans, exportable as Chrome-trace-event JSON.

The serving metrics (``serving/metrics.py``) answer "how is the fleet
doing in aggregate"; this module answers "where did THIS request's 40 ms
go" — queue wait vs admission scatter vs device residency vs
detokenize — and "where did THIS CST step's second go", in one shared
format, so a served request and a train step render side by side in
Perfetto (`https://ui.perfetto.dev`, load the exported JSON).

Design constraints (machine-checked by the CST-OBS analysis family,
docs/ANALYSIS.md):

* **Monotonic clocks only.**  Span times come from ``time.monotonic()``
  — never ``time.time()`` (CST-OBS-001): wall clocks step under NTP and
  a span that goes backwards poisons every downstream duration.  All
  emitters share the one monotonic base, so cross-thread spans line up.
* **Every span name is registered.**  :data:`SPAN_CATALOGUE` is the
  single source of truth (the ``METRIC_FAMILIES`` discipline applied to
  spans): emitting an unregistered name raises at runtime AND fails the
  AST pass (CST-OBS-002), and every entry must appear in
  docs/OBSERVABILITY.md.
* **Host-side only.**  Tracer calls must never be reachable from a
  jit-traced root (CST-OBS-003) — a span inside traced code would
  record trace time once and nothing thereafter.  The serving loops
  record around their dispatch/wait host calls instead; the
  double-buffer handles are what make the host-vs-device split honest.
* **Bounded.**  Finished spans land in per-thread ring buffers
  (``deque(maxlen=...)``): a tracer that is never exported costs O(1)
  memory, and the hot-path cost of one span is two monotonic reads and
  one deque append (no locks on the emit path; the registry lock is
  taken once per thread, at first emission).

Thread-safety: emission is lock-free per thread (each thread owns its
buffer); ``export``/``clear`` take the registry lock and snapshot every
thread's buffer.  Span/trace IDs come from a process-unique prefix
(``os.urandom``) plus an atomic counter — no wall clock, no collisions
across replicas' dumps.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from fnmatch import fnmatchcase
from typing import Any, Dict, Iterator, List, Optional, Tuple

# --------------------------------------------------------------------------
# The span-name registry — the METRIC_FAMILIES discipline applied to spans.
# Every name emitted anywhere in the package must match a family here
# (``*`` stands for a computed segment), carry the component that emits
# it, and be documented in docs/OBSERVABILITY.md.  The CST-OBS-002 rule
# enforces all three; ``Tracer`` additionally refuses unregistered names
# at runtime so a typo cannot ship silently.
SPAN_CATALOGUE: List[Tuple[str, str, str]] = [
    # (pattern, component, help)
    ("request", "serving",
     "root span of one /v1/caption request: submit -> response; its "
     "trace_id is echoed in the X-Trace-Id header and stamped as the "
     "exemplar on the total-latency histogram"),
    ("queue", "serving",
     "enqueue -> start of the admission tick that scattered the request "
     "into a decode slot (scheduler wait)"),
    ("admit", "serving",
     "admission tick start -> scatter complete (encode + slot claim)"),
    ("decode", "serving",
     "decode-slot residency: admission -> harvest fetch (device steps "
     "plus any frozen double-buffer ride)"),
    ("detok", "serving",
     "tokens -> text + tier-1 cache store for one harvested caption"),
    ("batch_decode", "serving",
     "MicroBatcher run-to-completion engine call for one coalesced "
     "batch (ladder fallback path)"),
    ("tick_dispatch", "serving",
     "host side of one slot-loop tick: admission encode + step-block "
     "dispatch; returns before device work completes"),
    ("tick_wait", "serving",
     "blocking wait on a dispatched tick's done flags — the exposed "
     "device-time residual after host/device overlap"),
    ("harvest", "serving",
     "host fetch + unpack of finished slots from one tick handle"),
    ("profile", "serving",
     "/debug/profile jax.profiler window (start -> stop)"),
    ("cst/step", "training",
     "one host-driven CST train step (PhaseClock start -> commit)"),
    ("phase/*", "training",
     "one PhaseClock lap interval inside a CST step (dispatch, "
     "sample_fetch, score, greedy_fetch, score_wait, update)"),
]

# Flight-recorder event names share the registry (an event is a
# zero-duration span in the timeline sense) — CST-OBS-002 checks
# ``FlightRecorder.event`` call sites against the same catalogue.
EVENT_CATALOGUE: List[Tuple[str, str, str]] = [
    ("tick", "flight",
     "one scheduler tick: admits/done/occupied counts + tick seq"),
    ("kill", "flight",
     "kill_replica was invoked on this replica"),
    ("worker_death", "flight",
     "the scheduler/worker thread died (exception recorded)"),
    ("drain_start", "flight",
     "graceful shutdown began: admissions closed, drain running"),
    ("drain_requeue", "flight",
     "requests moved off a dying replica onto survivors (counts)"),
    ("drain_exit", "flight",
     "the worker exited its loop (drain complete or hard stop)"),
    ("watchdog", "flight",
     "the drain/watchdog deadline expired with work still in flight"),
    ("dump", "flight",
     "a flight dump was written to disk (path + reason)"),
    ("shed", "flight",
     "a request was load-shed (priority eviction under overload, "
     "deadline expiry, or requeue-budget overflow); also recorded as a "
     "zero-length span on the request's trace when it carries one"),
    ("hedge", "flight",
     "a hedged duplicate of a slow request was dispatched onto a second "
     "healthy replica (first result wins, loser cancelled)"),
    ("chaos_fault", "flight",
     "a ChaosEngine injection fired at a registered FAULT_SITES site "
     "(serving/chaos.py; site + parameters in the tags)"),
    ("autoscale", "flight",
     "an elastic-autoscaler decision was applied (serving/autoscaler.py:"
     " action, reason, and the before/after replica counts in the tags;"
     " scale-downs additionally leave the PR-4 kill/drain events on the"
     " drained replica's own ring)"),
]

_ALL_PATTERNS = [p for p, _, _ in SPAN_CATALOGUE + EVENT_CATALOGUE]
_EXACT_NAMES = {p for p in _ALL_PATTERNS if "*" not in p}
_WILDCARDS = [p for p in _ALL_PATTERNS if "*" in p]

# Process-unique ID space: 4 random bytes at import + an atomic counter.
# No wall clock (CST-OBS-001) and no collisions when several replicas'
# dumps are merged into one timeline.
_RUN_TAG = os.urandom(4).hex()
_IDS = itertools.count(1)


def registered(name: str) -> bool:
    """Whether ``name`` matches a catalogue family (exact or wildcard)."""
    if name in _EXACT_NAMES:
        return True
    return any(fnmatchcase(name, p) for p in _WILDCARDS)


def new_trace_id() -> str:
    return f"t{_RUN_TAG}-{next(_IDS):x}"


def new_span_id() -> str:
    return f"s{_RUN_TAG}-{next(_IDS):x}"


class _ThreadBuf:
    """One thread's bounded ring of finished spans (owned by that
    thread; export snapshots it under the tracer registry lock — deque
    append/iteration are each atomic under the GIL, and export tolerates
    the one-span race a concurrent append could cause)."""

    def __init__(self, name: str, maxlen: int, thread=None):
        self.name = name
        self.thread = thread
        self.spans: deque = deque(maxlen=maxlen)


class _LiveSpan:
    """Context-manager handle from :meth:`Tracer.span`."""

    __slots__ = ("_tracer", "name", "trace_id", "span_id", "parent_id",
                 "tags", "_t0")

    def __init__(self, tracer, name, trace_id, parent_id, tags):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.tags = tags
        self._t0 = time.monotonic()

    def __enter__(self) -> "_LiveSpan":
        self._tracer._push(self)
        return self

    def __exit__(self, *exc) -> None:
        self._tracer._pop(self)
        self._tracer.record(
            self.name, self._t0, time.monotonic(),
            trace_id=self.trace_id, span_id=self.span_id,
            parent_id=self.parent_id, tags=self.tags,
        )


class _NullSpan:
    """Zero-cost stand-in when the tracer is disabled."""

    name = trace_id = span_id = parent_id = None
    tags: Dict[str, Any] = {}

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Tracer:
    """Thread-safe span recorder with per-thread bounded buffers.

    Two emission APIs:

    * :meth:`span` — a context manager for inline scopes (opens at
      ``__enter__``, records at ``__exit__``; nests per thread, so a
      child opened inside a parent's scope links automatically);
    * :meth:`record` — a completed interval from two already-measured
      ``time.monotonic()`` readings (the serving schedulers measure
      ``t_enqueue``/``t_admit`` anyway; re-measuring would lie).

    ``enabled=False`` turns every call into a cheap no-op — the paired
    ``trace_overhead_*`` bench rows compare the two states.
    """

    def __init__(self, buffer_spans: int = 4096, enabled: bool = True):
        self.enabled = bool(enabled)
        self.buffer_spans = int(buffer_spans)
        self._lock = threading.Lock()
        self._bufs: List[_ThreadBuf] = []
        # Spans of DEAD threads, folded into one shared bounded ring at
        # the next registration: HTTP handler threads live for one
        # request, and their request roots must survive them — while a
        # long-lived server must not leak one buffer per request served.
        self._retired: deque = deque(maxlen=self.buffer_spans)
        self._local = threading.local()
        # Monotonic origin: exported timestamps are relative to tracer
        # creation so Perfetto numbers stay small and human-scaled.
        self._t0 = time.monotonic()

    # ----------------------------------------------------------- plumbing
    def _buf(self) -> _ThreadBuf:
        buf = getattr(self._local, "buf", None)
        if buf is None:
            t = threading.current_thread()
            buf = _ThreadBuf(t.name, self.buffer_spans, thread=t)
            self._local.buf = buf
            with self._lock:
                keep = []
                for b in self._bufs:
                    if b.thread is not None and not b.thread.is_alive():
                        self._retired.extend(
                            (b.name, s) for s in b.spans
                        )
                    else:
                        keep.append(b)
                keep.append(buf)
                self._bufs = keep
        return buf

    def _stack(self) -> List[_LiveSpan]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, span: "_LiveSpan") -> None:
        self._stack().append(span)

    def _pop(self, span: "_LiveSpan") -> None:
        st = self._stack()
        if st and st[-1] is span:
            st.pop()

    def _check(self, name: str) -> None:
        if not registered(name):
            raise ValueError(
                f"span name {name!r} is not registered in "
                "observability/trace.py::SPAN_CATALOGUE — register and "
                "document it (docs/OBSERVABILITY.md) before emitting"
            )

    # ----------------------------------------------------------- emission
    def new_trace_id(self) -> str:
        return new_trace_id()

    def new_span_id(self) -> str:
        return new_span_id()

    def current_span(self) -> Optional[_LiveSpan]:
        st = self._stack()
        return st[-1] if st else None

    def span(
        self,
        name: str,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        tags: Optional[Dict[str, Any]] = None,
    ):
        """Context manager: time the enclosed scope as one span.  With
        no explicit parent, nests under the thread's innermost open
        span (same trace)."""
        if not self.enabled:
            return _NULL_SPAN
        self._check(name)
        cur = self.current_span()
        if parent_id is None and cur is not None:
            parent_id = cur.span_id
            if trace_id is None:
                trace_id = cur.trace_id
        if trace_id is None:
            trace_id = new_trace_id()
        return _LiveSpan(self, name, trace_id, parent_id, dict(tags or ()))

    def record(
        self,
        name: str,
        t0_s: float,
        t1_s: float,
        trace_id: Optional[str] = None,
        span_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        tags: Optional[Dict[str, Any]] = None,
    ) -> Optional[str]:
        """Record a completed span from two ``time.monotonic()``
        readings.  Returns the span id (``None`` when disabled)."""
        if not self.enabled:
            return None
        self._check(name)
        sid = span_id or new_span_id()
        self._buf().spans.append((
            name,
            float(t0_s), float(t1_s),
            trace_id or new_trace_id(),
            sid,
            parent_id,
            dict(tags) if tags else None,
        ))
        return sid

    # ------------------------------------------------------------- export
    def _snapshot(self) -> List[Tuple[str, List[tuple]]]:
        with self._lock:
            live = [(b.name, list(b.spans)) for b in self._bufs]
            retired = list(self._retired)
        grouped: Dict[str, List[tuple]] = {}
        for tname, s in retired:
            grouped.setdefault(tname, []).append(s)
        return list(grouped.items()) + live

    def spans(self) -> Iterator[Dict[str, Any]]:
        """All buffered finished spans as dicts (unordered across
        threads; per-thread order is emission order)."""
        for tname, spans in self._snapshot():
            for name, t0, t1, trace_id, sid, parent, tags in spans:
                yield {
                    "name": name,
                    "t0_s": t0,
                    "t1_s": t1,
                    "trace_id": trace_id,
                    "span_id": sid,
                    "parent_id": parent,
                    "thread": tname,
                    "tags": tags or {},
                }

    def export_chrome_trace(self) -> Dict[str, Any]:
        """Chrome-trace-event JSON (the ``traceEvents`` array format),
        loadable in Perfetto / ``chrome://tracing``.  One complete
        ("ph": "X") event per span; timestamps are microseconds relative
        to tracer creation on the shared monotonic base; one pid per
        process, one tid per emitting thread."""
        pid = os.getpid()
        events: List[Dict[str, Any]] = []
        tids: Dict[str, int] = {}
        for s in self.spans():
            tid = tids.setdefault(s["thread"], len(tids) + 1)
            args = {
                "trace_id": s["trace_id"],
                "span_id": s["span_id"],
            }
            if s["parent_id"]:
                args["parent_id"] = s["parent_id"]
            args.update(s["tags"])
            events.append({
                "name": s["name"],
                "ph": "X",
                "ts": round((s["t0_s"] - self._t0) * 1e6, 3),
                "dur": round(max(s["t1_s"] - s["t0_s"], 0.0) * 1e6, 3),
                "pid": pid,
                "tid": tid,
                "cat": s["name"].split("/", 1)[0],
                "args": args,
            })
        for tname, tid in tids.items():
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": tname},
            })
        return {"displayTimeUnit": "ms", "traceEvents": events}

    def export_json(self) -> str:
        return json.dumps(self.export_chrome_trace())

    def set_buffer_spans(self, n: int) -> None:
        """Re-bound the rings to ``n`` spans (the
        ``serving.trace_buffer_spans`` knob): the shared retired ring
        immediately (newest spans kept), per-thread rings for threads
        that register after the call — live threads own their deques,
        so resizing them in place would race their appends."""
        n = int(n)
        if n <= 0 or n == self.buffer_spans:
            return
        with self._lock:
            self.buffer_spans = n
            self._retired = deque(self._retired, maxlen=n)

    def clear(self) -> None:
        with self._lock:
            bufs = list(self._bufs)
            self._retired.clear()
        for b in bufs:
            b.spans.clear()


# --------------------------------------------------------------------------
# Process-global default tracer.  Subsystems take their handle once at
# construction (``get_tracer() if cfg.serving.tracing else null_tracer()``)
# so the on/off decision is a constructor-time branch, not a hot-path one.

_GLOBAL = Tracer()
_NULL = Tracer(enabled=False)


def get_tracer(buffer_spans: Optional[int] = None) -> Tracer:
    """The process-global tracer.  ``buffer_spans`` (the
    ``serving.trace_buffer_spans`` knob) re-bounds the rings: the
    retired ring immediately (newest spans kept), per-thread rings for
    threads registered after the call."""
    if buffer_spans:
        _GLOBAL.set_buffer_spans(buffer_spans)
    return _GLOBAL


def null_tracer() -> Tracer:
    return _NULL


def validate_chrome_trace(obj: Any) -> Dict[str, Any]:
    """Schema-check one exported Chrome-trace object (the contract the
    export tests and the flight-dump reader rely on).  Returns ``obj``
    or raises ValueError naming the violation."""

    def fail(msg: str) -> None:
        raise ValueError(f"malformed chrome trace: {msg}")

    if not isinstance(obj, dict) or "traceEvents" not in obj:
        fail("not a dict with 'traceEvents'")
    if not isinstance(obj["traceEvents"], list):
        fail("'traceEvents' must be a list")
    for i, ev in enumerate(obj["traceEvents"]):
        if not isinstance(ev, dict):
            fail(f"traceEvents[{i}] is not an object")
        for k in ("name", "ph", "pid", "tid"):
            if k not in ev:
                fail(f"traceEvents[{i}] missing {k!r}")
        if ev["ph"] == "X":
            for k in ("ts", "dur"):
                v = ev.get(k)
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    fail(f"traceEvents[{i}].{k} must be a number")
            if ev["dur"] < 0:
                fail(f"traceEvents[{i}] has negative duration")
            args = ev.get("args")
            if not isinstance(args, dict) or "trace_id" not in args:
                fail(f"traceEvents[{i}].args must carry trace_id")
            if not registered(ev["name"]):
                fail(f"traceEvents[{i}] name {ev['name']!r} unregistered")
    return obj
