"""End-to-end observability: span tracing, a crash flight recorder, and
profiler hooks — one timeline format for serving and training.

* ``trace``  — stdlib-only thread-safe span tracer (trace_id / span_id /
  parent links, monotonic clocks, bounded per-thread ring buffers) with
  Chrome-trace-event JSON export loadable in Perfetto, and the
  ``SPAN_CATALOGUE`` registry every emitted span name must be in
  (machine-checked by the CST-OBS analysis family).
* ``flight`` — per-replica ring-buffer flight recorder of recent spans +
  events, dumped to disk on worker death, ``kill_replica``, watchdog
  timeout, and SIGTERM drain; readable live at ``GET /debug/flight``.

Serving wires spans through the whole request path (``serving/server.py``
opens a root span per request; the slot loop records the host-side
dispatch/wait/harvest split) and training joins the same format
(``training/steps.py::PhaseClock`` phases are spans), so one Perfetto
timeline can show a CST step next to a served request.  Catalogue,
endpoints, and how to read the timeline: docs/OBSERVABILITY.md.
"""

from cst_captioning_tpu.observability.flight import (  # noqa: F401
    FlightRecorder,
    validate_flight_dump,
)
from cst_captioning_tpu.observability.trace import (  # noqa: F401
    EVENT_CATALOGUE,
    SPAN_CATALOGUE,
    Tracer,
    get_tracer,
    null_tracer,
    validate_chrome_trace,
)
