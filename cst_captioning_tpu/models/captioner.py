"""Multi-modal LSTM caption decoder, TPU-first.

Reference behavior being rebuilt (SURVEY.md §2 "Caption model", §3.1-3.2):
``model.py``'s ``CaptionModel`` embeds pre-extracted per-modality video
features (linear projection each), fuses them by temporal mean-pooling or
per-step temporal soft attention, runs a 1-2 layer LSTM-512 decoder with a
vocab softmax head, and exposes teacher-forced ``forward`` (with scheduled
sampling) plus autoregressive ``sample`` (greedy / multinomial with
temperature) returning sequences and per-token log-probabilities.

TPU-first design decisions (deliberately NOT a torch translation):
* The per-timestep Python loop (reference hot loop #1) is ``lax.scan``; the
  whole forward is one traced graph.
* Parameters are created in ``setup`` as raw arrays (``self.param``) and the
  scan bodies are pure closures over them — no module calls inside scan, so
  the same step function serves teacher forcing, sampling, and beam search
  (``init_decode`` / ``decode_one``) without retracing linen machinery.
* The vocab projection is applied to the whole (B, T, H) hidden sequence
  after the scan — one large MXU matmul instead of T small ones.
* Activations run in ``compute_dtype`` (bfloat16 by default); LSTM cell
  state and all softmax/loss math stay float32.
* Fixed shapes everywhere: ``sample`` runs exactly ``max_len`` steps with a
  finished-mask; there is no data-dependent Python control flow.

Token id convention (framework-wide): 0=PAD, 1=BOS, 2=EOS, 3=UNK, words
from 4.  PAD and EOS both terminate a sequence when sampled; the end token
slot is included in loss masks, padding after it is not.
"""

from __future__ import annotations

import functools
import logging
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from cst_captioning_tpu.constants import (  # noqa: F401  (re-exported)
    BOS_ID,
    EOS_ID,
    NUM_SPECIAL_TOKENS,
    PAD_ID,
    UNK_ID,
)
from cst_captioning_tpu.decoding.core import (  # noqa: F401  (re-exported)
    DecodeState,
    all_done,
    decode_step,
    init_core,
)
from cst_captioning_tpu.ops.quant import dequant_rows, quant_matmul
from cst_captioning_tpu.ops.rnn import (
    LSTMWeights,
    lstm_bias_init,
    lstm_kernel_init,
    lstm_step,
)

_log = logging.getLogger("cst_captioning_tpu.models")


def warn_fused_decline(kind: str, reason: str) -> None:
    """One log line whenever a requested ``use_pallas_*`` fast path is
    gated off (VERDICT r5 #4: a 2-layer or oddly-shaped config silently
    took the slow path and the perf story evaporated without a trace).
    Called at trace/build time, so it fires once per compiled config."""
    _log.warning(
        "%s requested but gated off: %s — using the scan path",
        kind, reason,
    )


class SampleOutput(NamedTuple):
    tokens: jax.Array    # (B, L) int32 — sampled ids, PAD after the end token
    logprobs: jax.Array  # (B, L) float32 — log p of each sampled token (0 after end)
    mask: jax.Array      # (B, L) float32 — 1 up to and including the end token


# DecodeState lives in decoding/core.py (the unified decode runtime)
# and is re-exported above for the many existing importers.


class DecodeCache(NamedTuple):
    """Per-video tensors fixed across decode steps."""

    ctx_static: jax.Array  # (B, E) mean-pooled fused context (meanpool mode)
    att_vals: jax.Array    # (B, F, E) projected frame features (attention mode)
    att_proj: jax.Array    # (B, F, A) pre-projected attention keys
    att_mask: jax.Array    # (B, F) frame validity
    cat_emb: jax.Array     # (B, C) category embedding ((B, 0) when unused)


def _repeat_cache(cache: DecodeCache, repeat: int) -> DecodeCache:
    """Tile each per-video cache row ``repeat`` times (row i -> rows
    i*repeat..(i+1)*repeat-1, matching ``jnp.repeat`` on the raw batch).

    This is THE seq_per_img / rollout fan-out: projecting B videos' raw
    features and repeating the (much smaller) projected cache does ~S x
    less GEMM work than repeating the raw (B, F, 2048/4096) features
    before the projections — at MSR-VTT shape (S=20) the projections are
    ~25% of step FLOPs when done after the repeat and ~1% when done
    before it, with bit-identical forward results (each row's GEMM is
    row-independent)."""
    if repeat <= 1:
        return cache
    return DecodeCache(
        *(jnp.repeat(x, repeat, axis=0) for x in cache)
    )


def _uniform_init(scale: float):
    def init(key, shape, dtype):
        return jax.random.uniform(key, shape, dtype, -scale, scale)

    return init


class CaptionModel(nn.Module):
    """See module docstring.  Field semantics follow ``ModelConfig``."""

    vocab_size: int
    rnn_size: int = 512
    num_layers: int = 1
    embed_size: int = 512
    fusion: str = "meanpool"            # meanpool | attention
    att_hidden_size: int = 512
    drop_prob: float = 0.5
    modalities: Tuple[str, ...] = ("resnet",)
    feature_dims: Tuple[int, ...] = (2048,)
    use_category: bool = False
    num_categories: int = 20
    category_embed_size: int = 64
    compute_dtype: str = "bfloat16"
    param_dtype: str = "float32"
    # int8 weight-only serving fast path (serving.dtype=int8w; ops/quant.py).
    # When set, the large GEMM weights (word_embed, logit_w, lstm*_w,
    # att_wf/att_wh) are EXPECTED to arrive as int8 codes with per-channel
    # float32 `<name>_scale` sibling leaves (declared in setup below, filled
    # by quant.quantize_params at engine boot or artifact build), and the
    # cdt-surface methods (_encode/_context/_step/_logits) apply them via
    # the scale-after-f32-accumulation helpers.  The fused Pallas paths
    # COMPOSE with this flag: the kernels stream the int8 code tiles plus
    # their scale rows and dequantize in-kernel with the same
    # quant_matmul semantics (ops/pallas_sampler.py, ops/pallas_beam.py,
    # ops/pallas_lstm.py, ops/pallas_attlstm.py), so int8w keeps the
    # VMEM-resident recurrence AND the 0.25x vocab tile.  Decisions stay
    # f32; parity is the `relaxed-serving` tier (analysis/jit_registry.py).
    # Fresh `init` still produces float weights + ones scales — the quant
    # branch is numerically the bf16 path until quantize_params runs.
    weight_quant: bool = False
    use_pallas: bool = False      # fused LSTM recurrence kernel fast path
    use_pallas_attention: bool = False  # fused Bahdanau attention step kernel
    # Whole-recurrence fused SAMPLER kernel (ops/pallas_sampler.py): the
    # CST rollout/greedy decode runs as one kernel (attention + LSTM +
    # streamed vocab logits + in-kernel sampling).  Greedy tokens are
    # bit-identical to the scan path at float32; under bf16 the kernel's
    # f32 state carry is slightly MORE precise, so rare near-tie greedy
    # picks may differ.  Multinomial draws from the same distribution
    # via a hash-Gumbel stream that differs from the scan path's
    # threefry stream (docs/PARITY.md).
    use_pallas_sampler: bool = False
    # Whole-recurrence fused BEAM-SEARCH kernel (ops/pallas_beam.py): the
    # eval beam decode runs as one kernel (attention + LSTM + streamed
    # vocab logits with an online per-beam top-K + in-kernel beam
    # reorder).  Token-exact vs decoding/beam.py at float32 (pinned);
    # the residual daylight is <1-ulp float-association at top-K tie
    # boundaries (docs/PARITY.md).  model_from_config gates this on a
    # real TPU backend and single-device meshes like the sampler.
    use_pallas_beam: bool = False
    # Tensor-parallel decode (ops/shard_decode.py): when set (a
    # jax.sharding.Mesh whose ``decode_axis`` size is > 1), the fused
    # beam/sampler paths dispatch to their shard_map port — each shard
    # streams its vocab tile and a cross-shard top-K candidate merge
    # (O(shards·K) bytes/step vs the forbidden O(V) gather) produces
    # globally exact tokens.  Gated by model_from_config through the
    # DECODE_KERNEL_CAPS table (decoding/core.py); requires V divisible
    # by the axis size (shard_decode_ok).
    decode_mesh: Optional[object] = None   # jax.sharding.Mesh (static)
    decode_axis: str = "model"
    # Bar UNK from the decode policy (sampling/beam/PG likelihood).  False
    # = reference parity; see mask_decode_logits.
    decode_suppress_unk: bool = False
    remat: bool = False       # rematerialize the decoder scan body
    # Frame/sequence parallelism (parallel/ring.py): shard the concatenated
    # frame axis of attention fusion over ``frame_axis`` of ``frame_mesh``;
    # each decode step does local scoring + one psum instead of holding
    # every frame on every device.  Exact vs dense (tests/test_ring.py).
    shard_frames: bool = False
    frame_mesh: Optional[object] = None     # jax.sharding.Mesh (static)
    frame_axis: str = "model"
    frame_batch_axis: Optional[str] = None  # compose with DP batch axis

    # ---------------------------------------------------------------- setup
    def setup(self):
        assert len(self.modalities) == len(self.feature_dims)
        pdt = jnp.dtype(self.param_dtype)
        E, H, A, V = (
            self.embed_size,
            self.rnn_size,
            self.att_hidden_size,
            self.vocab_size,
        )
        self.word_embed = self.param(
            "word_embed", _uniform_init(0.1), (V, E), pdt
        )
        self.proj_w = [
            self.param(f"proj_{m}_w", nn.initializers.glorot_uniform(), (d, E), pdt)
            for m, d in zip(self.modalities, self.feature_dims)
        ]
        self.proj_b = [
            self.param(f"proj_{m}_b", nn.initializers.zeros_init(), (E,), pdt)
            for m in self.modalities
        ]
        if self.fusion == "attention":
            self.att_wf = self.param(
                "att_wf", nn.initializers.glorot_uniform(), (E, A), pdt
            )
            self.att_wh = self.param(
                "att_wh", nn.initializers.glorot_uniform(), (H, A), pdt
            )
            self.att_b = self.param("att_b", nn.initializers.zeros_init(), (A,), pdt)
            self.att_v = self.param(
                "att_v", nn.initializers.glorot_uniform(), (A, 1), pdt
            )
        if self.use_category:
            self.cat_embed = self.param(
                "cat_embed",
                _uniform_init(0.1),
                (self.num_categories, self.category_embed_size),
                pdt,
            )
        in_dim = E + E + (self.category_embed_size if self.use_category else 0)
        lstm = []
        for layer in range(self.num_layers):
            d_in = in_dim if layer == 0 else H
            w = self.param(
                f"lstm{layer}_w", lstm_kernel_init, (d_in + H, 4 * H), pdt
            )
            b = self.param(f"lstm{layer}_b", lstm_bias_init, (4 * H,), pdt)
            lstm.append(LSTMWeights(w=w, b=b))
        self.lstm = lstm
        self.logit_w = self.param(
            "logit_w", nn.initializers.glorot_uniform(), (H, V), pdt
        )
        self.logit_b = self.param("logit_b", nn.initializers.zeros_init(), (V,), pdt)
        if self.weight_quant:
            # Per-channel dequant scales for the int8 serving path —
            # ordinary param leaves (always float32, whatever param_dtype
            # says) so they checkpoint, shard (parallel/partition.py pins
            # each to its weight's spec), and fingerprint like weights.
            # Ones at init: quant.quantize_params overwrites them together
            # with the int8 codes at engine boot / artifact build.
            ones = nn.initializers.ones_init()
            self.word_embed_scale = self.param(
                "word_embed_scale", ones, (V,), jnp.float32
            )
            self.logit_w_scale = self.param(
                "logit_w_scale", ones, (V,), jnp.float32
            )
            self.lstm_scales = [
                self.param(f"lstm{layer}_w_scale", ones, (4 * H,), jnp.float32)
                for layer in range(self.num_layers)
            ]
            if self.fusion == "attention":
                self.att_wf_scale = self.param(
                    "att_wf_scale", ones, (A,), jnp.float32
                )
                self.att_wh_scale = self.param(
                    "att_wh_scale", ones, (A,), jnp.float32
                )

    # ------------------------------------------------------------- encoding
    def _encode(
        self,
        feats: Dict[str, jax.Array],
        feat_masks: Dict[str, jax.Array],
        category: Optional[jax.Array],
    ) -> DecodeCache:
        """Project each modality to the shared embed dim and build the cache.

        ``feats[m]``: (B, F_m, D_m); ``feat_masks[m]``: (B, F_m) in {0,1}.
        Mean-pool context averages masked frames per modality, then averages
        modalities (keeps scale independent of modality count).  Attention
        values concatenate all modalities' frames along time.
        """
        cdt = jnp.dtype(self.compute_dtype)
        vals, masks, means = [], [], []
        for i, m in enumerate(self.modalities):
            f = feats[m].astype(cdt)
            v = (
                jnp.matmul(
                    f, self.proj_w[i].astype(cdt),
                    preferred_element_type=jnp.float32,
                )
                + self.proj_b[i].astype(jnp.float32)
            ).astype(cdt)
            fm = feat_masks[m].astype(jnp.float32)
            denom = jnp.maximum(fm.sum(-1, keepdims=True), 1.0)
            mean = (v.astype(jnp.float32) * fm[..., None]).sum(1) / denom
            vals.append(v)
            masks.append(fm)
            means.append(mean)
        ctx_static = (sum(means) / len(means)).astype(cdt)
        att_vals = jnp.concatenate(vals, axis=1)
        att_mask = jnp.concatenate(masks, axis=1)
        if self.fusion == "attention":
            if self.weight_quant:
                # int8 att_wf with per-output-unit scales applied after
                # the pinned f32 accumulation (ops/quant.py).
                att_proj = (
                    quant_matmul(att_vals, self.att_wf, self.att_wf_scale)
                    + self.att_b.astype(jnp.float32)
                ).astype(cdt)
            else:
                att_proj = (
                    jnp.matmul(
                        att_vals, self.att_wf.astype(cdt),
                        preferred_element_type=jnp.float32,
                    )
                    + self.att_b.astype(jnp.float32)
                ).astype(cdt)
        else:
            att_proj = jnp.zeros(att_vals.shape[:2] + (0,), cdt)
        if self.use_category:
            if category is None:
                raise ValueError(
                    "model was built with use_category=True but no `category` "
                    "ids were passed — a zeroed embedding would silently "
                    "degrade decoding"
                )
            cat_emb = self.cat_embed.astype(cdt)[category]
        else:
            cat_emb = jnp.zeros((att_vals.shape[0], 0), cdt)
        return DecodeCache(
            ctx_static=ctx_static,
            att_vals=att_vals,
            att_proj=att_proj,
            att_mask=att_mask,
            cat_emb=cat_emb,
        )

    def _context(self, cache: DecodeCache, h_top: jax.Array) -> jax.Array:
        """Per-step fused context: static mean-pool, or soft attention
        queried by the previous top-layer hidden state (Bahdanau MLP —
        reference ``model.py`` attention, SURVEY.md §2)."""
        if self.fusion != "attention":
            return cache.ctx_static
        cdt = jnp.dtype(self.compute_dtype)
        # f32 accumulation pinned (CST-DTY-003): under a bf16 compute
        # dtype the query GEMM must not accumulate in bf16.
        if self.weight_quant:
            q = quant_matmul(
                h_top.astype(cdt), self.att_wh, self.att_wh_scale
            ).astype(cdt)  # (B, A)
        else:
            q = jnp.matmul(
                h_top.astype(cdt), self.att_wh.astype(cdt),
                preferred_element_type=jnp.float32,
            ).astype(cdt)  # (B, A)
        mesh = self.frame_mesh
        if (
            self.shard_frames
            and mesh is not None
            # Dense fallback when the concatenated frame axis doesn't
            # divide the mesh axis (shard_map needs even splits).
            and cache.att_vals.shape[1] % mesh.shape[self.frame_axis] == 0
        ):
            from cst_captioning_tpu.parallel.ring import (
                sharded_context_attention,
            )

            batch_axis = self.frame_batch_axis
            if (
                batch_axis is not None
                and q.shape[0] % mesh.shape[batch_axis] != 0
            ):
                # e.g. param-init traces with a single example row.
                batch_axis = None
            return sharded_context_attention(
                q,
                cache.att_vals,
                cache.att_proj,
                cache.att_mask,
                self.att_v.astype(cdt),
                mesh,
                axis=self.frame_axis,
                batch_axis=batch_axis,
            )
        from cst_captioning_tpu.ops.pallas_attention import (
            fused_context_attention,
        )

        # One decode step of score -> masked softmax -> context; the
        # Pallas path reads att_proj/att_vals from HBM once per step
        # (ops/pallas_attention.py), the fallback is the dense XLA math.
        return fused_context_attention(
            q,
            cache.att_proj,
            cache.att_mask,
            cache.att_vals,
            self.att_v.astype(cdt),
            use_pallas=self.use_pallas_attention,
        )

    # ------------------------------------------------------------ step core
    def _step(
        self, state: DecodeState, cache: DecodeCache, tokens: jax.Array
    ) -> Tuple[DecodeState, jax.Array]:
        """One decoder step: embed ``tokens`` (B,), fuse context, run the
        LSTM stack.  Returns new state and the top hidden (B, H) — the vocab
        projection is applied by the caller (batched over time in forward,
        per-step in decode)."""
        cdt = jnp.dtype(self.compute_dtype)
        if self.weight_quant:
            # Gather int8 rows first (1 byte/elem of HBM traffic), then
            # reconstruct only the gathered rows (ops/quant.py).
            emb = dequant_rows(
                self.word_embed, self.word_embed_scale, tokens, cdt
            )
        else:
            emb = self.word_embed.astype(cdt)[tokens]
        ctx = self._context(cache, state.h[-1])
        x = jnp.concatenate([emb, ctx.astype(cdt), cache.cat_emb], axis=-1)
        hs, cs = [], []
        for layer in range(self.num_layers):
            h_new, c_new = lstm_step(
                self.lstm[layer],
                x,
                state.h[layer],
                state.c[layer],
                compute_dtype=cdt,
                w_scale=self.lstm_scales[layer] if self.weight_quant else None,
            )
            hs.append(h_new)
            cs.append(c_new)
            x = h_new
        return DecodeState(h=jnp.stack(hs), c=jnp.stack(cs)), x

    def _init_state(self, batch: int) -> DecodeState:
        cdt = jnp.dtype(self.compute_dtype)
        return DecodeState(
            h=jnp.zeros((self.num_layers, batch, self.rnn_size), cdt),
            c=jnp.zeros((self.num_layers, batch, self.rnn_size), jnp.float32),
        )

    @property
    def decode_shards(self) -> int:
        """Size of the decode mesh's model axis (1 = single-device
        fused kernels; > 1 = the shard_map port)."""
        mesh = self.decode_mesh
        if mesh is None:
            return 1
        return int(mesh.shape.get(self.decode_axis, 1))

    def _logits(self, h: jax.Array) -> jax.Array:
        cdt = jnp.dtype(self.compute_dtype)
        # The vocab GEMM accumulates f32 regardless of the compute
        # dtype (CST-DTY-003) — decode scores exit f32 by contract.
        if self.weight_quant:
            # int8 vocab tile: 0.25x the HBM bytes of the f32 projection
            # per step; the per-logit scale multiplies the f32 accumulator
            # so scores still exit f32 (and shard-aligned under TP — the
            # (V,) scale carries the same vocab sharding as logit_w's
            # columns).
            return quant_matmul(
                h.astype(cdt), self.logit_w, self.logit_w_scale
            ) + self.logit_b.astype(jnp.float32)
        return jnp.matmul(
            h.astype(cdt), self.logit_w.astype(cdt),
            preferred_element_type=jnp.float32,
        ) + self.logit_b.astype(jnp.float32)

    @staticmethod
    def mask_decode_logits(
        logits: jax.Array, suppress_unk: bool = False
    ) -> jax.Array:
        """The decode-time policy never emits PAD or BOS — EOS is the only
        terminator.  Applied identically in sampling, beam search, and the
        CST policy-gradient likelihood (which must match the rollout
        policy); teacher-forced XE logits stay unmasked.

        ``suppress_unk`` additionally bars UNK from the decode policy
        (``ModelConfig.decode_suppress_unk``).  Default False = reference
        parity: the reference's sampler can emit UNK, and because both
        sides vocab-encode references with OOV -> UNK, a sampled UNK can
        harvest in-loop reward from UNK-encoded reference n-grams
        (tests/test_cst.py::test_unk_reward_channel pins the behavior;
        docs/PARITY.md records the choice)."""
        out = logits.at[..., PAD_ID].set(-1e30).at[..., BOS_ID].set(-1e30)
        if suppress_unk:
            out = out.at[..., UNK_ID].set(-1e30)
        return out

    # --------------------------------------------------------------- forward
    def __call__(
        self,
        feats: Dict[str, jax.Array],
        feat_masks: Dict[str, jax.Array],
        input_ids: jax.Array,
        *,
        category: Optional[jax.Array] = None,
        ss_prob: jax.Array | float = 0.0,
        deterministic: bool = True,
        rng: Optional[jax.Array] = None,
        repeat: int = 1,
    ) -> jax.Array:
        """Teacher-forced forward.  ``input_ids`` (B, T) starts with BOS;
        returns logits (B, T, V) predicting ``input_ids`` shifted left.

        ``ss_prob`` enables scheduled sampling (reference ``opts.py``
        scheduled_sampling_*): with that probability per token, the input is
        the model's own sample from the previous step instead of the GT.

        ``repeat``: caption rows per video — ``feats`` holds B videos and
        ``input_ids`` B*repeat caption rows (row-major per video); the
        projected cache is tiled AFTER the feature projections
        (:func:`_repeat_cache`), not the raw features before them.
        """
        B, T = input_ids.shape
        cache = _repeat_cache(
            self._encode(feats, feat_masks, category), repeat
        )
        state0 = self._init_state(B)
        if rng is None:
            rng = jax.random.PRNGKey(0)
        # Statically-zero ss_prob (the XE/eval hot path) takes a branch with
        # no per-step vocab projection or sampling — the only logits matmul
        # is the single batched one over (B, T, H) below.
        use_ss = not (isinstance(ss_prob, float) and ss_prob == 0.0)

        if self.use_pallas and not use_ss and self.fusion != "attention":
            # Fused fast path: static per-video context, so every step's
            # input is known up front — input GEMMs batch over (B, T) and
            # the recurrence runs in the Pallas kernel (ops/pallas_lstm.py).
            h_seq = self._fused_forward(cache, input_ids)
            h_seq = self._output_dropout(h_seq, deterministic)
            return self._logits(h_seq)

        if (
            self.fusion == "attention"
            and self.use_pallas_attention
            and not use_ss
            and self.num_layers == 1
            and not self.shard_frames
        ):
            from cst_captioning_tpu.ops.pallas_attlstm import (
                attlstm_shapes_ok,
            )

            if attlstm_shapes_ok(
                B, self.rnn_size, self.att_hidden_size, self.embed_size,
                cache.att_proj.shape[1],
                jnp.dtype(self.compute_dtype).itemsize,
            ):
                # Whole-recurrence fused path (ops/pallas_attlstm.py): the
                # T-step attention+LSTM loop runs as ONE kernel with the
                # attention tensors VMEM-resident across time, instead of a
                # lax.scan launching a per-step attention kernel.
                h_seq = self._fused_attention_forward(cache, input_ids)
                h_seq = self._output_dropout(h_seq, deterministic)
                return self._logits(h_seq)

        def step(carry, tok_t):
            state, prev_sample, key = carry
            if use_ss:
                key, k_mix, k_samp = jax.random.split(key, 3)
                use_sample = jax.random.bernoulli(
                    k_mix, jnp.asarray(ss_prob, jnp.float32), (B,)
                )
                tok = jnp.where(use_sample, prev_sample, tok_t)
            else:
                tok = tok_t
            state, h_top = self._step(state, cache, tok)
            if use_ss:
                sampled = jax.random.categorical(k_samp, self._logits(h_top))
                prev_sample = sampled.astype(jnp.int32)
            return (state, prev_sample, key), h_top

        if self.remat:
            # Trade FLOPs for HBM: recompute the step in the backward pass
            # instead of saving per-step intermediates (TrainConfig.remat).
            # prevent_cse=False: scan already blocks cross-iteration CSE,
            # so the default optimization barriers would only hurt fusion.
            step = jax.checkpoint(step, prevent_cse=False)
        # At t=0 the input is BOS — never replaced (prev_sample init = column 0).
        (_, _, _), h_seq = jax.lax.scan(
            step,
            (state0, input_ids[:, 0], rng),
            jnp.swapaxes(input_ids, 0, 1),
        )
        h_seq = jnp.swapaxes(h_seq, 0, 1)  # (B, T, H)
        h_seq = self._output_dropout(h_seq, deterministic)
        return self._logits(h_seq)

    def _output_dropout(self, h_seq: jax.Array, deterministic: bool) -> jax.Array:
        if deterministic or self.drop_prob <= 0.0:
            return h_seq
        drop_rng = self.make_rng("dropout")
        keep = 1.0 - self.drop_prob
        mask = jax.random.bernoulli(drop_rng, keep, h_seq.shape)
        return jnp.where(mask, h_seq / keep, 0.0).astype(h_seq.dtype)

    def _fused_forward(
        self, cache: DecodeCache, input_ids: jax.Array
    ) -> jax.Array:
        """Batched-input-GEMM + Pallas recurrence path (meanpool fusion,
        no scheduled sampling).  Numerics per ``ops/rnn.py``: bf16 matmuls
        with float32 gate accumulation and float32 cell state."""
        from cst_captioning_tpu.ops.pallas_lstm import (
            lstm_recurrence,
            lstm_recurrence_quant,
        )

        cdt = jnp.dtype(self.compute_dtype)
        if self.weight_quant:
            emb = dequant_rows(
                self.word_embed, self.word_embed_scale, input_ids, cdt
            )                                                  # (B, T, E)
        else:
            emb = self.word_embed.astype(cdt)[input_ids]       # (B, T, E)
        # Static per-video rows (context + category) hit their kernel rows
        # ONCE per batch row, not once per timestep: gx = emb @ Wx_emb +
        # (static @ Wx_static + b) broadcast over T.
        static = jnp.concatenate(
            [cache.ctx_static.astype(cdt), cache.cat_emb], axis=-1
        )  # (B, E [+C])
        x = emb
        for layer in range(self.num_layers):
            w, b = self.lstm[layer]
            ws = self.lstm_scales[layer] if self.weight_quant else None
            dx = x.shape[-1]
            # Under weight_quant the row slices are int8 codes sharing
            # one (4H,) per-channel scale; each slice's f32-pinned GEMM
            # is scaled AFTER its accumulation — the scale distributes
            # over the row-split sum (quant_matmul semantics).
            wx = w[:dx].astype(cdt)
            gx = jnp.einsum(
                "btd,dg->btg", x.astype(cdt), wx,
                preferred_element_type=jnp.float32,
            )
            if self.weight_quant:
                gx = gx * ws.astype(jnp.float32)[None, None, :]
            if layer == 0:
                d_in = dx + static.shape[-1]
                w_static = w[dx:d_in].astype(cdt)
                gstatic = jnp.einsum(
                    "bd,dg->bg", static, w_static,
                    preferred_element_type=jnp.float32,
                )
                if self.weight_quant:
                    gstatic = gstatic * ws.astype(jnp.float32)[None, :]
                gx = gx + gstatic[:, None, :]
            else:
                d_in = dx
            gx = gx + b.astype(jnp.float32)
            if self.weight_quant:
                x = lstm_recurrence_quant(
                    gx, w[d_in:], ws, compute_dtype=cdt, use_pallas=True
                )
            else:
                x = lstm_recurrence(gx, w[d_in:].astype(cdt), True)
        return x

    def _fused_attention_forward(
        self, cache: DecodeCache, input_ids: jax.Array
    ) -> jax.Array:
        """Whole-recurrence attention path: batch the token-embedding and
        static-category input GEMMs over (B, T), then run the sequential
        attention-query + context + gate chain in the fused kernel.
        Weight-row layout follows ``_step``'s concat order
        [emb | ctx | cat | hidden]."""
        from cst_captioning_tpu.ops.pallas_attlstm import (
            attlstm_recurrence,
            attlstm_recurrence_quant,
        )

        cdt = jnp.dtype(self.compute_dtype)
        w, b = self.lstm[0]
        E = self.embed_size
        C = cache.cat_emb.shape[-1]
        ws = self.lstm_scales[0] if self.weight_quant else None
        if self.weight_quant:
            emb = dequant_rows(
                self.word_embed, self.word_embed_scale, input_ids, cdt
            )                                               # (B, T, E)
        else:
            emb = self.word_embed.astype(cdt)[input_ids]    # (B, T, E)
        gx = jnp.einsum(
            "bte,eg->btg", emb, w[:E].astype(cdt),
            preferred_element_type=jnp.float32,
        )
        if self.weight_quant:
            gx = gx * ws.astype(jnp.float32)[None, None, :]
        gx = gx + b.astype(jnp.float32)
        if C:
            gcat = jnp.einsum(
                "bc,cg->bg", cache.cat_emb,
                w[2 * E : 2 * E + C].astype(cdt),
                preferred_element_type=jnp.float32,
            )
            if self.weight_quant:
                gcat = gcat * ws.astype(jnp.float32)[None, :]
            gx = gx + gcat[:, None, :]
        if self.weight_quant:
            # int8 code slices + their scales stream into the kernel;
            # dequant happens in-kernel (ops/pallas_attlstm.py).
            return attlstm_recurrence_quant(
                gx,
                w[2 * E + C :],
                w[E : 2 * E],
                ws,
                self.att_wh,
                self.att_wh_scale,
                self.att_v.astype(cdt),
                cache.att_proj,
                cache.att_mask,
                cache.att_vals,
                cdt,
            )
        return attlstm_recurrence(
            gx,
            w[2 * E + C :].astype(cdt),
            w[E : 2 * E].astype(cdt),
            self.att_wh.astype(cdt),
            self.att_v.astype(cdt),
            cache.att_proj,
            cache.att_mask,
            cache.att_vals,
        )

    # --------------------------------------------------------------- decode
    def init_decode(
        self,
        feats: Dict[str, jax.Array],
        feat_masks: Dict[str, jax.Array],
        category: Optional[jax.Array] = None,
    ) -> Tuple[DecodeState, DecodeCache]:
        """Entry point for external decoders (beam search): encode once,
        return (initial state, per-video cache)."""
        some = feats[self.modalities[0]]
        return self._init_state(some.shape[0]), self._encode(
            feats, feat_masks, category
        )

    def decode_logits(
        self, state: DecodeState, cache: DecodeCache, tokens: jax.Array
    ) -> Tuple[DecodeState, jax.Array]:
        """One decode step → (new state, float32 decode-policy LOGITS
        (B, V), PAD/BOS masked out) — the model hook the unified decode
        core (``decoding/core.py::decode_step``) drives; each mode
        applies its own log_softmax/temperature on top."""
        state, h_top = self._step(state, cache, tokens)
        return state, self.mask_decode_logits(
            self._logits(h_top), self.decode_suppress_unk
        )

    def decode_verify(
        self, state: DecodeState, cache: DecodeCache, tokens_k: jax.Array
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """``k`` chained decode steps with ONE batched vocab projection —
        the verify pass of speculative decode (decoding/speculative.py).

        ``tokens_k`` is (k, B) int32: row 0 each row's current token,
        rows 1.. the draft's proposals.  Returns ``(h_all, c_all,
        logits)`` where ``h_all``/``c_all`` are (k, layers, B, H) state
        snapshots AFTER consuming ``tokens_k[:j+1]`` and ``logits`` row
        ``j*B + b`` is batch row ``b``'s masked decode-policy logits
        after its (j+1)-token prefix.  The k recurrence steps stay
        sequential (hidden-sized — cheap), but the vocab GEMM, the
        dominant per-step cost, runs ONCE over the stacked (k*B, H)
        hiddens.  Logits stay flat 2-D so the TP logits sharding
        constraint and ``make_tp_row_pick`` compose unchanged
        (serving/slots.py)."""

        def step(st, tok):
            st, h_top = self._step(st, cache, tok)
            return st, (st.h, st.c, h_top)

        _, (hs, cs, tops) = jax.lax.scan(step, state, tokens_k)
        logits = self.mask_decode_logits(
            self._logits(tops.reshape((-1,) + tops.shape[2:])),
            self.decode_suppress_unk,
        )
        return hs, cs, logits

    def decode_one(
        self, state: DecodeState, cache: DecodeCache, tokens: jax.Array
    ) -> Tuple[DecodeState, jax.Array]:
        """One decode step → (new state, float32 log-probs (B, V)) under
        the decode policy (PAD/BOS masked out)."""
        state, logits = self.decode_logits(state, cache, tokens)
        return state, jax.nn.log_softmax(logits, axis=-1)

    def sample(
        self,
        feats: Dict[str, jax.Array],
        feat_masks: Dict[str, jax.Array],
        *,
        rng: Optional[jax.Array] = None,
        category: Optional[jax.Array] = None,
        max_len: int = 30,
        greedy: bool = True,
        temperature: float = 1.0,
        repeat: int = 1,
        early_exit: bool = True,
    ) -> SampleOutput:
        """Autoregressive decode under ``jit``: up to ``max_len`` steps,
        finished sequences emit PAD with zero log-prob (fixed shapes — no
        data-dependent output shapes).  ``greedy=True`` is the SCST
        baseline path; ``greedy=False`` is the multinomial rollout
        (temperature-scaled), with log-probs taken from the same scaled
        distribution the token was drawn from, as REINFORCE requires.

        ``early_exit`` (default True): stop the loop once every row has
        finished — the same all-rows-finished ``lax.while_loop`` the
        scan beam got in PR 3, output-identical to the full-length scan
        (see :meth:`_sample_from_cache`).

        ``repeat``: rollouts per video (CST_MS) — the projected cache is
        tiled after the feature projections, so S rollouts cost S x the
        decode but 1 x the encode (:func:`_repeat_cache`).
        """
        state, cache = self.init_decode(feats, feat_masks, category)
        if repeat > 1:
            cache = _repeat_cache(cache, repeat)
            state = self._init_state(cache.ctx_static.shape[0])
        return self._sample_from_cache(
            state, cache, rng=rng, max_len=max_len, greedy=greedy,
            temperature=temperature, early_exit=early_exit,
        )

    def sample_with_baseline(
        self,
        feats: Dict[str, jax.Array],
        feat_masks: Dict[str, jax.Array],
        *,
        rng: jax.Array,
        category: Optional[jax.Array] = None,
        max_len: int = 30,
        temperature: float = 1.0,
        repeat: int = 1,
        with_greedy: bool = True,
        early_exit: bool = True,
    ) -> Tuple[SampleOutput, Optional[SampleOutput]]:
        """Multinomial rollout (``repeat`` per video) plus the optional
        greedy-baseline decode sharing ONE feature encode.  The CST step
        previously ran two ``sample`` calls, each paying the full
        ``_encode`` (feature projections + attention keys) for the same
        batch; here both decodes read the same projected cache (VERDICT
        r3 #3).  Returns ``(rollout, greedy-or-None)``."""
        state0, cache = self.init_decode(feats, feat_masks, category)
        rcache = _repeat_cache(cache, repeat) if repeat > 1 else cache
        rstate = (
            self._init_state(rcache.ctx_static.shape[0])
            if repeat > 1
            else state0
        )
        rollout = self._sample_from_cache(
            rstate, rcache, rng=rng, max_len=max_len, greedy=False,
            temperature=temperature, early_exit=early_exit,
        )
        if not with_greedy:
            return rollout, None
        greedy = self._sample_from_cache(
            state0, cache, max_len=max_len, greedy=True,
            early_exit=early_exit,
        )
        return rollout, greedy

    def _sample_from_cache(
        self,
        state: DecodeState,
        cache: DecodeCache,
        *,
        rng: Optional[jax.Array] = None,
        max_len: int = 30,
        greedy: bool = True,
        temperature: float = 1.0,
        zero_state: bool = True,
        early_exit: bool = True,
    ) -> SampleOutput:
        """``zero_state``: both public callers (sample /
        sample_with_baseline) pass a fresh ``_init_state``, which the
        fused sampler kernel assumes (it always decodes from zeros).  A
        future warm-state caller MUST pass ``zero_state=False`` to get
        the scan path — the fused route would silently ignore ``state``.
        """
        B = state.h.shape[1]
        if rng is None:
            rng = jax.random.PRNGKey(0)

        if (
            zero_state
            and self.use_pallas_sampler
            and self.fusion in ("attention", "meanpool")
        ):
            if self.num_layers != 1 or self.shard_frames:
                warn_fused_decline(
                    "use_pallas_sampler",
                    f"num_layers={self.num_layers}, "
                    f"shard_frames={self.shard_frames} (kernel covers "
                    "single-layer unsharded decoders)",
                )
            else:
                from cst_captioning_tpu.ops.pallas_sampler import (
                    sampler_shapes_ok,
                )

                static_ctx = self.fusion != "attention"
                # The shard_map port (decode_shards > 1) is pure XLA —
                # the kernel's VMEM/lane-width shape gate doesn't apply
                # (model_from_config already gated V % M == 0).
                if self.decode_shards > 1 or sampler_shapes_ok(
                    B, self.rnn_size, self.att_hidden_size,
                    self.embed_size, cache.att_proj.shape[1],
                    jnp.dtype(self.compute_dtype).itemsize,
                    static_ctx=static_ctx,
                ):
                    return self._fused_sample(
                        cache, rng=rng, max_len=max_len, greedy=greedy,
                        temperature=temperature,
                    )
                warn_fused_decline(
                    "use_pallas_sampler",
                    f"shape gate: B={B}, H={self.rnn_size}, "
                    f"A={self.att_hidden_size}, E={self.embed_size}, "
                    f"F={cache.att_proj.shape[1]} fails sampler_shapes_ok",
                )

        # The per-step math is the unified decode core's row mode
        # (decoding/core.py::decode_step) — the legacy threefry batch
        # stream rides in the carry (``CoreState.rng``) and greedy
        # ignores it.  ``early_exit`` swaps the fixed-length scan for
        # an all-rows-finished while_loop: buffers start at PAD/0, so
        # the steps it skips would only have re-written those exact
        # values — output-identical (the PR-3 beam argument, pinned by
        # tests/test_decode_core.py).
        def step_logits(st, tok):
            st, h_top = self._step(st, cache, tok)
            return st, self.mask_decode_logits(
                self._logits(h_top), self.decode_suppress_unk
            )

        mode = "greedy" if greedy else "sample"
        core0 = init_core(
            state, B, 1, max_len, mode=mode,
            rng=None if greedy else rng,
        )

        def step(st):
            return decode_step(
                step_logits, st, mode=mode, temperature=temperature
            )

        if early_exit:
            st = jax.lax.while_loop(
                lambda st: (st.step[0] < max_len) & ~all_done(st),
                step,
                core0,
            )
        else:
            st, _ = jax.lax.scan(
                lambda c, _: (step(c), None), core0, None, length=max_len
            )
        return SampleOutput(
            tokens=st.seqs[:, 0, :],
            logprobs=st.lps[:, 0, :],
            mask=(st.seqs[:, 0, :] != PAD_ID).astype(jnp.float32),
        )

    def _fused_gx_static(self, cache: DecodeCache) -> jax.Array:
        """Per-row static gate contribution for the fused decode kernels
        (sampler AND beam): lstm bias broadcast + the category rows of
        the layer-0 kernel.  Weight-row layout follows ``_step``'s concat
        order [emb | ctx | cat | hidden]."""
        cdt = jnp.dtype(self.compute_dtype)
        w, b = self.lstm[0]
        E = self.embed_size
        C = cache.cat_emb.shape[-1]
        B = cache.att_proj.shape[0]
        gx_static = jnp.broadcast_to(
            b.astype(jnp.float32)[None, :], (B, b.shape[0])
        )
        if C:
            gcat = jnp.einsum(
                "bc,cg->bg", cache.cat_emb,
                w[2 * E : 2 * E + C].astype(cdt),
                preferred_element_type=jnp.float32,
            )
            if self.weight_quant:
                # Category rows are layer-0 kernel rows: int8 codes
                # sharing the (4H,) lstm scale, applied post-accumulation.
                gcat = gcat * self.lstm_scales[0].astype(jnp.float32)[None, :]
            gx_static = gx_static + gcat
        return gx_static

    def fused_beam(
        self,
        feats: Dict[str, jax.Array],
        feat_masks: Dict[str, jax.Array],
        category: Optional[jax.Array] = None,
        *,
        beam_size: int,
        max_len: int,
    ) -> Tuple[jax.Array, jax.Array]:
        """Whole-recurrence fused beam search (ops/pallas_beam.py):
        encode once, then the entire (B, K) beam recurrence runs as ONE
        kernel.  Returns the raw ``(seqs (B, K, L), scores (B, K))``
        pair for ``decoding.beam.finalize_beams`` — callers dispatch
        through :func:`cst_captioning_tpu.decoding.beam.beam_search`,
        which owns the shape gate and the scan-path fallback.

        Under ``decode_mesh`` (model axis > 1) the recurrence dispatches
        to the shard_map port instead (``ops/shard_decode.py``): each
        shard streams only its vocab tile and the per-step top-K merges
        across shards via an O(shards·K) candidate all-gather."""
        from cst_captioning_tpu.ops.pallas_beam import (
            attlstm_beam,
            lstm_beam,
        )

        cdt = jnp.dtype(self.compute_dtype)
        cache = self._encode(feats, feat_masks, category)
        w, _ = self.lstm[0]
        E = self.embed_size
        C = cache.cat_emb.shape[-1]
        gx_static = self._fused_gx_static(cache)
        common = dict(
            beam_size=beam_size,
            max_len=max_len,
            suppress_unk=self.decode_suppress_unk,
        )
        if self.weight_quant:
            # int8w: weights stay int8 codes — the kernel dequantizes
            # in-kernel from the streamed scale rows (0.25x vocab tile).
            wcast = lambda x: x  # noqa: E731
            common["compute_dtype"] = self.compute_dtype
        else:
            wcast = lambda x: x.astype(cdt)  # noqa: E731
        if self.decode_shards > 1:
            from cst_captioning_tpu.ops.shard_decode import (
                sharded_attlstm_beam,
                sharded_lstm_beam,
            )

            attlstm_beam = functools.partial(
                sharded_attlstm_beam, mesh=self.decode_mesh,
                axis=self.decode_axis,
            )
            lstm_beam = functools.partial(
                sharded_lstm_beam, mesh=self.decode_mesh,
                axis=self.decode_axis,
            )
        if self.fusion == "attention":
            if self.weight_quant:
                common["quant"] = (
                    self.word_embed_scale,
                    self.logit_w_scale,
                    self.lstm_scales[0],
                    self.att_wh_scale,
                )
            return attlstm_beam(
                gx_static,
                wcast(w[:E]),
                wcast(w[2 * E + C :]),
                wcast(w[E : 2 * E]),
                wcast(self.att_wh),
                self.att_v.astype(cdt),
                cache.att_proj,
                cache.att_mask,
                cache.att_vals,
                wcast(self.word_embed),
                wcast(self.logit_w),
                self.logit_b.astype(jnp.float32),
                **common,
            )
        gctx = jnp.einsum(
            "be,eg->bg", cache.ctx_static.astype(cdt),
            w[E : 2 * E].astype(cdt),
            preferred_element_type=jnp.float32,
        )
        if self.weight_quant:
            gctx = gctx * self.lstm_scales[0].astype(jnp.float32)[None, :]
            common["quant"] = (
                self.word_embed_scale,
                self.logit_w_scale,
                self.lstm_scales[0],
            )
        gx_static = gx_static + gctx
        return lstm_beam(
            gx_static,
            wcast(w[:E]),
            wcast(w[2 * E + C :]),
            wcast(self.word_embed),
            wcast(self.logit_w),
            self.logit_b.astype(jnp.float32),
            **common,
        )

    def _fused_sample(
        self,
        cache: DecodeCache,
        *,
        rng: jax.Array,
        max_len: int,
        greedy: bool,
        temperature: float,
    ) -> SampleOutput:
        """Whole-recurrence fused sampling (ops/pallas_sampler.py).
        Weight-row layout follows ``_step``'s concat order
        [emb | ctx | cat | hidden], like ``_fused_attention_forward``.
        Meanpool fusion folds the static context's gate contribution
        into ``gx_static`` and takes the attention-free kernel.  Under
        ``decode_mesh`` (model axis > 1) the recurrence dispatches to
        the shard_map port (``ops/shard_decode.py``) — identical
        hash-Gumbel stream, per-shard vocab tiles, cross-shard
        candidate merge."""
        from cst_captioning_tpu.ops.pallas_sampler import (
            attlstm_sample,
            lstm_sample,
        )

        if self.decode_shards > 1:
            from cst_captioning_tpu.ops.shard_decode import (
                sharded_attlstm_sample,
                sharded_lstm_sample,
            )

            attlstm_sample = functools.partial(
                sharded_attlstm_sample, mesh=self.decode_mesh,
                axis=self.decode_axis,
            )
            lstm_sample = functools.partial(
                sharded_lstm_sample, mesh=self.decode_mesh,
                axis=self.decode_axis,
            )

        cdt = jnp.dtype(self.compute_dtype)
        w, b = self.lstm[0]
        E = self.embed_size
        C = cache.cat_emb.shape[-1]
        gx_static = self._fused_gx_static(cache)
        # Any PRNG impl's key -> TWO int32 seed words (the kernel's hash
        # stream fans them out per row/step/position).  Both words enter
        # the stream, so the effective seed space is 64-bit — a single
        # collapsed word had ~1e-3 birthday-collision odds of replaying
        # a step's Gumbel noise over a ~100k-step CST run (ADVICE r5 #2).
        seed = jax.random.bits(rng, (2,), jnp.uint32).astype(jnp.int32)
        common = dict(
            max_len=max_len,
            greedy=greedy,
            temperature=temperature,
            suppress_unk=self.decode_suppress_unk,
        )
        if self.weight_quant:
            # int8w: weights stay int8 codes — the kernel dequantizes
            # in-kernel from the streamed scale rows (0.25x vocab tile).
            wcast = lambda x: x  # noqa: E731
            common["compute_dtype"] = self.compute_dtype
        else:
            wcast = lambda x: x.astype(cdt)  # noqa: E731
        if self.fusion == "attention":
            if self.weight_quant:
                common["quant"] = (
                    self.word_embed_scale,
                    self.logit_w_scale,
                    self.lstm_scales[0],
                    self.att_wh_scale,
                )
            toks, lps, mask = attlstm_sample(
                gx_static,
                wcast(w[:E]),
                wcast(w[2 * E + C :]),
                wcast(w[E : 2 * E]),
                wcast(self.att_wh),
                self.att_v.astype(cdt),
                cache.att_proj,
                cache.att_mask,
                cache.att_vals,
                wcast(self.word_embed),
                wcast(self.logit_w),
                self.logit_b.astype(jnp.float32),
                seed,
                **common,
            )
        else:
            gctx = jnp.einsum(
                "be,eg->bg", cache.ctx_static.astype(cdt),
                w[E : 2 * E].astype(cdt),
                preferred_element_type=jnp.float32,
            )
            if self.weight_quant:
                gctx = gctx * self.lstm_scales[0].astype(
                    jnp.float32
                )[None, :]
                common["quant"] = (
                    self.word_embed_scale,
                    self.logit_w_scale,
                    self.lstm_scales[0],
                )
            gx_static = gx_static + gctx
            toks, lps, mask = lstm_sample(
                gx_static,
                wcast(w[:E]),
                wcast(w[2 * E + C :]),
                wcast(self.word_embed),
                wcast(self.logit_w),
                self.logit_b.astype(jnp.float32),
                seed,
                **common,
            )
        return SampleOutput(tokens=toks, logprobs=lps, mask=mask)


def _scan_greedy_runner(ctx):
    """Registry runner: the reference scan-path greedy decode."""
    import numpy as np

    out = ctx.make_model().apply(
        ctx.params, ctx.feats, ctx.masks, category=ctx.category,
        max_len=ctx.max_len, greedy=True, method="sample",
    )
    return {
        "tokens": np.asarray(out.tokens),
        "lps": np.asarray(out.logprobs),
        "mask": np.asarray(out.mask),
    }


from cst_captioning_tpu.decoding.core import register_backend  # noqa: E402

register_backend("scan_greedy", _scan_greedy_runner, kind="greedy")


SERVING_DTYPES = ("f32", "bf16", "int8w")


def model_from_config(cfg, mesh=None, serving_dtype=None) -> CaptionModel:
    """Build a CaptionModel from a ``Config`` (see ``config.py``).

    ``mesh`` enables frame sharding when ``model.shard_frames`` is set:
    the frame axis shards over the mesh's "model" axis, composing with the
    "data" batch axis when present.

    ``serving_dtype`` is the low-precision SERVING override
    (``serving.dtype``): passed only by the inference engine, never by the
    trainer, so ``f32``/``None`` leaves the model byte-identical to
    today's build.  ``bf16`` forces ``compute_dtype=bfloat16``; ``int8w``
    additionally sets ``weight_quant`` (int8 codes + per-channel scales,
    ops/quant.py).  The fused Pallas kernels COMPOSE with ``int8w``: they
    stream the int8 code tiles plus per-channel scale rows and dequantize
    in-kernel with ``quant_matmul`` semantics, so the same structural
    gates apply as for float serving (layer count, mesh shape, shape
    tables) and quantization itself never declines a kernel.
    """
    m, d = cfg.model, cfg.data
    if serving_dtype is not None and serving_dtype not in SERVING_DTYPES:
        raise ValueError(
            f"unknown serving.dtype {serving_dtype!r}; expected one of "
            f"{SERVING_DTYPES}"
        )
    compute_dtype = m.compute_dtype
    weight_quant = False
    if serving_dtype in ("bf16", "int8w"):
        compute_dtype = "bfloat16"
        weight_quant = serving_dtype == "int8w"
    if m.feature_fusion not in ("meanpool", "attention"):
        raise ValueError(
            f"unknown feature_fusion {m.feature_fusion!r}; "
            "expected 'meanpool' or 'attention'"
        )
    shard_frames = bool(getattr(m, "shard_frames", False)) and mesh is not None
    if shard_frames and m.feature_fusion != "attention":
        raise ValueError(
            "model.shard_frames requires feature_fusion='attention' "
            "(meanpool has no per-step frame attention to shard)"
        )
    if shard_frames and "model" not in mesh.shape:
        raise ValueError(
            "model.shard_frames shards frames over the mesh 'model' axis, "
            f"but the mesh has axes {tuple(mesh.shape)} — add a model axis "
            "to train.mesh_shape"
        )
    batch_axis = (
        "data" if mesh is not None and mesh.shape.get("data", 1) > 1 else None
    )
    use_pallas_attention = getattr(m, "use_pallas_attention", False)
    use_pallas_lstm = m.use_pallas_lstm

    # The fused sampler and beam kernels are gated by the CAPABILITY
    # TABLE (decoding/core.py::DECODE_KERNEL_CAPS, machine-checked by
    # CST-SHD-005): a model-sharded (vocab-over-model) mesh dispatches
    # to the shard_map port with the cross-shard top-K candidate merge
    # (ops/shard_decode.py) — pure XLA, so it runs on any backend;
    # batch-sharded (data > 1) meshes still decline (no SPMD rule, no
    # batch-axis port), as do off-TPU SINGLE-device runs (the Pallas
    # kernel would run in interpret mode, orders of magnitude slower
    # than the scan path).  Every gated-off request logs the reason
    # (VERDICT r5 #4: silent declines lose the perf story untraceably).
    from cst_captioning_tpu.decoding.core import kernel_supports

    model_ways = mesh.shape.get("model", 1) if mesh is not None else 1
    data_ways = (
        mesh.devices.size // model_ways if mesh is not None else 1
    )

    def _decode_kernel_gate(flag_name: str) -> bool:
        if not getattr(m, flag_name, False):
            return False
        if m.num_layers != 1:
            # The in-model gate would decline anyway; say so up front.
            warn_fused_decline(
                flag_name,
                f"num_layers={m.num_layers} (kernel covers single-layer "
                "decoders)",
            )
            return False
        if data_ways > 1 and not kernel_supports(flag_name, "data"):
            warn_fused_decline(
                flag_name,
                f"{mesh.devices.size}-device mesh with batch sharding "
                f"({data_ways}-way data) — pallas_call has no SPMD "
                "partitioning rule and no shard_map port covers the "
                "batch axis",
            )
            return False
        if model_ways > 1:
            if not kernel_supports(flag_name, "model"):
                warn_fused_decline(
                    flag_name,
                    f"vocab sharded {model_ways}-way over `model` — "
                    "no cross-shard merge port for this kernel "
                    "(DECODE_KERNEL_CAPS)",
                )
                return False
            from cst_captioning_tpu.ops.shard_decode import (
                shard_decode_ok,
            )

            if not shard_decode_ok(
                m.vocab_size, model_ways, cfg.eval.beam_size
            ):
                warn_fused_decline(
                    flag_name,
                    f"vocab {m.vocab_size} does not tile evenly over "
                    f"the {model_ways}-way model axis (need V % M == 0 "
                    "and V/M >= beam width) — pad the vocab for the "
                    "sharded fast path",
                )
                return False
            # The shard_map port is pure XLA — no interpret-mode
            # cliff — so it engages on any backend.
            return True
        if jax.default_backend() != "tpu":
            warn_fused_decline(
                flag_name,
                f"backend is {jax.default_backend()!r}, not tpu "
                "(interpret mode would crawl)",
            )
            return False
        return True

    use_pallas_sampler = _decode_kernel_gate("use_pallas_sampler")
    use_pallas_beam = _decode_kernel_gate("use_pallas_beam")
    decode_mesh = (
        mesh
        if model_ways > 1 and (use_pallas_sampler or use_pallas_beam)
        else None
    )
    if (
        use_pallas_attention
        and mesh is not None
        and mesh.devices.size > 1
    ):
        # pallas_call has no SPMD partitioning rule: inside the jitted,
        # batch-sharded train step it would fail to lower (or force a full
        # gather) and _pick_bt would tile from the GLOBAL batch.  The
        # dense XLA attention math shards fine; frame sharding
        # (shard_frames) is the multi-device fast path.  Disabled even
        # when shard_frames is set: _context's non-divisible-frames
        # fallback would otherwise still reach the kernel.
        import logging

        hint = (
            "the sharded-fusion (shard_frames) path is active; the "
            "kernel would only have been reached by the non-divisible-"
            "frames dense fallback"
            if shard_frames
            else "set model.shard_frames for sharded fusion"
        )
        logging.getLogger("cst_captioning_tpu.models").warning(
            "use_pallas_attention disabled: the fused kernel has no SPMD "
            "partitioning rule for the %d-device mesh — using the dense "
            "attention math (%s)",
            mesh.devices.size, hint,
        )
        use_pallas_attention = False
    return CaptionModel(
        shard_frames=shard_frames,
        frame_mesh=mesh if shard_frames else None,
        frame_axis="model",
        frame_batch_axis=batch_axis if shard_frames else None,
        use_pallas_attention=use_pallas_attention,
        use_pallas_sampler=use_pallas_sampler,
        use_pallas_beam=use_pallas_beam,
        decode_mesh=decode_mesh,
        decode_suppress_unk=getattr(m, "decode_suppress_unk", False),
        vocab_size=m.vocab_size,
        rnn_size=m.rnn_size,
        num_layers=m.num_layers,
        embed_size=m.input_encoding_size,
        fusion=m.feature_fusion,
        att_hidden_size=m.att_hidden_size,
        drop_prob=m.drop_prob,
        modalities=tuple(d.feature_modalities),
        feature_dims=tuple(d.feature_dims[k] for k in d.feature_modalities),
        use_category=m.use_category,
        num_categories=d.num_categories,
        category_embed_size=m.category_embed_size,
        compute_dtype=compute_dtype,
        param_dtype=m.param_dtype,
        weight_quant=weight_quant,
        use_pallas=use_pallas_lstm,
        remat=cfg.train.remat,
    )
