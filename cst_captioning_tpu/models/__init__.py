"""Model layer: multi-modal feature fusion + LSTM caption decoder.

Rebuilds the capabilities of the reference's ``model.py`` (SURVEY.md §2:
``CaptionModel`` — per-modality projection, mean-pool or temporal soft
attention fusion, 1-2 layer LSTM-512, vocab softmax; teacher-forced
``forward``; autoregressive ``sample``) as a Flax module whose time loops
are ``lax.scan`` and whose matmuls are batched for the MXU.
"""

from cst_captioning_tpu.models.captioner import (  # noqa: F401
    CaptionModel,
    SampleOutput,
    PAD_ID,
    BOS_ID,
    EOS_ID,
    UNK_ID,
    NUM_SPECIAL_TOKENS,
    model_from_config,
)
