"""Config -> dataset construction (the reference wires this inline in
``train.py``/``test.py`` from ``opts.py`` path flags)."""

from __future__ import annotations

import json
from typing import Dict, Optional, Tuple

import numpy as np

from cst_captioning_tpu.config import Config
from cst_captioning_tpu.data.datasets import (
    CaptionDataset,
    H5Dataset,
    make_synthetic_dataset,
)
from cst_captioning_tpu.data.vocab import Vocabulary


def load_consensus_weights(
    path: str, ds: CaptionDataset
) -> Dict[str, np.ndarray]:
    """Load per-caption consensus weights (the reference's precomputed WXE
    CIDEr scores, SURVEY.md §3.4) and key them by video id.

    Formats: ``.json`` — {video_id: [w, ...]}; ``.npy`` — one flat float
    array aligned with the dataset's caption rows in dataset order (the
    label-h5 ``captions`` layout written by ``tools/prepare_data.py``).
    """
    if path.endswith(".json"):
        with open(path) as f:
            raw = json.load(f)
        out = {k: np.asarray(v, np.float32) for k, v in raw.items()}
        # Validate counts for every covered video — a short vector would
        # otherwise IndexError (or silently misalign) at caption-sampling
        # time deep inside the training loop.
        by_id = {ds.video_id(i): i for i in range(len(ds))}
        for vid, w in out.items():
            if vid in by_id:
                n = ds.captions(by_id[vid]).shape[0]
                if w.shape[0] != n:
                    raise ValueError(
                        f"consensus file {path}: video {vid!r} has "
                        f"{w.shape[0]} weights but {n} captions"
                    )
        return out
    flat = np.load(path).astype(np.float32)
    out: Dict[str, np.ndarray] = {}
    pos = 0
    for i in range(len(ds)):
        n = ds.captions(i).shape[0]
        out[ds.video_id(i)] = flat[pos : pos + n]
        pos += n
    if pos != flat.shape[0]:
        raise ValueError(
            f"consensus file {path} has {flat.shape[0]} weights but the "
            f"dataset's caption rows total {pos}"
        )
    return out


def build_dataset(
    cfg: Config, split: str, vocab: Optional[Vocabulary] = None
) -> Tuple[CaptionDataset, Vocabulary]:
    """Build one split.  ``data.dataset == "synthetic"`` generates the toy
    corpus (split names map to different seeds so train/val differ);
    otherwise ``data.label_file`` is a path template with a ``{split}``
    placeholder (as written by ``tools/prepare_data.py``) or a literal
    path, and ``data.feature_files`` maps modality -> feature h5.

    ``data.consensus_file`` (optional, train split only; ``{split}``
    template allowed) overrides the per-caption consensus weights used by
    WXE / the weighted CST reward."""
    d = cfg.data
    if d.dataset == "synthetic":
        seed = {"train": 0, "val": 1, "test": 2}.get(split, 3)
        ds, vb = make_synthetic_dataset(
            num_videos=max(d.batch_size * 2, 16),
            feature_dims=dict(d.feature_dims),
            max_frames=d.max_frames,
            max_words=d.max_seq_len - 2,
            num_categories=d.num_categories if cfg.model.use_category else 0,
            seed=seed,
        )
        ds_out: CaptionDataset = ds
        vocab = vocab or vb
    else:
        if vocab is None:
            if not d.vocab_file:
                raise ValueError("data.vocab_file is required for h5 datasets")
            vocab = Vocabulary.load(d.vocab_file)
        label = d.label_file.format(split=split)
        ds_out = H5Dataset(label, dict(d.feature_files), vocab)
    if d.consensus_file and split == "train":
        ds_out.set_caption_weights(
            load_consensus_weights(
                d.consensus_file.format(split=split), ds_out
            )
        )
    return ds_out, vocab
