"""Config -> dataset construction (the reference wires this inline in
``train.py``/``test.py`` from ``opts.py`` path flags)."""

from __future__ import annotations

from typing import Optional, Tuple

from cst_captioning_tpu.config import Config
from cst_captioning_tpu.data.datasets import (
    CaptionDataset,
    H5Dataset,
    make_synthetic_dataset,
)
from cst_captioning_tpu.data.vocab import Vocabulary


def build_dataset(
    cfg: Config, split: str, vocab: Optional[Vocabulary] = None
) -> Tuple[CaptionDataset, Vocabulary]:
    """Build one split.  ``data.dataset == "synthetic"`` generates the toy
    corpus (split names map to different seeds so train/val differ);
    otherwise ``data.label_file`` is a path template with a ``{split}``
    placeholder (as written by ``tools/prepare_data.py``) or a literal
    path, and ``data.feature_files`` maps modality -> feature h5."""
    d = cfg.data
    if d.dataset == "synthetic":
        seed = {"train": 0, "val": 1, "test": 2}.get(split, 3)
        ds, vb = make_synthetic_dataset(
            num_videos=max(d.batch_size * 2, 16),
            feature_dims=dict(d.feature_dims),
            max_frames=d.max_frames,
            max_words=d.max_seq_len - 2,
            num_categories=d.num_categories if cfg.model.use_category else 0,
            seed=seed,
        )
        return ds, (vocab or vb)
    if vocab is None:
        if not d.vocab_file:
            raise ValueError("data.vocab_file is required for h5 datasets")
        vocab = Vocabulary.load(d.vocab_file)
    label = d.label_file.format(split=split)
    ds = H5Dataset(label, dict(d.feature_files), vocab)
    return ds, vocab
