"""Fixed-shape batch iterator with device prefetch.

Reference equivalent: ``dataloader.py``'s ``get_batch(split)`` (SURVEY.md
§2/§3.1) — batches videos, samples ``seq_per_img`` captions each, builds the
padded id matrix + mask.  TPU-first differences:

* Every batch has *identical* shapes (batch padded by wrapping around the
  video list on the final partial batch when ``drop_last=False``) so the
  jitted train step never recompiles.
* Frames are uniformly subsampled / zero-padded to ``max_frames`` with a
  validity mask — the reference's variable-length h5 reads become static
  (B, F, D) tensors.
* ``shard_id / num_shards`` slice the video list per host process for
  multi-host data parallelism (each host feeds its own chips).
* ``prefetch_to_device`` overlaps host batch assembly + H2D transfer with
  device compute via a daemon thread (the reference blocks on h5 reads and
  ``.cuda()`` per step).
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Iterator, List, NamedTuple

import numpy as np

from cst_captioning_tpu.data.datasets import CaptionDataset


class Batch(NamedTuple):
    """One fixed-shape training batch (all numpy, host-side).

    B = videos per batch, S = seq_per_img, F = max_frames, L = caption slots
    (max_words + 2 for BOS/EOS).
    """

    feats: Dict[str, np.ndarray]        # m -> (B, F, D_m) float32
    feat_masks: Dict[str, np.ndarray]   # m -> (B, F) float32
    captions: np.ndarray                # (B, S, L) int32
    weights: np.ndarray                 # (B, S) float32 consensus weights
    category: np.ndarray                # (B,) int32
    video_idx: np.ndarray               # (B,) int32 dataset indices
    video_ids: List[str]                # host-side ids (not shipped to device)


def subsample_frames(frames: np.ndarray, max_frames: int) -> np.ndarray:
    """Uniform temporal subsample to at most ``max_frames`` rows."""
    if frames.shape[0] <= max_frames:
        return frames
    idx = np.linspace(0, frames.shape[0] - 1, max_frames).round().astype(int)
    return frames[idx]


class BatchIterator:
    """Epoch-based iterator over a :class:`CaptionDataset`."""

    def __init__(
        self,
        dataset: CaptionDataset,
        batch_size: int,
        seq_per_img: int,
        max_frames: int,
        *,
        shuffle: bool = True,
        drop_last: bool = False,
        seed: int = 0,
        shard_id: int = 0,
        num_shards: int = 1,
    ):
        if not (0 <= shard_id < num_shards):
            raise ValueError(f"bad shard {shard_id}/{num_shards}")
        self.ds = dataset
        self.batch_size = batch_size
        self.seq_per_img = seq_per_img
        self.max_frames = max_frames
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        # Host sharding: contiguous-stride split of the video index space.
        self._indices = np.arange(shard_id, len(dataset), num_shards)
        self.caption_len = int(dataset.captions(0).shape[1])

    def num_batches(self) -> int:
        n = len(self._indices)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def epoch(self, epoch: int) -> Iterator[Batch]:
        """Deterministic per-epoch stream (seed + epoch -> permutation)."""
        order = self._indices.copy()
        rng = np.random.RandomState(self.seed + 1000003 * epoch)
        if self.shuffle:
            rng.shuffle(order)
        n = len(order)
        limit = (n // self.batch_size) * self.batch_size if self.drop_last else n
        for start in range(0, limit, self.batch_size):
            chunk = order[start : start + self.batch_size]
            if len(chunk) < self.batch_size:
                # Wrap-around pad (tiling as needed when the shard is
                # smaller than a batch): keeps shapes static; duplicated
                # videos contribute slightly more gradient once per epoch.
                pad = np.resize(order, self.batch_size - len(chunk))
                chunk = np.concatenate([chunk, pad])
            yield self._assemble(chunk, rng)

    # ------------------------------------------------------------ assembly
    def _assemble(self, idxs: np.ndarray, rng: np.random.RandomState) -> Batch:
        B, S, F, L = (
            len(idxs),
            self.seq_per_img,
            self.max_frames,
            self.caption_len,
        )
        # Packed fast path (data/packed.py): one vectorized gather per
        # modality instead of B per-video reads (SURVEY.md hot loop #3).
        batched = getattr(self.ds, "features_batch", lambda *_: None)(
            idxs, F
        )
        if batched is not None:
            feats, fmasks = batched
        else:
            feats = {
                m: np.zeros((B, F, d), np.float32)
                for m, d in self.ds.feature_dims.items()
            }
            fmasks = {
                m: np.zeros((B, F), np.float32)
                for m in self.ds.feature_dims
            }
        captions = np.zeros((B, S, L), np.int32)
        weights = np.ones((B, S), np.float32)
        category = np.zeros((B,), np.int32)
        for b, i in enumerate(idxs):
            i = int(i)
            if batched is None:
                for m, fr in self.ds.features(i).items():
                    fr = subsample_frames(fr, F)
                    feats[m][b, : fr.shape[0]] = fr
                    fmasks[m][b, : fr.shape[0]] = 1.0
            caps = self.ds.captions(i)
            w = self.ds.caption_weights(i)
            n = caps.shape[0]
            # Sample seq_per_img captions per video: without replacement
            # when possible, with replacement otherwise (reference
            # dataloader.py behavior for videos with few captions).
            pick = (
                rng.choice(n, S, replace=False)
                if n >= S
                else rng.choice(n, S, replace=True)
            )
            captions[b] = caps[pick]
            weights[b] = w[pick]
            category[b] = self.ds.category(i)
        return Batch(
            feats=feats,
            feat_masks=fmasks,
            captions=captions,
            weights=weights,
            category=category,
            video_idx=idxs.astype(np.int32),
            video_ids=[self.ds.video_id(int(i)) for i in idxs],
        )


def prefetch_to_device(
    batches: Iterator[Batch],
    size: int = 2,
    sharding=None,
) -> Iterator[Batch]:
    """Stage batches onto the device(s) ahead of consumption.

    A daemon thread assembles host batches and ``jax.device_put``s the array
    fields (with ``sharding`` when given — the data-parallel batch sharding
    in the mesh path), so H2D transfer overlaps the previous step's compute.
    ``video_ids`` stays on host.
    """
    q: "queue.Queue" = queue.Queue(maxsize=size)
    END = object()
    stop = threading.Event()

    def _put(item) -> bool:
        """Bounded put that gives up once the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    from cst_captioning_tpu.parallel.sharding import make_placer

    _place = make_placer(sharding)

    def worker():
        try:
            for b in batches:
                arrays = b._asdict()
                put = {
                    k: _place(v)
                    if isinstance(v, (np.ndarray,))
                    else (
                        {m: _place(a) for m, a in v.items()}
                        if isinstance(v, dict)
                        else v
                    )
                    for k, v in arrays.items()
                }
                if not _put(Batch(**put)):
                    return
            _put(END)
        except BaseException as e:  # noqa: BLE001
            # Poison-pill the queue with the CAPTURED exception (its
            # __traceback__ survives on the instance) so the consumer
            # re-raises it instead of the epoch silently ending short.
            _put(e)

    thread = threading.Thread(
        target=worker, daemon=True, name="prefetch_to_device"
    )
    thread.start()
    try:
        while True:
            item = q.get()
            if item is END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        # Abandoned mid-epoch (exception/GeneratorExit in the consumer)
        # or finished: release the worker so it exits instead of
        # blocking on a full queue, drain anything it already staged,
        # and JOIN it — a crashed epoch must not leak a daemon thread
        # holding device-resident batches (it would pin device memory
        # for the life of the process).
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
        thread.join(timeout=10.0)
