"""Packed contiguous feature storage — the streaming input path.

Reference equivalent (SURVEY.md §2 "Data loading" / §3 hot loop #3): the
reference reads one h5 dataset per video per step — fine for a 2017-era
GPU, but random small reads are the classic host-side bottleneck feeding
a TPU.  This module replaces them with one contiguous array per modality:

* layout: ``<dir>/<modality>.npy`` shaped (V, F, D) — every video already
  uniformly subsampled/zero-padded to F frames at pack time — plus
  ``<dir>/meta.json`` ({"modality", "num_videos", "frames", "dim",
  "dtype", "frame_counts", "video_ids"}).
* reads are ``np.memmap`` fancy-indexed gathers: assembling a (B, F, D)
  batch is ONE vectorized copy out of the OS page cache instead of B
  h5 dataset lookups; a whole epoch streams the file sequentially.
* ``dtype="float16"`` halves the bytes on disk and in flight (features
  feed a bfloat16 matmul, so half precision storage costs nothing).

``H5Dataset`` accepts a packed directory anywhere a feature h5 path is
expected (``data.feature_files``), and ``BatchIterator`` uses the batched
gather automatically when every modality is packed
(``H5Dataset.features_batch``).  ``tools/pack_features.py`` converts
per-video h5s; :func:`pack_dataset` packs any ``CaptionDataset`` (used by
tests/benchmarks).

**Remote stores** (SURVEY.md §2 L1 plan: stream from object storage):
any fsspec URL works as the packed directory — ``gs://bucket/dir``,
``s3://…``, ``memory://…`` — detected by the ``://`` in the path.  The
meta json is read through fsspec and row gathers become ranged reads
against the remote ``.npy`` (header parsed once; each row is one
``seek+read`` through fsspec's block cache), so no full-file download is
needed.  Local paths keep the mmap fast path unchanged.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Sequence, Tuple

import numpy as np


def _meta_path(directory: str, modality: str) -> str:
    return os.path.join(directory, f"{modality}.meta.json")


def _arr_path(directory: str, modality: str) -> str:
    return os.path.join(directory, f"{modality}.npy")


def pack_modality(
    directory: str,
    modality: str,
    video_ids: List[str],
    frames_iter,
    max_frames: int,
    dim: int,
    dtype: str = "float32",
) -> str:
    """Write one modality's packed array.

    ``frames_iter`` yields one (F_i, D) array per video in ``video_ids``
    order; each is uniformly subsampled / zero-padded to ``max_frames``.
    Streams straight into the memmap — peak memory is one video.
    """
    from cst_captioning_tpu.data.loader import subsample_frames

    os.makedirs(directory, exist_ok=True)
    path = _arr_path(directory, modality)
    V = len(video_ids)
    out = np.lib.format.open_memmap(
        path, mode="w+", dtype=np.dtype(dtype), shape=(V, max_frames, dim)
    )
    counts = np.zeros((V,), np.int32)
    for i, frames in enumerate(frames_iter):
        fr = subsample_frames(np.asarray(frames), max_frames)
        out[i, : fr.shape[0]] = fr
        out[i, fr.shape[0] :] = 0
        counts[i] = fr.shape[0]
    out.flush()
    del out
    with open(_meta_path(directory, modality), "w") as f:
        json.dump(
            {
                "modality": modality,
                "num_videos": V,
                "frames": max_frames,
                "dim": dim,
                "dtype": dtype,
                "frame_counts": counts.tolist(),
                "video_ids": video_ids,
            },
            f,
        )
    return path


def pack_dataset(
    ds,
    directory: str,
    max_frames: int,
    modalities: Sequence[str] = (),
    dtype: str = "float32",
) -> Dict[str, str]:
    """Pack every (or the named) modalities of a ``CaptionDataset``."""
    modalities = list(modalities) or list(ds.feature_dims)
    vids = [ds.video_id(i) for i in range(len(ds))]
    paths = {}
    for m in modalities:
        paths[m] = pack_modality(
            directory,
            m,
            vids,
            (ds.features(i)[m] for i in range(len(ds))),
            max_frames,
            int(ds.feature_dims[m]),
            dtype=dtype,
        )
    return paths


def _is_remote(path: str) -> bool:
    return "://" in path


class _RemoteNpyRows:
    """Row-gather view of a remote ``.npy`` through fsspec: the header is
    parsed once, then ``[i]`` / ``[array_of_i]`` become ranged reads
    (seek + read of one row's bytes) against the remote object — no full
    download.  Supports exactly the access patterns ``PackedSource``
    uses."""

    def __init__(self, fs, path: str):
        self._fs = fs
        self._path = path
        with fs.open(path, "rb") as f:
            version = np.lib.format.read_magic(f)
            if version == (1, 0):
                shape, fortran, dtype = np.lib.format.read_array_header_1_0(f)
            else:
                shape, fortran, dtype = np.lib.format.read_array_header_2_0(f)
            if fortran:
                raise ValueError(f"{path}: fortran-order npy unsupported")
            self._offset = f.tell()
        self.shape = shape
        self.dtype = dtype
        self._row_bytes = int(np.prod(shape[1:])) * dtype.itemsize
        # No block cache: training gathers are SHUFFLED row reads, so a
        # readahead cache would fetch a multi-MB block per ~100KB row.
        # Single rows use exact ranged reads; batches use one
        # fs.cat_ranges call (concurrent on async filesystems).
        self._f = fs.open(path, "rb", cache_type="none")
        self._has_cat_ranges = hasattr(fs, "cat_ranges")

    def _span(self, i: int):
        start = self._offset + int(i) * self._row_bytes
        return start, start + self._row_bytes

    def _read_row(self, i: int) -> np.ndarray:
        start, end = self._span(i)
        self._f.seek(start)
        buf = self._f.read(self._row_bytes)
        return np.frombuffer(buf, dtype=self.dtype).reshape(self.shape[1:])

    def __getitem__(self, key):
        if isinstance(key, (int, np.integer)):
            return self._read_row(key)
        idxs = np.asarray(key)
        if not self._has_cat_ranges:
            return np.stack([self._read_row(i) for i in idxs])
        spans = [self._span(i) for i in idxs]
        # on_error="raise": the fsspec default ("return") hands back
        # exception OBJECTS inside the list, which frombuffer would then
        # bury under a TypeError.
        bufs = self._fs.cat_ranges(
            [self._path] * len(spans),
            [s for s, _ in spans],
            [e for _, e in spans],
            on_error="raise",
        )
        return np.stack([
            np.frombuffer(b, dtype=self.dtype).reshape(self.shape[1:])
            for b in bufs
        ])


class PackedSource:
    """Reader for one packed modality — memmap-backed for local paths
    (reads hit the OS page cache), ranged fsspec reads for remote URLs
    (``gs://…``, ``memory://…``)."""

    def __init__(self, directory: str, modality: str):
        if _is_remote(directory):
            import fsspec

            fs, root = fsspec.core.url_to_fs(directory)
            meta_path = root.rstrip("/") + f"/{modality}.meta.json"
            with fs.open(meta_path) as f:
                self.meta = json.load(f)
            self._arr = _RemoteNpyRows(
                fs, root.rstrip("/") + f"/{modality}.npy"
            )
        else:
            with open(_meta_path(directory, modality)) as f:
                self.meta = json.load(f)
            self._arr = np.load(
                _arr_path(directory, modality), mmap_mode="r"
            )
        self.modality = modality
        self.frames = int(self.meta["frames"])
        self.dim = int(self.meta["dim"])
        self.frame_counts = np.asarray(self.meta["frame_counts"], np.int32)
        self.video_ids = list(self.meta["video_ids"])
        assert self._arr.shape == (
            len(self.video_ids),
            self.frames,
            self.dim,
        ), self._arr.shape

    def get(self, idx: int) -> np.ndarray:
        """(F_i, D) float32 — trimmed to the video's true frame count
        (CaptionDataset.features contract)."""
        n = int(self.frame_counts[idx])
        return np.asarray(self._arr[idx][:n], np.float32)

    def get_batch(
        self, idxs: np.ndarray, max_frames: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One vectorized gather -> ((B, F, D) stored dtype, (B, F) mask).

        Features keep the STORED dtype (float16 packs skip the f32
        round-trip: the model casts to its compute dtype on device, and
        half-precision host arrays also halve the H2D transfer).
        Requires ``max_frames == packed frames``: a silent leading-frames
        crop would diverge from the per-video path's uniform subsample —
        pack at the training max_frames (the caller falls back to
        per-video reads on mismatch).
        """
        if max_frames != self.frames:
            raise ValueError(
                f"loader max_frames={max_frames} != packed frames="
                f"{self.frames} for modality {self.modality!r} — repack "
                "at the training max_frames"
            )
        feats = self._arr[idxs]  # THE gather: one memcpy
        counts = np.minimum(self.frame_counts[idxs], max_frames)
        mask = (
            np.arange(max_frames)[None, :] < counts[:, None]
        ).astype(np.float32)
        return feats, mask


def is_packed_dir(path: str) -> bool:
    """Heuristic used by ``H5Dataset``: a directory containing at least
    one ``*.meta.json`` packed-modality pair (local or fsspec URL)."""
    if _is_remote(path):
        import fsspec

        fs, root = fsspec.core.url_to_fs(path)
        try:
            names = fs.ls(root, detail=False)
        except FileNotFoundError:
            # Only "no such directory" maps to False; auth/transport
            # errors propagate — swallowing them would misroute the path
            # to the h5 reader and bury the real cause.
            return False
        return any(str(n).endswith(".meta.json") for n in names)
    if not os.path.isdir(path):
        return False
    return any(n.endswith(".meta.json") for n in os.listdir(path))
