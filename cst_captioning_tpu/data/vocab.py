"""Vocabulary: word <-> id mapping, caption encode/decode.

Reference equivalents: vocab-building in the offline prep scripts (frequency
threshold + UNK replacement, SURVEY.md §3.4) and ``utils.py``'s
``decode_sequence`` (ids -> words, stopping at the end token).

Framework-wide token convention (models/captioner.py): 0=PAD, 1=BOS, 2=EOS,
3=UNK, real words from 4.
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Dict, Iterable, List, Sequence

import numpy as np

from cst_captioning_tpu.constants import (
    BOS_ID,
    EOS_ID,
    NUM_SPECIAL_TOKENS,
    PAD_ID,
    UNK_ID,
)

SPECIAL_TOKENS = ("<pad>", "<bos>", "<eos>", "<unk>")


class Vocabulary:
    """Immutable word<->id table with encode/decode helpers."""

    def __init__(self, words: Sequence[str]):
        """``words``: the non-special vocabulary, in fixed order."""
        self.idx_to_word: List[str] = list(SPECIAL_TOKENS) + list(words)
        self.word_to_idx: Dict[str, int] = {
            w: i for i, w in enumerate(self.idx_to_word)
        }
        if len(self.word_to_idx) != len(self.idx_to_word):
            raise ValueError("duplicate words in vocabulary")

    def __len__(self) -> int:
        return len(self.idx_to_word)

    def __contains__(self, word: str) -> bool:
        return word in self.word_to_idx

    # ------------------------------------------------------------ building
    @classmethod
    def build(
        cls, tokenized_captions: Iterable[Sequence[str]], min_freq: int = 1
    ) -> "Vocabulary":
        """Frequency-thresholded vocab (reference prep: words below the
        threshold become UNK).  Order: descending frequency, then lexical —
        deterministic across runs."""
        counts = Counter()
        for caption in tokenized_captions:
            counts.update(caption)
        kept = [w for w, c in counts.items() if c >= min_freq]
        kept.sort(key=lambda w: (-counts[w], w))
        return cls(kept)

    # ------------------------------------------------------------ encoding
    def encode(self, tokens: Sequence[str], max_len: int) -> np.ndarray:
        """[BOS, w1..wn, EOS, PAD...] of length ``max_len + 2``; captions
        longer than ``max_len`` words are truncated."""
        ids = np.full((max_len + 2,), PAD_ID, np.int32)
        ids[0] = BOS_ID
        toks = list(tokens)[:max_len]
        for i, t in enumerate(toks):
            ids[1 + i] = self.word_to_idx.get(t, UNK_ID)
        ids[1 + len(toks)] = EOS_ID
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        """ids -> sentence, stopping at PAD/EOS, skipping BOS.  Ids beyond
        the table (model.vocab_size padded above len(vocab) for TP-friendly
        shapes) decode as <unk> instead of crashing."""
        words = []
        n = len(self.idx_to_word)
        for i in ids:
            i = int(i)
            if i in (PAD_ID, EOS_ID):
                break
            if i == BOS_ID:
                continue
            words.append(self.idx_to_word[i] if 0 <= i < n else "<unk>")
        return " ".join(words)

    # ------------------------------------------------------------------ io
    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"words": self.idx_to_word[NUM_SPECIAL_TOKENS:]}, f)

    @classmethod
    def load(cls, path: str) -> "Vocabulary":
        with open(path) as f:
            return cls(json.load(f)["words"])


def decode_sequence(vocab: Vocabulary, seqs: np.ndarray) -> List[str]:
    """Batch ids (B, T) -> list of sentences (reference ``utils.py``
    ``decode_sequence``)."""
    return [vocab.decode(row) for row in np.asarray(seqs)]
