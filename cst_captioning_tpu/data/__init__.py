"""Data layer: vocabulary, dataset backends, fixed-shape batch iterator.

Rebuilds the reference's ``dataloader.py`` capabilities (SURVEY.md §2 "Data
loading": N feature h5 files + label h5 + cocofmt GT JSONs; batches videos,
samples ``seq_per_img`` captions each, builds padded id matrices + masks)
as a TPU-first pipeline: every batch has identical shapes (no recompiles),
host batch assembly overlaps device compute via a prefetch thread, and the
iterator can shard videos across hosts for multi-process training.
"""

from cst_captioning_tpu.data.vocab import Vocabulary, decode_sequence  # noqa: F401
from cst_captioning_tpu.data.datasets import (  # noqa: F401
    CaptionDataset,
    InMemoryDataset,
    H5Dataset,
    make_synthetic_dataset,
)
from cst_captioning_tpu.data.loader import (  # noqa: F401
    Batch,
    BatchIterator,
    prefetch_to_device,
)
