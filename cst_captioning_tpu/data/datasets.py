"""Dataset backends: in-memory, HDF5, and a learnable synthetic corpus.

Reference equivalent: ``dataloader.py`` (SURVEY.md §2) — opens one feature
h5 per modality (resnet / c3d / mfcc), a label h5 (encoded caption matrix +
per-video start/end index), and cocofmt GT JSONs.  Here a dataset object is
one split; the vocabulary is shared across splits.

The synthetic backend generates a corpus with real signal (caption tokens
are a deterministic function of the video's latent topic, features are the
topic embedding plus noise) so integration tests can overfit it — SURVEY.md
§4 "tiny synthetic dataset → overfit ... to near-zero XE loss".
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cst_captioning_tpu.data.vocab import Vocabulary


class CaptionDataset:
    """Interface: one split of a captioning dataset."""

    vocab: Vocabulary
    feature_dims: Dict[str, int]
    # Externally-supplied per-caption consensus weights (video_id -> (N,)),
    # e.g. from ``data.consensus_file`` — takes precedence over whatever
    # the backend stores (reference: precomputed WXE consensus scores
    # distributed separately from the label file).
    _weight_override: Optional[Dict[str, np.ndarray]] = None

    def __len__(self) -> int:
        raise NotImplementedError

    def video_id(self, idx: int) -> str:
        raise NotImplementedError

    def features(self, idx: int) -> Dict[str, np.ndarray]:
        """modality -> (num_frames, dim) float32 (variable frame count)."""
        raise NotImplementedError

    def captions(self, idx: int) -> np.ndarray:
        """(num_captions, T+2) int32 encoded [BOS..EOS PAD...] rows."""
        raise NotImplementedError

    def set_caption_weights(self, weights: Dict[str, np.ndarray]) -> None:
        """Override consensus weights ({video_id: (num_captions,)})."""
        self._weight_override = {
            k: np.asarray(v, np.float32) for k, v in weights.items()
        }

    def caption_weights(self, idx: int) -> np.ndarray:
        """(num_captions,) float32 consensus weights (ones when absent)."""
        if self._weight_override is not None:
            w = self._weight_override.get(self.video_id(idx))
            if w is not None:
                return w
        return self._stored_caption_weights(idx)

    def _stored_caption_weights(self, idx: int) -> np.ndarray:
        return np.ones((self.captions(idx).shape[0],), np.float32)

    def category(self, idx: int) -> int:
        return 0

    def references(self, idx: int) -> List[str]:
        """Raw reference strings (for eval ground truth / CST rewards)."""
        raise NotImplementedError


class InMemoryDataset(CaptionDataset):
    def __init__(
        self,
        vocab: Vocabulary,
        video_ids: Sequence[str],
        features: Dict[str, List[np.ndarray]],
        captions: List[np.ndarray],
        references: List[List[str]],
        weights: Optional[List[np.ndarray]] = None,
        categories: Optional[Sequence[int]] = None,
    ):
        self.vocab = vocab
        self._ids = list(video_ids)
        self._feats = features
        self._caps = captions
        self._refs = references
        self._weights = weights
        self._cats = list(categories) if categories is not None else None
        self.feature_dims = {
            m: int(arrs[0].shape[-1]) for m, arrs in features.items()
        }
        n = len(self._ids)
        for m, arrs in features.items():
            assert len(arrs) == n, f"modality {m}: {len(arrs)} != {n} videos"
        assert len(captions) == n and len(references) == n

    def __len__(self) -> int:
        return len(self._ids)

    def video_id(self, idx: int) -> str:
        return self._ids[idx]

    def features(self, idx: int) -> Dict[str, np.ndarray]:
        return {m: arrs[idx] for m, arrs in self._feats.items()}

    def captions(self, idx: int) -> np.ndarray:
        return self._caps[idx]

    def _stored_caption_weights(self, idx: int) -> np.ndarray:
        if self._weights is None:
            return super()._stored_caption_weights(idx)
        return self._weights[idx]

    def category(self, idx: int) -> int:
        return self._cats[idx] if self._cats is not None else 0

    def references(self, idx: int) -> List[str]:
        return self._refs[idx]


class H5Dataset(CaptionDataset):
    """HDF5-backed split, mirroring the reference's on-disk layout
    (SURVEY.md §2 "Data loading"): one feature file per modality plus a
    label file.

    Schema (written by ``tools/prepare_data.py``):
      feature file ``<modality>.h5``: one dataset per video id, (F, D).
      label file: ``captions`` (total, T+2) int32; ``cap_start``/``cap_end``
      (V,) int64 index ranges per video; ``weights`` (total,) float32;
      ``category`` (V,) int32; ``video_ids`` (V,) utf-8 strings; plus a
      ``refs`` group: per-video raw reference strings for eval/rewards.
    """

    def __init__(self, label_file: str, feature_files: Dict[str, str],
                 vocab: Vocabulary):
        import h5py  # deferred: h5 path only

        from cst_captioning_tpu.data.packed import (
            PackedSource,
            is_packed_dir,
        )

        self.vocab = vocab
        self._lab = h5py.File(label_file, "r")
        self._ids = [
            v.decode() if isinstance(v, bytes) else str(v)
            for v in self._lab["video_ids"][()]
        ]
        self._start = self._lab["cap_start"][()]
        self._end = self._lab["cap_end"][()]
        # Each modality is either a per-video h5 (reference layout) or a
        # packed contiguous directory (data/packed.py streaming layout).
        self._h5 = {}
        self._packed = {}
        self._packed_remap = {}
        for m, p in feature_files.items():
            if is_packed_dir(p):
                src = PackedSource(p, m)
                order = {v: i for i, v in enumerate(src.video_ids)}
                missing = [v for v in self._ids if v not in order]
                if missing:
                    raise ValueError(
                        f"packed modality {m!r} at {p} is missing "
                        f"{len(missing)} of this split's videos "
                        f"(first: {missing[:3]})"
                    )
                self._packed[m] = src
                self._packed_remap[m] = np.asarray(
                    [order[v] for v in self._ids], np.int64
                )
            else:
                self._h5[m] = h5py.File(p, "r")
        self.feature_dims = {
            m: int(f[self._ids[0]].shape[-1]) for m, f in self._h5.items()
        }
        self.feature_dims.update(
            {m: src.dim for m, src in self._packed.items()}
        )

    def __len__(self) -> int:
        return len(self._ids)

    def video_id(self, idx: int) -> str:
        return self._ids[idx]

    def features(self, idx: int) -> Dict[str, np.ndarray]:
        vid = self._ids[idx]
        out = {m: f[vid][()].astype(np.float32) for m, f in self._h5.items()}
        for m, src in self._packed.items():
            out[m] = src.get(int(self._packed_remap[m][idx]))
        return out

    def features_batch(self, idxs: np.ndarray, max_frames: int):
        """Vectorized batch gather — available when EVERY modality is
        packed; returns (feats {m: (B,F,D)}, masks {m: (B,F)}) or None
        (the loader then falls back to per-video reads)."""
        if self._h5 or not self._packed:
            return None
        if any(src.frames != max_frames for src in self._packed.values()):
            # Packed at a different frame count: the fast gather would
            # change the temporal subsample — use the per-video path
            # (PackedSource.get + subsample_frames), which stays exact.
            return None
        feats, masks = {}, {}
        for m, src in self._packed.items():
            feats[m], masks[m] = src.get_batch(
                self._packed_remap[m][np.asarray(idxs)], max_frames
            )
        return feats, masks

    def captions(self, idx: int) -> np.ndarray:
        return self._lab["captions"][self._start[idx] : self._end[idx]].astype(
            np.int32
        )

    def _stored_caption_weights(self, idx: int) -> np.ndarray:
        if "weights" not in self._lab:
            return super()._stored_caption_weights(idx)
        return self._lab["weights"][self._start[idx] : self._end[idx]].astype(
            np.float32
        )

    def category(self, idx: int) -> int:
        if "category" not in self._lab:
            return 0
        return int(self._lab["category"][idx])

    def references(self, idx: int) -> List[str]:
        refs = self._lab["refs"][self.video_id(idx)][()]
        return [r.decode() if isinstance(r, bytes) else str(r) for r in refs]

    def close(self) -> None:
        for f in self._h5.values():
            f.close()
        self._lab.close()


# --------------------------------------------------------------- synthetic

_SYNTH_NOUNS = [
    "cat", "dog", "man", "woman", "car", "ball", "bird", "horse", "child",
    "robot", "chef", "dancer", "player", "singer", "train",
]
_SYNTH_VERBS = [
    "runs", "jumps", "sings", "drives", "cooks", "plays", "walks", "flies",
    "dances", "sleeps",
]
_SYNTH_ADVS = ["quickly", "slowly", "happily", "loudly", "quietly", "gracefully"]


def make_synthetic_dataset(
    num_videos: int = 50,
    refs_per_video: int = 3,
    feature_dims: Optional[Dict[str, int]] = None,
    max_frames: int = 6,
    max_words: int = 10,
    noise: float = 0.1,
    num_categories: int = 0,
    seed: int = 0,
) -> Tuple[InMemoryDataset, Vocabulary]:
    """Learnable toy corpus.  Video ``i`` has a topic (noun, verb); its
    features are a fixed random embedding of the topic plus per-frame noise;
    its references are "<noun> <verb> [<adverb>]" with the adverb varying
    across references (so consensus scoring has real variance)."""
    feature_dims = feature_dims or {"resnet": 64}
    rng = np.random.RandomState(seed)
    topics = [
        (rng.randint(len(_SYNTH_NOUNS)), rng.randint(len(_SYNTH_VERBS)))
        for _ in range(num_videos)
    ]
    per_video_refs: List[List[str]] = []
    for n_i, v_i in topics:
        refs = []
        for r in range(refs_per_video):
            words = [_SYNTH_NOUNS[n_i], _SYNTH_VERBS[v_i]]
            if r > 0:
                words.append(_SYNTH_ADVS[(n_i + v_i + r) % len(_SYNTH_ADVS)])
            refs.append(" ".join(words))
        per_video_refs.append(refs)
    # Seed-INDEPENDENT vocabulary over the full synthetic word lists: any
    # split (train/val/test at different seeds) shares one id<->word table,
    # so decoding val predictions with the train vocab is always correct.
    vocab = Vocabulary(_SYNTH_NOUNS + _SYNTH_VERBS + _SYNTH_ADVS)

    # Topic embeddings from a seed-independent generator so every split
    # maps topic t to the same feature cluster.
    topic_rng = np.random.RandomState(20260729)
    topic_embed = {
        m: topic_rng.randn(len(_SYNTH_NOUNS) * len(_SYNTH_VERBS), d).astype(
            np.float32
        )
        for m, d in feature_dims.items()
    }
    feats: Dict[str, List[np.ndarray]] = {m: [] for m in feature_dims}
    caps: List[np.ndarray] = []
    for n_i, v_i in topics:
        t = n_i * len(_SYNTH_VERBS) + v_i
        nf = rng.randint(max_frames // 2 + 1, max_frames + 1)
        for m in feature_dims:
            base = topic_embed[m][t]
            frames = base[None, :] + noise * rng.randn(nf, base.shape[0]).astype(
                np.float32
            )
            feats[m].append(frames.astype(np.float32))
    for refs in per_video_refs:
        caps.append(
            np.stack([vocab.encode(r.split(), max_words) for r in refs])
        )
    cats = (
        [rng.randint(num_categories) for _ in range(num_videos)]
        if num_categories
        else None
    )
    ds = InMemoryDataset(
        vocab=vocab,
        video_ids=[f"video{i}" for i in range(num_videos)],
        features=feats,
        captions=caps,
        references=per_video_refs,
        categories=cats,
    )
    return ds, vocab
