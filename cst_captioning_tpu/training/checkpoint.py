"""Orbax checkpointing with keep-best + warm-start semantics.

Reference equivalent (SURVEY.md §5 "Checkpoint / resume"): ``torch.save``
of model+optimizer+infos each epoch, a "best on val CIDEr" copy, and CST
stages warm-starting from the WXE/XE checkpoint (``--start_from``).

Layout: ``<path>/params`` and ``<path>/opt`` are separate orbax items so a
warm start (params only — each stage restarts its optimizer/LR schedule)
never needs to know the previous stage's optimizer structure.
``<path>/infos.json`` is a human-readable sidecar (epoch, val metrics).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _abs(path: str) -> str:
    return os.path.abspath(path)


def _replicated_sharding() -> NamedSharding:
    """Fully-replicated sharding over ALL devices (every process)."""
    mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("_all",))
    return NamedSharding(mesh, P())


def _is_host_local(x) -> bool:
    """True for arrays orbax cannot serialize in a multi-process run:
    plain host values, or jax.Arrays living only on this process's
    devices (e.g. an un-meshed ``state.step`` counter)."""
    if not isinstance(x, jax.Array):
        return True
    return jax.process_count() > 1 and x.sharding.is_fully_addressable


def _globalize(tree):
    """Multi-host save support: lift host-local leaves to globally
    replicated arrays (the value is identical on every process — step
    counters, un-meshed scalars).  Single-process: identity."""
    if jax.process_count() == 1:
        return tree
    rep = _replicated_sharding()

    def fix(x):
        if not _is_host_local(x):
            return x
        arr = np.asarray(x)
        return jax.make_array_from_callback(
            arr.shape, rep, lambda idx, a=arr: a[idx]
        )

    return jax.tree.map(fix, tree)


def _abstract(x):
    """Shape/dtype struct carrying the template's sharding, so restored
    arrays land exactly where the live state's arrays are (mesh-sharded
    params, replicated opt counters, ...).  Host-local templates map to
    the replicated global sharding on multi-process runs (matching
    ``_globalize`` at save time)."""
    if isinstance(x, jax.Array) and not _is_host_local(x):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    x = np.asarray(x)
    if jax.process_count() > 1:
        return jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=_replicated_sharding()
        )
    x = jnp.asarray(x)
    return jax.ShapeDtypeStruct(x.shape, x.dtype)


def save_checkpoint(path: str, state, extra: Optional[Dict[str, Any]] = None
                    ) -> None:
    """Save a TrainState: params + (opt_state, step) + json sidecar."""
    path = _abs(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(
        os.path.join(path, "params"), _globalize(state.params), force=True
    )
    ckptr.save(
        os.path.join(path, "opt"),
        _globalize(
            {"opt_state": state.opt_state, "step": jnp.asarray(state.step)}
        ),
        force=True,
    )
    ckptr.wait_until_finished()
    # Orbax coordinates the array writes across processes; the json
    # sidecar has no such coordination — only rank 0 writes it, or
    # multi-host runs on a shared filesystem race on the same file.
    if extra is not None and jax.process_index() == 0:
        with open(os.path.join(path, "infos.json"), "w") as f:
            json.dump(extra, f, indent=2, default=str)


def load_infos(path: str) -> Dict[str, Any]:
    p = os.path.join(_abs(path), "infos.json")
    if not os.path.exists(p):
        return {}
    with open(p) as f:
        return json.load(f)


def restore_checkpoint(path: str, state):
    """Full resume: params + optimizer + step into ``state``'s structure."""
    path = _abs(path)
    ckptr = ocp.StandardCheckpointer()
    params = ckptr.restore(
        os.path.join(path, "params"),
        jax.tree.map(_abstract, state.params),
    )
    opt = ckptr.restore(
        os.path.join(path, "opt"),
        {
            "opt_state": jax.tree.map(_abstract, state.opt_state),
            "step": _abstract(state.step),
        },
    )
    step = opt["step"]
    if isinstance(step, jax.Array) and not step.sharding.is_fully_addressable:
        # Globally-replicated scalar (multi-host save): every process holds
        # the same value in its local shard.
        step = step.addressable_shards[0].data
    return state.replace(
        params=params,
        opt_state=opt["opt_state"],
        step=int(np.asarray(step)),
    )


def restore_params(path: str, params_template):
    """Warm start (reference ``--start_from``): parameters only."""
    path = _abs(path)
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(
        os.path.join(path, "params"),
        jax.tree.map(_abstract, params_template),
    )
