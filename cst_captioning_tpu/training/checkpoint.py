"""Orbax checkpointing with keep-best + warm-start semantics.

Reference equivalent (SURVEY.md §5 "Checkpoint / resume"): ``torch.save``
of model+optimizer+infos each epoch, a "best on val CIDEr" copy, and CST
stages warm-starting from the WXE/XE checkpoint (``--start_from``).

Layout: ``<path>/params`` and ``<path>/opt`` are separate orbax items so a
warm start (params only — each stage restarts its optimizer/LR schedule)
never needs to know the previous stage's optimizer structure.
``<path>/infos.json`` is a human-readable sidecar (epoch, val metrics).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _abs(path: str) -> str:
    return os.path.abspath(path)


def _replicated_sharding() -> NamedSharding:
    """Fully-replicated sharding over ALL devices (every process)."""
    mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("_all",))
    return NamedSharding(mesh, P())


def _is_host_local(x) -> bool:
    """True for arrays orbax cannot serialize in a multi-process run:
    plain host values, or jax.Arrays living only on this process's
    devices (e.g. an un-meshed ``state.step`` counter)."""
    if not isinstance(x, jax.Array):
        return True
    return jax.process_count() > 1 and x.sharding.is_fully_addressable


def _globalize(tree):
    """Multi-host save support: lift host-local leaves to globally
    replicated arrays (the value is identical on every process — step
    counters, un-meshed scalars).  Single-process: identity."""
    if jax.process_count() == 1:
        return tree
    rep = _replicated_sharding()

    def fix(x):
        if not _is_host_local(x):
            return x
        arr = np.asarray(x)
        return jax.make_array_from_callback(
            arr.shape, rep, lambda idx, a=arr: a[idx]
        )

    return jax.tree.map(fix, tree)


def _abstract(x):
    """Shape/dtype struct carrying the template's sharding, so restored
    arrays land exactly where the live state's arrays are (mesh-sharded
    params, replicated opt counters, ...).  Host-local templates map to
    the replicated global sharding on multi-process runs (matching
    ``_globalize`` at save time)."""
    if isinstance(x, jax.Array) and not _is_host_local(x):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    x = np.asarray(x)
    if jax.process_count() > 1:
        return jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=_replicated_sharding()
        )
    x = jnp.asarray(x)
    return jax.ShapeDtypeStruct(x.shape, x.dtype)


def sharding_metadata(params) -> Dict[str, Any]:
    """Machine-readable record of HOW a param tree was sharded at save
    time: the mesh shape (``"2x4"``-style, matching the bench
    ``*_mesh_shape`` contract), axis names, and per-leaf PartitionSpec
    strings.  Restore does NOT need it (the template's shardings drive
    the reshard) — it exists so a checkpoint names the topology it came
    from, making cross-topology loads auditable from the sidecar alone.
    """
    mesh = None
    specs: Dict[str, str] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        name = "/".join(
            getattr(k, "key", getattr(k, "name", str(k))) for k in path
        )
        sh = getattr(leaf, "sharding", None)
        if isinstance(sh, NamedSharding):
            mesh = mesh or sh.mesh
            specs[name] = str(sh.spec)
        else:
            specs[name] = "unsharded"
    meta: Dict[str, Any] = {"specs": specs}
    if mesh is not None:
        meta["mesh_shape"] = "x".join(
            str(mesh.shape[a]) for a in mesh.axis_names
        )
        meta["mesh_axes"] = list(mesh.axis_names)
    else:
        meta["mesh_shape"] = "1x1"
        meta["mesh_axes"] = []
    return meta


def saved_sharding(path: str) -> Dict[str, Any]:
    """The sharding metadata a checkpoint was saved with ({} for
    checkpoints from before the sidecar carried it)."""
    info = load_infos(path)
    sh = info.get("sharding", {})
    return sh if isinstance(sh, dict) else {}


def save_checkpoint(path: str, state, extra: Optional[Dict[str, Any]] = None
                    ) -> None:
    """Save a TrainState: params + (opt_state, step) + json sidecar.

    The sidecar always records the save-time mesh/spec metadata
    (:func:`sharding_metadata`) under ``"sharding"`` — restore onto a
    DIFFERENT topology is supported (the restore template's shardings
    drive an orbax reshard; tests/test_partition.py pins the 1x1 ->
    {2x1, 1x2, 2x2} round trips bit-identical)."""
    path = _abs(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(
        os.path.join(path, "params"), _globalize(state.params), force=True
    )
    ckptr.save(
        os.path.join(path, "opt"),
        _globalize(
            {"opt_state": state.opt_state, "step": jnp.asarray(state.step)}
        ),
        force=True,
    )
    ckptr.wait_until_finished()
    # Orbax coordinates the array writes across processes; the json
    # sidecar has no such coordination — only rank 0 writes it, or
    # multi-host runs on a shared filesystem race on the same file.
    if jax.process_index() == 0:
        infos = dict(extra) if extra is not None else {}
        infos.setdefault("sharding", sharding_metadata(state.params))
        with open(os.path.join(path, "infos.json"), "w") as f:
            json.dump(infos, f, indent=2, default=str)


def load_infos(path: str) -> Dict[str, Any]:
    p = os.path.join(_abs(path), "infos.json")
    if not os.path.exists(p):
        return {}
    with open(p) as f:
        return json.load(f)


_log = logging.getLogger("cst_captioning_tpu.checkpoint")


def _log_reshard(path: str, state) -> None:
    """Cross-topology load visibility: when the checkpoint's recorded
    mesh differs from the restore template's, say so — the restore
    itself is a plain orbax reshard (template shardings win), but a
    silent topology change is worth one log line in the run record."""
    saved = saved_sharding(path).get("mesh_shape")
    if not saved:
        return
    now = sharding_metadata(state.params).get("mesh_shape")
    if now != saved:
        _log.info(
            "checkpoint %s was saved on a %s mesh; resharding onto %s "
            "(template shardings drive the reshard)",
            path, saved, now,
        )


def restore_checkpoint(path: str, state):
    """Full resume: params + optimizer + step into ``state``'s structure.

    Cross-topology by construction: every leaf restores to the
    TEMPLATE's sharding (``_abstract`` carries it), so a checkpoint
    saved on one mesh loads onto any other whose leaf shapes match —
    orbax reshards during the read."""
    path = _abs(path)
    _log_reshard(path, state)
    ckptr = ocp.StandardCheckpointer()
    params = ckptr.restore(
        os.path.join(path, "params"),
        jax.tree.map(_abstract, state.params),
    )
    opt = ckptr.restore(
        os.path.join(path, "opt"),
        {
            "opt_state": jax.tree.map(_abstract, state.opt_state),
            "step": _abstract(state.step),
        },
    )
    step = opt["step"]
    if isinstance(step, jax.Array) and not step.sharding.is_fully_addressable:
        # Globally-replicated scalar (multi-host save): every process holds
        # the same value in its local shard.
        step = step.addressable_shards[0].data
    return state.replace(
        params=params,
        opt_state=opt["opt_state"],
        step=int(np.asarray(step)),
    )


def restore_params(path: str, params_template):
    """Warm start (reference ``--start_from``): parameters only."""
    path = _abs(path)
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(
        os.path.join(path, "params"),
        jax.tree.map(_abstract, params_template),
    )
