"""Orbax checkpointing with keep-best + warm-start semantics.

Reference equivalent (SURVEY.md §5 "Checkpoint / resume"): ``torch.save``
of model+optimizer+infos each epoch, a "best on val CIDEr" copy, and CST
stages warm-starting from the WXE/XE checkpoint (``--start_from``).

Layout: ``<path>/params`` and ``<path>/opt`` are separate orbax items so a
warm start (params only — each stage restarts its optimizer/LR schedule)
never needs to know the previous stage's optimizer structure.
``<path>/infos.json`` is a human-readable sidecar (epoch, val metrics).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp


def _abs(path: str) -> str:
    return os.path.abspath(path)


def _abstract(x):
    """Shape/dtype struct carrying the template's sharding, so restored
    arrays land exactly where the live state's arrays are (mesh-sharded
    params, replicated opt counters, ...)."""
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    x = jnp.asarray(x)
    return jax.ShapeDtypeStruct(x.shape, x.dtype)


def save_checkpoint(path: str, state, extra: Optional[Dict[str, Any]] = None
                    ) -> None:
    """Save a TrainState: params + (opt_state, step) + json sidecar."""
    path = _abs(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, "params"), state.params, force=True)
    ckptr.save(
        os.path.join(path, "opt"),
        {"opt_state": state.opt_state, "step": jnp.asarray(state.step)},
        force=True,
    )
    ckptr.wait_until_finished()
    if extra is not None:
        with open(os.path.join(path, "infos.json"), "w") as f:
            json.dump(extra, f, indent=2, default=str)


def load_infos(path: str) -> Dict[str, Any]:
    p = os.path.join(_abs(path), "infos.json")
    if not os.path.exists(p):
        return {}
    with open(p) as f:
        return json.load(f)


def restore_checkpoint(path: str, state):
    """Full resume: params + optimizer + step into ``state``'s structure."""
    path = _abs(path)
    ckptr = ocp.StandardCheckpointer()
    params = ckptr.restore(
        os.path.join(path, "params"),
        jax.tree.map(_abstract, state.params),
    )
    opt = ckptr.restore(
        os.path.join(path, "opt"),
        {
            "opt_state": jax.tree.map(_abstract, state.opt_state),
            "step": _abstract(state.step),
        },
    )
    return state.replace(
        params=params,
        opt_state=opt["opt_state"],
        step=int(opt["step"]),
    )


def restore_params(path: str, params_template):
    """Warm start (reference ``--start_from``): parameters only."""
    path = _abs(path)
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(
        os.path.join(path, "params"),
        jax.tree.map(_abstract, params_template),
    )
