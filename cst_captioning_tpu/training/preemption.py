"""Preemption handling: checkpoint-on-SIGTERM for TPU/GKE evictions.

Reference equivalent: none — the reference is a single-GPU research
script (SURVEY.md §5 "Failure detection": resume-from-checkpoint covers
preemption).  Cloud TPU VMs and GKE nodes deliver SIGTERM with a grace
window before eviction; this module turns that signal into a save of the
``last`` checkpoint so ``train.resume`` continues the run exactly where
it stopped (``tests/test_resume.py`` proves resumed == uninterrupted).

Usage (the Trainer wires this automatically via ``fit``):

    guard = PreemptionGuard.install()
    for epoch in ...:
        ...train...
        if guard.triggered:
            save_checkpoint(...); break

The handler itself only sets a flag — checkpointing from inside a signal
handler would re-enter orbax/XLA mid-step.  The epoch loop polls the
flag at step granularity and exits through the normal save path.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Optional

log = logging.getLogger("cst_captioning_tpu.preemption")


class PreemptionGuard:
    """Latches SIGTERM (and optionally SIGINT) into a thread-safe flag."""

    _installed: Optional["PreemptionGuard"] = None

    def __init__(self):
        self._event = threading.Event()
        self._prev = {}

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def _handler(self, signum, frame):
        log.warning(
            "signal %s received — will checkpoint and stop at the next "
            "step boundary", signal.Signals(signum).name,
        )
        self._event.set()
        prev = self._prev.get(signum)
        if callable(prev):  # chain to any previously-installed handler
            prev(signum, frame)

    @classmethod
    def install(cls, signals=(signal.SIGTERM,)) -> "PreemptionGuard":
        """Idempotent: repeated installs return the same guard.  Only the
        main thread may set signal handlers; elsewhere returns a guard
        that never triggers (e.g. Trainer built inside a test worker)."""
        if cls._installed is not None:
            return cls._installed
        guard = cls()
        if threading.current_thread() is not threading.main_thread():
            log.info("not on the main thread — preemption guard inert")
            return guard
        for sig in signals:
            try:
                guard._prev[sig] = signal.signal(sig, guard._handler)
            except (ValueError, OSError) as e:
                log.info("cannot install handler for %s (%s)", sig, e)
        cls._installed = guard
        return guard

    @classmethod
    def _reset_for_tests(cls) -> None:
        if cls._installed is not None:
            for sig, prev in cls._installed._prev.items():
                try:
                    signal.signal(sig, prev)
                except (ValueError, OSError):
                    pass
        cls._installed = None
