"""Jitted train/eval step factories and optimizer construction.

Reference equivalent: the inner block of ``train.py``'s epoch loop
(SURVEY.md §3.1) — forward, masked-(W)XE loss, backward, Adam step, LR
decay, grad clip.  Here the whole block is ONE jitted function with donated
state; the LR decay is an optax schedule (factor ``lr_decay`` every
``lr_decay_every`` epochs, reference ``opts.py`` flags) baked into the
optimizer, so no Python-side LR mutation exists.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state

from cst_captioning_tpu.models.captioner import CaptionModel, PAD_ID
from cst_captioning_tpu.ops.losses import weighted_cross_entropy


class TrainState(train_state.TrainState):
    """flax TrainState (params, tx, opt_state, step) — no extra fields."""


class PhaseClock:
    """Per-step wall-time breakdown for host-driven train steps.

    The split/pipelined CST layouts interleave device dispatches with
    host reward scoring; knowing WHERE a step's wall time goes (sample
    fetch vs host scoring vs exposed scoring stall vs update dispatch)
    is what makes reward-scoring regressions visible in training logs
    instead of only in bench runs.  Usage per step::

        clock.start()
        ... ; clock.lap("sample_fetch_ms")
        ... ; clock.lap("score_ms")
        clock.commit(into)   # rounds + writes phase dict, adds total_ms

    ``lap(key)`` ACCUMULATES into ``key`` (call sites inside loops add
    up), so one step's phases always sum to ``total_ms`` minus unlapped
    gaps.  The dict written by ``commit`` is plain host floats — the
    trainer averages them into the epoch entry and TensorBoard.

    Every lap is ALSO a span (observability/trace.py): ``lap(key)``
    records the interval as ``phase/<key>`` and ``commit`` closes the
    step's ``cst/step`` root, in the same Chrome-trace format the
    serving /debug/trace export uses — so a CST step and a served
    request render in one Perfetto timeline
    (``train.trace_file`` writes the export at the end of fit()).
    Clocks are ``time.monotonic()`` — the tracer's base, and the only
    clock the CST-OBS rules allow on a span path.
    """

    def __init__(self, tags: Optional[Dict[str, str]] = None,
                 tracer=None):
        if tracer is None:
            from cst_captioning_tpu.observability.trace import get_tracer

            tracer = get_tracer()
        self.tracer = tracer
        self.tags = dict(tags or ())
        self._t0 = None
        self._last = None
        self._acc: Dict[str, float] = {}
        self._trace_id: Optional[str] = None
        self._root_id: Optional[str] = None

    def start(self) -> None:
        self._t0 = self._last = time.monotonic()
        self._acc = {}
        if self.tracer.enabled:
            self._trace_id = self.tracer.new_trace_id()
            self._root_id = self.tracer.new_span_id()

    def lap(self, key: str) -> None:
        now = time.monotonic()
        self._acc[key] = self._acc.get(key, 0.0) + (now - self._last) * 1e3
        if self.tracer.enabled:
            name = key[:-3] if key.endswith("_ms") else key
            self.tracer.record(
                f"phase/{name}", self._last, now,
                trace_id=self._trace_id, parent_id=self._root_id,
                tags=self.tags or None,
            )
        self._last = now

    def commit(self, into: Dict[str, float]) -> Dict[str, float]:
        now = time.monotonic()
        total = (now - self._t0) * 1e3
        if self.tracer.enabled:
            self.tracer.record(
                "cst/step", self._t0, now,
                trace_id=self._trace_id, span_id=self._root_id,
                tags=self.tags or None,
            )
        into.clear()
        into.update({k: round(v, 3) for k, v in self._acc.items()})
        into["total_ms"] = round(total, 3)
        return into


def make_lr_schedule(cfg_train, steps_per_epoch: int) -> optax.Schedule:
    """lr * decay^(epoch // decay_every), epoch = step // steps_per_epoch."""
    base, decay, every = (
        cfg_train.learning_rate,
        cfg_train.lr_decay,
        cfg_train.lr_decay_every,
    )
    if every <= 0 or decay >= 1.0 - 1e-9:
        return optax.constant_schedule(base)
    decay_steps = max(1, every * steps_per_epoch)

    def schedule(step):
        return base * jnp.power(decay, step // decay_steps)

    return schedule


def make_optimizer(cfg_train, steps_per_epoch: int) -> optax.GradientTransformation:
    sched = make_lr_schedule(cfg_train, steps_per_epoch)
    parts = []
    if cfg_train.grad_clip > 0:
        parts.append(optax.clip_by_global_norm(cfg_train.grad_clip))
    if cfg_train.optimizer == "adam":
        if cfg_train.weight_decay > 0:
            parts.append(
                optax.adamw(
                    sched,
                    b1=cfg_train.beta1,
                    b2=cfg_train.beta2,
                    eps=cfg_train.epsilon,
                    weight_decay=cfg_train.weight_decay,
                )
            )
        else:
            parts.append(
                optax.adam(
                    sched,
                    b1=cfg_train.beta1,
                    b2=cfg_train.beta2,
                    eps=cfg_train.epsilon,
                )
            )
    elif cfg_train.optimizer == "sgd":
        parts.append(optax.sgd(sched))
    elif cfg_train.optimizer == "rmsprop":
        parts.append(optax.rmsprop(sched))
    else:
        raise ValueError(f"unknown optimizer {cfg_train.optimizer!r}")
    return optax.chain(*parts)


def create_train_state(
    rng: jax.Array,
    model: CaptionModel,
    tx: optax.GradientTransformation,
    sample_batch: Dict[str, Any],
    mesh=None,
) -> TrainState:
    """Initialize params from one (host) batch's shapes.

    With a ``mesh``, parameters are placed per the tensor-parallel rules
    (``parallel/sharding.py``) BEFORE ``tx.init`` so the Adam moments
    inherit each param's sharding.
    """
    feats = {m: jnp.asarray(v[:1]) for m, v in sample_batch["feats"].items()}
    masks = {
        m: jnp.asarray(v[:1]) for m, v in sample_batch["feat_masks"].items()
    }
    ids = jnp.asarray(sample_batch["captions"][:1, 0, :-1])
    cat = (
        jnp.asarray(sample_batch["category"][:1])
        if model.use_category
        else None
    )
    params = model.init(rng, feats, masks, ids, category=cat)
    if mesh is None:
        return TrainState.create(apply_fn=model.apply, params=params, tx=tx)

    from cst_captioning_tpu.parallel import partition
    from cst_captioning_tpu.parallel.sharding import shard_params

    params = shard_params(params, mesh)
    state = TrainState.create(apply_fn=model.apply, params=params, tx=tx)
    # Optimizer state is placed by the SAME rule table as the params
    # (partition.match_partition_rules port: Adam's mu/nu mirror the
    # param tree so the regexes match their paths; optax's scalar
    # counters replicate).  zeros_like of sharded params already lands
    # the moments right — the explicit placement makes it a CHECKED
    # contract instead of an inherited accident, and commits the stray
    # default-device counters so every state leaf has a consistent
    # placement (checkpoint restore preserves leaf shardings — mixed
    # placements would clash after resume).
    return state.replace(
        opt_state=partition.shard_tree(state.opt_state, mesh)
    )


def _flatten_batch(captions, weights):
    """(B, S, L) captions -> caption-major (B*S, L) + flat weights.

    Features/category are NOT tiled here: the model's ``repeat=S`` tiles
    the projected cache after the feature projections (the reference
    tiles raw features on host in ``dataloader.py`` — S x the projection
    GEMMs for identical results; see ``_repeat_cache``)."""
    B, S, L = captions.shape
    caps = captions.reshape(B * S, L)
    w = weights.reshape(B * S)
    return caps, w, S


def sharded_step_kwargs(mesh, state_template, n_batch_args: int,
                        n_extra_args: int = 1):
    """``in_shardings``/``out_shardings`` for an update-step jit:
    TrainState in/out per the partition rules, batch args over ``data``,
    trailing extras (rng, traced knobs) replicated, metrics replicated.
    Returns {} off-mesh so call sites stay unconditional."""
    if mesh is None or state_template is None:
        return {}
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cst_captioning_tpu.parallel import partition

    state_sh = partition.state_shardings(state_template, mesh)
    batch = NamedSharding(mesh, partition.batch_spec(mesh))
    rep = NamedSharding(mesh, P())
    return dict(
        in_shardings=(
            (state_sh,) + (batch,) * n_batch_args + (rep,) * n_extra_args
        ),
        out_shardings=(state_sh, rep),
    )


def make_xe_train_step(
    model: CaptionModel,
    mesh=None,
    state_template=None,
) -> Callable:
    """XE/WXE train step. WXE == XE with non-uniform ``weights`` (the loader
    supplies consensus weights; ones for plain XE), reference train_mode
    switch in ``train.py``.

    Signature (shared with the CST step so the trainer dispatches
    uniformly): ``(state, feats, feat_masks, captions(B,S,L), weights(B,S),
    category(B,)|None, video_idx(B,), rng, ss_prob) -> (state, metrics)``;
    ``video_idx`` is unused here (the CST step needs it for reward refs).

    With a ``mesh`` + ``state_template`` the jit becomes NamedSharding-
    in/out: state per the partition rules (vocab tensors + Adam moments
    over ``model``), batch args over ``data``, and the (rows, T, V)
    logits pinned rows-over-data x vocab-over-model before the loss so
    XLA keeps the dominant vocab matmul sharded instead of all-gathering
    the logits early.  Donation is preserved either way.
    """
    logits_sharding = None
    if mesh is not None:
        from cst_captioning_tpu.parallel import partition

        logits_sharding = partition.logits_sharding(mesh, ndim=3)

    def train_step(state, feats, feat_masks, captions, weights, category,
                   video_idx, rng, ss_prob):
        caps, w, S = _flatten_batch(captions, weights)
        inputs, targets = caps[:, :-1], caps[:, 1:]
        tmask = (targets != PAD_ID).astype(jnp.float32)
        rng_drop, rng_ss = jax.random.split(rng)

        def loss_fn(params):
            logits = state.apply_fn(
                params,
                feats,
                feat_masks,
                inputs,
                category=category,
                ss_prob=ss_prob,
                deterministic=False,
                rng=rng_ss,
                rngs={"dropout": rng_drop},
                repeat=S,
            )
            if logits_sharding is not None:
                logits = jax.lax.with_sharding_constraint(
                    logits, logits_sharding
                )
            return weighted_cross_entropy(logits, targets, tmask, w)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        gnorm = optax.global_norm(grads)
        state = state.apply_gradients(grads=grads)
        return state, {"loss": loss, "grad_norm": gnorm}

    # ss_prob is static so the model's statically-zero scheduled-sampling
    # guard applies (it changes a handful of times per run — one recompile
    # per distinct value, reference schedule steps every 5 epochs).
    # Six batch-sharded args (feats..video_idx), one replicated (rng);
    # ss_prob is static so it takes no sharding slot.
    return jax.jit(
        train_step,
        donate_argnums=(0,),
        static_argnums=(8,),
        **sharded_step_kwargs(mesh, state_template, 6, 1),
    )


def make_greedy_sample_fn(model: CaptionModel, max_len: int) -> Callable:
    """Jitted greedy decode for validation (reference per-epoch val pass)."""

    def sample(params, feats, feat_masks, category):
        return model.apply(
            params,
            feats,
            feat_masks,
            category=category,
            max_len=max_len,
            greedy=True,
            method="sample",
        ).tokens

    return jax.jit(sample)
