"""CST / SCST training step: consensus-based self-critical REINFORCE.

Reference equivalent (SURVEY.md §3.2, the paper's core loop in
``train.py``): per step — greedy decode for the baseline, multinomial
rollout(s), in-loop CIDEr-D scoring of both against the video's references,
advantage = reward - baseline, policy-gradient loss on the rollout
log-probs.  Variants (reference Makefile targets):

* ``cst_baseline="greedy"``  — CST_MS_Greedy / classic SCST (greedy-decode
  reward as baseline, arXiv:1612.00563).
* ``cst_baseline="scb"``     — CST_MS_SCB: the paper's self-consensus
  baseline; with S rollouts per video the baseline for rollout j is the
  leave-one-out mean reward of the video's other rollouts.
* ``cst_baseline="none"``    — raw REINFORCE (no baseline).
* ``CST_GT_None`` (GT captions as "samples" weighted by consensus) is the
  WXE path in ``training/steps.py`` — no sampling involved.

TPU-first design: the ENTIRE step — S multinomial rollouts, greedy
baseline decode, reward lookup, PG loss, backward, Adam update — is one
jitted graph.  The only host work is the CIDEr-D scorer, reached through
``jax.experimental.io_callback`` (SURVEY.md §3.2: the reference crosses
device<->host twice per step; here XLA overlaps the callback with device
compute, and references are pre-cooked at startup).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental import io_callback

from cst_captioning_tpu.constants import BOS_ID, EOS_ID, PAD_ID
from cst_captioning_tpu.models.captioner import CaptionModel
from cst_captioning_tpu.ops.losses import reward_criterion
from cst_captioning_tpu.training.rewards import CiderDRewarder


def make_cst_train_step(
    model: CaptionModel, cfg, train_ds
) -> Callable:
    """Build the jitted CST step.  Same signature as the XE step
    (``trainer.py`` dispatch): ``(state, feats, feat_masks, captions,
    weights, category, video_idx, rng, ss_prob) -> (state, metrics)``;
    ``captions``/``weights``/``ss_prob`` are unused (sampling-based regime).
    """
    rewarder = CiderDRewarder(
        train_ds,
        df_mode=cfg.data.idf_file or "corpus",
    )
    S = max(1, cfg.train.cst_num_samples)
    baseline_kind = cfg.train.cst_baseline
    if baseline_kind not in ("greedy", "scb", "none"):
        raise ValueError(f"unknown cst_baseline {baseline_kind!r}")
    if baseline_kind == "scb" and S < 2:
        raise ValueError(
            "cst_baseline='scb' needs cst_num_samples >= 2 (the leave-one-"
            "out consensus baseline is undefined for a single rollout)"
        )
    temperature = cfg.train.sample_temperature
    max_len = cfg.data.max_seq_len

    def host_score(video_idx, tokens):
        return rewarder.score_ids(video_idx, tokens).astype(np.float32)

    def score(video_idx, tokens):
        return io_callback(
            host_score,
            jax.ShapeDtypeStruct((tokens.shape[0],), jnp.float32),
            video_idx,
            tokens,
        )

    def train_step(state, feats, feat_masks, captions, weights, category,
                   video_idx, rng, ss_prob):
        B = video_idx.shape[0]
        feats_r = {m: jnp.repeat(v, S, axis=0) for m, v in feats.items()}
        masks_r = {m: jnp.repeat(v, S, axis=0) for m, v in feat_masks.items()}
        cat_r = jnp.repeat(category, S, axis=0) if category is not None else None
        vid_r = jnp.repeat(video_idx, S, axis=0)

        # --- rollouts + rewards (no gradient; recomputed under grad below)
        rollout = state.apply_fn(
            state.params,
            feats_r,
            masks_r,
            rng=rng,
            category=cat_r,
            max_len=max_len,
            greedy=False,
            temperature=temperature,
            method="sample",
        )
        rewards = score(vid_r, rollout.tokens)  # (B*S,)

        if baseline_kind == "greedy":
            greedy = state.apply_fn(
                state.params,
                feats,
                feat_masks,
                category=category,
                max_len=max_len,
                greedy=True,
                method="sample",
            )
            baseline = jnp.repeat(score(video_idx, greedy.tokens), S, axis=0)
        elif baseline_kind == "scb":
            # Leave-one-out mean over the video's other rollouts.
            r = rewards.reshape(B, S)
            if S > 1:
                loo = (r.sum(axis=1, keepdims=True) - r) / (S - 1)
            else:
                loo = jnp.zeros_like(r)
            baseline = loo.reshape(B * S)
        else:
            baseline = jnp.zeros_like(rewards)
        advantage = rewards - baseline

        # --- PG loss: re-run teacher forcing over the SAMPLED tokens so the
        # graph from logits to params is differentiable (the rollout above
        # is decode-only).  Input = [BOS, tok_0..tok_{L-2}], target = tokens.
        bos = jnp.full((B * S, 1), BOS_ID, jnp.int32)
        inputs = jnp.concatenate([bos, rollout.tokens[:, :-1]], axis=1)
        # Finished rows feed EOS, not PAD, to keep embeddings defined.
        inputs = jnp.where(inputs == PAD_ID, EOS_ID, inputs)

        def loss_fn(params):
            logits = state.apply_fn(
                params, feats_r, masks_r, inputs, category=cat_r
            )
            # REINFORCE needs log-probs of the distribution that was
            # actually sampled from: same PAD/BOS masking AND the same
            # temperature scaling as the rollout policy.
            logits = CaptionModel.mask_decode_logits(logits) / jnp.asarray(
                temperature, jnp.float32
            )
            logp = jax.nn.log_softmax(logits, axis=-1)
            tok_lp = jnp.take_along_axis(
                logp, rollout.tokens[..., None], axis=-1
            )[..., 0]
            # Post-EOS slots hold PAD (= -inf under the masked policy);
            # zero them before the masked reduction so 0 * -inf never
            # produces NaN.
            tok_lp = jnp.where(rollout.mask > 0, tok_lp, 0.0)
            return reward_criterion(tok_lp, rollout.mask, advantage)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        gnorm = optax.global_norm(grads)
        state = state.apply_gradients(grads=grads)
        return state, {
            "loss": loss,
            "grad_norm": gnorm,
            "reward": rewards.mean(),
            "baseline": baseline.mean(),
            "advantage": advantage.mean(),
        }

    # ss_prob stays a traced (unused) arg — marking it static would recompile
    # the whole rollout+backward graph whenever a scheduled-sampling config
    # ticks its probability.
    return jax.jit(train_step, donate_argnums=(0,))
