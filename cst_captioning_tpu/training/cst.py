"""CST / SCST training step: consensus-based self-critical REINFORCE.

Reference equivalent (SURVEY.md §3.2, the paper's core loop in
``train.py``): per step — greedy decode for the baseline, multinomial
rollout(s), in-loop CIDEr-D scoring of both against the video's references,
advantage = reward - baseline, policy-gradient loss on the rollout
log-probs.  Variants (reference Makefile targets):

* ``cst_baseline="greedy"``  — CST_MS_Greedy / classic SCST (greedy-decode
  reward as baseline, arXiv:1612.00563).
* ``cst_baseline="scb"``     — CST_MS_SCB: the paper's self-consensus
  baseline; with S rollouts per video the baseline for rollout j is the
  leave-one-out mean reward of the video's other rollouts.
* ``cst_baseline="none"``    — raw REINFORCE (no baseline).
* ``CST_GT_None`` (GT captions as "samples" weighted by consensus) is the
  WXE path in ``training/steps.py`` — no sampling involved.

Execution strategies (picked automatically):

* **one-graph** — the ENTIRE step (S rollouts, greedy baseline decode,
  reward lookup, PG loss, backward, Adam) is one jitted graph; the host
  CIDEr-D scorer is reached through ``jax.experimental.io_callback`` and
  XLA overlaps it with device compute.
* **split** — some TPU runtimes (e.g. the tunneled axon PJRT used here)
  don't implement host send/recv callbacks.  The step then runs as two
  jitted graphs with host scoring between dispatches — exactly the
  reference's own loop structure (two device<->host crossings per step,
  SURVEY.md §3.2) with identical math and negligible overhead (the
  crossing payload is token ids + a float per sample).

``io_callback_supported()`` probes the backend once per process.

Host scoring itself is scheduled OFF the device critical path (r9):
``cfg.train.reward_workers`` shards rows across a persistent
multiprocess :class:`~cst_captioning_tpu.training.rewards.RewardPool`
(bit-identical scores), and ``cfg.train.overlap_rewards`` makes the
split step feed rollout chunks to the scorer as they are harvested —
scoring proceeds in the pool while the greedy-baseline decode still
runs on device — blocking only at the PG-update dispatch, so step time
approaches ``max(t_device, t_score) + t_update`` (docs/PERF.md r9,
parity argument in docs/PARITY.md).
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental import io_callback

from cst_captioning_tpu.constants import BOS_ID, EOS_ID, PAD_ID
from cst_captioning_tpu.decoding.core import (
    CoreState,
    DecodeState,
    decode_step,
    register_backend,
    row_sample_fn,
)
from cst_captioning_tpu.models.captioner import (
    CaptionModel,
    _repeat_cache,
)
from cst_captioning_tpu.ops.losses import reward_criterion
from cst_captioning_tpu.training.rewards import (
    CiderDRewarder,
    make_reward_scorer,
)
from cst_captioning_tpu.training.steps import PhaseClock

log = logging.getLogger("cst_captioning_tpu.cst")


@functools.lru_cache(maxsize=None)
def dispatch_latency_ms() -> float:
    """Median round-trip of a trivial jitted dispatch on the default
    backend.  On a local TPU-VM host this is ~O(0.1 ms); through a
    tunneled/remote runtime it can be >100 ms — large enough that any
    scheme spending extra dispatches to overlap host work (the chunked
    split CST step) costs more than it recovers."""
    f = jax.jit(lambda x: x + 1.0)
    x = jnp.float32(0.0)
    float(f(x))  # compile
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(f(x))
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2] * 1e3


@functools.lru_cache(maxsize=None)
def io_callback_supported() -> bool:
    """Probe: does the current default backend execute io_callback?"""
    try:
        out = jax.jit(
            lambda x: io_callback(
                lambda a: np.float32(np.asarray(a) + 1.0),
                jax.ShapeDtypeStruct((), jnp.float32),
                x,
            )
        )(jnp.float32(1.0))
        return float(out) == 2.0
    except Exception as e:
        log.info("io_callback unsupported on this backend (%s)", e)
        return False


def _validate(cfg):
    S = max(1, cfg.train.cst_num_samples)
    baseline_kind = cfg.train.cst_baseline
    if baseline_kind not in ("greedy", "scb", "none", "gt_consensus"):
        raise ValueError(f"unknown cst_baseline {baseline_kind!r}")
    if baseline_kind == "scb" and S < 2:
        raise ValueError(
            "cst_baseline='scb' needs cst_num_samples >= 2 (the leave-one-"
            "out consensus baseline is undefined for a single rollout)"
        )
    return S, baseline_kind


def _baseline_from(rewards: np.ndarray, greedy_scores, S: int,
                   baseline_kind: str, gt_rows=None) -> np.ndarray:
    """Host-side baseline shared by the split and pipelined layouts:
    greedy-decode reward (SCST), leave-one-out rollout mean (SCB), the
    per-video GT-caption consensus score (the SURVEY §3.2 SCB reading;
    ``gt_rows`` = (B,) gathered from ``CiderDRewarder.gt_consensus``),
    or zeros.  ``rewards`` is the (B*S,) rollout reward vector in
    repeated row order; ``greedy_scores`` the (B,) greedy rewards."""
    if baseline_kind == "greedy":
        return np.repeat(
            np.asarray(greedy_scores, np.float32), S, axis=0
        )
    if baseline_kind == "scb":
        r = rewards.reshape(-1, S)
        loo = (r.sum(axis=1, keepdims=True) - r) / (S - 1)
        return loo.reshape(-1).astype(np.float32)
    if baseline_kind == "gt_consensus":
        return np.repeat(np.asarray(gt_rows, np.float32), S, axis=0)
    return np.zeros_like(rewards)


def _pg_update(state, feats, feat_masks, category, S, tokens, mask,
               advantage, temperature, suppress_unk=False,
               logits_sharding=None):
    """PG loss + Adam update: re-run teacher forcing over the SAMPLED
    tokens so the graph from logits to params is differentiable (the
    rollout is decode-only).  Input = [BOS, tok_0..tok_{L-2}].  ``feats``
    holds the B un-tiled videos; ``repeat=S`` tiles the projected cache
    to the B*S sampled rows (see ``_repeat_cache``).

    ``logits_sharding`` (mesh runs only): pins the (rows, T, V) logits to
    rows-over-data × V-over-model before the log_softmax.  Without the
    pin, the SPMD partitioner is free to flatten the softmax's (rows, T)
    max/sum reductions onto ALL devices and then cannot broadcast them
    back against the vocab-sharded logits without an involuntary full
    rematerialization — the exact cliff the dryrun's tripwire fails on
    (__graft_entry__._dryrun_multichip_impl)."""
    B = tokens.shape[0]
    bos = jnp.full((B, 1), BOS_ID, jnp.int32)
    inputs = jnp.concatenate([bos, tokens[:, :-1]], axis=1)
    # Finished rows feed EOS, not PAD, to keep embeddings defined.
    inputs = jnp.where(inputs == PAD_ID, EOS_ID, inputs)

    def loss_fn(params):
        logits = state.apply_fn(
            params, feats, feat_masks, inputs, category=category, repeat=S
        )
        if logits_sharding is not None:
            logits = jax.lax.with_sharding_constraint(
                logits, logits_sharding
            )
        # REINFORCE needs log-probs of the distribution that was actually
        # sampled from: same PAD/BOS(/UNK) masking AND the same
        # temperature scaling as the rollout policy.
        logits = CaptionModel.mask_decode_logits(
            logits, suppress_unk
        ) / jnp.asarray(temperature, jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok_lp = jnp.take_along_axis(logp, tokens[..., None], axis=-1)[..., 0]
        # Post-EOS slots hold PAD (= -inf under the masked policy); zero
        # them before the masked reduction so 0 * -inf never produces NaN.
        tok_lp = jnp.where(mask > 0, tok_lp, 0.0)
        return reward_criterion(tok_lp, mask, advantage)

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    gnorm = optax.global_norm(grads)
    state = state.apply_gradients(grads=grads)
    return state, loss, gnorm


def make_cst_train_step(
    model: CaptionModel, cfg, train_ds, mesh=None, state_template=None
) -> Callable:
    """Build the CST step.  Same signature as the XE step (``trainer.py``
    dispatch): ``(state, feats, feat_masks, captions, weights, category,
    video_idx, rng, ss_prob) -> (state, metrics)``; ``captions`` /
    ``weights`` / ``ss_prob`` are unused (sampling-based regime).

    ``mesh``: the trainer's device mesh, if any — the one-graph step then
    shards the reward io_callback over the data axis instead of letting
    SPMD funnel every crossing through device 0."""
    if cfg.train.cst_use_gt:
        # CST_GT_None: the "samples" are the GT captions weighted by their
        # consensus scores — no rollout, mathematically the WXE regime
        # (reference Makefile target CST_GT_None; SURVEY.md §3.2).
        from cst_captioning_tpu.training.steps import make_xe_train_step

        log.info("cst_use_gt: dispatching CST_GT_None to the WXE step")
        return make_xe_train_step(
            model, mesh=mesh, state_template=state_template
        )
    # Validate BEFORE the io_callback early return: a typo'd layout must
    # fail on every backend, not only when the config first reaches a
    # runtime without host callbacks.
    layout = getattr(cfg.train, "cst_split_layout", "auto")
    if layout not in ("auto", "pipeline", "chunked"):
        raise ValueError(f"unknown cst_split_layout {layout!r}")
    rollout_layout = getattr(cfg.train, "cst_rollout", "scan")
    if rollout_layout not in ("scan", "padded", "slot"):
        raise ValueError(f"unknown cst_rollout {rollout_layout!r}")
    rewarder = CiderDRewarder(
        train_ds,
        df_mode=cfg.data.idf_file or "corpus",
        weighted_refs=cfg.train.cst_weighted_reward,
    )
    # Parallel reward pool (cfg.train.reward_workers > 1): rollout rows
    # shard across a persistent multiprocess pool with the df/ref tables
    # pickled once at start — bit-identical scores, ~1/W the host
    # scoring wall time (training/rewards.py::RewardPool).  Every layout
    # below consumes the same scorer surface (score_ids/submit/stream).
    scorer = make_reward_scorer(
        rewarder, max(0, getattr(cfg.train, "reward_workers", 0))
    )
    if scorer is not rewarder:
        log.info(
            "CST reward scoring: multiprocess pool with %d workers",
            scorer.num_workers,
        )
    if rollout_layout != "scan":
        # Slot-based (or its padded bit-twin) rollout: rows exit on EOS
        # and stream straight to the scorer — a host-driven loop on
        # every backend (the one-graph io_callback step keeps the
        # fused "scan" rollout; this path trades one graph for ~E[len]/L
        # of its decode steps, docs/PERF.md r10).
        log.info("CST rollout layout: %s (slot decode runtime)",
                 rollout_layout)
        return _make_slot_step(model, cfg, scorer, rollout_layout)
    if io_callback_supported():
        if layout != "auto":
            # The split layouts only exist for backends WITHOUT host
            # callbacks; on io_callback-capable backends the one-graph
            # step is strictly better (no per-step graph break), so an
            # explicit layout request is advisory here (ADVICE r4 #1).
            log.warning(
                "cst_split_layout=%r ignored: backend supports "
                "io_callback, using the one-graph CST step (split "
                "layouts apply only to backends without host callbacks)",
                layout,
            )
        return _make_one_graph_step(
            model, cfg, scorer, mesh=mesh, state_template=state_template
        )
    use_pipeline = layout == "pipeline" or (
        layout == "auto"
        and dispatch_latency_ms() > _CHUNK_MAX_DISPATCH_MS
    )
    if use_pipeline:
        log.warning(
            "backend lacks io_callback support — using the PIPELINED "
            "split CST step (one dispatch per step: previous update + "
            "next rollout; dispatch latency %.1f ms)",
            dispatch_latency_ms(),
        )
        return _make_pipelined_step(model, cfg, scorer)
    log.warning(
        "backend lacks io_callback support — using the split CST step "
        "(jitted rollout / host scoring / jitted update)"
    )
    return _make_split_step(model, cfg, scorer)


# ------------------------------------------------------- one-graph variant

def _make_one_graph_step(
    model, cfg, scorer, mesh=None, state_template=None
) -> Callable:
    S, baseline_kind = _validate(cfg)
    temperature = cfg.train.sample_temperature
    max_len = cfg.data.max_seq_len
    gt_base = (
        jnp.asarray(scorer.gt_consensus())
        if baseline_kind == "gt_consensus"
        else None
    )

    # With a RewardPool scorer the callback shards its rows across the
    # worker processes — the io_callback's host window shrinks by ~1/W
    # with bit-identical scores.
    def host_score(video_idx, tokens):
        return scorer.score_ids(video_idx, tokens).astype(np.float32)

    pg_logits_sharding = None
    if mesh is not None:
        from cst_captioning_tpu.parallel import partition

        # Rows-over-data x vocab-over-model (partition.logits_spec, the
        # single definition site of the boundary spec): keeps the PG
        # log_softmax on the sharded logits instead of the involuntary-
        # full-remat cliff (see _pg_update docstring).
        pg_logits_sharding = partition.logits_sharding(mesh, ndim=3)

    if (
        mesh is not None
        and mesh.shape.get("data", 1) > 1
        # The per-shard callback is only CORRECT where shard_map has
        # first-class callback lowering (the top-level jax.shard_map
        # era).  Under the older jax.experimental.shard_map the
        # io_callback silently lowers to a maximal device-0 call over
        # ONE shard's rows — wrong rewards for every other shard
        # (pinned by test_cst.py::TestShardedRewardCallback, which
        # compares sharded vs unsharded scoring) — so those versions
        # take the plain global callback below instead.
        and hasattr(jax, "shard_map")
    ):
        # Sharded reward crossing (VERDICT r2 #3): an unannotated
        # io_callback compiles to a {maximal device=0} sharding, and SPMD
        # replicates-then-repartitions around it every step ("Involuntary
        # full rematerialization").  Scoring is per-row, so run the
        # callback INSIDE shard_map: each shard scores its own rows and
        # the results are born with the batch sharding.  When the row
        # count also divides the model axis, rows split over BOTH axes —
        # otherwise model-axis replicas would each re-invoke the host
        # scorer on the same rows (host scoring is hot loop #2,
        # SURVEY.md §3).
        from jax.sharding import PartitionSpec as P

        from cst_captioning_tpu.parallel.mesh import shard_map

        other_axes = tuple(
            a for a, n in mesh.shape.items() if a != "data" and n > 1
        )
        other_ways = int(np.prod([mesh.shape[a] for a in other_axes] or [1]))
        data_ways = mesh.shape["data"]

        def score(video_idx, tokens):
            rows = tokens.shape[0]
            axes = (
                ("data",) + other_axes
                if other_axes and rows % (data_ways * other_ways) == 0
                else ("data",)
            )

            def body(vi, tk):
                return io_callback(
                    host_score,
                    jax.ShapeDtypeStruct((tk.shape[0],), jnp.float32),
                    vi,
                    tk,
                )

            # check_rep=False: the callback's outputs are per-shard host
            # results — nothing for the replication checker to prove.
            return shard_map(
                body,
                mesh=mesh,
                in_specs=(P(axes), P(axes, None)),
                out_specs=P(axes),
                check_rep=False,
            )(video_idx, tokens)
    else:
        rep_sharding = None
        if mesh is not None:
            # Old-shard_map fallback on a mesh: the global callback runs
            # on device 0 regardless; explicitly REPLICATING its tiny
            # operands/result makes every crossing a plain broadcast the
            # partitioner handles without the involuntary-full-remat
            # cliff the dryrun tripwire fails on (the tensors are B·S
            # int32 rows — bytes, not activations).
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            rep_sharding = NamedSharding(mesh, P())

        def score(video_idx, tokens):
            if rep_sharding is not None:
                video_idx = jax.lax.with_sharding_constraint(
                    video_idx, rep_sharding
                )
                tokens = jax.lax.with_sharding_constraint(
                    tokens, rep_sharding
                )
            out = io_callback(
                host_score,
                jax.ShapeDtypeStruct((tokens.shape[0],), jnp.float32),
                video_idx,
                tokens,
            )
            if rep_sharding is not None:
                out = jax.lax.with_sharding_constraint(out, rep_sharding)
            return out

    def train_step(state, feats, feat_masks, captions, weights, category,
                   video_idx, rng, ss_prob):
        B = video_idx.shape[0]
        vid_r = jnp.repeat(video_idx, S, axis=0)
        rollout = state.apply_fn(
            state.params, feats, feat_masks, rng=rng, category=category,
            max_len=max_len, greedy=False, temperature=temperature,
            method="sample", repeat=S,
        )
        rewards = score(vid_r, rollout.tokens)  # (B*S,)

        if baseline_kind == "greedy":
            greedy = state.apply_fn(
                state.params, feats, feat_masks, category=category,
                max_len=max_len, greedy=True, method="sample",
            )
            baseline = jnp.repeat(score(video_idx, greedy.tokens), S, axis=0)
        elif baseline_kind == "scb":
            r = rewards.reshape(B, S)
            loo = (r.sum(axis=1, keepdims=True) - r) / (S - 1)
            baseline = loo.reshape(B * S)
        elif baseline_kind == "gt_consensus":
            # Device gather of the startup-computed per-video GT
            # consensus scores — no extra host crossing.
            baseline = jnp.repeat(gt_base[video_idx], S, axis=0)
        else:
            baseline = jnp.zeros_like(rewards)
        advantage = rewards - baseline

        state, loss, gnorm = _pg_update(
            state, feats, feat_masks, category, S, rollout.tokens,
            rollout.mask, advantage, temperature,
            suppress_unk=model.decode_suppress_unk,
            logits_sharding=pg_logits_sharding,
        )
        return state, {
            "loss": loss,
            "grad_norm": gnorm,
            "reward": rewards.mean(),
            "baseline": baseline.mean(),
            "advantage": advantage.mean(),
        }

    # ss_prob stays a traced (unused) arg — marking it static would
    # recompile the whole rollout+backward graph whenever a scheduled-
    # sampling config ticks its probability.  On a mesh the jit is
    # NamedSharding-in/out (state per the partition rules, six batch
    # args over data, rng + ss_prob replicated) with donation kept.
    from cst_captioning_tpu.training.steps import sharded_step_kwargs

    return jax.jit(
        train_step,
        donate_argnums=(0,),
        **sharded_step_kwargs(mesh, state_template, 6, 2),
    )


# ----------------------------------------------------------- split variant

# Above this per-dispatch latency, chunked scoring overlap can't pay for
# its extra dispatches (see _make_split_step docstring).
_CHUNK_MAX_DISPATCH_MS = 5.0


# ------------------------------------------------------- pipelined variant

def _make_pipelined_step(model, cfg, scorer) -> Callable:
    """Software-pipelined split step for high-dispatch-latency (tunneled)
    runtimes — VERDICT r3 #3's dispatch-tax attack.

    The plain split step pays TWO dispatch round-trips per step (rollout,
    then update) with host scoring between them; through a ~100 ms tunnel
    the RTTs dominate the step.  Here each call dispatches ONE graph:

        [apply the PREVIOUS batch's PG update] -> [rollout + greedy
        baseline for THIS batch with the freshly-updated params]

    then fetches and scores this batch's tokens, holding the resulting
    advantage as the next call's pending update.  The parameter
    trajectory is IDENTICAL to the unpipelined step (same updates, same
    order, same rng; only the dispatch boundaries move) — pinned by
    ``tests/test_cst.py::test_pipelined_layout_matches_split``.

    Consequences callers must know:
    * ``metrics['loss']/['grad_norm']`` lag one step (they describe the
      update applied this call, i.e. the previous batch); the first call
      returns no loss.  Reward stats are current.
    * ``train_step.flush(state)`` applies the final pending update; the
      trainer runs it at every epoch/preemption boundary so checkpoints,
      eval, and ``steps_done`` accounting always see fully-applied params.
    * The rollout and greedy baseline share one feature encode
      (``CaptionModel.sample_with_baseline``); the PG update re-encodes
      inside the loss so the projection/attention-key weights keep their
      gradient — that encode is load-bearing, not redundant.
    """
    S, baseline_kind = _validate(cfg)
    temperature = cfg.train.sample_temperature
    max_len = cfg.data.max_seq_len
    need_greedy = baseline_kind == "greedy"

    def _rollout(params, feats, feat_masks, category, rng):
        rollout, greedy = model.apply(
            params, feats, feat_masks, rng=rng, category=category,
            max_len=max_len, temperature=temperature, repeat=S,
            with_greedy=need_greedy, method="sample_with_baseline",
        )
        greedy_tokens = (
            greedy.tokens if need_greedy
            else jnp.zeros((1, max_len), jnp.int32)
        )
        return rollout.tokens, rollout.mask, greedy_tokens

    first_dispatch = jax.jit(_rollout)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def update_and_rollout(state, pfeats, pmasks, pcat, ptokens, pmask,
                           padv, feats, feat_masks, category, rng):
        state, loss, gnorm = _pg_update(
            state, pfeats, pmasks, pcat, S, ptokens, pmask, padv,
            temperature, suppress_unk=model.decode_suppress_unk,
        )
        tokens, mask, greedy_tokens = _rollout(
            state.params, feats, feat_masks, category, rng
        )
        return state, loss, gnorm, tokens, mask, greedy_tokens

    @functools.partial(jax.jit, donate_argnums=(0,))
    def update_only(state, pfeats, pmasks, pcat, ptokens, pmask, padv):
        return _pg_update(
            state, pfeats, pmasks, pcat, S, ptokens, pmask, padv,
            temperature, suppress_unk=model.decode_suppress_unk,
        )

    pending: dict = {}
    phase_ms: dict = {}

    gt_base_np = (
        scorer.gt_consensus() if baseline_kind == "gt_consensus" else None
    )

    def _score(vid, tokens_np, greedy_np):
        vid_r = np.repeat(vid, S, axis=0)
        # Submit rollout AND greedy scoring before the first wait: a
        # pooled scorer works both concurrently across its processes.
        pending = scorer.submit(vid_r, tokens_np)
        g_pending = scorer.submit(vid, greedy_np) if need_greedy else None
        rewards = pending.wait().astype(np.float32)
        greedy_scores = g_pending.wait() if g_pending is not None else None
        return rewards, _baseline_from(
            rewards, greedy_scores, S, baseline_kind,
            gt_rows=None if gt_base_np is None else gt_base_np[vid],
        )

    def train_step(state, feats, feat_masks, captions, weights, category,
                   video_idx, rng, ss_prob):
        vid = np.asarray(video_idx)
        metrics = {}
        t0 = time.perf_counter()
        if not pending:
            tokens, mask, greedy_tokens = first_dispatch(
                state.params, feats, feat_masks, category, rng
            )
        else:
            p = pending
            state, loss, gnorm, tokens, mask, greedy_tokens = (
                update_and_rollout(
                    state, p["feats"], p["masks"], p["category"],
                    p["tokens"], p["mask"], jnp.asarray(p["advantage"]),
                    feats, feat_masks, category, rng,
                )
            )
            metrics["loss"] = loss
            metrics["grad_norm"] = gnorm
        # Fetch blocks on [update + rollout] compute plus one RTT.
        tokens_np = np.asarray(tokens)
        greedy_np = np.asarray(greedy_tokens) if need_greedy else None
        t1 = time.perf_counter()
        rewards, baseline = _score(vid, tokens_np, greedy_np)
        t2 = time.perf_counter()
        advantage = rewards - baseline
        pending.clear()
        pending.update(
            feats=feats, masks=feat_masks, category=category,
            tokens=tokens, mask=mask, advantage=advantage,
        )
        phase_ms.update(
            dispatch_and_device_ms=round((t1 - t0) * 1e3, 2),
            host_score_ms=round((t2 - t1) * 1e3, 2),
        )
        # Host floats, deliberately NOT device arrays: uploading stats the
        # host just computed would enqueue three extra transfers per step
        # through the (possibly 100ms-RTT) transport, and every consumer
        # (trainer accumulators, logging) wants host scalars anyway.
        metrics.update(
            reward=float(rewards.mean()),
            baseline=float(baseline.mean()),
            advantage=float(advantage.mean()),
        )
        return state, metrics

    def flush(state):
        """Apply the pending update (if any) -> (state, metrics|None)."""
        if not pending:
            return state, None
        p = pending
        state, loss, gnorm = update_only(
            state, p["feats"], p["masks"], p["category"], p["tokens"],
            p["mask"], jnp.asarray(p["advantage"]),
        )
        pending.clear()
        return state, {"loss": loss, "grad_norm": gnorm}

    def reset():
        """Drop the pending update WITHOUT applying it.  The trainer
        calls this at epoch entry: after an aborted epoch (exception
        between dispatch and flush) the held update belongs to an
        abandoned batch and applying it to the next epoch's state would
        corrupt the trajectory (ADVICE r4 #2)."""
        pending.clear()

    train_step.flush = flush
    train_step.reset = reset
    train_step.phase_ms = phase_ms
    train_step.layout = "pipeline"
    train_step.scorer = scorer
    return train_step


def _chunk_count(requested: int, B: int) -> int:
    """Largest divisor of ``B`` that is <= ``requested`` (>= 1)."""
    k = max(1, min(requested, B))
    while B % k:
        k -= 1
    return k


def _make_split_step(model, cfg, scorer) -> Callable:
    """Two-phase CST step for backends without io_callback — with the
    host scorer pipelined against device compute (SURVEY.md §7 hard part
    #1: the scorer "must overlap with device compute").

    The rollout is dispatched as K batch chunks, all enqueued before the
    host blocks: while the device computes chunks c+1..K (and the greedy
    baseline decode), the host scores chunk c's tokens.  Device idle time
    during scoring drops from the full scoring cost to ~1/K of it; the
    math is identical for any K (every chunk samples from the same
    params — only the rng stream differs from the unchunked dispatch,
    which K=1 reproduces bit-for-bit).

    **Overlapped reward scheduling** (``cfg.train.overlap_rewards``):
    the rollout decode and the greedy-baseline decode are already
    dispatched as independent device computations; with overlap on, each
    rollout chunk is FED to the scorer's stream the moment its tokens
    are fetched — an async pool scorer (``train.reward_workers``) then
    scores in its worker processes while the device still runs the
    greedy decode — and the host blocks only once, right before the
    PG-update dispatch.  Step time approaches
    ``max(t_device, t_score) + t_update`` instead of the serial sum
    (docs/PERF.md).  Overlap off reproduces the serial in-place scoring
    schedule; both produce bit-identical rewards and updates
    (docs/PARITY.md, pinned by tests/test_cst.py).

    Per-call wall-time phases (dispatch / sample fetch / score / greedy
    fetch / score wait / update) are recorded on ``train_step.phase_ms``
    — the trainer folds their epoch means into the history entry and
    TensorBoard.

    Chunking pays ~2K-1 EXTRA dispatches per step, so it only wins when
    per-dispatch latency is far below the scorer cost.  On a tunneled
    runtime (measured ~140 ms RTT here, vs a ~44 ms scorer) it LOSES
    2-3x; the step therefore probes :func:`dispatch_latency_ms` once and
    falls back to the fused single-dispatch layout (rollout + greedy
    baseline in ONE graph) when dispatch latency exceeds
    ``_CHUNK_MAX_DISPATCH_MS``."""
    S, baseline_kind = _validate(cfg)
    temperature = cfg.train.sample_temperature
    max_len = cfg.data.max_seq_len
    need_greedy = baseline_kind == "greedy"
    overlap = bool(getattr(cfg.train, "overlap_rewards", True))
    gt_base_np = (
        scorer.gt_consensus() if baseline_kind == "gt_consensus" else None
    )
    k_requested = max(1, getattr(cfg.train, "cst_score_chunks", 1))
    # High-latency (tunneled) runtimes take the FUSED single-dispatch
    # layout: every extra dispatch costs a full RTT, more than any
    # host-scoring overlap recovers.  Low-latency hosts keep separate
    # rollout/greedy dispatches even at K=1 — scoring the rollout while
    # the device computes the greedy baseline is free overlap there.
    latency_gated = dispatch_latency_ms() > _CHUNK_MAX_DISPATCH_MS
    if latency_gated and k_requested > 1:
        log.warning(
            "cst_score_chunks=%d disabled: per-dispatch latency %.1f ms "
            "exceeds %.0f ms — extra dispatches would cost more than the "
            "host-scoring overlap recovers (tunneled runtime)",
            k_requested, dispatch_latency_ms(), _CHUNK_MAX_DISPATCH_MS,
        )

    @jax.jit
    def rollout_chunk(params, feats, feat_masks, category, rng):
        rollout = model.apply(
            params, feats, feat_masks, rng=rng, category=category,
            max_len=max_len, greedy=False, temperature=temperature,
            method="sample", repeat=S,
        )
        return rollout.tokens, rollout.mask

    @jax.jit
    def rollout_fused(params, feats, feat_masks, category, rng):
        """K=1 layout: rollout AND greedy baseline in one dispatch (two
        device->host crossings per step total, the reference's own
        structure, SURVEY.md §3.2)."""
        tokens, mask = rollout_chunk.__wrapped__(
            params, feats, feat_masks, category, rng
        )
        greedy_tokens = (
            model.apply(
                params, feats, feat_masks, category=category,
                max_len=max_len, greedy=True, method="sample",
            ).tokens
            if need_greedy
            else jnp.zeros((1, max_len), jnp.int32)
        )
        return tokens, mask, greedy_tokens

    @jax.jit
    def greedy_chunk(params, feats, feat_masks, category):
        return model.apply(
            params, feats, feat_masks, category=category,
            max_len=max_len, greedy=True, method="sample",
        ).tokens

    @functools.partial(jax.jit, donate_argnums=(0,))
    def update_fn(state, feats, feat_masks, category, tokens_chunks,
                  mask_chunks, advantage):
        # Chunks concatenate back to the exact repeated row order
        # (chunk c holds rows [lo*S, hi*S) of the repeated batch).
        tokens = jnp.concatenate(tokens_chunks, axis=0)
        mask = jnp.concatenate(mask_chunks, axis=0)
        state, loss, gnorm = _pg_update(
            state, feats, feat_masks, category, S, tokens, mask,
            advantage, temperature,
            suppress_unk=model.decode_suppress_unk,
        )
        return state, loss, gnorm

    def _multi_device(x) -> bool:
        return (
            isinstance(x, jax.Array) and len(x.sharding.device_set) > 1
        )

    clock = PhaseClock(tags={"layout": "split"})
    phase_ms: dict = {}

    def train_step(state, feats, feat_masks, captions, weights, category,
                   video_idx, rng, ss_prob):
        clock.start()
        vid = np.asarray(video_idx)
        B = vid.shape[0]
        # Chunk slices ignore any data-axis sharding: on a multi-device
        # batch each chunk would span a device subset and force per-chunk
        # resharding — costlier than the scoring overlap saves.  The
        # split path is the single-chip io_callback workaround; sharded
        # batches run unchunked.
        sharded = any(map(_multi_device, feats.values())) or _multi_device(
            video_idx
        )
        K = (
            1
            if (sharded or latency_gated)
            else _chunk_count(k_requested, B)
        )
        step = B // K
        bounds = [(c * step, (c + 1) * step) for c in range(K)]

        def bslice(lo, hi):
            f = {m: v[lo:hi] for m, v in feats.items()}
            fm = {m: v[lo:hi] for m, v in feat_masks.items()}
            cat = category[lo:hi] if category is not None else None
            return f, fm, cat

        # Phase 1 — enqueue EVERYTHING the scorer will consume before
        # blocking.  Tunneled runtime: one fused dispatch (rollout +
        # greedy).  Otherwise: K rollout chunks, then the greedy
        # baseline decode (its compute hides the tail rollout chunks'
        # scoring; at K=1 it still hides the rollout's scoring).
        if latency_gated:
            tokens, mask, greedy_tokens = rollout_fused(
                state.params, feats, feat_masks, category, rng
            )
            dispatched = [(tokens, mask)]
            greedy_parts = [greedy_tokens] if need_greedy else []
        else:
            dispatched = []
            for c, (lo, hi) in enumerate(bounds):
                crng = jax.random.fold_in(rng, c) if K > 1 else rng
                f, fm, cat = bslice(lo, hi)
                dispatched.append(
                    rollout_chunk(state.params, f, fm, cat, crng)
                )
            greedy_parts = (
                [
                    greedy_chunk(state.params, *bslice(lo, hi))
                    for lo, hi in bounds
                ]
                if need_greedy
                else []
            )
        clock.lap("dispatch_ms")

        # Phase 2 — host scoring, streamed: np.asarray(chunk c) blocks
        # only on chunk c's dispatch; later chunks (and the greedy
        # baseline decode) keep the device busy.  With overlap on, each
        # fetched chunk is fed to the scorer stream — a pooled scorer
        # works it in other processes immediately — and the single
        # blocking wait lands just before the update dispatch.
        stream = scorer.stream() if overlap else None
        reward_parts = []
        for c, (tokens, mask) in enumerate(dispatched):
            lo, hi = bounds[c]
            vid_r = np.repeat(vid[lo:hi], S, axis=0)
            tokens_np = np.asarray(tokens)
            clock.lap("sample_fetch_ms")
            if stream is not None:
                stream.feed(vid_r, tokens_np)
            else:
                reward_parts.append(
                    scorer.score_ids(vid_r, tokens_np).astype(np.float32)
                )
            clock.lap("score_ms")

        greedy_pending = None
        greedy_scores = None
        if need_greedy:
            greedy_np = []
            for toks in greedy_parts:
                greedy_np.append(np.asarray(toks))
                clock.lap("greedy_fetch_ms")
            if overlap:
                greedy_pending = [
                    scorer.submit(vid[lo:hi], toks)
                    for (lo, hi), toks in zip(bounds, greedy_np)
                ]
            else:
                greedy_scores = np.concatenate([
                    scorer.score_ids(vid[lo:hi], toks).astype(np.float32)
                    for (lo, hi), toks in zip(bounds, greedy_np)
                ])
            clock.lap("score_ms")

        rewards = (
            stream.finish() if stream is not None
            else np.concatenate(reward_parts)
        )
        if greedy_pending is not None:
            greedy_scores = np.concatenate(
                [p.wait() for p in greedy_pending]
            ).astype(np.float32)
        clock.lap("score_wait_ms")
        baseline = _baseline_from(
            rewards, greedy_scores, S, baseline_kind,
            gt_rows=None if gt_base_np is None else gt_base_np[vid],
        )
        advantage = rewards - baseline

        # Phase 3 — one PG update over the full batch (donated state:
        # param/optimizer buffers are reused, not copied).
        state, loss, gnorm = update_fn(
            state, feats, feat_masks, category,
            tuple(t for t, _ in dispatched),
            tuple(m for _, m in dispatched),
            jnp.asarray(advantage),
        )
        clock.lap("update_ms")
        clock.commit(phase_ms)
        return state, {
            "loss": loss,
            "grad_norm": gnorm,
            "reward": jnp.float32(rewards.mean()),
            "baseline": jnp.float32(baseline.mean()),
            "advantage": jnp.float32(advantage.mean()),
        }

    train_step.phase_ms = phase_ms
    train_step.layout = "split"
    train_step.scorer = scorer
    return train_step


# ---------------------------------------------------- slot rollout variant

class SlotRolloutState(NamedTuple):
    """Device-resident state of the CST rollout slot matrix: the unified
    decode carry plus per-slot occupancy metadata.  ``row_id`` is the
    occupant's GLOBAL row index in the step's rollout matrix (sampled
    rows first, then greedy-baseline rows; -1 = empty) — the identity
    the row-keyed PRNG derives from, so slot position and admission
    order cannot change any sampled token."""

    core: CoreState
    cache: Any                # DecodeCache rows, leaves lead with (S,)
    row_id: jax.Array         # (S,) int32
    is_sample: jax.Array      # (S,) bool — multinomial vs greedy row


class SlotRollout:
    """Slot-based CST rollout decode: sampled-rollout and greedy-
    baseline rows occupy persistent device slots, exit on EOS, and are
    harvested at step boundaries — the serving slot machinery
    (PR 3) reused in training, via the same unified decode core.

    ``layout="slot"``: ``n_slots`` slots (< total rows) with admission
    as slots free — total decode cost ~ sum(row lengths) instead of
    rows x L.  ``layout="padded"``: every row resident from tick 0 and
    exactly ceil(L/block) ticks — today's padded cost, same row-keyed
    math, used as the bit-identical baseline of the paired bench rows.

    Sampling is row-keyed (``decoding/core.py::row_sample_fn``): row
    ``r`` at decode position ``t`` draws from
    ``fold_in(fold_in(rng, r), t)`` — never from slot position or
    admission tick — so both layouts produce bit-identical tokens per
    row, and therefore bit-identical rewards, losses and params
    (docs/PARITY.md "slot rollout invariance"; pinned by
    tests/test_cst.py and the shared parity harness).
    """

    def __init__(self, model, *, max_len: int, temperature: float,
                 n_slots: int = 0, block: int = 1, padded: bool = False):
        self.model = model
        self.L = int(max_len)
        self.T = float(temperature)
        self.n_slots_cfg = int(n_slots)
        self.block = max(1, int(block))
        self.padded = bool(padded)
        self._tick_fns: dict = {}
        self._sst_cache: dict = {}

        def prepare(params, feats, masks, category, repeat, need_greedy):
            _, cache = model.apply(
                params, feats, masks, category, method="init_decode"
            )
            rcache = _repeat_cache(cache, repeat)
            if need_greedy:
                return jax.tree.map(
                    lambda a, b: jnp.concatenate([a, b], axis=0),
                    rcache, cache,
                )
            return rcache

        self._prepare = jax.jit(prepare, static_argnums=(4, 5))

    # ------------------------------------------------------------- device
    def _tick_fn(self, A: int):
        """One compiled rollout iteration: scatter A admissions (gather
        their pre-encoded cache rows by row id), then run the step
        block.  Mirrors ``serving/slots.py`` exactly — constant
        dispatches per iteration regardless of churn."""
        if A in self._tick_fns:
            return self._tick_fns[A]
        model, L, T, block = self.model, self.L, self.T, self.block

        @jax.jit
        def tick(params, sst: SlotRolloutState, cache_all, admit_ids,
                 admit_slots, rng, n_sample_rows):
            if A:
                rows = jax.tree.map(lambda x: x[admit_ids], cache_all)
                # Padding repeats the LAST (id, slot) pair: duplicate
                # scatter indices write identical values — idempotent.
                cache = jax.tree.map(
                    lambda leaf, r: leaf.at[admit_slots].set(
                        r.astype(leaf.dtype)
                    ),
                    sst.cache, rows,
                )
                co = sst.core
                core = co._replace(
                    state=DecodeState(
                        h=co.state.h.at[:, admit_slots].set(0.0),
                        c=co.state.c.at[:, admit_slots].set(0.0),
                    ),
                    seqs=co.seqs.at[admit_slots].set(PAD_ID),
                    finished=co.finished.at[admit_slots].set(False),
                    tokens=co.tokens.at[admit_slots].set(BOS_ID),
                    step=co.step.at[admit_slots].set(0),
                )
                sst = SlotRolloutState(
                    core=core,
                    cache=cache,
                    row_id=sst.row_id.at[admit_slots].set(admit_ids),
                    is_sample=sst.is_sample.at[admit_slots].set(
                        admit_ids < n_sample_rows
                    ),
                )

            def step_logits(state, tokens):
                return model.apply(
                    params, state, sst.cache, tokens,
                    method="decode_logits",
                )

            sample_fn = row_sample_fn(rng, sst.row_id, sst.is_sample)
            core = sst.core
            for _ in range(block):
                core = decode_step(
                    step_logits, core, mode="sample", temperature=T,
                    sample_fn=sample_fn,
                )
            sst = sst._replace(core=core)
            done = jnp.all(core.finished, axis=-1) | (core.step >= L)
            return sst, done, core.seqs

        self._tick_fns[A] = tick
        return tick

    def _init_state(self, S: int, cache_all) -> SlotRolloutState:
        model, L = self.model, self.L
        cdt = jnp.dtype(model.compute_dtype)
        core = CoreState(
            state=DecodeState(
                h=jnp.zeros((model.num_layers, S, model.rnn_size), cdt),
                c=jnp.zeros(
                    (model.num_layers, S, model.rnn_size), jnp.float32
                ),
            ),
            seqs=jnp.full((S, 1, L), PAD_ID, jnp.int32),
            scores=None,
            lps=None,
            # Empty slots ride as finished/step=L: done, frozen.
            finished=jnp.ones((S, 1), bool),
            tokens=jnp.full((S,), BOS_ID, jnp.int32),
            step=jnp.full((S,), L, jnp.int32),
            rng=None,
        )
        cache = jax.tree.map(
            lambda x: jnp.zeros((S,) + x.shape[1:], x.dtype), cache_all
        )
        return SlotRolloutState(
            core=core,
            cache=cache,
            row_id=jnp.full((S,), -1, jnp.int32),
            is_sample=jnp.zeros((S,), bool),
        )

    # --------------------------------------------------------------- host
    def resolve_slots(self, n_rows: int) -> int:
        if self.padded:
            return n_rows
        if self.n_slots_cfg > 0:
            return min(self.n_slots_cfg, n_rows)
        # Default: quarter of the rows (>= 8) — enough churn headroom
        # that freed slots refill while stragglers run (docs/PERF.md r10).
        return max(1, min(n_rows, max(8, -(-n_rows // 4))))

    def run(self, params, feats, feat_masks, category, rng, *,
            repeat: int, need_greedy: bool, on_harvest=None):
        """Decode ``B*repeat`` sampled rows (+ B greedy rows) through
        the slot matrix.  ``on_harvest(row_ids, tokens)`` fires at every
        harvest boundary with freshly-exited rows — the CST step streams
        them straight into ``RewardPool.submit`` so scoring overlaps the
        remaining decode.  Returns ``(tokens (N, L) int32, stats)``;
        rows ``[0, B*repeat)`` are the rollout, the tail the greedy
        baseline."""
        B = next(iter(feats.values())).shape[0]
        n_sample = B * repeat
        N = n_sample + (B if need_greedy else 0)
        L, block = self.L, self.block
        S = self.resolve_slots(N)
        cache_all = self._prepare(
            params, feats, feat_masks, category, repeat, need_greedy
        )
        # Reuse the previous step's final slot state for this geometry:
        # leftover rows ride FROZEN (finished, step=L, never harvested)
        # and every op is row-independent, so stale co-residents cannot
        # change an admitted row's numbers — the same argument that
        # makes admission order irrelevant (docs/PARITY.md).
        sst = self._sst_cache.get(S)
        if sst is None:
            sst = self._init_state(S, cache_all)
        n_sample_arr = jnp.int32(n_sample)
        pending = list(range(N))
        free = list(range(S))
        occupied: dict = {}
        admit_tick: dict = {}
        out = np.full((N, L), PAD_ID, np.int32)
        ticks = 0
        row_steps = 0
        min_ticks = -(-L // block)  # padded layout: today's full-L cost
        while pending or occupied:
            n = min(len(free), len(pending))
            ids = [pending.pop(0) for _ in range(n)]
            if n:
                # ONE admission bucket (A = S, padded by repeating the
                # last (id, slot) pair — duplicate scatters of identical
                # values are idempotent): exactly two compiled tick
                # variants per geometry (admit / pure-step), where a
                # per-count bucket ladder would re-trace mid-epoch on
                # every new harvest pattern.
                A = S
                slots = [free.pop() for _ in range(n)]
                ids_arr = jnp.asarray(
                    np.asarray(ids + [ids[-1]] * (A - n), np.int32)
                )
                slot_arr = jnp.asarray(
                    np.asarray(slots + [slots[-1]] * (A - n), np.int32)
                )
                for s, r in zip(slots, ids):
                    occupied[s] = r
                    admit_tick[s] = ticks
            else:
                A = 0
                ids_arr = slot_arr = None
            sst, done, seqs = self._tick_fn(A)(
                params, sst, cache_all, ids_arr, slot_arr, rng,
                n_sample_arr,
            )
            ticks += 1
            if self.padded and ticks < min_ticks:
                continue  # padded twin: every row pays the full L steps
            done_np = np.asarray(done)
            done_slots = [s for s in occupied if done_np[s]]
            if not done_slots:
                continue
            seqs_np = np.asarray(seqs)
            h_ids, h_toks = [], []
            for s in done_slots:
                r = occupied.pop(s)
                free.append(s)
                out[r] = seqs_np[s, 0]
                row_steps += min((ticks - admit_tick.pop(s)) * block, L)
                h_ids.append(r)
                h_toks.append(out[r])
            if on_harvest is not None:
                on_harvest(h_ids, np.stack(h_toks))
        self._sst_cache[S] = sst
        lengths = (out != PAD_ID).sum(axis=1)
        stats = {
            "rollout_ticks": ticks,
            "rollout_decode_steps": ticks * block,
            "rollout_steps_per_row": round(row_steps / max(1, N), 3),
            "rollout_mean_len": round(float(lengths.mean()), 3),
            "rollout_slots": S,
            "rollout_rows": N,
        }
        return out, stats


def _make_slot_step(model, cfg, scorer, layout: str) -> Callable:
    """CST step whose rollout runs through :class:`SlotRollout`
    (``layout`` = "slot" or its bit-twin "padded").  Phase structure
    mirrors ``_make_split_step``: decode (slot loop, harvested rows
    streamed to the scorer as they exit), one blocking reward wait,
    one jitted PG update.  Rewards are paired back to rows BY ROW ID,
    not harvest order — harvest order carries no information
    (docs/PARITY.md)."""
    S, baseline_kind = _validate(cfg)
    temperature = cfg.train.sample_temperature
    max_len = cfg.data.max_seq_len
    need_greedy = baseline_kind == "greedy"
    gt_base_np = (
        scorer.gt_consensus() if baseline_kind == "gt_consensus" else None
    )
    rollout = SlotRollout(
        model,
        max_len=max_len,
        temperature=temperature,
        n_slots=max(0, getattr(cfg.train, "cst_slot_count", 0)),
        block=max(1, getattr(cfg.train, "cst_slot_block_steps", 1)),
        padded=layout == "padded",
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def update_fn(state, feats, feat_masks, category, tokens, advantage):
        mask = (tokens != PAD_ID).astype(jnp.float32)
        return _pg_update(
            state, feats, feat_masks, category, S, tokens, mask,
            advantage, temperature,
            suppress_unk=model.decode_suppress_unk,
        )

    def _trim_len(tokens_np) -> int:
        """Time-axis bucket for the PG update: the rollout's rows exit
        on EOS, so every column past the longest harvested row is PAD
        with mask 0 — zero loss, zero gradient.  Trimming them cuts the
        update's T-step scan to ~max(len)/L of its cost.  Power-of-two
        buckets bound the jit cache; BOTH layouts trim from the SAME
        (bit-identical) token matrix, so the padded-vs-slot parity
        contract is untouched (docs/PARITY.md r10)."""
        longest = int((tokens_np != PAD_ID).sum(axis=1).max())
        t = 8
        while t < longest + 1:
            t *= 2
        return min(t, max_len)

    clock = PhaseClock(tags={"layout": layout})
    phase_ms: dict = {}
    last_stats: dict = {}

    def train_step(state, feats, feat_masks, captions, weights, category,
                   video_idx, rng, ss_prob):
        clock.start()
        vid = np.asarray(video_idx)
        B = vid.shape[0]
        n_sample = B * S
        pending: list = []

        def on_harvest(row_ids, tokens):
            # Stream freshly-exited rows to the scorer: a pooled scorer
            # works them in its processes while the slot loop keeps
            # decoding.  Rewards scatter back by row id at the wait.
            samp = [(r, i) for i, r in enumerate(row_ids) if r < n_sample]
            gred = [(r, i) for i, r in enumerate(row_ids) if r >= n_sample]
            if samp:
                rows = np.asarray([r for r, _ in samp])
                pending.append((
                    rows,
                    scorer.submit(vid[rows // S],
                                  tokens[[i for _, i in samp]]),
                ))
            if gred:
                rows = np.asarray([r for r, _ in gred])
                pending.append((
                    rows,
                    scorer.submit(vid[rows - n_sample],
                                  tokens[[i for _, i in gred]]),
                ))

        tokens_all, stats = rollout.run(
            state.params, feats, feat_masks, category, rng,
            repeat=S, need_greedy=need_greedy, on_harvest=on_harvest,
        )
        last_stats.clear()
        last_stats.update(stats)
        clock.lap("dispatch_ms")

        scores_all = np.zeros((tokens_all.shape[0],), np.float32)
        for rows, p in pending:
            scores_all[rows] = p.wait()
        rewards = scores_all[:n_sample]
        greedy_scores = scores_all[n_sample:] if need_greedy else None
        clock.lap("score_wait_ms")

        baseline = _baseline_from(
            rewards, greedy_scores, S, baseline_kind,
            gt_rows=None if gt_base_np is None else gt_base_np[vid],
        )
        advantage = rewards - baseline
        Lt = _trim_len(tokens_all[:n_sample])
        last_stats["update_trim_len"] = Lt
        state, loss, gnorm = update_fn(
            state, feats, feat_masks, category,
            jnp.asarray(tokens_all[:n_sample, :Lt]),
            jnp.asarray(advantage),
        )
        clock.lap("update_ms")
        clock.commit(phase_ms)
        # Host floats for the host-computed stats (the pipelined-step
        # convention): re-uploading them would cost device transfers
        # every step for values every consumer wants on the host.
        return state, {
            "loss": loss,
            "grad_norm": gnorm,
            "reward": float(rewards.mean()),
            "baseline": float(baseline.mean()),
            "advantage": float(advantage.mean()),
            "rollout_steps_per_row": float(
                stats["rollout_steps_per_row"]
            ),
        }

    train_step.phase_ms = phase_ms
    train_step.layout = f"slot:{layout}"
    train_step.scorer = scorer
    train_step.rollout_stats = last_stats
    return train_step


# ------------------------------------------------ parity-harness backends

def _rollout_runner(ctx, layout: str):
    """Registry runner: the full CST rollout token matrix (sampled +
    greedy-baseline rows) through the requested layout."""
    model = ctx.make_model()
    ro = SlotRollout(
        model, max_len=ctx.max_len, temperature=ctx.temperature,
        padded=layout == "padded",
    )
    tokens, stats = ro.run(
        ctx.params, ctx.feats, ctx.masks, ctx.category, ctx.rng,
        repeat=ctx.repeat, need_greedy=True,
    )
    return {"tokens": tokens, "stats": stats}


register_backend(
    "padded_rollout",
    lambda ctx: _rollout_runner(ctx, "padded"),
    kind="rollout",
)
register_backend(
    "slot_rollout",
    lambda ctx: _rollout_runner(ctx, "slot"),
    kind="rollout",
    ref="padded_rollout",
)
