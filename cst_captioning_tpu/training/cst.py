"""CST / SCST training step: consensus-based self-critical REINFORCE.

Reference equivalent (SURVEY.md §3.2, the paper's core loop in
``train.py``): per step — greedy decode for the baseline, multinomial
rollout(s), in-loop CIDEr-D scoring of both against the video's references,
advantage = reward - baseline, policy-gradient loss on the rollout
log-probs.  Variants (reference Makefile targets):

* ``cst_baseline="greedy"``  — CST_MS_Greedy / classic SCST (greedy-decode
  reward as baseline, arXiv:1612.00563).
* ``cst_baseline="scb"``     — CST_MS_SCB: the paper's self-consensus
  baseline; with S rollouts per video the baseline for rollout j is the
  leave-one-out mean reward of the video's other rollouts.
* ``cst_baseline="none"``    — raw REINFORCE (no baseline).
* ``CST_GT_None`` (GT captions as "samples" weighted by consensus) is the
  WXE path in ``training/steps.py`` — no sampling involved.

Execution strategies (picked automatically):

* **one-graph** — the ENTIRE step (S rollouts, greedy baseline decode,
  reward lookup, PG loss, backward, Adam) is one jitted graph; the host
  CIDEr-D scorer is reached through ``jax.experimental.io_callback`` and
  XLA overlaps it with device compute.
* **split** — some TPU runtimes (e.g. the tunneled axon PJRT used here)
  don't implement host send/recv callbacks.  The step then runs as two
  jitted graphs with host scoring between dispatches — exactly the
  reference's own loop structure (two device<->host crossings per step,
  SURVEY.md §3.2) with identical math and negligible overhead (the
  crossing payload is token ids + a float per sample).

``io_callback_supported()`` probes the backend once per process.

Host scoring itself is scheduled OFF the device critical path (r9):
``cfg.train.reward_workers`` shards rows across a persistent
multiprocess :class:`~cst_captioning_tpu.training.rewards.RewardPool`
(bit-identical scores), and ``cfg.train.overlap_rewards`` makes the
split step feed rollout chunks to the scorer as they are harvested —
scoring proceeds in the pool while the greedy-baseline decode still
runs on device — blocking only at the PG-update dispatch, so step time
approaches ``max(t_device, t_score) + t_update`` (docs/PERF.md r9,
parity argument in docs/PARITY.md).
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental import io_callback

from cst_captioning_tpu.constants import BOS_ID, EOS_ID, PAD_ID
from cst_captioning_tpu.models.captioner import CaptionModel
from cst_captioning_tpu.ops.losses import reward_criterion
from cst_captioning_tpu.training.rewards import (
    CiderDRewarder,
    make_reward_scorer,
)
from cst_captioning_tpu.training.steps import PhaseClock

log = logging.getLogger("cst_captioning_tpu.cst")


@functools.lru_cache(maxsize=None)
def dispatch_latency_ms() -> float:
    """Median round-trip of a trivial jitted dispatch on the default
    backend.  On a local TPU-VM host this is ~O(0.1 ms); through a
    tunneled/remote runtime it can be >100 ms — large enough that any
    scheme spending extra dispatches to overlap host work (the chunked
    split CST step) costs more than it recovers."""
    f = jax.jit(lambda x: x + 1.0)
    x = jnp.float32(0.0)
    float(f(x))  # compile
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(f(x))
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2] * 1e3


@functools.lru_cache(maxsize=None)
def io_callback_supported() -> bool:
    """Probe: does the current default backend execute io_callback?"""
    try:
        out = jax.jit(
            lambda x: io_callback(
                lambda a: np.float32(np.asarray(a) + 1.0),
                jax.ShapeDtypeStruct((), jnp.float32),
                x,
            )
        )(jnp.float32(1.0))
        return float(out) == 2.0
    except Exception as e:
        log.info("io_callback unsupported on this backend (%s)", e)
        return False


def _validate(cfg):
    S = max(1, cfg.train.cst_num_samples)
    baseline_kind = cfg.train.cst_baseline
    if baseline_kind not in ("greedy", "scb", "none", "gt_consensus"):
        raise ValueError(f"unknown cst_baseline {baseline_kind!r}")
    if baseline_kind == "scb" and S < 2:
        raise ValueError(
            "cst_baseline='scb' needs cst_num_samples >= 2 (the leave-one-"
            "out consensus baseline is undefined for a single rollout)"
        )
    return S, baseline_kind


def _baseline_from(rewards: np.ndarray, greedy_scores, S: int,
                   baseline_kind: str, gt_rows=None) -> np.ndarray:
    """Host-side baseline shared by the split and pipelined layouts:
    greedy-decode reward (SCST), leave-one-out rollout mean (SCB), the
    per-video GT-caption consensus score (the SURVEY §3.2 SCB reading;
    ``gt_rows`` = (B,) gathered from ``CiderDRewarder.gt_consensus``),
    or zeros.  ``rewards`` is the (B*S,) rollout reward vector in
    repeated row order; ``greedy_scores`` the (B,) greedy rewards."""
    if baseline_kind == "greedy":
        return np.repeat(
            np.asarray(greedy_scores, np.float32), S, axis=0
        )
    if baseline_kind == "scb":
        r = rewards.reshape(-1, S)
        loo = (r.sum(axis=1, keepdims=True) - r) / (S - 1)
        return loo.reshape(-1).astype(np.float32)
    if baseline_kind == "gt_consensus":
        return np.repeat(np.asarray(gt_rows, np.float32), S, axis=0)
    return np.zeros_like(rewards)


def _pg_update(state, feats, feat_masks, category, S, tokens, mask,
               advantage, temperature, suppress_unk=False,
               logits_sharding=None):
    """PG loss + Adam update: re-run teacher forcing over the SAMPLED
    tokens so the graph from logits to params is differentiable (the
    rollout is decode-only).  Input = [BOS, tok_0..tok_{L-2}].  ``feats``
    holds the B un-tiled videos; ``repeat=S`` tiles the projected cache
    to the B*S sampled rows (see ``_repeat_cache``).

    ``logits_sharding`` (mesh runs only): pins the (rows, T, V) logits to
    rows-over-data × V-over-model before the log_softmax.  Without the
    pin, the SPMD partitioner is free to flatten the softmax's (rows, T)
    max/sum reductions onto ALL devices and then cannot broadcast them
    back against the vocab-sharded logits without an involuntary full
    rematerialization — the exact cliff the dryrun's tripwire fails on
    (__graft_entry__._dryrun_multichip_impl)."""
    B = tokens.shape[0]
    bos = jnp.full((B, 1), BOS_ID, jnp.int32)
    inputs = jnp.concatenate([bos, tokens[:, :-1]], axis=1)
    # Finished rows feed EOS, not PAD, to keep embeddings defined.
    inputs = jnp.where(inputs == PAD_ID, EOS_ID, inputs)

    def loss_fn(params):
        logits = state.apply_fn(
            params, feats, feat_masks, inputs, category=category, repeat=S
        )
        if logits_sharding is not None:
            logits = jax.lax.with_sharding_constraint(
                logits, logits_sharding
            )
        # REINFORCE needs log-probs of the distribution that was actually
        # sampled from: same PAD/BOS(/UNK) masking AND the same
        # temperature scaling as the rollout policy.
        logits = CaptionModel.mask_decode_logits(
            logits, suppress_unk
        ) / jnp.asarray(temperature, jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok_lp = jnp.take_along_axis(logp, tokens[..., None], axis=-1)[..., 0]
        # Post-EOS slots hold PAD (= -inf under the masked policy); zero
        # them before the masked reduction so 0 * -inf never produces NaN.
        tok_lp = jnp.where(mask > 0, tok_lp, 0.0)
        return reward_criterion(tok_lp, mask, advantage)

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    gnorm = optax.global_norm(grads)
    state = state.apply_gradients(grads=grads)
    return state, loss, gnorm


def make_cst_train_step(
    model: CaptionModel, cfg, train_ds, mesh=None
) -> Callable:
    """Build the CST step.  Same signature as the XE step (``trainer.py``
    dispatch): ``(state, feats, feat_masks, captions, weights, category,
    video_idx, rng, ss_prob) -> (state, metrics)``; ``captions`` /
    ``weights`` / ``ss_prob`` are unused (sampling-based regime).

    ``mesh``: the trainer's device mesh, if any — the one-graph step then
    shards the reward io_callback over the data axis instead of letting
    SPMD funnel every crossing through device 0."""
    if cfg.train.cst_use_gt:
        # CST_GT_None: the "samples" are the GT captions weighted by their
        # consensus scores — no rollout, mathematically the WXE regime
        # (reference Makefile target CST_GT_None; SURVEY.md §3.2).
        from cst_captioning_tpu.training.steps import make_xe_train_step

        log.info("cst_use_gt: dispatching CST_GT_None to the WXE step")
        return make_xe_train_step(model)
    # Validate BEFORE the io_callback early return: a typo'd layout must
    # fail on every backend, not only when the config first reaches a
    # runtime without host callbacks.
    layout = getattr(cfg.train, "cst_split_layout", "auto")
    if layout not in ("auto", "pipeline", "chunked"):
        raise ValueError(f"unknown cst_split_layout {layout!r}")
    rewarder = CiderDRewarder(
        train_ds,
        df_mode=cfg.data.idf_file or "corpus",
        weighted_refs=cfg.train.cst_weighted_reward,
    )
    # Parallel reward pool (cfg.train.reward_workers > 1): rollout rows
    # shard across a persistent multiprocess pool with the df/ref tables
    # pickled once at start — bit-identical scores, ~1/W the host
    # scoring wall time (training/rewards.py::RewardPool).  Every layout
    # below consumes the same scorer surface (score_ids/submit/stream).
    scorer = make_reward_scorer(
        rewarder, max(0, getattr(cfg.train, "reward_workers", 0))
    )
    if scorer is not rewarder:
        log.info(
            "CST reward scoring: multiprocess pool with %d workers",
            scorer.num_workers,
        )
    if io_callback_supported():
        if layout != "auto":
            # The split layouts only exist for backends WITHOUT host
            # callbacks; on io_callback-capable backends the one-graph
            # step is strictly better (no per-step graph break), so an
            # explicit layout request is advisory here (ADVICE r4 #1).
            log.warning(
                "cst_split_layout=%r ignored: backend supports "
                "io_callback, using the one-graph CST step (split "
                "layouts apply only to backends without host callbacks)",
                layout,
            )
        return _make_one_graph_step(model, cfg, scorer, mesh=mesh)
    use_pipeline = layout == "pipeline" or (
        layout == "auto"
        and dispatch_latency_ms() > _CHUNK_MAX_DISPATCH_MS
    )
    if use_pipeline:
        log.warning(
            "backend lacks io_callback support — using the PIPELINED "
            "split CST step (one dispatch per step: previous update + "
            "next rollout; dispatch latency %.1f ms)",
            dispatch_latency_ms(),
        )
        return _make_pipelined_step(model, cfg, scorer)
    log.warning(
        "backend lacks io_callback support — using the split CST step "
        "(jitted rollout / host scoring / jitted update)"
    )
    return _make_split_step(model, cfg, scorer)


# ------------------------------------------------------- one-graph variant

def _make_one_graph_step(model, cfg, scorer, mesh=None) -> Callable:
    S, baseline_kind = _validate(cfg)
    temperature = cfg.train.sample_temperature
    max_len = cfg.data.max_seq_len
    gt_base = (
        jnp.asarray(scorer.gt_consensus())
        if baseline_kind == "gt_consensus"
        else None
    )

    # With a RewardPool scorer the callback shards its rows across the
    # worker processes — the io_callback's host window shrinks by ~1/W
    # with bit-identical scores.
    def host_score(video_idx, tokens):
        return scorer.score_ids(video_idx, tokens).astype(np.float32)

    pg_logits_sharding = None
    if mesh is not None:
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        pg_logits_sharding = NamedSharding(
            mesh,
            P(
                "data",
                None,
                "model" if mesh.shape.get("model", 1) > 1 else None,
            ),
        )

    if (
        mesh is not None
        and mesh.shape.get("data", 1) > 1
        # The per-shard callback is only CORRECT where shard_map has
        # first-class callback lowering (the top-level jax.shard_map
        # era).  Under the older jax.experimental.shard_map the
        # io_callback silently lowers to a maximal device-0 call over
        # ONE shard's rows — wrong rewards for every other shard
        # (pinned by test_cst.py::TestShardedRewardCallback, which
        # compares sharded vs unsharded scoring) — so those versions
        # take the plain global callback below instead.
        and hasattr(jax, "shard_map")
    ):
        # Sharded reward crossing (VERDICT r2 #3): an unannotated
        # io_callback compiles to a {maximal device=0} sharding, and SPMD
        # replicates-then-repartitions around it every step ("Involuntary
        # full rematerialization").  Scoring is per-row, so run the
        # callback INSIDE shard_map: each shard scores its own rows and
        # the results are born with the batch sharding.  When the row
        # count also divides the model axis, rows split over BOTH axes —
        # otherwise model-axis replicas would each re-invoke the host
        # scorer on the same rows (host scoring is hot loop #2,
        # SURVEY.md §3).
        from jax.sharding import PartitionSpec as P

        from cst_captioning_tpu.parallel.mesh import shard_map

        other_axes = tuple(
            a for a, n in mesh.shape.items() if a != "data" and n > 1
        )
        other_ways = int(np.prod([mesh.shape[a] for a in other_axes] or [1]))
        data_ways = mesh.shape["data"]

        def score(video_idx, tokens):
            rows = tokens.shape[0]
            axes = (
                ("data",) + other_axes
                if other_axes and rows % (data_ways * other_ways) == 0
                else ("data",)
            )

            def body(vi, tk):
                return io_callback(
                    host_score,
                    jax.ShapeDtypeStruct((tk.shape[0],), jnp.float32),
                    vi,
                    tk,
                )

            # check_rep=False: the callback's outputs are per-shard host
            # results — nothing for the replication checker to prove.
            return shard_map(
                body,
                mesh=mesh,
                in_specs=(P(axes), P(axes, None)),
                out_specs=P(axes),
                check_rep=False,
            )(video_idx, tokens)
    else:
        rep_sharding = None
        if mesh is not None:
            # Old-shard_map fallback on a mesh: the global callback runs
            # on device 0 regardless; explicitly REPLICATING its tiny
            # operands/result makes every crossing a plain broadcast the
            # partitioner handles without the involuntary-full-remat
            # cliff the dryrun tripwire fails on (the tensors are B·S
            # int32 rows — bytes, not activations).
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            rep_sharding = NamedSharding(mesh, P())

        def score(video_idx, tokens):
            if rep_sharding is not None:
                video_idx = jax.lax.with_sharding_constraint(
                    video_idx, rep_sharding
                )
                tokens = jax.lax.with_sharding_constraint(
                    tokens, rep_sharding
                )
            out = io_callback(
                host_score,
                jax.ShapeDtypeStruct((tokens.shape[0],), jnp.float32),
                video_idx,
                tokens,
            )
            if rep_sharding is not None:
                out = jax.lax.with_sharding_constraint(out, rep_sharding)
            return out

    def train_step(state, feats, feat_masks, captions, weights, category,
                   video_idx, rng, ss_prob):
        B = video_idx.shape[0]
        vid_r = jnp.repeat(video_idx, S, axis=0)
        rollout = state.apply_fn(
            state.params, feats, feat_masks, rng=rng, category=category,
            max_len=max_len, greedy=False, temperature=temperature,
            method="sample", repeat=S,
        )
        rewards = score(vid_r, rollout.tokens)  # (B*S,)

        if baseline_kind == "greedy":
            greedy = state.apply_fn(
                state.params, feats, feat_masks, category=category,
                max_len=max_len, greedy=True, method="sample",
            )
            baseline = jnp.repeat(score(video_idx, greedy.tokens), S, axis=0)
        elif baseline_kind == "scb":
            r = rewards.reshape(B, S)
            loo = (r.sum(axis=1, keepdims=True) - r) / (S - 1)
            baseline = loo.reshape(B * S)
        elif baseline_kind == "gt_consensus":
            # Device gather of the startup-computed per-video GT
            # consensus scores — no extra host crossing.
            baseline = jnp.repeat(gt_base[video_idx], S, axis=0)
        else:
            baseline = jnp.zeros_like(rewards)
        advantage = rewards - baseline

        state, loss, gnorm = _pg_update(
            state, feats, feat_masks, category, S, rollout.tokens,
            rollout.mask, advantage, temperature,
            suppress_unk=model.decode_suppress_unk,
            logits_sharding=pg_logits_sharding,
        )
        return state, {
            "loss": loss,
            "grad_norm": gnorm,
            "reward": rewards.mean(),
            "baseline": baseline.mean(),
            "advantage": advantage.mean(),
        }

    # ss_prob stays a traced (unused) arg — marking it static would
    # recompile the whole rollout+backward graph whenever a scheduled-
    # sampling config ticks its probability.
    return jax.jit(train_step, donate_argnums=(0,))


# ----------------------------------------------------------- split variant

# Above this per-dispatch latency, chunked scoring overlap can't pay for
# its extra dispatches (see _make_split_step docstring).
_CHUNK_MAX_DISPATCH_MS = 5.0


# ------------------------------------------------------- pipelined variant

def _make_pipelined_step(model, cfg, scorer) -> Callable:
    """Software-pipelined split step for high-dispatch-latency (tunneled)
    runtimes — VERDICT r3 #3's dispatch-tax attack.

    The plain split step pays TWO dispatch round-trips per step (rollout,
    then update) with host scoring between them; through a ~100 ms tunnel
    the RTTs dominate the step.  Here each call dispatches ONE graph:

        [apply the PREVIOUS batch's PG update] -> [rollout + greedy
        baseline for THIS batch with the freshly-updated params]

    then fetches and scores this batch's tokens, holding the resulting
    advantage as the next call's pending update.  The parameter
    trajectory is IDENTICAL to the unpipelined step (same updates, same
    order, same rng; only the dispatch boundaries move) — pinned by
    ``tests/test_cst.py::test_pipelined_layout_matches_split``.

    Consequences callers must know:
    * ``metrics['loss']/['grad_norm']`` lag one step (they describe the
      update applied this call, i.e. the previous batch); the first call
      returns no loss.  Reward stats are current.
    * ``train_step.flush(state)`` applies the final pending update; the
      trainer runs it at every epoch/preemption boundary so checkpoints,
      eval, and ``steps_done`` accounting always see fully-applied params.
    * The rollout and greedy baseline share one feature encode
      (``CaptionModel.sample_with_baseline``); the PG update re-encodes
      inside the loss so the projection/attention-key weights keep their
      gradient — that encode is load-bearing, not redundant.
    """
    S, baseline_kind = _validate(cfg)
    temperature = cfg.train.sample_temperature
    max_len = cfg.data.max_seq_len
    need_greedy = baseline_kind == "greedy"

    def _rollout(params, feats, feat_masks, category, rng):
        rollout, greedy = model.apply(
            params, feats, feat_masks, rng=rng, category=category,
            max_len=max_len, temperature=temperature, repeat=S,
            with_greedy=need_greedy, method="sample_with_baseline",
        )
        greedy_tokens = (
            greedy.tokens if need_greedy
            else jnp.zeros((1, max_len), jnp.int32)
        )
        return rollout.tokens, rollout.mask, greedy_tokens

    first_dispatch = jax.jit(_rollout)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def update_and_rollout(state, pfeats, pmasks, pcat, ptokens, pmask,
                           padv, feats, feat_masks, category, rng):
        state, loss, gnorm = _pg_update(
            state, pfeats, pmasks, pcat, S, ptokens, pmask, padv,
            temperature, suppress_unk=model.decode_suppress_unk,
        )
        tokens, mask, greedy_tokens = _rollout(
            state.params, feats, feat_masks, category, rng
        )
        return state, loss, gnorm, tokens, mask, greedy_tokens

    @functools.partial(jax.jit, donate_argnums=(0,))
    def update_only(state, pfeats, pmasks, pcat, ptokens, pmask, padv):
        return _pg_update(
            state, pfeats, pmasks, pcat, S, ptokens, pmask, padv,
            temperature, suppress_unk=model.decode_suppress_unk,
        )

    pending: dict = {}
    phase_ms: dict = {}

    gt_base_np = (
        scorer.gt_consensus() if baseline_kind == "gt_consensus" else None
    )

    def _score(vid, tokens_np, greedy_np):
        vid_r = np.repeat(vid, S, axis=0)
        # Submit rollout AND greedy scoring before the first wait: a
        # pooled scorer works both concurrently across its processes.
        pending = scorer.submit(vid_r, tokens_np)
        g_pending = scorer.submit(vid, greedy_np) if need_greedy else None
        rewards = pending.wait().astype(np.float32)
        greedy_scores = g_pending.wait() if g_pending is not None else None
        return rewards, _baseline_from(
            rewards, greedy_scores, S, baseline_kind,
            gt_rows=None if gt_base_np is None else gt_base_np[vid],
        )

    def train_step(state, feats, feat_masks, captions, weights, category,
                   video_idx, rng, ss_prob):
        vid = np.asarray(video_idx)
        metrics = {}
        t0 = time.perf_counter()
        if not pending:
            tokens, mask, greedy_tokens = first_dispatch(
                state.params, feats, feat_masks, category, rng
            )
        else:
            p = pending
            state, loss, gnorm, tokens, mask, greedy_tokens = (
                update_and_rollout(
                    state, p["feats"], p["masks"], p["category"],
                    p["tokens"], p["mask"], jnp.asarray(p["advantage"]),
                    feats, feat_masks, category, rng,
                )
            )
            metrics["loss"] = loss
            metrics["grad_norm"] = gnorm
        # Fetch blocks on [update + rollout] compute plus one RTT.
        tokens_np = np.asarray(tokens)
        greedy_np = np.asarray(greedy_tokens) if need_greedy else None
        t1 = time.perf_counter()
        rewards, baseline = _score(vid, tokens_np, greedy_np)
        t2 = time.perf_counter()
        advantage = rewards - baseline
        pending.clear()
        pending.update(
            feats=feats, masks=feat_masks, category=category,
            tokens=tokens, mask=mask, advantage=advantage,
        )
        phase_ms.update(
            dispatch_and_device_ms=round((t1 - t0) * 1e3, 2),
            host_score_ms=round((t2 - t1) * 1e3, 2),
        )
        # Host floats, deliberately NOT device arrays: uploading stats the
        # host just computed would enqueue three extra transfers per step
        # through the (possibly 100ms-RTT) transport, and every consumer
        # (trainer accumulators, logging) wants host scalars anyway.
        metrics.update(
            reward=float(rewards.mean()),
            baseline=float(baseline.mean()),
            advantage=float(advantage.mean()),
        )
        return state, metrics

    def flush(state):
        """Apply the pending update (if any) -> (state, metrics|None)."""
        if not pending:
            return state, None
        p = pending
        state, loss, gnorm = update_only(
            state, p["feats"], p["masks"], p["category"], p["tokens"],
            p["mask"], jnp.asarray(p["advantage"]),
        )
        pending.clear()
        return state, {"loss": loss, "grad_norm": gnorm}

    def reset():
        """Drop the pending update WITHOUT applying it.  The trainer
        calls this at epoch entry: after an aborted epoch (exception
        between dispatch and flush) the held update belongs to an
        abandoned batch and applying it to the next epoch's state would
        corrupt the trajectory (ADVICE r4 #2)."""
        pending.clear()

    train_step.flush = flush
    train_step.reset = reset
    train_step.phase_ms = phase_ms
    train_step.layout = "pipeline"
    train_step.scorer = scorer
    return train_step


def _chunk_count(requested: int, B: int) -> int:
    """Largest divisor of ``B`` that is <= ``requested`` (>= 1)."""
    k = max(1, min(requested, B))
    while B % k:
        k -= 1
    return k


def _make_split_step(model, cfg, scorer) -> Callable:
    """Two-phase CST step for backends without io_callback — with the
    host scorer pipelined against device compute (SURVEY.md §7 hard part
    #1: the scorer "must overlap with device compute").

    The rollout is dispatched as K batch chunks, all enqueued before the
    host blocks: while the device computes chunks c+1..K (and the greedy
    baseline decode), the host scores chunk c's tokens.  Device idle time
    during scoring drops from the full scoring cost to ~1/K of it; the
    math is identical for any K (every chunk samples from the same
    params — only the rng stream differs from the unchunked dispatch,
    which K=1 reproduces bit-for-bit).

    **Overlapped reward scheduling** (``cfg.train.overlap_rewards``):
    the rollout decode and the greedy-baseline decode are already
    dispatched as independent device computations; with overlap on, each
    rollout chunk is FED to the scorer's stream the moment its tokens
    are fetched — an async pool scorer (``train.reward_workers``) then
    scores in its worker processes while the device still runs the
    greedy decode — and the host blocks only once, right before the
    PG-update dispatch.  Step time approaches
    ``max(t_device, t_score) + t_update`` instead of the serial sum
    (docs/PERF.md).  Overlap off reproduces the serial in-place scoring
    schedule; both produce bit-identical rewards and updates
    (docs/PARITY.md, pinned by tests/test_cst.py).

    Per-call wall-time phases (dispatch / sample fetch / score / greedy
    fetch / score wait / update) are recorded on ``train_step.phase_ms``
    — the trainer folds their epoch means into the history entry and
    TensorBoard.

    Chunking pays ~2K-1 EXTRA dispatches per step, so it only wins when
    per-dispatch latency is far below the scorer cost.  On a tunneled
    runtime (measured ~140 ms RTT here, vs a ~44 ms scorer) it LOSES
    2-3x; the step therefore probes :func:`dispatch_latency_ms` once and
    falls back to the fused single-dispatch layout (rollout + greedy
    baseline in ONE graph) when dispatch latency exceeds
    ``_CHUNK_MAX_DISPATCH_MS``."""
    S, baseline_kind = _validate(cfg)
    temperature = cfg.train.sample_temperature
    max_len = cfg.data.max_seq_len
    need_greedy = baseline_kind == "greedy"
    overlap = bool(getattr(cfg.train, "overlap_rewards", True))
    gt_base_np = (
        scorer.gt_consensus() if baseline_kind == "gt_consensus" else None
    )
    k_requested = max(1, getattr(cfg.train, "cst_score_chunks", 1))
    # High-latency (tunneled) runtimes take the FUSED single-dispatch
    # layout: every extra dispatch costs a full RTT, more than any
    # host-scoring overlap recovers.  Low-latency hosts keep separate
    # rollout/greedy dispatches even at K=1 — scoring the rollout while
    # the device computes the greedy baseline is free overlap there.
    latency_gated = dispatch_latency_ms() > _CHUNK_MAX_DISPATCH_MS
    if latency_gated and k_requested > 1:
        log.warning(
            "cst_score_chunks=%d disabled: per-dispatch latency %.1f ms "
            "exceeds %.0f ms — extra dispatches would cost more than the "
            "host-scoring overlap recovers (tunneled runtime)",
            k_requested, dispatch_latency_ms(), _CHUNK_MAX_DISPATCH_MS,
        )

    @jax.jit
    def rollout_chunk(params, feats, feat_masks, category, rng):
        rollout = model.apply(
            params, feats, feat_masks, rng=rng, category=category,
            max_len=max_len, greedy=False, temperature=temperature,
            method="sample", repeat=S,
        )
        return rollout.tokens, rollout.mask

    @jax.jit
    def rollout_fused(params, feats, feat_masks, category, rng):
        """K=1 layout: rollout AND greedy baseline in one dispatch (two
        device->host crossings per step total, the reference's own
        structure, SURVEY.md §3.2)."""
        tokens, mask = rollout_chunk.__wrapped__(
            params, feats, feat_masks, category, rng
        )
        greedy_tokens = (
            model.apply(
                params, feats, feat_masks, category=category,
                max_len=max_len, greedy=True, method="sample",
            ).tokens
            if need_greedy
            else jnp.zeros((1, max_len), jnp.int32)
        )
        return tokens, mask, greedy_tokens

    @jax.jit
    def greedy_chunk(params, feats, feat_masks, category):
        return model.apply(
            params, feats, feat_masks, category=category,
            max_len=max_len, greedy=True, method="sample",
        ).tokens

    @functools.partial(jax.jit, donate_argnums=(0,))
    def update_fn(state, feats, feat_masks, category, tokens_chunks,
                  mask_chunks, advantage):
        # Chunks concatenate back to the exact repeated row order
        # (chunk c holds rows [lo*S, hi*S) of the repeated batch).
        tokens = jnp.concatenate(tokens_chunks, axis=0)
        mask = jnp.concatenate(mask_chunks, axis=0)
        state, loss, gnorm = _pg_update(
            state, feats, feat_masks, category, S, tokens, mask,
            advantage, temperature,
            suppress_unk=model.decode_suppress_unk,
        )
        return state, loss, gnorm

    def _multi_device(x) -> bool:
        return (
            isinstance(x, jax.Array) and len(x.sharding.device_set) > 1
        )

    clock = PhaseClock()
    phase_ms: dict = {}

    def train_step(state, feats, feat_masks, captions, weights, category,
                   video_idx, rng, ss_prob):
        clock.start()
        vid = np.asarray(video_idx)
        B = vid.shape[0]
        # Chunk slices ignore any data-axis sharding: on a multi-device
        # batch each chunk would span a device subset and force per-chunk
        # resharding — costlier than the scoring overlap saves.  The
        # split path is the single-chip io_callback workaround; sharded
        # batches run unchunked.
        sharded = any(map(_multi_device, feats.values())) or _multi_device(
            video_idx
        )
        K = (
            1
            if (sharded or latency_gated)
            else _chunk_count(k_requested, B)
        )
        step = B // K
        bounds = [(c * step, (c + 1) * step) for c in range(K)]

        def bslice(lo, hi):
            f = {m: v[lo:hi] for m, v in feats.items()}
            fm = {m: v[lo:hi] for m, v in feat_masks.items()}
            cat = category[lo:hi] if category is not None else None
            return f, fm, cat

        # Phase 1 — enqueue EVERYTHING the scorer will consume before
        # blocking.  Tunneled runtime: one fused dispatch (rollout +
        # greedy).  Otherwise: K rollout chunks, then the greedy
        # baseline decode (its compute hides the tail rollout chunks'
        # scoring; at K=1 it still hides the rollout's scoring).
        if latency_gated:
            tokens, mask, greedy_tokens = rollout_fused(
                state.params, feats, feat_masks, category, rng
            )
            dispatched = [(tokens, mask)]
            greedy_parts = [greedy_tokens] if need_greedy else []
        else:
            dispatched = []
            for c, (lo, hi) in enumerate(bounds):
                crng = jax.random.fold_in(rng, c) if K > 1 else rng
                f, fm, cat = bslice(lo, hi)
                dispatched.append(
                    rollout_chunk(state.params, f, fm, cat, crng)
                )
            greedy_parts = (
                [
                    greedy_chunk(state.params, *bslice(lo, hi))
                    for lo, hi in bounds
                ]
                if need_greedy
                else []
            )
        clock.lap("dispatch_ms")

        # Phase 2 — host scoring, streamed: np.asarray(chunk c) blocks
        # only on chunk c's dispatch; later chunks (and the greedy
        # baseline decode) keep the device busy.  With overlap on, each
        # fetched chunk is fed to the scorer stream — a pooled scorer
        # works it in other processes immediately — and the single
        # blocking wait lands just before the update dispatch.
        stream = scorer.stream() if overlap else None
        reward_parts = []
        for c, (tokens, mask) in enumerate(dispatched):
            lo, hi = bounds[c]
            vid_r = np.repeat(vid[lo:hi], S, axis=0)
            tokens_np = np.asarray(tokens)
            clock.lap("sample_fetch_ms")
            if stream is not None:
                stream.feed(vid_r, tokens_np)
            else:
                reward_parts.append(
                    scorer.score_ids(vid_r, tokens_np).astype(np.float32)
                )
            clock.lap("score_ms")

        greedy_pending = None
        greedy_scores = None
        if need_greedy:
            greedy_np = []
            for toks in greedy_parts:
                greedy_np.append(np.asarray(toks))
                clock.lap("greedy_fetch_ms")
            if overlap:
                greedy_pending = [
                    scorer.submit(vid[lo:hi], toks)
                    for (lo, hi), toks in zip(bounds, greedy_np)
                ]
            else:
                greedy_scores = np.concatenate([
                    scorer.score_ids(vid[lo:hi], toks).astype(np.float32)
                    for (lo, hi), toks in zip(bounds, greedy_np)
                ])
            clock.lap("score_ms")

        rewards = (
            stream.finish() if stream is not None
            else np.concatenate(reward_parts)
        )
        if greedy_pending is not None:
            greedy_scores = np.concatenate(
                [p.wait() for p in greedy_pending]
            ).astype(np.float32)
        clock.lap("score_wait_ms")
        baseline = _baseline_from(
            rewards, greedy_scores, S, baseline_kind,
            gt_rows=None if gt_base_np is None else gt_base_np[vid],
        )
        advantage = rewards - baseline

        # Phase 3 — one PG update over the full batch (donated state:
        # param/optimizer buffers are reused, not copied).
        state, loss, gnorm = update_fn(
            state, feats, feat_masks, category,
            tuple(t for t, _ in dispatched),
            tuple(m for _, m in dispatched),
            jnp.asarray(advantage),
        )
        clock.lap("update_ms")
        clock.commit(phase_ms)
        return state, {
            "loss": loss,
            "grad_norm": gnorm,
            "reward": jnp.float32(rewards.mean()),
            "baseline": jnp.float32(baseline.mean()),
            "advantage": jnp.float32(advantage.mean()),
        }

    train_step.phase_ms = phase_ms
    train_step.layout = "split"
    train_step.scorer = scorer
    return train_step
