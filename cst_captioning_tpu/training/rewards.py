"""In-loop CIDEr-D reward scoring for CST/SCST — the host side.

Reference equivalent (SURVEY.md §3.2): the reference decodes sampled id
sequences to strings and calls ``CiderD.compute_score`` against each
video's references every training step.  SURVEY.md ranks this host scorer
as hot loop #2: it must stay far cheaper than the device step.

TPU-first design:
* Scoring happens directly on **token ids** — references are vocab-encoded
  once at startup, so n-grams are tuples of ints and the per-step
  ids->string->re-tokenize round trip is gone.  (Id n-grams and word
  n-grams are in bijection under a fixed vocab, so scores are identical to
  string scoring; the reference's own reward path scores vocab-decoded
  strings, carrying exactly the same information.)
* Reference n-gram vectors are **pre-cooked per video** at startup
  (``cook_refs_vec``) — per step only the candidates are cooked.
* The scorer is called from inside the jitted CST step through
  ``jax.experimental.io_callback`` (see ``training/cst.py``).
* A drop-in C++ scorer (``native/``) replaces the Python inner loop when
  built — same cooked-ref layout, same results.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

from cst_captioning_tpu.constants import BOS_ID, EOS_ID, PAD_ID, UNK_ID
from cst_captioning_tpu.data.datasets import CaptionDataset
from cst_captioning_tpu.metrics.cider import (
    _CiderBase,
    ciderd_score_vec,
    compute_doc_freq,
    cook_refs_vec,
    precook,
)
from cst_captioning_tpu.metrics.tokenizer import ptb_tokenize


def ids_until_end(row: Sequence[int]) -> List[int]:
    """Candidate tokens: everything before the first PAD/EOS, skipping BOS
    (sampled sequences never contain BOS, but encoded refs do)."""
    out = []
    for t in row:
        t = int(t)
        if t in (PAD_ID, EOS_ID):
            break
        if t == BOS_ID:
            continue
        out.append(t)
    return out


class CiderDRewarder:
    """CIDEr-D over token-id sequences with startup-cooked references."""

    def __init__(
        self,
        dataset: CaptionDataset,
        df_mode: str = "corpus",
        use_d: bool = True,
        backend: str = "auto",
        weighted_refs: bool = False,
    ):
        """``df_mode="corpus"``: document frequencies over this dataset's
        reference sets (the reference's train-corpus idf option);
        otherwise a path to a saved idf table (reference pickle parity) —
        in that case the table's *string* n-grams are re-encoded through
        the vocab so they match id n-grams.

        ``backend``: "auto" builds the C++ scorer (``native/ciderd.cpp``)
        and silently falls back to Python when g++/packing bounds don't
        allow it; "native" raises instead of falling back; "python" skips
        the native path.

        ``weighted_refs``: weight each reference's CIDEr-D contribution by
        the dataset's per-caption consensus weight (``caption_weights``) —
        the paper's weighted consensus reward (driver config 4, "20-ref
        weighted CIDEr").  Videos whose weight count doesn't match their
        reference count fall back to the uniform mean.
        """
        self.vocab = dataset.vocab
        self.use_d = use_d
        w2i = self.vocab.word_to_idx

        def encode_tokens(tokens: List[str]) -> List[int]:
            return [w2i.get(t, UNK_ID) for t in tokens]

        # Vocab-encode every video's references (tokenize like the metric
        # pipeline so idf tables and eval tokenization agree).
        self._encoded_refs: List[List[List[int]]] = []
        self._cooked_refs = []
        self._ref_weights: Optional[List[Optional[np.ndarray]]] = (
            [] if weighted_refs else None
        )
        n_mismatch = 0
        for i in range(len(dataset)):
            refs = dataset.references(i)
            encoded = [encode_tokens(ptb_tokenize(r)) for r in refs]
            self._encoded_refs.append(encoded)
            self._cooked_refs.append([precook(e) for e in encoded])
            if weighted_refs:
                w = np.asarray(dataset.caption_weights(i), np.float32)
                if w.shape[0] == len(refs):
                    self._ref_weights.append(w)
                else:
                    self._ref_weights.append(None)
                    n_mismatch += 1
        if n_mismatch:
            import logging

            logging.getLogger("cst_captioning_tpu.rewards").warning(
                "weighted_refs: %d/%d videos have a caption-weight count "
                "that doesn't match their reference count — those score "
                "with the uniform mean", n_mismatch, len(dataset),
            )

        if df_mode == "corpus":
            self.doc_freq = compute_doc_freq(self._cooked_refs)
            self.log_ref_len = math.log(float(max(len(dataset), 2)))
            self._df_external = None
        else:
            base = _CiderBase(df_mode=df_mode)
            # Re-key string n-grams to id n-grams.
            self.doc_freq = {}
            for ngram, df in base._df.items():
                key = tuple(w2i.get(w, UNK_ID) for w in ngram)
                # Collisions (via UNK) keep the max df — conservative idf.
                self.doc_freq[key] = max(df, self.doc_freq.get(key, 0.0))
            self.log_ref_len = base._log_ref_len
            self._df_external = self.doc_freq

        self._native = None
        self.backend = "python"
        if backend in ("auto", "native"):
            try:
                if not use_d:
                    from cst_captioning_tpu.native import NativeUnavailable

                    raise NativeUnavailable(
                        "plain CIDEr (use_d=False) has no native scorer"
                    )
                from cst_captioning_tpu.native import NativeCiderD

                self._native = NativeCiderD(
                    self._encoded_refs,
                    df=self._df_external,
                    log_ref_len=self.log_ref_len,
                    vocab_size=len(self.vocab),
                    ref_weights=self._ref_weights,
                )
                self.backend = "native"
            except Exception as e:
                if backend == "native":
                    raise
                import logging

                logging.getLogger("cst_captioning_tpu.rewards").info(
                    "native CiderD unavailable (%s); using python scorer", e
                )
        # Python tf-idf ref vectors: only cooked when actually scoring in
        # Python (the native finalize performs the same cooking in C++).
        self._ref_vecs = (
            None
            if self._native is not None
            else [
                cook_refs_vec(refs, self.doc_freq, self.log_ref_len)
                for refs in self._cooked_refs
            ]
        )

    def gt_consensus(self) -> np.ndarray:
        """(num_videos,) mean leave-one-out CIDEr-D of each video's GT
        captions, under this rewarder's df table, scale, AND reference
        weighting — the SURVEY.md §3.2 reading of the paper's SCB
        baseline ("baseline from GT-caption consensus scores"), in the
        same units as ``score_ids`` rewards: when the rewarder weights
        references (``weighted_refs``), each leave-one-out score uses the
        remaining siblings' consensus weights exactly as ``score_ids``
        does for rollouts.  Computed once; callers cache it.

        Distinct from the dataset's stored ``caption_weights``: those are
        normalized to mean 1.0 per video for the WXE loss and are NOT in
        reward units."""
        if self._native is not None:
            # Threaded C++ leave-one-out (ADVICE r4 #3): at MSR-VTT scale
            # this is ~200k scorings, a significant one-time startup cost
            # in Python.  Parity: tests/test_native_ciderd.py.
            return self._native.gt_consensus()
        out = np.zeros((len(self._cooked_refs),), np.float32)
        for i, cooked in enumerate(self._cooked_refs):
            if len(cooked) < 2:
                continue
            # Cook each reference's tf-idf vector ONCE; every
            # leave-one-out score slices the vector list.
            vecs = cook_refs_vec(cooked, self.doc_freq, self.log_ref_len)
            w = (
                None if self._ref_weights is None
                else self._ref_weights[i]
            )
            scores = []
            for j, c in enumerate(cooked):
                loo_w = (
                    None if w is None
                    else np.concatenate([w[:j], w[j + 1:]])
                )
                scores.append(ciderd_score_vec(
                    c, vecs[:j] + vecs[j + 1:], self.doc_freq,
                    self.log_ref_len, use_d=self.use_d,
                    ref_weights=loo_w,
                ))
            out[i] = float(np.mean(scores))
        return out

    def score_ids(
        self, video_idx: np.ndarray, token_ids: np.ndarray
    ) -> np.ndarray:
        """(B,) video dataset indices + (B, L) sampled ids -> (B,) float32
        CIDEr-D scores (x10 scale, like the reference scorer)."""
        video_idx = np.asarray(video_idx)
        token_ids = np.asarray(token_ids)
        if self._native is not None:
            return self._native.score_ids(video_idx, token_ids)
        out = np.zeros((token_ids.shape[0],), np.float32)
        for b in range(token_ids.shape[0]):
            vid = int(video_idx[b])
            cand = precook(ids_until_end(token_ids[b]))
            out[b] = ciderd_score_vec(
                cand,
                self._ref_vecs[vid],
                self.doc_freq,
                self.log_ref_len,
                use_d=self.use_d,
                ref_weights=(
                    None
                    if self._ref_weights is None
                    else self._ref_weights[vid]
                ),
            )
        return out
