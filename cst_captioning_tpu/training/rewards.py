"""In-loop CIDEr-D reward scoring for CST/SCST — the host side.

Reference equivalent (SURVEY.md §3.2): the reference decodes sampled id
sequences to strings and calls ``CiderD.compute_score`` against each
video's references every training step.  SURVEY.md ranks this host scorer
as hot loop #2: it must stay far cheaper than the device step.

TPU-first design:
* Scoring happens directly on **token ids** — references are vocab-encoded
  once at startup, so n-grams are tuples of ints and the per-step
  ids->string->re-tokenize round trip is gone.  (Id n-grams and word
  n-grams are in bijection under a fixed vocab, so scores are identical to
  string scoring; the reference's own reward path scores vocab-decoded
  strings, carrying exactly the same information.)
* Reference n-gram vectors are **pre-cooked per video** at startup
  (``cook_refs_vec``) — per step only the candidates are cooked.
* The scorer is called from inside the jitted CST step through
  ``jax.experimental.io_callback`` (see ``training/cst.py``).
* A drop-in C++ scorer (``native/``) replaces the Python inner loop when
  built — same cooked-ref layout, same results.
"""

from __future__ import annotations

import atexit
import math
import multiprocessing
import pickle
from typing import List, Optional

import numpy as np

from cst_captioning_tpu.constants import UNK_ID
from cst_captioning_tpu.data.datasets import CaptionDataset
from cst_captioning_tpu.metrics.cider import (
    _CiderBase,
    ciderd_score_rows,
    ciderd_score_vec,
    compute_doc_freq,
    cook_refs_vec,
    precook,
)
from cst_captioning_tpu.metrics.reward_worker import (  # noqa: F401
    ids_until_end,  # canonical home: metrics/reward_worker.py (jax-free)
    pool_init,
    pool_score,
)
from cst_captioning_tpu.metrics.tokenizer import ptb_tokenize


class CiderDRewarder:
    """CIDEr-D over token-id sequences with startup-cooked references."""

    def __init__(
        self,
        dataset: CaptionDataset,
        df_mode: str = "corpus",
        use_d: bool = True,
        backend: str = "auto",
        weighted_refs: bool = False,
    ):
        """``df_mode="corpus"``: document frequencies over this dataset's
        reference sets (the reference's train-corpus idf option);
        otherwise a path to a saved idf table (reference pickle parity) —
        in that case the table's *string* n-grams are re-encoded through
        the vocab so they match id n-grams.

        ``backend``: "auto" builds the C++ scorer (``native/ciderd.cpp``)
        and silently falls back to Python when g++/packing bounds don't
        allow it; "native" raises instead of falling back; "python" skips
        the native path.

        ``weighted_refs``: weight each reference's CIDEr-D contribution by
        the dataset's per-caption consensus weight (``caption_weights``) —
        the paper's weighted consensus reward (driver config 4, "20-ref
        weighted CIDEr").  Videos whose weight count doesn't match their
        reference count fall back to the uniform mean.
        """
        self.vocab = dataset.vocab
        self.use_d = use_d
        w2i = self.vocab.word_to_idx

        def encode_tokens(tokens: List[str]) -> List[int]:
            return [w2i.get(t, UNK_ID) for t in tokens]

        # Vocab-encode every video's references (tokenize like the metric
        # pipeline so idf tables and eval tokenization agree).
        self._encoded_refs: List[List[List[int]]] = []
        self._cooked_refs = []
        self._ref_weights: Optional[List[Optional[np.ndarray]]] = (
            [] if weighted_refs else None
        )
        n_mismatch = 0
        for i in range(len(dataset)):
            refs = dataset.references(i)
            encoded = [encode_tokens(ptb_tokenize(r)) for r in refs]
            self._encoded_refs.append(encoded)
            self._cooked_refs.append([precook(e) for e in encoded])
            if weighted_refs:
                w = np.asarray(dataset.caption_weights(i), np.float32)
                if w.shape[0] == len(refs):
                    self._ref_weights.append(w)
                else:
                    self._ref_weights.append(None)
                    n_mismatch += 1
        if n_mismatch:
            import logging

            logging.getLogger("cst_captioning_tpu.rewards").warning(
                "weighted_refs: %d/%d videos have a caption-weight count "
                "that doesn't match their reference count — those score "
                "with the uniform mean", n_mismatch, len(dataset),
            )

        if df_mode == "corpus":
            self.doc_freq = compute_doc_freq(self._cooked_refs)
            self.log_ref_len = math.log(float(max(len(dataset), 2)))
            self._df_external = None
        else:
            base = _CiderBase(df_mode=df_mode)
            # Re-key string n-grams to id n-grams.
            self.doc_freq = {}
            for ngram, df in base._df.items():
                key = tuple(w2i.get(w, UNK_ID) for w in ngram)
                # Collisions (via UNK) keep the max df — conservative idf.
                self.doc_freq[key] = max(df, self.doc_freq.get(key, 0.0))
            self.log_ref_len = base._log_ref_len
            self._df_external = self.doc_freq

        self._native = None
        self.backend = "python"
        if backend in ("auto", "native"):
            try:
                if not use_d:
                    from cst_captioning_tpu.native import NativeUnavailable

                    raise NativeUnavailable(
                        "plain CIDEr (use_d=False) has no native scorer"
                    )
                from cst_captioning_tpu.native import NativeCiderD

                self._native = NativeCiderD(
                    self._encoded_refs,
                    df=self._df_external,
                    log_ref_len=self.log_ref_len,
                    vocab_size=len(self.vocab),
                    ref_weights=self._ref_weights,
                )
                self.backend = "native"
            except Exception as e:
                if backend == "native":
                    raise
                import logging

                logging.getLogger("cst_captioning_tpu.rewards").info(
                    "native CiderD unavailable (%s); using python scorer", e
                )
        # Python tf-idf ref vectors: only cooked when actually scoring in
        # Python (the native finalize performs the same cooking in C++).
        self._ref_vecs = (
            None
            if self._native is not None
            else [
                cook_refs_vec(refs, self.doc_freq, self.log_ref_len)
                for refs in self._cooked_refs
            ]
        )

    def gt_consensus(self) -> np.ndarray:
        """(num_videos,) mean leave-one-out CIDEr-D of each video's GT
        captions, under this rewarder's df table, scale, AND reference
        weighting — the SURVEY.md §3.2 reading of the paper's SCB
        baseline ("baseline from GT-caption consensus scores"), in the
        same units as ``score_ids`` rewards: when the rewarder weights
        references (``weighted_refs``), each leave-one-out score uses the
        remaining siblings' consensus weights exactly as ``score_ids``
        does for rollouts.  Computed once; callers cache it.

        Distinct from the dataset's stored ``caption_weights``: those are
        normalized to mean 1.0 per video for the WXE loss and are NOT in
        reward units."""
        if self._native is not None:
            # Threaded C++ leave-one-out (ADVICE r4 #3): at MSR-VTT scale
            # this is ~200k scorings, a significant one-time startup cost
            # in Python.  Parity: tests/test_native_ciderd.py.
            return self._native.gt_consensus()
        out = np.zeros((len(self._cooked_refs),), np.float32)
        for i, cooked in enumerate(self._cooked_refs):
            if len(cooked) < 2:
                continue
            # Cook each reference's tf-idf vector ONCE; every
            # leave-one-out score slices the vector list.
            vecs = cook_refs_vec(cooked, self.doc_freq, self.log_ref_len)
            w = (
                None if self._ref_weights is None
                else self._ref_weights[i]
            )
            scores = []
            for j, c in enumerate(cooked):
                loo_w = (
                    None if w is None
                    else np.concatenate([w[:j], w[j + 1:]])
                )
                scores.append(ciderd_score_vec(
                    c, vecs[:j] + vecs[j + 1:], self.doc_freq,
                    self.log_ref_len, use_d=self.use_d,
                    ref_weights=loo_w,
                ))
            out[i] = float(np.mean(scores))
        return out

    def score_ids(
        self, video_idx: np.ndarray, token_ids: np.ndarray
    ) -> np.ndarray:
        """(B,) video dataset indices + (B, L) sampled ids -> (B,) float32
        CIDEr-D scores (x10 scale, like the reference scorer)."""
        video_idx = np.asarray(video_idx)
        token_ids = np.asarray(token_ids)
        if self._native is not None:
            return self._native.score_ids(video_idx, token_ids)
        vids = [int(v) for v in video_idx]
        cands = [
            precook(ids_until_end(token_ids[b]))
            for b in range(token_ids.shape[0])
        ]
        return ciderd_score_rows(
            cands,
            [self._ref_vecs[v] for v in vids],
            self.doc_freq,
            self.log_ref_len,
            use_d=self.use_d,
            ref_weights_rows=(
                None
                if self._ref_weights is None
                else [self._ref_weights[v] for v in vids]
            ),
        )

    # Async surface (eager here): the CST step schedules scoring through
    # submit()/stream() uniformly; the serial rewarder computes at the
    # call site, the RewardPool overlaps it with device compute.
    def submit(self, video_idx, token_ids) -> "PendingScores":
        return PendingScores([self.score_ids(video_idx, token_ids)])

    def stream(self) -> "RewardStream":
        return RewardStream(self)


class PendingScores:
    """Handle for in-flight reward scoring.  ``wait()`` concatenates the
    per-shard results in submission order — the order the serial scorer
    would have produced — so async delivery cannot permute rows."""

    def __init__(self, parts: list):
        self._parts = parts

    def wait(self) -> np.ndarray:
        out = [
            p.get() if hasattr(p, "get") else p for p in self._parts
        ]
        if not out:
            return np.zeros((0,), np.float32)
        return np.concatenate(out).astype(np.float32, copy=False)


class RewardStream:
    """Streaming scorer front end: ``feed()`` accepts rollout token rows
    as they are harvested from the device (chunk by chunk), ``finish()``
    blocks once and returns the concatenated scores in feed order."""

    def __init__(self, scorer):
        self._scorer = scorer
        self._pending: List[PendingScores] = []

    def feed(self, video_idx, token_ids) -> None:
        self._pending.append(self._scorer.submit(video_idx, token_ids))

    def finish(self) -> np.ndarray:
        out = [p.wait() for p in self._pending]
        self._pending = []
        if not out:
            return np.zeros((0,), np.float32)
        return np.concatenate(out)


# ----------------------------------------------------- multiprocess pool

class RewardPool:
    """Persistent multiprocess CIDEr-D reward pool.

    Wraps a python-backend :class:`CiderDRewarder`: rollout rows are
    sharded contiguously across ``num_workers`` worker processes and the
    per-shard results concatenated in order — BIT-IDENTICAL to serial
    scoring, because rows are independent and the workers run the exact
    same :func:`~cst_captioning_tpu.metrics.cider.ciderd_score_rows`
    loop (docs/PARITY.md).  The corpus n-gram document-frequency table
    and the cooked reference sets are pickled to the workers ONCE at
    pool start; per call only the token rows cross the process boundary.

    ``submit()`` returns a :class:`PendingScores` handle and
    ``stream()`` a :class:`RewardStream` — the CST step feeds rollout
    chunks as they come off the device and blocks only at the PG-update
    dispatch, so host scoring hides under device decode time
    (``training/cst.py``).

    ``simulate_ms_per_row`` is a bench/test-only knob: an idle
    ``time.sleep`` per row in the workers, modeling scorer cost that
    does not contend with the accelerator (the ``tools/overlap_sim.py``
    technique) on hosts too small to exhibit it — it never changes the
    computed scores.
    """

    def __init__(
        self,
        rewarder: CiderDRewarder,
        num_workers: int,
        start_method: Optional[str] = None,
        simulate_ms_per_row: float = 0.0,
    ):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        self._inner = rewarder
        self.num_workers = num_workers
        self.backend = f"python-pool{num_workers}"
        payload = pickle.dumps(
            {
                "cooked_refs": rewarder._cooked_refs,
                "doc_freq": dict(rewarder.doc_freq),
                "log_ref_len": rewarder.log_ref_len,
                "use_d": rewarder.use_d,
                "ref_weights": rewarder._ref_weights,
                "simulate_ms_per_row": float(simulate_ms_per_row),
            },
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        if start_method is None:
            # forkserver: workers fork from a CLEAN spawn-created server
            # process, never from this (jax-threaded) one — plain fork
            # from a long-lived jax parent deadlocked reproducibly (a
            # child can inherit a lock a jax thread held at fork time;
            # the failure jax's os.fork RuntimeWarning describes).  The
            # worker-side module is jax-free by construction
            # (metrics/reward_worker.py), so the per-worker import cost
            # is ~0.1 s, paid once at pool start.
            methods = multiprocessing.get_all_start_methods()
            start_method = (
                "forkserver" if "forkserver" in methods else "spawn"
            )
        ctx = multiprocessing.get_context(start_method)
        self._pool = ctx.Pool(
            num_workers, initializer=pool_init, initargs=(payload,)
        )
        self._closed = False
        atexit.register(self.close)

    # ------------------------------------------------------------- scoring
    def _shards(self, video_idx, token_ids):
        n = token_ids.shape[0]
        k = min(self.num_workers, n)
        bounds = np.linspace(0, n, k + 1).round().astype(int)
        return [
            (video_idx[lo:hi], token_ids[lo:hi])
            for lo, hi in zip(bounds[:-1], bounds[1:])
            if hi > lo
        ]

    def submit(self, video_idx, token_ids) -> PendingScores:
        """Shard rows across the workers; returns immediately."""
        video_idx = np.asarray(video_idx)
        token_ids = np.asarray(token_ids)
        return PendingScores([
            self._pool.apply_async(pool_score, (shard,))
            for shard in self._shards(video_idx, token_ids)
        ])

    def score_ids(self, video_idx, token_ids) -> np.ndarray:
        return self.submit(video_idx, token_ids).wait()

    def stream(self) -> RewardStream:
        return RewardStream(self)

    def gt_consensus(self) -> np.ndarray:
        return self._inner.gt_consensus()

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._pool.terminate()
            self._pool.join()

    def __enter__(self) -> "RewardPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def make_reward_scorer(
    rewarder: CiderDRewarder, num_workers: int, **pool_kwargs
):
    """Wrap ``rewarder`` in a :class:`RewardPool` when it would help.

    ``num_workers <= 1`` keeps the serial scorer; the native C++ backend
    is already threaded internally, so pooling it would only add IPC.
    """
    if num_workers <= 1:
        return rewarder
    if rewarder.backend != "python":
        import logging

        logging.getLogger("cst_captioning_tpu.rewards").info(
            "reward_workers=%d ignored: the %s scorer backend is already "
            "parallel", num_workers, rewarder.backend,
        )
        return rewarder
    return RewardPool(rewarder, num_workers, **pool_kwargs)
