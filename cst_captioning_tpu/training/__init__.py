"""Training layer: jitted steps, optimizer, trainer loop, checkpointing.

Rebuilds the reference's ``train.py`` (SURVEY.md §2 "Training driver",
§3.1-3.2): epoch loop with XE / WXE / CST mode switch, Adam + stepwise LR
decay + grad clipping, per-epoch validation language eval, keep-best on val
CIDEr, early stopping, history json, checkpoint/warm-start staging
(XE -> WXE -> CST).
"""

from cst_captioning_tpu.training.steps import (  # noqa: F401
    TrainState,
    create_train_state,
    make_xe_train_step,
    make_greedy_sample_fn,
)
from cst_captioning_tpu.training.trainer import Trainer  # noqa: F401
