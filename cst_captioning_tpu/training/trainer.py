"""Trainer: the reference ``train.py`` driver, rebuilt around jitted steps.

Epoch loop with scheduled-sampling schedule, per-epoch validation language
eval (greedy decode -> metric suite), keep-best on val CIDEr, early
stopping on patience, history json, per-epoch + best checkpoints, and
warm-start staging (XE -> WXE -> CST via ``train.start_from``) — SURVEY.md
§2 "Training driver" / §5.

The CST (REINFORCE) step is provided by ``training/cst.py``; this class
dispatches on ``cfg.train.train_mode``.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, List, Optional

import jax
import numpy as np

from cst_captioning_tpu.config import Config
from cst_captioning_tpu.data.datasets import CaptionDataset
from cst_captioning_tpu.data.loader import BatchIterator, prefetch_to_device
from cst_captioning_tpu.data.vocab import Vocabulary
from cst_captioning_tpu.models.captioner import model_from_config
from cst_captioning_tpu.training import checkpoint as ckpt
from cst_captioning_tpu.training.steps import (
    create_train_state,
    make_greedy_sample_fn,
    make_optimizer,
    make_xe_train_step,
)

log = logging.getLogger("cst_captioning_tpu.trainer")


def scheduled_sampling_prob(cfg_model, epoch: int) -> float:
    """Reference ``opts.py`` schedule: zero before ``start``, then
    ``increase_prob`` more every ``increase_every`` epochs, capped."""
    if cfg_model.scheduled_sampling_start < 0:
        return 0.0
    if epoch < cfg_model.scheduled_sampling_start:
        return 0.0
    # frac = (epoch - start) // every: ss_prob stays 0 for the first
    # `every` epochs after start (reference opts.py semantics).
    frac = (
        epoch - cfg_model.scheduled_sampling_start
    ) // cfg_model.scheduled_sampling_increase_every
    return float(
        min(
            cfg_model.scheduled_sampling_increase_prob * frac,
            cfg_model.scheduled_sampling_max_prob,
        )
    )


class Trainer:
    def __init__(
        self,
        cfg: Config,
        train_ds: CaptionDataset,
        val_ds: Optional[CaptionDataset] = None,
        workdir: Optional[str] = None,
        shard_id: Optional[int] = None,
        num_shards: Optional[int] = None,
    ):
        # Multi-host default: each process loads its own shard of every
        # global batch (parallel/distributed.py).  Explicit sharding must
        # specify both values — a lone shard_id has no defined total.
        if (shard_id is None) != (num_shards is None):
            raise ValueError(
                "pass both shard_id and num_shards, or neither "
                f"(got shard_id={shard_id}, num_shards={num_shards})"
            )
        if num_shards is None:
            shard_id, num_shards = jax.process_index(), jax.process_count()
        self.cfg = cfg
        self.train_ds = train_ds
        self.val_ds = val_ds
        self.vocab: Vocabulary = train_ds.vocab
        if cfg.model.vocab_size == 0:
            cfg.model.vocab_size = len(self.vocab)
        self.workdir = workdir or os.path.join(
            cfg.train.checkpoint_dir, cfg.name
        )
        os.makedirs(self.workdir, exist_ok=True)

        # Device mesh (reference: .cuda()/DataParallel only).  A single
        # device degenerates to no mesh; otherwise params go on the mesh
        # per the TP rules and batches are sharded over the data axis.
        # Built before the model: frame sharding (model.shard_frames)
        # closes over the mesh.
        if len(jax.devices()) > 1:
            from cst_captioning_tpu.parallel import (
                batch_sharding,
                mesh_from_config,
            )

            self.mesh = mesh_from_config(cfg)
            data_ways = self.mesh.shape.get("data", 1)
            if cfg.data.batch_size % data_ways:
                raise ValueError(
                    f"data.batch_size={cfg.data.batch_size} must be "
                    f"divisible by the data mesh axis ({data_ways}) — "
                    "sharded batches require even splits"
                )
            self._batch_sharding = batch_sharding(self.mesh)
        else:
            self.mesh = None
            self._batch_sharding = None

        self.model = model_from_config(cfg, mesh=self.mesh)
        self.train_iter = BatchIterator(
            train_ds,
            batch_size=cfg.data.batch_size,
            seq_per_img=cfg.data.seq_per_img,
            max_frames=cfg.data.max_frames,
            shuffle=cfg.data.shuffle,
            drop_last=cfg.data.drop_last,
            seed=cfg.train.seed,
            shard_id=shard_id,
            num_shards=num_shards,
        )
        steps_per_epoch = max(1, self.train_iter.num_batches())
        self.tx = make_optimizer(cfg.train, steps_per_epoch)

        # All training randomness is derived per (seed, epoch, step) via
        # fold_in — resume-from-checkpoint reproduces the exact stream an
        # uninterrupted run would have used.  The impl is passed to the
        # key itself (keys carry their impl; every derived key inherits
        # it) — NOT via global config, which would leak across trainers.
        self._base_rng = self._make_base_rng(cfg.train.rng_impl)
        init_rng = jax.random.fold_in(self._base_rng, 0x5EED)
        first = next(iter(self.train_iter.epoch(0)))
        self.state = create_train_state(
            init_rng, self.model, self.tx, first._asdict(), mesh=self.mesh
        )
        if cfg.train.start_from:
            log.info("warm start from %s", cfg.train.start_from)
            self.state = self.state.replace(
                params=ckpt.restore_params(
                    cfg.train.start_from, self.state.params
                )
            )
        self._build_steps()
        self.history: Dict[str, dict] = {}
        self.best_score = -np.inf
        self.best_epoch = -1
        self.start_epoch = 0
        self._patience = 0
        # Mid-epoch preemption bookkeeping: how many of start_epoch's
        # steps the restored params already contain (those batches are
        # consumed-but-not-redispatched on replay, so resumed ==
        # uninterrupted holds even for a mid-epoch eviction).
        self._resume_skip_steps = 0
        self._epoch_steps_done = 0
        if cfg.train.resume:
            self._try_resume()
        # False = armed, True = tracing, None = finished/disabled.
        self._profiling = False if cfg.train.profile_dir else None
        # Set when fit() exits through the preemption path — callers
        # (cli/pipeline.py) must not continue to later stages.
        self.preempted = False
        # Optional TensorBoard events (SURVEY.md §5 "Metrics / logging":
        # the reference has history json only; tf.summary is the rebuild's
        # optional extra).  Rank-0 only — one event stream per run.
        self._tb = None
        if cfg.train.tensorboard_dir and jax.process_index() == 0:
            try:
                import tensorflow as tf

                # TF must never claim the accelerators JAX is using —
                # its default GPU behavior preallocates nearly all
                # device memory.  Summary writing is host-side only.
                # Best-effort: raises if TF already initialized devices.
                for kind in ("GPU", "TPU"):
                    try:
                        tf.config.set_visible_devices([], kind)
                    except (ValueError, RuntimeError):
                        pass
                # Namespace per run name: pipeline stages (xe/wxe/cst)
                # each restart at epoch 0 — one shared logdir would
                # interleave three unrelated curves under the same tags.
                self._tb = tf.summary.create_file_writer(
                    os.path.join(cfg.train.tensorboard_dir, cfg.name)
                )
            except ImportError:
                log.warning(
                    "train.tensorboard_dir set but tensorflow is not "
                    "importable — TensorBoard logging disabled"
                )

    def _tb_log(self, epoch: int, entry: Dict) -> None:
        if self._tb is None:
            return
        import tensorflow as tf

        with self._tb.as_default(step=epoch):
            for k, v in entry.items():
                if isinstance(v, (int, float)) and np.isfinite(v):
                    tf.summary.scalar(f"train/{k}", v)
                elif isinstance(v, dict):  # val metrics
                    for mk, mv in v.items():
                        if isinstance(mv, (int, float)) and np.isfinite(mv):
                            tf.summary.scalar(f"val/{mk}", mv)
        self._tb.flush()

    # ------------------------------------------------------------- plumbing
    def _make_base_rng(self, impl: str) -> jax.Array:
        # TYPED keys (jax.random.key) carry their impl through every
        # fold_in/split/bernoulli downstream; raw PRNGKey arrays would be
        # re-interpreted under the process default impl.
        if impl:
            return jax.random.key(self.cfg.train.seed, impl=impl)
        return jax.random.PRNGKey(self.cfg.train.seed)

    def _try_resume(self) -> None:
        """Preemption recovery (SURVEY.md §5 "resume-from-checkpoint"):
        restore params+optimizer+step from <workdir>/last, continue at the
        next epoch with the best-score/patience counters reinstated."""
        last = os.path.join(self.workdir, "last")
        infos = ckpt.load_infos(last)
        if not infos:
            log.info("resume requested but no checkpoint at %s — fresh run",
                     last)
            return
        # Checkpoints from before the rng_impl field (rounds 1-2) were all
        # trained under the then-default threefry2x32 — a missing key must
        # resume under THAT impl, not whatever the current config default
        # is, or the replayed stream silently diverges (ADVICE r2 #2).
        saved_impl = infos.get("rng_impl", "threefry2x32")
        if saved_impl and saved_impl != self.cfg.train.rng_impl:
            # The checkpoint's stream was generated under a different
            # PRNG impl; honor it so the resumed run replays the exact
            # stream the uninterrupted run would have used.
            log.warning(
                "resume: checkpoint used rng_impl=%s (config says %s) — "
                "using the checkpoint's impl",
                saved_impl, self.cfg.train.rng_impl,
            )
            self.cfg.train.rng_impl = saved_impl
            self._base_rng = self._make_base_rng(saved_impl)
        self.state = ckpt.restore_checkpoint(last, self.state)
        if "steps_done" in infos:
            # Mid-epoch preemption save: params contain the first
            # ``steps_done`` updates of ``epoch``.  Replay that epoch from
            # the next step — per-(epoch, step) fold-in RNG and the
            # deterministic per-epoch batch order make the continuation
            # bit-identical to an uninterrupted run.
            self.start_epoch = int(infos["epoch"])
            self._resume_skip_steps = int(infos["steps_done"])
        else:
            self.start_epoch = int(infos["epoch"]) + 1
        bs = infos.get("best_score")
        self.best_score = -np.inf if bs is None else float(bs)
        self.best_epoch = int(infos.get("best_epoch", -1))
        self._patience = int(infos.get("patience", 0))
        hist_path = os.path.join(self.workdir, self.cfg.train.history_file)
        if os.path.exists(hist_path):
            with open(hist_path) as f:
                self.history = json.load(f)
        log.info(
            "resumed from %s: continuing at epoch %d (step %d, best %.4f)",
            last, self.start_epoch, int(self.state.step), self.best_score,
        )

    def _build_steps(self) -> None:
        # On a mesh the update-step jits are NamedSharding-in/out: the
        # TrainState contract comes from the partition rules (vocab
        # tensors + optimizer moments over `model`, everything else
        # replicated), batches shard over `data`.  self.state exists by
        # the time steps are built, so it is the sharding template.
        mode = self.cfg.train.train_mode
        if mode in ("xe", "wxe"):
            self._train_step = make_xe_train_step(
                self.model, mesh=self.mesh, state_template=self.state
            )
        elif mode == "cst":
            from cst_captioning_tpu.training.cst import make_cst_train_step

            self._train_step = make_cst_train_step(
                self.model, self.cfg, self.train_ds, mesh=self.mesh,
                state_template=self.state,
            )
        else:
            raise ValueError(f"unknown train_mode {mode!r}")
        self._sample_fn = make_greedy_sample_fn(
            self.model, self.cfg.eval.max_decode_len
        )

    def _category(self, batch) -> Optional[jax.Array]:
        return batch.category if self.model.use_category else None

    # Multi-host preemption agreement cadence: the allgather must run at
    # the SAME steps on every host (a conditional collective deadlocks),
    # so it fires on a fixed step modulus — cheap enough to stay off the
    # hot path, frequent enough to act well inside an eviction grace
    # window.
    PREEMPTION_SYNC_EVERY = 10

    def _stop_agreed(self, stop_flag, step: Optional[int] = None) -> bool:
        """Global stop decision.  Single-host: the local flag.  Multi-host:
        an allgather of every process's flag — run unconditionally at
        fixed step boundaries (``step % PREEMPTION_SYNC_EVERY == 0``, or
        always when ``step`` is None, e.g. at epoch ends) so all hosts
        break at the same point and the coordinated checkpoint save sees
        identical state everywhere."""
        if jax.process_count() == 1:
            return stop_flag.triggered
        if step is not None and step % self.PREEMPTION_SYNC_EVERY != 0:
            return False
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.int32(stop_flag.triggered)
        )
        return bool(np.max(flags))

    def _last_extra(self, epoch: int, **overrides) -> Dict:
        """Resume metadata for a `last` checkpoint — shared by the
        periodic and preemption save sites so new counters can't drift
        between them."""
        extra = {
            "epoch": epoch,
            "best_score": (
                None if self.best_score == -np.inf else self.best_score
            ),
            "best_epoch": self.best_epoch,
            "patience": self._patience,
            # Resume replays the RNG stream — which only reproduces under
            # the SAME prng impl; recorded so resume can match it.
            "rng_impl": self.cfg.train.rng_impl,
        }
        extra.update(overrides)
        return extra

    def _profile_step(self, epoch: int, nsteps: int) -> None:
        """jax.profiler trace of the first ``train.profile_window_steps``
        steps of the first epoch (SURVEY.md §5 "Tracing / profiling" —
        absent in the reference; the trainer-side twin of the serving
        ``/debug/profile?ms=N`` window); closed at epoch end if the
        epoch is shorter."""
        if epoch != 0 or self._profiling is None:
            return
        window = max(1, int(self.cfg.train.profile_window_steps))
        if nsteps == 1 and not self._profiling:
            jax.profiler.start_trace(self.cfg.train.profile_dir)
            self._profiling = True
            log.info("profiler trace started -> %s", self.cfg.train.profile_dir)
        elif nsteps == 1 + window and self._profiling:
            jax.profiler.stop_trace()
            self._profiling = None  # done for this run
            log.info("profiler trace written to %s", self.cfg.train.profile_dir)

    # ------------------------------------------------------------ training
    def train_epoch(
        self, epoch: int, stop_flag=None, skip_steps: int = 0
    ) -> Dict[str, float]:
        """One epoch.  ``skip_steps`` batches are consumed but not
        dispatched — mid-epoch preemption resume: the restored params
        already contain those updates, and replaying the remainder under
        the same per-(epoch, step) fold-in RNG reproduces the
        uninterrupted run exactly."""
        cfg = self.cfg
        ss_prob = scheduled_sampling_prob(cfg.model, epoch)
        # Pipelined CST step: drop any update left pending by an ABORTED
        # previous epoch (an exception — e.g. the nan_check
        # FloatingPointError — raised between dispatch and flush).  In the
        # normal flow the epoch-end flush already cleared it, so this is a
        # no-op; after an abort the stale update belongs to an abandoned
        # trajectory and must not leak into this epoch's first call.
        reset = getattr(self._train_step, "reset", None)
        if reset is not None:
            reset()
        # Plain XE ignores consensus weights (reference train_mode switch).
        use_weights = cfg.train.train_mode != "xe"
        # Device scalars accumulated without forcing a host sync per step;
        # converted once at epoch end.
        acc: Dict[str, List[jax.Array]] = {}
        # Host-side per-phase wall-time breakdown (split/pipelined CST
        # steps expose ``phase_ms``): epoch means land in the history
        # entry and TensorBoard as ``phase_*_ms``, so a reward-scoring
        # regression shows up in training logs, not only in bench runs.
        step_phases = getattr(self._train_step, "phase_ms", None)
        phase_acc: Dict[str, List[float]] = {}
        t0 = time.time()
        nsteps = 0  # steps dispatched by THIS call (logging/throughput)
        self._epoch_steps_done = skip_steps
        epoch_rng = jax.random.fold_in(self._base_rng, epoch)
        batches = self.train_iter.epoch(epoch)
        if skip_steps:
            # Drop already-applied batches BEFORE the device prefetch so
            # skipping costs host batch assembly only, not H2D transfer.
            import itertools

            batches = itertools.islice(batches, skip_steps, None)
        for i, batch in enumerate(
            prefetch_to_device(batches, sharding=self._batch_sharding),
            start=skip_steps,
        ):
            # Poll BEFORE dispatching (a post-signal step would fold an
            # update into state beyond what the checkpoint's steps_done
            # records, and would eat into the eviction grace window).
            if stop_flag is not None and self._stop_agreed(
                stop_flag, step=i
            ):
                log.warning(
                    "preemption: stopping epoch %d before step %d",
                    epoch, i,
                )
                break
            step_rng = jax.random.fold_in(epoch_rng, i)
            weights = (
                batch.weights
                if use_weights
                else jax.numpy.ones_like(batch.weights)
            )
            self.state, metrics = self._train_step(
                self.state,
                batch.feats,
                batch.feat_masks,
                batch.captions,
                weights,
                self._category(batch),
                batch.video_idx,
                step_rng,
                ss_prob,
            )
            for k, v in metrics.items():
                acc.setdefault(k, []).append(v)
            if step_phases:
                for k, v in step_phases.items():
                    phase_acc.setdefault(k, []).append(v)
            self._epoch_steps_done = i + 1
            nsteps += 1
            if cfg.train.nan_check and "loss" in metrics:
                # Debug guard (SURVEY.md §5 "sanitizers"): forces a host
                # sync per step — enable only while hunting instabilities.
                # (The pipelined CST step's first call has no loss yet.)
                loss_now = float(metrics["loss"])
                if not np.isfinite(loss_now):
                    raise FloatingPointError(
                        f"non-finite loss {loss_now} at epoch {epoch} step "
                        f"{nsteps} (grad_norm="
                        f"{float(metrics.get('grad_norm', float('nan')))})"
                    )
            if cfg.train.profile_dir:
                self._profile_step(epoch, nsteps)
            if nsteps % cfg.train.log_every == 0 and "loss" in metrics:
                log.info(
                    "epoch %d step %d loss %.4f (%.2f steps/s)",
                    epoch, nsteps, float(metrics["loss"]),
                    nsteps / (time.time() - t0),
                )
        if self._profiling:  # epoch ended before the trace window closed
            jax.profiler.stop_trace()
            self._profiling = None
        # Pipelined CST step: apply the pending (one-step-delayed) update
        # before anything reads the params — eval, keep-best, checkpoints,
        # and the steps_done accounting all assume fully-applied state.
        flush = getattr(self._train_step, "flush", None)
        if flush is not None:
            self.state, flush_metrics = flush(self.state)
            if flush_metrics:
                for k, v in flush_metrics.items():
                    acc.setdefault(k, []).append(v)
        # Throughput truth: every dispatched step is asynchronous, so the
        # clock must not be read until the device has actually finished
        # the last update — block on the (possibly flushed) state before
        # timing, so steps_per_sec is completed-steps/s, not the rate at
        # which this host enqueued work.
        jax.block_until_ready(self.state.params)
        elapsed_s = max(time.time() - t0, 1e-9)
        out = {
            f"train_{k}" if k == "loss" else k: float(
                np.mean([float(x) for x in v])
            )
            for k, v in acc.items()
        }
        out.setdefault("train_loss", float("nan"))
        out["ss_prob"] = ss_prob
        out["steps_per_sec"] = nsteps / elapsed_s
        for k, v in phase_acc.items():
            out[f"phase_{k}"] = float(np.mean(v))
        return out

    # ---------------------------------------------------------- evaluation
    def predict(self, ds: CaptionDataset) -> Dict[str, str]:
        """Greedy-decode every video once -> {video_id: caption}."""
        from cst_captioning_tpu.evaluation import decode_dataset

        def decode(feats, feat_masks, category):
            return self._sample_fn(self.state.params, feats, feat_masks,
                                   category)

        return decode_dataset(
            ds, self.cfg, decode, self.model.use_category,
            sharding=self._batch_sharding, vocab=self.vocab,
        )

    def evaluate(self, ds: Optional[CaptionDataset] = None) -> Dict[str, float]:
        from cst_captioning_tpu.evaluation import (
            load_cocofmt_gt,
            score_predictions,
        )

        is_val = ds is None or ds is self.val_ds
        ds = ds or self.val_ds
        assert ds is not None, "no validation dataset"
        # The configured GT json is the VAL split's — only applies when
        # evaluating that split (an explicit other dataset scores against
        # its own references).
        cocofmt = self.cfg.data.cocofmt_files.get("val", "") if is_val else ""
        return score_predictions(
            ds, self.predict(ds), self.cfg.eval.metrics,
            gts=load_cocofmt_gt(cocofmt) if cocofmt else None,
        )

    # ----------------------------------------------------------------- fit
    def fit(self) -> Dict[str, dict]:
        from cst_captioning_tpu.training.preemption import PreemptionGuard

        cfg = self.cfg
        # SIGTERM (TPU/GKE eviction) -> save `last` + clean exit; resume
        # picks up exactly where the run stopped (SURVEY.md §5).
        guard = PreemptionGuard.install()
        for epoch in range(self.start_epoch, cfg.train.max_epochs):
            entry = self.train_epoch(
                epoch,
                stop_flag=guard,
                skip_steps=(
                    self._resume_skip_steps
                    if epoch == self.start_epoch
                    else 0
                ),
            )
            if self._stop_agreed(guard):
                # Record exactly how far the interrupted epoch got: resume
                # replays the REMAINDER of this epoch (skipping the
                # steps_done batches already folded into params), so the
                # continuation is bit-identical to an uninterrupted run.
                ckpt.save_checkpoint(
                    os.path.join(self.workdir, "last"),
                    self.state,
                    self._last_extra(
                        epoch,
                        preempted_during=epoch,
                        steps_done=self._epoch_steps_done,
                    ),
                )
                self.preempted = True
                log.warning(
                    "preemption checkpoint saved (%s); exiting fit",
                    os.path.join(self.workdir, "last"),
                )
                break
            if self.val_ds is not None and (epoch + 1) % cfg.train.eval_every == 0:
                val = self.evaluate()
                entry["val"] = val
                score = val.get(
                    "CIDEr",
                    next(
                        (v for v in val.values() if isinstance(v, float)),
                        -np.inf,
                    ),
                )
                if score > self.best_score:
                    self.best_score = score
                    self.best_epoch = epoch
                    self._patience = 0
                    ckpt.save_checkpoint(
                        os.path.join(self.workdir, "best"),
                        self.state,
                        {"epoch": epoch, "val": val, "config": cfg.to_dict()},
                    )
                else:
                    self._patience += 1
                log.info(
                    "epoch %d val %s (best CIDEr %.4f @ %d)",
                    epoch,
                    {
                        k: round(v, 4) if isinstance(v, float) else v
                        for k, v in val.items()
                    },
                    self.best_score, self.best_epoch,
                )
            if (epoch + 1) % cfg.train.save_checkpoint_every == 0:
                ckpt.save_checkpoint(
                    os.path.join(self.workdir, "last"),
                    self.state,
                    self._last_extra(epoch, history=entry),
                )
            self._tb_log(epoch, entry)
            self.history[str(epoch)] = entry
            # Rank-0 guard: every process keeps the in-memory history (it
            # feeds return values / resume), but only one writes the file
            # on a shared filesystem.
            if jax.process_index() == 0:
                with open(
                    os.path.join(self.workdir, cfg.train.history_file), "w"
                ) as f:
                    json.dump(self.history, f, indent=2)
            if (
                self.val_ds is not None
                and cfg.train.max_patience > 0
                and self._patience >= cfg.train.max_patience
            ):
                log.info("early stop at epoch %d", epoch)
                break
        self._export_trace()
        return self.history

    def _export_trace(self) -> None:
        """Write the span tracer's Chrome-trace JSON to
        ``train.trace_file`` (PhaseClock phases are spans in the same
        format the serving /debug/trace export uses — one Perfetto
        timeline for a CST step and a served request).  Rank-0 only;
        no-op with the knob unset."""
        path = self.cfg.train.trace_file
        if not path or jax.process_index() != 0:
            return
        from cst_captioning_tpu.observability.trace import get_tracer

        with open(path, "w") as f:
            f.write(get_tracer().export_json())
        log.info("span trace written to %s (load in Perfetto)", path)
