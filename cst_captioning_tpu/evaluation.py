"""Evaluation driver — the reference's ``test.py`` (SURVEY.md §3.3):
load checkpoint -> beam-decode the split -> write cocofmt predictions
json -> run the metric suite -> write scores json.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

import numpy as np

from cst_captioning_tpu.config import Config
from cst_captioning_tpu.data.datasets import CaptionDataset
from cst_captioning_tpu.data.loader import BatchIterator
from cst_captioning_tpu.data.vocab import decode_sequence
from cst_captioning_tpu.decoding.beam import make_beam_search_fn
from cst_captioning_tpu.metrics.evaluator import language_eval
from cst_captioning_tpu.models.captioner import CaptionModel


def decode_dataset(
    ds: CaptionDataset,
    cfg: Config,
    decode_fn,
    use_category: bool,
    sharding=None,
    vocab=None,
) -> Dict[str, str]:
    """Decode every video once -> {video_id: caption}.

    ``decode_fn(feats, feat_masks, category|None) -> tokens (B, L)`` — the
    greedy sampler during training validation, the beam searcher at test
    time.  Shared batching: seq_per_img=1, no shuffle, wrap-around
    duplicates collapse via the dict keying.  ``sharding`` (the trainer's
    data-axis batch sharding) parallelizes decode over the mesh too.
    ``vocab`` decodes ids back to words — pass the TRAINING vocab (model
    ids are defined by it); defaults to ``ds.vocab`` which is only correct
    when the dataset was built with that same vocabulary.
    """
    vocab = vocab or ds.vocab
    it = BatchIterator(
        ds,
        batch_size=cfg.data.batch_size,
        seq_per_img=1,
        max_frames=cfg.data.max_frames,
        shuffle=False,
        drop_last=False,
    )
    from cst_captioning_tpu.parallel.sharding import make_placer

    place = make_placer(sharding)
    preds: Dict[str, str] = {}
    for batch in it.epoch(0):
        cat = place(batch.category) if use_category else None
        tokens = decode_fn(
            {m: place(v) for m, v in batch.feats.items()},
            {m: place(v) for m, v in batch.feat_masks.items()},
            cat,
        )
        for vid, sent in zip(
            batch.video_ids, decode_sequence(vocab, np.asarray(tokens))
        ):
            preds[vid] = sent
    return preds


def load_cocofmt_gt(path: str) -> Dict[str, list]:
    """cocofmt ground-truth json ({"annotations": [{"image_id",
    "caption"}]}, the reference's coco-caption GT files) -> {vid: [refs]}."""
    with open(path) as f:
        raw = json.load(f)
    gts: Dict[str, list] = {}
    # Keyed off annotations only: an "images" entry with zero annotations
    # must NOT yield an empty reference list (metrics crash on refs=[]).
    for ann in raw["annotations"]:
        gts.setdefault(str(ann["image_id"]), []).append(ann["caption"])
    return gts


def score_predictions(
    ds: CaptionDataset,
    preds: Dict[str, str],
    metrics,
    gts: Optional[Dict[str, list]] = None,
) -> Dict[str, float]:
    """Run the metric suite; ground truth comes from ``gts`` (e.g. a
    cocofmt file via ``data.cocofmt_files``) or the dataset's references."""
    if gts is None:
        gts = {ds.video_id(i): ds.references(i) for i in range(len(ds))}
    else:
        # Score only the decoded videos (the cocofmt file may cover more).
        matched = {vid: gts[vid] for vid in preds if vid in gts}
        if not matched:
            raise ValueError(
                "no overlap between predicted video ids and the cocofmt "
                f"ground truth (e.g. pred {next(iter(preds), '?')!r} vs gt "
                f"{next(iter(gts), '?')!r}) — id scheme mismatch?"
            )
        if len(matched) < len(preds):
            import logging

            logging.getLogger("cst_captioning_tpu.eval").warning(
                "cocofmt ground truth covers %d/%d predicted videos — "
                "scoring the covered subset only",
                len(matched), len(preds),
            )
        gts = matched
    res = {vid: [preds[vid]] for vid in gts}
    return language_eval(gts, res, metrics=metrics)


def beam_decode_dataset(
    model: CaptionModel,
    params,
    ds: CaptionDataset,
    cfg: Config,
) -> Dict[str, str]:
    """Beam-decode every video once -> {video_id: caption}."""
    if getattr(model, "use_pallas_beam", False):
        # Engagement visibility: whether THIS eval pays per-step scan
        # orchestration or the fused kernel (the dispatch itself lives
        # in decoding/beam.py; batch shape decides, so probe at the
        # configured batch size).
        import logging

        from cst_captioning_tpu.decoding.beam import fused_beam_engaged

        probe = {
            m: np.zeros((cfg.data.batch_size, cfg.data.max_frames, 1))
            for m in model.modalities
        }
        engaged, reason = fused_beam_engaged(
            model, probe, cfg.eval.beam_size
        )
        logging.getLogger("cst_captioning_tpu.eval").info(
            "beam decode backend: %s",
            "fused Pallas kernel" if engaged
            else f"lax.scan ({reason})",
        )
    beam_fn = make_beam_search_fn(
        model,
        beam_size=cfg.eval.beam_size,
        max_len=cfg.eval.max_decode_len,
        length_normalize=cfg.eval.length_normalize,
    )

    def decode(feats, feat_masks, category):
        return beam_fn(params, feats, feat_masks, category).tokens

    return decode_dataset(ds, cfg, decode, model.use_category)


def evaluate_dataset(
    model: CaptionModel,
    params,
    ds: CaptionDataset,
    cfg: Config,
    out_dir: Optional[str] = None,
) -> Tuple[Dict[str, float], Dict[str, str]]:
    """Full eval: beam decode + metric suite (+ json artifacts).

    Returns (scores, predictions).  When ``out_dir`` is set, writes
    ``predictions.json`` (cocofmt-results style: a list of
    {"image_id", "caption"}) and ``scores.json`` — the reference's two
    eval artifacts.
    """
    preds = beam_decode_dataset(model, params, ds, cfg)
    cocofmt = cfg.data.cocofmt_files.get(cfg.eval.eval_split, "")
    scores = score_predictions(
        ds, preds, cfg.eval.metrics,
        gts=load_cocofmt_gt(cocofmt) if cocofmt else None,
    )
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "predictions.json"), "w") as f:
            json.dump(
                [{"image_id": vid, "caption": c} for vid, c in preds.items()],
                f,
                indent=2,
            )
        with open(os.path.join(out_dir, "scores.json"), "w") as f:
            json.dump(scores, f, indent=2)
    return scores, preds
