"""Sharding rules: batch over ``data``, vocab-sized params over ``model``.

The annotations here are the entire parallelism "implementation": under
``jit``, XLA GSPMD propagates them through the scan/matmuls and inserts
the collectives (grad psum over ``data``; logit all-gather / embedding
collective over ``model``) on the ICI mesh.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cst_captioning_tpu.parallel.partition import (
    PARTITION_RULES,
    compiled_rules,
)

# Parameter-name -> spec rules for the model axis.  The only tensors worth
# sharding in an LSTM captioner are vocab-sized (V ~ 10-20k):
#   word_embed (V, E) — rows sharded over model
#   logit_w    (H, V) — columns sharded over model
# Everything else (LSTM kernels, projections, attention MLP) is tiny and
# replicated.  The table itself lives in ``parallel/partition.py``
# (PARTITION_RULES — the CST-SHD-checked single definition site); this
# module keeps the compiled first-match view older call sites use.
DEFAULT_PARAM_RULES = tuple(compiled_rules(PARTITION_RULES))


def param_spec(path: str, rules=DEFAULT_PARAM_RULES) -> P:
    for pat, spec in rules:
        if pat.search(path):
            return spec
    return P()


def _path_str(path) -> str:
    return "/".join(
        getattr(k, "key", getattr(k, "name", str(k))) for k in path
    )


def _divisible(x, spec: P, mesh: Mesh) -> bool:
    for dim, axis in enumerate(spec):
        if axis is None:
            continue
        if dim >= x.ndim or x.shape[dim] % mesh.shape[axis] != 0:
            return False
    return True


def shard_params(params, mesh: Mesh, rules=DEFAULT_PARAM_RULES):
    """Place params on the mesh per the rules (replicated by default).
    With a size-1 model axis every spec degenerates to full replication —
    plain DP — so this is safe to apply unconditionally.

    A tensor whose sharded dimension doesn't divide the mesh axis falls
    back to replication (correctness first; pad the vocab to a multiple of
    the model axis to get the sharding benefit)."""

    def place(path, x):
        spec = param_spec(_path_str(path), rules)
        if not _divisible(x, spec, mesh):
            spec = P()
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis batch sharding: (B, ...) split over ``data``."""
    return NamedSharding(mesh, P("data"))


def replicate(tree, mesh: Mesh):
    return jax.device_put(tree, NamedSharding(mesh, P()))


def put_host_batch(x, sharding: NamedSharding):
    """Place one host array with ``sharding``.

    Single-process: plain ``device_put``.  Multi-process (pod slices over
    DCN): the global mesh isn't fully addressable from one process, so the
    host array — this process's shard of the global batch, as produced by
    ``BatchIterator(shard_id=process_index)`` — is assembled into the
    global array with ``jax.make_array_from_process_local_data``.
    """
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_process_local_data(sharding, np.asarray(x))


def shard_batch(tree, mesh: Mesh):
    """Place every array leaf with leading-axis data sharding."""
    sh = batch_sharding(mesh)
    return jax.tree.map(lambda x: put_host_batch(x, sh), tree)


def make_placer(sharding=None):
    """Host-array placement closure shared by the prefetch worker and the
    decode path: mesh-aware when a sharding is given, plain device_put
    otherwise."""
    if sharding is None:
        return jax.device_put
    return lambda x: put_host_batch(x, sharding)
