"""Multi-process (multi-host) bootstrap.

The reference has no distributed backend at all (SURVEY.md §2: no
NCCL/MPI/Gloo — single GPU).  Here multi-host runs ride JAX's standard
distributed runtime: ``jax.distributed.initialize`` wires the hosts over
DCN, every process sees the global device set, the mesh spans all chips,
and collectives ride ICI within a slice / DCN across slices.

Usage (same command on every host; TPU pods autodetect everything):

    from cst_captioning_tpu.parallel import distributed
    distributed.ensure_initialized()
    trainer = Trainer(cfg, train_ds, val_ds)   # shards data per process

The data layer composes via ``BatchIterator(shard_id=process_index,
num_shards=process_count)`` — each host assembles only its shard of every
global batch, and ``put_host_batch`` assembles the global array with
``jax.make_array_from_process_local_data`` (parallel/sharding.py).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

log = logging.getLogger("cst_captioning_tpu.parallel")

_INITIALIZED = False


def ensure_initialized(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Idempotent ``jax.distributed.initialize``.

    On TPU pods all three arguments autodetect from the metadata server /
    environment; set them explicitly (or via JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID) for CPU/GPU clusters.  A
    single-process run (no coordinator configured) is a no-op.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    explicit = coordinator_address is not None
    on_tpu_pod = (
        os.environ.get("TPU_WORKER_HOSTNAMES") is not None
        or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS") is not None
    )
    if not explicit and not on_tpu_pod:
        log.debug("single-process run; skipping jax.distributed.initialize")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=(
            num_processes
            if num_processes is not None
            else _env_int("JAX_NUM_PROCESSES")
        ),
        # `or` would drop an explicit process_id=0 (the coordinator rank).
        process_id=(
            process_id if process_id is not None else _env_int("JAX_PROCESS_ID")
        ),
    )
    _INITIALIZED = True
    log.info(
        "jax.distributed initialized: process %d/%d, %d local / %d global "
        "devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v else None


def process_shard() -> tuple:
    """(shard_id, num_shards) for host-sharded data loading."""
    return jax.process_index(), jax.process_count()
