"""Sequence/context parallelism: ring attention over a mesh axis.

The reference has no long-context story (SURVEY.md §5: captions <=30
tokens, <=40 feature frames) — but this framework treats long feature
streams as first-class: hour-long videos at dense frame rates produce
sequences that do not fit one chip's HBM, and attention over them must
shard the SEQUENCE axis, not just the batch.

Two primitives, both exact (not approximations):

* :func:`ring_attention` — blockwise-softmax attention where Q/K/V are
  sharded along the sequence axis; K/V blocks rotate around the ring via
  ``ppermute`` (ICI neighbor exchanges, overlapping compute with
  transfer), with flash-attention-style running (m, l, o) accumulators in
  float32.  This is the standard ring-attention construction
  (arXiv:2310.01889) built on ``shard_map`` + XLA collectives.
* :func:`sharded_context_attention` — the captioner's Bahdanau
  single-query attention with the FRAME axis sharded: each device scores
  its local frames and the global softmax is assembled with one psum of
  (local max, corrected sum, corrected weighted value) — one collective
  per decode step instead of gathering all frames to every device.

Both are tested for exactness against the dense computation on the
8-device CPU mesh (tests/test_ring.py).  ``sharded_context_attention`` is
integrated into the captioner behind ``model.shard_frames``
(models/captioner.py ``_context``), composing with the DP batch axis.

(An all-to-all "Ulysses" variant existed in round 2 but was removed:
every attention in this model family is single-query Bahdanau — there is
no multi-head axis for the all_to_all to re-shard, so no non-test code
could ever call it; VERDICT r2 weak #4.)
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from cst_captioning_tpu.parallel.mesh import shard_map

NEG_INF = -1e30


def _vary(x, axis: str):
    """Mark ``x`` device-varying over ``axis`` on jax versions whose
    shard_map has varying-axis typing (``jax.lax.pcast``); identity on
    older pins where no varying types exist to unify.  Version-compat
    sibling of ``parallel.mesh.shard_map``."""
    pcast = getattr(jax.lax, "pcast", None)
    return x if pcast is None else pcast(x, axis, to="varying")


def _ring_body(q, k0, v0, kmask0, axis: str, scale: float, p: int):
    """shard_map body: local q (B, Sq, H), rotating k/v (B, Sk, H).
    ``p`` is the static ring size (``mesh.shape[axis]`` — passed in
    rather than read via ``jax.lax.axis_size``, which newer jax only)."""
    B, Sq, H = q.shape
    qf = q.astype(jnp.float32) * scale

    # Accumulators marked device-varying over the ring axis so shard_map's
    # varying-axis typing matches across fori_loop iterations (the loop
    # body's outputs are varying; replicated-typed zeros would not unify).
    vary = lambda x: _vary(x, axis)  # noqa: E731
    m0 = vary(jnp.full((B, Sq), NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((B, Sq), jnp.float32))
    o0 = vary(jnp.zeros((B, Sq, v0.shape[-1]), jnp.float32))
    perm = [(i, (i + 1) % p) for i in range(p)]

    def step(i, carry):
        m, l, o, k, v, kmask = carry
        s = jnp.einsum(
            "bqh,bkh->bqk", qf, k.astype(jnp.float32)
        )  # (B, Sq, Sk)
        s = jnp.where(kmask[:, None, :] > 0, s, NEG_INF)
        s_max = jnp.max(s, axis=-1)                       # (B, Sq)
        m_new = jnp.maximum(m, s_max)
        # Renormalize the old accumulators, fold in this block.
        alpha = jnp.exp(m - m_new)
        pexp = jnp.exp(s - m_new[..., None])
        pexp = jnp.where(kmask[:, None, :] > 0, pexp, 0.0)
        l = l * alpha + pexp.sum(-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bqk,bkh->bqh", pexp, v.astype(jnp.float32)
        )
        # Rotate K/V (and their mask) one hop around the ring — except on
        # the final iteration, whose rotated blocks would be discarded.
        def rotate(args):
            k_, v_, km_ = args
            return (
                jax.lax.ppermute(k_, axis, perm),
                jax.lax.ppermute(v_, axis, perm),
                jax.lax.ppermute(km_, axis, perm),
            )

        k, v, kmask = jax.lax.cond(
            i < p - 1, rotate, lambda args: args, (k, v, kmask)
        )
        return m_new, l, o, k, v, kmask

    m, l, o, _, _, _ = jax.lax.fori_loop(
        0, p, step, (m0, l0, o0, k0, v0, kmask0)
    )
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "model",
    kv_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Exact attention with Q/K/V (B, S, H) sharded along S over ``axis``.

    ``kv_mask`` (B, S) marks valid key positions (padding excluded).
    Returns the attention output, sharded like ``q``.  Scale is
    1/sqrt(head_dim).
    """
    if kv_mask is None:
        kv_mask = jnp.ones(k.shape[:2], jnp.float32)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P(None, axis, None)
    mspec = P(None, axis)
    fn = shard_map(
        functools.partial(
            _ring_body, axis=axis, scale=scale, p=mesh.shape[axis]
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec, mspec),
        out_specs=spec,
    )
    return fn(q, k, v, kv_mask)


def _ctx_body(query, vals, proj, mask, att_v, axis: str):
    """shard_map body for single-query Bahdanau attention with the frame
    axis sharded: local scores + one psum of (max, sum, weighted value).

    query (B, A) replicated; vals (B, Fl, E), proj (B, Fl, A), mask
    (B, Fl) local frame shards.
    """
    s = jnp.tanh(proj + query[:, None, :]) @ att_v          # (B, Fl, 1)
    s = s[..., 0].astype(jnp.float32)
    s = jnp.where(mask > 0, s, NEG_INF)
    m_loc = jnp.max(s, axis=-1)                              # (B,)
    # The softmax max-shift cancels in value AND gradient, so stopping
    # gradients through it is exact.  stop_gradient goes INSIDE: pmax has
    # no differentiation rule, and AD only skips it when every operand
    # tangent is already zero (training differentiates this body).
    m = jax.lax.pmax(jax.lax.stop_gradient(m_loc), axis)
    e = jnp.where(mask > 0, jnp.exp(s - m[:, None]), 0.0)
    l = jax.lax.psum(e.sum(-1), axis)                        # (B,)
    ctx = jax.lax.psum(
        jnp.einsum("bf,bfe->be", e, vals.astype(jnp.float32)), axis
    )
    return (ctx / jnp.maximum(l, 1e-30)[:, None]).astype(vals.dtype)


def sharded_context_attention(
    query: jax.Array,
    att_vals: jax.Array,
    att_proj: jax.Array,
    att_mask: jax.Array,
    att_v: jax.Array,
    mesh: Mesh,
    axis: str = "model",
    batch_axis: Optional[str] = None,
) -> jax.Array:
    """Frame-sharded Bahdanau context attention (the captioner's per-step
    fusion, SURVEY.md §2 "Caption model"), exact vs the dense version.

    query (B, A) — projected decoder state (replicated over ``axis``);
    att_vals (B, F, E) / att_proj (B, F, A) / att_mask (B, F) — sharded
    along F over ``axis``;  att_v (A, 1) — the scoring vector.
    ``batch_axis`` additionally shards B (data parallelism composes with
    the frame sharding instead of being gathered away).
    """
    fn = shard_map(
        functools.partial(_ctx_body, axis=axis),
        mesh=mesh,
        in_specs=(
            P(batch_axis, None),
            P(batch_axis, axis, None),
            P(batch_axis, axis, None),
            P(batch_axis, axis),
            P(),
        ),
        out_specs=P(batch_axis, None),
    )
    return fn(query, att_vals, att_proj, att_mask, att_v)
