"""Sequence/context parallelism: ring attention over a mesh axis.

The reference has no long-context story (SURVEY.md §5: captions <=30
tokens, <=40 feature frames) — but this framework treats long feature
streams as first-class: hour-long videos at dense frame rates produce
sequences that do not fit one chip's HBM, and attention over them must
shard the SEQUENCE axis, not just the batch.

Three primitives, all exact (not approximations):

* :func:`ring_attention` — blockwise-softmax attention where Q/K/V are
  sharded along the sequence axis; K/V blocks rotate around the ring via
  ``ppermute`` (ICI neighbor exchanges, overlapping compute with
  transfer), with flash-attention-style running (m, l, o) accumulators in
  float32.  This is the standard ring-attention construction
  (arXiv:2310.01889) built on ``shard_map`` + XLA collectives.
* :func:`ulysses_attention` — the all-to-all sequence-parallel layout
  (arXiv:2309.14509): one all_to_all pair swaps the sequence shard for a
  head shard, each device attends densely over the full sequence for its
  heads.  Complements ring (fewer, bigger collectives vs streaming
  exchanges with O(S/P) memory).
* :func:`sharded_context_attention` — the captioner's Bahdanau
  single-query attention with the FRAME axis sharded: each device scores
  its local frames and the global softmax is assembled with one psum of
  (local max, corrected sum, corrected weighted value) — one collective
  per decode step instead of gathering all frames to every device.

Both are tested for exactness against the dense computation on the
8-device CPU mesh (tests/test_ring.py).  ``sharded_context_attention`` is
integrated into the captioner behind ``model.shard_frames``
(models/captioner.py ``_context``), composing with the DP batch axis.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _ring_body(q, k0, v0, kmask0, axis: str, scale: float):
    """shard_map body: local q (B, Sq, H), rotating k/v (B, Sk, H)."""
    p = jax.lax.axis_size(axis)
    B, Sq, H = q.shape
    qf = q.astype(jnp.float32) * scale

    # Accumulators marked device-varying over the ring axis so shard_map's
    # varying-axis typing matches across fori_loop iterations (the loop
    # body's outputs are varying; replicated-typed zeros would not unify).
    vary = lambda x: jax.lax.pcast(x, axis, to="varying")  # noqa: E731
    m0 = vary(jnp.full((B, Sq), NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((B, Sq), jnp.float32))
    o0 = vary(jnp.zeros((B, Sq, v0.shape[-1]), jnp.float32))
    perm = [(i, (i + 1) % p) for i in range(p)]

    def step(i, carry):
        m, l, o, k, v, kmask = carry
        s = jnp.einsum(
            "bqh,bkh->bqk", qf, k.astype(jnp.float32)
        )  # (B, Sq, Sk)
        s = jnp.where(kmask[:, None, :] > 0, s, NEG_INF)
        s_max = jnp.max(s, axis=-1)                       # (B, Sq)
        m_new = jnp.maximum(m, s_max)
        # Renormalize the old accumulators, fold in this block.
        alpha = jnp.exp(m - m_new)
        pexp = jnp.exp(s - m_new[..., None])
        pexp = jnp.where(kmask[:, None, :] > 0, pexp, 0.0)
        l = l * alpha + pexp.sum(-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bqk,bkh->bqh", pexp, v.astype(jnp.float32)
        )
        # Rotate K/V (and their mask) one hop around the ring — except on
        # the final iteration, whose rotated blocks would be discarded.
        def rotate(args):
            k_, v_, km_ = args
            return (
                jax.lax.ppermute(k_, axis, perm),
                jax.lax.ppermute(v_, axis, perm),
                jax.lax.ppermute(km_, axis, perm),
            )

        k, v, kmask = jax.lax.cond(
            i < p - 1, rotate, lambda args: args, (k, v, kmask)
        )
        return m_new, l, o, k, v, kmask

    m, l, o, _, _, _ = jax.lax.fori_loop(
        0, p, step, (m0, l0, o0, k0, v0, kmask0)
    )
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "model",
    kv_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Exact attention with Q/K/V (B, S, H) sharded along S over ``axis``.

    ``kv_mask`` (B, S) marks valid key positions (padding excluded).
    Returns the attention output, sharded like ``q``.  Scale is
    1/sqrt(head_dim).
    """
    if kv_mask is None:
        kv_mask = jnp.ones(k.shape[:2], jnp.float32)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P(None, axis, None)
    mspec = P(None, axis)
    fn = jax.shard_map(
        functools.partial(_ring_body, axis=axis, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec, mspec),
        out_specs=spec,
    )
    return fn(q, k, v, kv_mask)


def _ulysses_body(q, k, v, kv_mask, axis: str, scale: float):
    """shard_map body: inputs sequence-sharded (B, S/P, H, D); all_to_all
    re-shards heads so each device holds the FULL sequence for H/P heads,
    attends densely, and all_to_alls back.  One collective pair per call
    (vs ring's P-1 neighbor exchanges) — the better layout when S/P chunks
    are small and head count is divisible."""
    # seq-shard -> head-shard: split heads (axis 2), concat sequence (1).
    qh = jax.lax.all_to_all(q, axis, split_axis=2, concat_axis=1, tiled=True)
    kh = jax.lax.all_to_all(k, axis, split_axis=2, concat_axis=1, tiled=True)
    vh = jax.lax.all_to_all(v, axis, split_axis=2, concat_axis=1, tiled=True)
    mask_full = jax.lax.all_gather(kv_mask, axis, axis=1, tiled=True)  # (B, S)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk",
        qh.astype(jnp.float32) * scale,
        kh.astype(jnp.float32),
    )
    s = jnp.where(mask_full[:, None, None, :] > 0, s, NEG_INF)
    a = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", a, vh.astype(jnp.float32))
    out = out.astype(q.dtype)
    # head-shard -> seq-shard: split sequence (1), concat heads (2).
    return jax.lax.all_to_all(
        out, axis, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "model",
    kv_mask: Optional[jax.Array] = None,
    batch_axis: Optional[str] = None,
) -> jax.Array:
    """Exact multi-head attention with Q/K/V (B, S, H, D) sharded along S
    over ``axis`` — the all-to-all ("Ulysses", arXiv:2309.14509) layout.

    Requires S and the head count H both divisible by the axis size.
    ``kv_mask`` (B, S) marks valid key positions.  Complements
    :func:`ring_attention` (same math, different collective pattern):
    ulysses does one all_to_all pair and a fully dense local attention;
    ring streams K/V blocks around the ICI ring with O(S/P) memory.
    """
    ways = mesh.shape[axis]
    B, S, H, D = q.shape
    S_kv = k.shape[1]
    # Cross-length attention (S_q != S_kv) is legal, like ring_attention:
    # both sequence axes ride the all_to_all, so both must divide.
    if S % ways or S_kv % ways or H % ways:
        raise ValueError(
            f"ulysses_attention needs q seq ({S}), kv seq ({S_kv}) and "
            f"heads ({H}) divisible by mesh axis {axis!r} ({ways})"
        )
    if kv_mask is None:
        kv_mask = jnp.ones(k.shape[:2], jnp.float32)
    scale = 1.0 / (D ** 0.5)
    qspec = P(batch_axis, axis, None, None)
    mspec = P(batch_axis, axis)
    fn = jax.shard_map(
        functools.partial(_ulysses_body, axis=axis, scale=scale),
        mesh=mesh,
        in_specs=(qspec, qspec, qspec, mspec),
        out_specs=qspec,
    )
    return fn(q, k, v, kv_mask)


def _ctx_body(query, vals, proj, mask, att_v, axis: str):
    """shard_map body for single-query Bahdanau attention with the frame
    axis sharded: local scores + one psum of (max, sum, weighted value).

    query (B, A) replicated; vals (B, Fl, E), proj (B, Fl, A), mask
    (B, Fl) local frame shards.
    """
    s = jnp.tanh(proj + query[:, None, :]) @ att_v          # (B, Fl, 1)
    s = s[..., 0].astype(jnp.float32)
    s = jnp.where(mask > 0, s, NEG_INF)
    m_loc = jnp.max(s, axis=-1)                              # (B,)
    # The softmax max-shift cancels in value AND gradient, so stopping
    # gradients through it is exact.  stop_gradient goes INSIDE: pmax has
    # no differentiation rule, and AD only skips it when every operand
    # tangent is already zero (training differentiates this body).
    m = jax.lax.pmax(jax.lax.stop_gradient(m_loc), axis)
    e = jnp.where(mask > 0, jnp.exp(s - m[:, None]), 0.0)
    l = jax.lax.psum(e.sum(-1), axis)                        # (B,)
    ctx = jax.lax.psum(
        jnp.einsum("bf,bfe->be", e, vals.astype(jnp.float32)), axis
    )
    return (ctx / jnp.maximum(l, 1e-30)[:, None]).astype(vals.dtype)


def sharded_context_attention(
    query: jax.Array,
    att_vals: jax.Array,
    att_proj: jax.Array,
    att_mask: jax.Array,
    att_v: jax.Array,
    mesh: Mesh,
    axis: str = "model",
    batch_axis: Optional[str] = None,
) -> jax.Array:
    """Frame-sharded Bahdanau context attention (the captioner's per-step
    fusion, SURVEY.md §2 "Caption model"), exact vs the dense version.

    query (B, A) — projected decoder state (replicated over ``axis``);
    att_vals (B, F, E) / att_proj (B, F, A) / att_mask (B, F) — sharded
    along F over ``axis``;  att_v (A, 1) — the scoring vector.
    ``batch_axis`` additionally shards B (data parallelism composes with
    the frame sharding instead of being gathered away).
    """
    fn = jax.shard_map(
        functools.partial(_ctx_body, axis=axis),
        mesh=mesh,
        in_specs=(
            P(batch_axis, None),
            P(batch_axis, axis, None),
            P(batch_axis, axis, None),
            P(batch_axis, axis),
            P(),
        ),
        out_specs=P(batch_axis, None),
    )
    return fn(query, att_vals, att_proj, att_mask, att_v)
