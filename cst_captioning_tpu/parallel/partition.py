"""Regex partition rules -> ``PartitionSpec`` pytrees over params AND
optimizer state (the snippet-[3] ``match_partition_rules`` port).

The rule table below is THE single definition site of how every
parameter family lands on the 2D (data x model) mesh.  Three contracts
keep it honest, machine-checked by the CST-SHD analysis family
(analysis/partitioning.py; catalogue in docs/ANALYSIS.md):

* every known param leaf matches EXACTLY ONE rule — no silent
  replicated fallthrough for a new tensor, no ambiguous double match
  (CST-SHD-001);
* every ``with_sharding_constraint`` site in the package is registered
  in ``analysis/jit_registry.py::SHARDING_CONSTRAINT_REGISTRY`` with a
  prose justification (CST-SHD-002);
* a rule whose regex matches no known leaf is stale (CST-SHD-003).

``KNOWN_PARAM_LEAVES`` is the static mirror of the real param trees —
tests/test_partition.py pins it against actual ``model.init`` trees for
every fusion/category configuration, so the AST-level cross-check can
never drift from the code.

Rules are written as plain literals (regex string, axis-name tuple) so
the jax-free analysis pass can read them straight off the AST.
Specs follow the Mesh-TensorFlow named-axis style: vocab-sized tensors
shard over ``model`` (rows of the embedding, columns of the logit
head), everything else — LSTM kernels, feature projections, the
attention MLP, category embedding — is small and replicated.  Optax
optimizer state needs no second table: Adam's mu/nu mirror the param
tree leaf-for-leaf, so the SAME regexes match their paths, and scalar
leaves (step counters) are never partitioned.
"""

from __future__ import annotations

import re
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (regex over the flattened leaf path, PartitionSpec axes as a literal
# tuple).  First element of each spec tuple maps to dim 0, etc.; an
# empty tuple is full replication.  Exactly-one-match per leaf is the
# CST-SHD-001 contract — regexes are written mutually exclusive on
# purpose (no catch-all).
PARTITION_RULES = (
    (r"word_embed$", ("model", None)),       # (V, E): vocab rows
    (r"logit_w$", (None, "model")),          # (H, V): vocab columns
    (r"logit_b$", ("model",)),               # (V,)
    (r"lstm\d+_[wb]$", ()),                  # recurrence: replicated
    (r"proj_[A-Za-z0-9]+_[wb]$", ()),        # feature projections
    (r"att_(b|v|wf|wh)$", ()),               # Bahdanau attention MLP
    (r"cat_embed$", ()),                     # category embedding
    # int8 weight-only serving (ops/quant.py): each per-channel scale
    # vector shards on the SAME mesh axis as the channel dimension of
    # the weight it dequantizes, so the post-accumulation multiply is
    # shard-aligned — no gather.  (V,)-sized scales follow the vocab
    # axis; per-gate/per-attention-unit scales are small and replicate
    # with their kernels.  The `$`-anchored weight rules above cannot
    # match `*_scale` names, so exactly-one-match (CST-SHD-001) holds.
    (r"word_embed_scale$", ("model",)),      # (V,): rows of word_embed
    (r"logit_w_scale$", ("model",)),         # (V,): columns of logit_w
    (r"lstm\d+_w_scale$", ()),               # (4H,): replicated kernels
    (r"att_w[fh]_scale$", ()),               # (A,): replicated att MLP
    # Speculative-decode draft tree (decoding/speculative.py): a tiny
    # (draft_hidden-sized) LSTM + head, replicated on every shard —
    # its entire job is cheap local proposals; the verify step's vocab
    # GEMM is the sharded one.  The "draft_" prefix keeps these names
    # out of every full-model regex's reach (all are `$`-anchored on
    # suffixes the draft names don't share), preserving CST-SHD-001.
    (r"draft_(embed|cell_[wb]|head_[wb])$", ()),
)

# Canonical param-leaf names across every model configuration
# (meanpool/attention fusion, category on/off, both bundled feature
# modalities, 1-2 LSTM layers).  tests/test_partition.py asserts this
# list covers — and is covered by — real init trees, so CST-SHD's
# static cross-check tracks the code by construction.
KNOWN_PARAM_LEAVES = (
    "word_embed",
    "logit_w",
    "logit_b",
    "lstm0_w",
    "lstm0_b",
    "lstm1_w",
    "lstm1_b",
    "proj_resnet_w",
    "proj_resnet_b",
    "proj_c3d_w",
    "proj_c3d_b",
    "att_b",
    "att_v",
    "att_wf",
    "att_wh",
    "cat_embed",
    # int8w serving scale leaves (weight_quant trees only; see the scale
    # rules above and tests/test_partition.py's weight_quant variant).
    "word_embed_scale",
    "logit_w_scale",
    "lstm0_w_scale",
    "lstm1_w_scale",
    "att_wf_scale",
    "att_wh_scale",
    # Speculative-decode draft tree (decoding/speculative.py::
    # make_draft_params; tests/test_partition.py walks a real draft
    # tree so these can't go stale).
    "draft_embed",
    "draft_cell_w",
    "draft_cell_b",
    "draft_head_w",
    "draft_head_b",
)


def compiled_rules(
    rules: Sequence[Tuple[str, tuple]] = PARTITION_RULES,
):
    """[(compiled regex, PartitionSpec)] from the literal table."""
    return [(re.compile(pat), P(*spec)) for pat, spec in rules]


def path_str(path) -> str:
    """Flattened tree path -> ``a/b/c`` string the rules match against."""
    return "/".join(
        getattr(k, "key", getattr(k, "name", str(k))) for k in path
    )


def _is_scalar(leaf) -> bool:
    shape = getattr(leaf, "shape", None)
    return shape is None or len(shape) == 0 or int(np.prod(shape)) == 1


def spec_for_leaf(name: str, leaf=None, rules=None, strict: bool = True) -> P:
    """Spec for one leaf path.  Scalars are never partitioned.  With
    ``strict`` (the default) a leaf matching zero or more than one rule
    raises — the runtime twin of CST-SHD-001."""
    if leaf is not None and _is_scalar(leaf):
        return P()
    rules = rules if rules is not None else compiled_rules()
    hits = [(pat.pattern, spec) for pat, spec in rules if pat.search(name)]
    if len(hits) == 1:
        return hits[0][1]
    if not strict:
        return hits[0][1] if hits else P()
    if not hits:
        raise ValueError(
            f"no partition rule matches param leaf {name!r} — add a rule "
            "to parallel/partition.py::PARTITION_RULES (and its name to "
            "KNOWN_PARAM_LEAVES)"
        )
    raise ValueError(
        f"param leaf {name!r} matches {len(hits)} partition rules "
        f"({[h[0] for h in hits]}) — rules must partition the leaves "
        "exactly once"
    )


def match_partition_rules(rules, tree, strict: bool = True):
    """Pytree of ``PartitionSpec`` for ``tree`` per ``rules`` — works on
    a param dict, an optax optimizer state, or a whole flax TrainState
    (mu/nu mirror the param tree so the same regexes match; scalar
    leaves map to ``P()``).  ``rules`` may be the literal table or
    pre-compiled pairs."""
    if rules and isinstance(rules[0][0], str):
        rules = compiled_rules(rules)

    def spec(path, leaf):
        return spec_for_leaf(path_str(path), leaf, rules, strict=strict)

    return jax.tree_util.tree_map_with_path(spec, tree)


def _divisible(leaf, spec: P, mesh: Mesh) -> bool:
    shape = getattr(leaf, "shape", ())
    for dim, axis in enumerate(spec):
        if axis is None:
            continue
        if dim >= len(shape) or shape[dim] % mesh.shape[axis] != 0:
            return False
    return True


def tree_shardings(tree, mesh: Mesh, rules=None, strict: bool = True):
    """Pytree of ``NamedSharding`` for ``tree`` on ``mesh``.  A leaf
    whose sharded dim doesn't divide its mesh axis falls back to
    replication (correctness first — pad the vocab to a multiple of the
    model axis to get the sharding benefit)."""
    rules = compiled_rules(rules if rules is not None else PARTITION_RULES)

    def shard(path, leaf):
        spec = spec_for_leaf(path_str(path), leaf, rules, strict=strict)
        if not _divisible(leaf, spec, mesh):
            spec = P()
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(shard, tree)


def state_shardings(state, mesh: Mesh, rules=None):
    """``NamedSharding`` pytree for a whole TrainState: rule-matched
    params AND optimizer moments, replicated scalars/counters — the
    in/out sharding contract of every update-step jit."""
    return tree_shardings(state, mesh, rules=rules)


def shard_tree(tree, mesh: Mesh, rules=None):
    """Commit every leaf of ``tree`` to the mesh per the rules (the
    placement twin of :func:`tree_shardings`)."""
    sh = tree_shardings(tree, mesh, rules=rules)
    return jax.tree.map(jax.device_put, tree, sh)


def replicated(mesh: Optional[Mesh]) -> Optional[NamedSharding]:
    return None if mesh is None else NamedSharding(mesh, P())


def batch_spec(mesh: Mesh) -> P:
    return P("data" if mesh.shape.get("data", 1) > 1 else None)


def logits_spec(mesh: Mesh, ndim: int = 3) -> P:
    """Rows-over-data x vocab-over-model spec for an activation whose
    LAST dim is the vocab: (rows, T, V) training logits or (rows, V)
    decode-step logits.  Axes of size 1 degrade to ``None`` so the spec
    is always valid on the mesh at hand."""
    data = "data" if mesh.shape.get("data", 1) > 1 else None
    model = "model" if mesh.shape.get("model", 1) > 1 else None
    return P(*((data,) + (None,) * (ndim - 2) + (model,)))


def logits_sharding(
    mesh: Optional[Mesh], ndim: int = 3
) -> Optional[NamedSharding]:
    """``NamedSharding`` for :func:`logits_spec`, or None off-mesh."""
    if mesh is None:
        return None
    return NamedSharding(mesh, logits_spec(mesh, ndim))


def rows_sharding(
    mesh: Mesh, shape: Tuple[int, ...], row_axis: int = 0
) -> NamedSharding:
    """Activation sharding for slot/row-major serving state: the
    ``row_axis`` dim shards over ``data`` when the mesh carries
    data > 1 AND the dim divides it; every other case — including the
    whole (data=1, model=N) submesh family — is replication.  THE one
    spec site for slot-state placement (serving/slots.py::
    SlotDecoder._slot_shardings), so the ISSUE-14 activation-sharding
    rule lives beside the param rule table it extends."""
    data = int(mesh.shape.get("data", 1))
    if (
        data > 1
        and len(shape) > row_axis
        and shape[row_axis] % data == 0
    ):
        spec = [None] * len(shape)
        spec[row_axis] = "data"
        return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P())


def constrain(x, sharding: Optional[NamedSharding]):
    """``with_sharding_constraint`` that degrades to identity off-mesh —
    the one helper every activation-boundary pin routes through, so the
    CST-SHD-002 registry has a single raw-constraint site to anchor."""
    if sharding is None:
        return x
    return jax.lax.with_sharding_constraint(x, sharding)


def mesh_shape_str(mesh: Optional[Mesh]) -> str:
    """``"2x4"``-style string (axis order as declared) — the
    ``*_mesh_shape`` bench-record format validate_record enforces."""
    if mesh is None:
        return "1x1"
    return "x".join(str(mesh.shape[a]) for a in mesh.axis_names)
