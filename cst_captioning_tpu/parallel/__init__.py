"""Parallelism over a ``jax.sharding.Mesh``.

The reference's entire device story is ``.cuda()`` + optional single-host
``nn.DataParallel`` (SURVEY.md §2 "Parallelism strategies"); there is no
distributed backend at all.  This package rebuilds that capability the TPU
way and leaves headroom the reference never had:

* ``data`` mesh axis — batch sharding (DP).  Gradients all-reduce over ICI
  via the psum XLA inserts under ``jit`` when inputs are sharded batch-wise
  and params are replicated.
* ``model`` mesh axis — tensor-parallel sharding of the vocab-sized
  parameters (word embedding + logit head), the only tensors in an
  LSTM-512 captioner big enough to shard.  XLA inserts the all-gather /
  reduce-scatter collectives from the sharding annotations.
* Multi-host: each process feeds its own chips (``BatchIterator``'s
  shard_id/num_shards) and ``jax.distributed`` handles DCN bootstrap; the
  mesh spans all devices.

No torch-style replicate/scatter/gather module exists here on purpose:
sharding annotations + the compiler ARE the parallelism implementation
(jax-ml.github.io/scaling-book's recipe: pick a mesh, annotate shardings,
let XLA insert collectives).
"""

from cst_captioning_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    mesh_from_config,
)
from cst_captioning_tpu.parallel.partition import (  # noqa: F401
    KNOWN_PARAM_LEAVES,
    PARTITION_RULES,
    logits_sharding,
    match_partition_rules,
    mesh_shape_str,
    shard_tree,
    state_shardings,
    tree_shardings,
)
from cst_captioning_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    make_placer,
    put_host_batch,
    replicate,
    shard_batch,
    shard_params,
    param_spec,
)
from cst_captioning_tpu.parallel.ring import (  # noqa: F401
    ring_attention,
    sharded_context_attention,
)
from cst_captioning_tpu.parallel import distributed  # noqa: F401
