"""Mesh construction from config.

``TrainConfig.mesh_shape`` is an ordered {axis: size} dict (e.g.
``{"data": -1, "model": 1}``); a single ``-1`` absorbs the remaining
devices, mirroring how the reference's DataParallel absorbed "all visible
GPUs" — except here the axes generalize beyond DP.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

log = logging.getLogger("cst_captioning_tpu.parallel")

# jax moved shard_map from jax.experimental to the top level (and
# renamed its replication check check_rep -> check_vma) across the
# 0.4.x -> 0.5+ series; this container's pinned jax only has the
# experimental home, newer ones only document the top-level one.  One
# compat wrapper here so every call site (ring attention, the sharded
# CST reward callback) works against either — no new dependency, just
# the import/kwarg dance.
try:
    _shard_map_impl = jax.shard_map
except AttributeError:  # pragma: no cover - depends on pinned jax
    from jax.experimental.shard_map import (  # type: ignore
        shard_map as _shard_map_impl,
    )


def shard_map(f, *, mesh, in_specs, out_specs, check_rep=None):
    """Version-portable ``shard_map``.  ``check_rep=False`` disables the
    static replication check under whichever spelling this jax uses
    (``check_rep`` old / ``check_vma`` new) — needed around
    ``io_callback`` bodies, whose outputs the checker cannot prove
    replicated; ``None`` keeps the version's default."""
    kwargs = {}
    if check_rep is not None:
        import inspect

        params = inspect.signature(_shard_map_impl).parameters
        for name in ("check_rep", "check_vma"):
            if name in params:
                kwargs[name] = check_rep
                break
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def make_mesh(
    shape: Dict[str, int], devices: Optional[Sequence] = None
) -> Mesh:
    if not shape:
        raise ValueError("mesh shape is empty — need at least one axis")
    devices = list(devices if devices is not None else jax.devices())
    # Deterministic device order across hosts: jax.devices() is id-sorted
    # on a single process, but an explicit (process_index, id) sort makes
    # the multi-host mesh layout independent of enumeration quirks — the
    # same {axis: size} dict must place the same device at the same mesh
    # coordinate on every host, or collectives deadlock.
    devices.sort(
        key=lambda d: (getattr(d, "process_index", 0), getattr(d, "id", 0))
    )
    n = len(devices)
    sizes = dict(shape)
    bad = {k: v for k, v in sizes.items() if v != -1 and v < 1}
    if bad:
        raise ValueError(
            f"mesh axes must be positive (or -1 to absorb): {bad}"
        )
    wild = [k for k, v in sizes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError(f"at most one -1 axis allowed, got {wild}")
    fixed = int(np.prod([v for v in sizes.values() if v != -1]))
    if wild:
        if n % fixed:
            fixed_axes = {
                k: v for k, v in sizes.items() if v != -1
            }
            raise ValueError(
                f"{n} devices not divisible by fixed axes {fixed_axes} "
                f"(product {fixed}) — axis {wild[0]!r} cannot absorb "
                f"{n}/{fixed} ways; pick sizes whose product divides "
                "the device count"
            )
        sizes[wild[0]] = n // fixed
    total = int(np.prod(list(sizes.values())))
    if total > n:
        raise ValueError(
            f"mesh {sizes} needs {total} devices, have {n} — shrink an "
            "axis or add devices"
        )
    if total < n:
        log.warning(
            "mesh %s uses %d of %d devices — %d chips idle",
            sizes, total, n, n - total,
        )
    dims = [sizes[k] for k in sizes]
    if total == n:
        # ICI-topology-aware assignment: collectives on the trailing
        # (model) axis ride adjacent links.
        try:
            from jax.experimental import mesh_utils

            mesh_devices = mesh_utils.create_device_mesh(
                dims, devices=devices
            )
            return Mesh(mesh_devices, tuple(sizes.keys()))
        except Exception as e:  # virtual/CPU platforms lack topology info
            log.debug("create_device_mesh failed (%s); enumeration order", e)
    mesh_devices = np.asarray(devices[:total]).reshape(dims)
    return Mesh(mesh_devices, tuple(sizes.keys()))


def mesh_from_config(cfg, devices: Optional[Sequence] = None) -> Mesh:
    return make_mesh(dict(cfg.train.mesh_shape), devices)


def submesh_groups(devices: Sequence, group_size: int) -> list:
    """Deterministic per-replica device groups for (R, M) serving
    grids: id-sort (the same (process, id) key :func:`make_mesh`
    uses), then contiguous ``group_size``-device slices — replica i
    always gets devices [i·M, (i+1)·M), so the fleet layout is a pure
    function of config + enumeration, and on real hardware contiguous
    groups ride adjacent ICI links for the cross-shard candidate
    merge (ISSUE 14)."""
    if group_size < 1:
        raise ValueError(f"submesh group size {group_size} < 1")
    devs = sorted(
        devices,
        key=lambda d: (
            getattr(d, "process_index", 0), getattr(d, "id", 0)
        ),
    )
    return [
        devs[i:i + group_size]
        for i in range(0, len(devs) - group_size + 1, group_size)
    ]
