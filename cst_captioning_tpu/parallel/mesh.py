"""Mesh construction from config.

``TrainConfig.mesh_shape`` is an ordered {axis: size} dict (e.g.
``{"data": -1, "model": 1}``); a single ``-1`` absorbs the remaining
devices, mirroring how the reference's DataParallel absorbed "all visible
GPUs" — except here the axes generalize beyond DP.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

log = logging.getLogger("cst_captioning_tpu.parallel")


def make_mesh(
    shape: Dict[str, int], devices: Optional[Sequence] = None
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = dict(shape)
    wild = [k for k, v in sizes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError(f"at most one -1 axis allowed, got {wild}")
    fixed = int(np.prod([v for v in sizes.values() if v != -1]))
    if wild:
        if n % fixed:
            raise ValueError(
                f"{n} devices not divisible by fixed axes product {fixed}"
            )
        sizes[wild[0]] = n // fixed
    total = int(np.prod(list(sizes.values())))
    if total > n:
        raise ValueError(f"mesh {sizes} needs {total} devices, have {n}")
    if total < n:
        log.warning(
            "mesh %s uses %d of %d devices — %d chips idle",
            sizes, total, n, n - total,
        )
    dims = [sizes[k] for k in sizes]
    if total == n:
        # ICI-topology-aware assignment: collectives on the trailing
        # (model) axis ride adjacent links.
        try:
            from jax.experimental import mesh_utils

            mesh_devices = mesh_utils.create_device_mesh(
                dims, devices=devices
            )
            return Mesh(mesh_devices, tuple(sizes.keys()))
        except Exception as e:  # virtual/CPU platforms lack topology info
            log.debug("create_device_mesh failed (%s); enumeration order", e)
    mesh_devices = np.asarray(devices[:total]).reshape(dims)
    return Mesh(mesh_devices, tuple(sizes.keys()))


def mesh_from_config(cfg, devices: Optional[Sequence] = None) -> Mesh:
    return make_mesh(dict(cfg.train.mesh_shape), devices)
