"""Fixed-shape batched beam search.

Reference equivalent: ``sample.py`` / ``sample_beam`` (SURVEY.md §2 "Beam
search", §3.3) — beam≈5 decode keeping per-beam log-probs, end-token
collapse, length handling.

TPU-first design (NOT the reference's per-video Python loop):
* The whole search is one ``lax.scan`` of exactly ``max_len`` steps over a
  static ``(B, K)`` beam grid; every video in the batch is decoded
  simultaneously.
* Finished beams are "frozen": their token distribution collapses to PAD
  at zero cost, so they ride along in the grid and stay comparable — no
  dynamic beam removal (the reference pops finished beams from a list).
* Beam reordering is a gather on the flat ``B*K`` axis of the LSTM state;
  hypothesis tokens are carried in a pre-allocated ``(B, K, L)`` buffer
  updated with ``dynamic_update_index_in_dim`` — all shapes static.
* Length normalization (divide by token count) is applied once at
  finalize, matching the common beam length-penalty choice; toggleable via
  ``length_normalize`` (``EvalConfig.length_normalize``).

Fused fast path: when the model requests ``use_pallas_beam`` and the
shapes pass ``beam_shapes_ok``, the whole recurrence dispatches to the
fused Pallas kernel (``ops/pallas_beam.py``) instead of the per-step
scan — same semantics, same :func:`finalize_beams` epilogue, declared
token-exact at float32 (docs/PARITY.md records the tie-order contract).
The path composes with ``serving.dtype=int8w``: quantized models hand
the kernel int8 code tiles plus per-channel scales and it dequantizes
in-kernel (f32-pinned accumulation, scale after — ``quant_matmul``
semantics), streaming vocab tiles at a quarter of the f32 bytes.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from cst_captioning_tpu.constants import PAD_ID
from cst_captioning_tpu.decoding.core import (
    NEG_INF,
    all_done,
    decode_step,
    init_core,
    register_backend,
)

if TYPE_CHECKING:  # annotation-only: avoids the captioner import cycle
    from cst_captioning_tpu.models.captioner import CaptionModel


class BeamResult(NamedTuple):
    tokens: jax.Array       # (B, L) int32 — best hypothesis per video
    score: jax.Array        # (B,) float32 — its (normalized) log-prob
    all_tokens: jax.Array   # (B, K, L) int32 — full beam, best-first
    all_scores: jax.Array   # (B, K) float32


def finalize_beams(
    seqs: jax.Array,
    scores: jax.Array,
    length_normalize: bool = True,
) -> BeamResult:
    """Shared epilogue of BOTH beam backends: length-normalize (divide
    by token count) and order best-first.  ``seqs`` (B, K, L) int32,
    ``scores`` (B, K) float32 — the raw end-of-scan beam state."""
    if length_normalize:
        lengths = jnp.maximum((seqs != PAD_ID).sum(-1), 1)     # (B, K)
        final = scores / lengths.astype(jnp.float32)
    else:
        final = scores
    order = jnp.argsort(-final, axis=-1)                       # best-first
    batch_ix = jnp.arange(seqs.shape[0])[:, None]
    all_tokens = seqs[batch_ix, order]
    all_scores = final[batch_ix, order]
    return BeamResult(
        tokens=all_tokens[:, 0],
        score=all_scores[:, 0],
        all_tokens=all_tokens,
        all_scores=all_scores,
    )


def fused_beam_engaged(
    model: CaptionModel,
    feats,
    beam_size: int,
) -> Tuple[bool, str]:
    """Whether the fused beam kernel will take this decode — the shape/
    config gate shared by :func:`beam_search` (dispatch), evaluation.py
    (engagement log) and bench.py (the ``beam_fused`` extra).  Returns
    ``(engaged, reason-when-not)``; purely static, safe under trace."""
    if not getattr(model, "use_pallas_beam", False):
        return False, "use_pallas_beam off"
    if model.fusion not in ("attention", "meanpool"):
        return False, f"fusion={model.fusion!r}"
    if model.num_layers != 1 or model.shard_frames:
        return False, (
            f"num_layers={model.num_layers}, "
            f"shard_frames={model.shard_frames} (kernel covers "
            "single-layer unsharded decoders)"
        )
    if getattr(model, "decode_shards", 1) > 1:
        # Tensor-parallel port (ops/shard_decode.py): pure XLA, so the
        # Pallas VMEM/lane-width gate doesn't apply — only the even
        # vocab tiling does.
        from cst_captioning_tpu.ops.shard_decode import shard_decode_ok

        if shard_decode_ok(
            model.vocab_size, model.decode_shards, beam_size
        ):
            return True, ""
        return False, (
            f"vocab {model.vocab_size} does not tile evenly over the "
            f"{model.decode_shards}-way model axis"
        )
    from cst_captioning_tpu.ops.pallas_beam import beam_shapes_ok

    B = feats[model.modalities[0]].shape[0]
    F = sum(feats[m].shape[1] for m in model.modalities)
    ok = beam_shapes_ok(
        B, beam_size, model.vocab_size, model.rnn_size,
        model.att_hidden_size, model.embed_size, F,
        jnp.dtype(model.compute_dtype).itemsize,
        static_ctx=model.fusion != "attention",
    )
    if not ok:
        return False, (
            f"shape gate: B={B}, K={beam_size}, V={model.vocab_size}, "
            f"F={F} fails beam_shapes_ok"
        )
    return True, ""


def beam_search(
    model: "CaptionModel",
    params,
    feats,
    feat_masks,
    *,
    category=None,
    beam_size: int = 5,
    max_len: int = 30,
    length_normalize: bool = True,
    early_exit: bool = True,
) -> BeamResult:
    """Run beam search for a batch of videos.  Pure function of arrays —
    safe to wrap in ``jit`` (see :func:`make_beam_search_fn`)."""
    K = beam_size
    engaged, reason = fused_beam_engaged(model, feats, K)
    if engaged:
        # Whole-recurrence fused kernel (ops/pallas_beam.py): no
        # per-step launches, no (B*K, V) logits materialization.
        seqs, scores = model.apply(
            params, feats, feat_masks, category,
            beam_size=K, max_len=max_len, method="fused_beam",
        )
        return finalize_beams(seqs, scores, length_normalize)
    if getattr(model, "use_pallas_beam", False):
        from cst_captioning_tpu.models.captioner import warn_fused_decline

        warn_fused_decline("use_pallas_beam", reason)
    state, cache = model.apply(
        params, feats, feat_masks, category, method="init_decode"
    )
    return beam_search_from_state(
        model, params, state, cache,
        beam_size=K, max_len=max_len, length_normalize=length_normalize,
        early_exit=early_exit,
    )


def beam_search_from_state(
    model: CaptionModel,
    params,
    state,
    cache,
    *,
    beam_size: int = 5,
    max_len: int = 30,
    length_normalize: bool = True,
    early_exit: bool = True,
) -> BeamResult:
    """Scan-path beam search from a pre-encoded ``(state, cache)`` pair
    (``CaptionModel.init_decode``).  This IS the tail of
    :func:`beam_search` — the serving engine calls it directly so a
    feature-cache hit (serving/cache.py tier 2) skips the encoder
    projections while producing the identical token stream.

    ``early_exit=True`` (default) swaps the fixed ``max_len`` scan for a
    ``lax.while_loop`` that stops as soon as EVERY beam of EVERY row has
    finished — MSR-VTT captions average ~9-12 tokens against a 28-30
    cap, so batch eval typically pays ~max-caption-length steps instead
    of ``max_len``.  Token/score parity with the full scan is exact: a
    step in which all beams are finished only re-ranks equal-score
    PAD-frozen beams (``lax.top_k`` breaks ties by index, preserving the
    relative order of equal-score beams), and :func:`finalize_beams`
    sorts best-first with a stable argsort either way, so skipping those
    steps cannot change any output (pinned by
    tests/test_serving.py::test_beam_early_exit_parity).

    The per-step recurrence itself lives in ``decoding/core.py``
    (:func:`~cst_captioning_tpu.decoding.core.decode_step`) — this
    function owns only the beam expansion, the loop, and the finalize
    epilogue."""
    K = beam_size
    B = state.h.shape[1]

    # Expand every per-video tensor to the flat (B*K) beam axis.
    state = state._replace(
        h=jnp.repeat(state.h, K, axis=1), c=jnp.repeat(state.c, K, axis=1)
    )
    cache = jax.tree.map(lambda x: jnp.repeat(x, K, axis=0), cache)

    def step_logits(st, tokens):
        return model.apply(
            params, st, cache, tokens, method="decode_logits"
        )  # float32 decode-policy logits (B*K, V)

    core0 = init_core(state, B, K, max_len, mode="beam")

    def step(st, _):
        return decode_step(step_logits, st, mode="beam"), None

    if early_exit:
        st = jax.lax.while_loop(
            lambda st: (st.step[0] < max_len) & ~all_done(st),
            lambda st: step(st, None)[0],
            core0,
        )
    else:
        st, _ = jax.lax.scan(step, core0, None, length=max_len)
    return finalize_beams(st.seqs, st.scores, length_normalize)


def _scan_beam_runner(ctx):
    """Registry runner: the reference scan-path beam decode."""
    import numpy as np

    r = beam_search(
        ctx.make_model(), ctx.params, ctx.feats, ctx.masks,
        category=ctx.category, beam_size=ctx.beam_size,
        max_len=ctx.max_len,
    )
    return {
        "tokens": np.asarray(r.all_tokens[:, 0]),
        "scores": np.asarray(r.all_scores[:, 0]),
        "all_tokens": np.asarray(r.all_tokens),
    }


register_backend("scan_beam", _scan_beam_runner, kind="beam")


def make_beam_search_fn(
    model: CaptionModel,
    beam_size: int,
    max_len: int,
    length_normalize: bool = True,
    early_exit: bool = True,
) -> Callable:
    """Jitted ``(params, feats, feat_masks, category) -> BeamResult``."""

    def fn(params, feats, feat_masks, category=None):
        return beam_search(
            model,
            params,
            feats,
            feat_masks,
            category=category,
            beam_size=beam_size,
            max_len=max_len,
            length_normalize=length_normalize,
            early_exit=early_exit,
        )

    return jax.jit(fn)
