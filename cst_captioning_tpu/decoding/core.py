"""The ONE autoregressive decode step — every consumer imports it here.

Before this module the repo carried four copies of the same per-step
decode recurrence: the eval scan beam (``decoding/beam.py``), the fused
Pallas beam (``ops/pallas_beam.py``), the fused sampler
(``ops/pallas_sampler.py``) and the serving slot decoder
(``serving/slots.py``) — exactly the drift hazard the portable-O(1)-
caching line of work (PAPERS.md, arXiv:2603.09555) warns about: a fix
or kernel improvement in one copy silently misses the other three.
This module is the consolidation:

* :class:`DecodeState` — the autoregressive (h, c) carry (moved here
  from ``models/captioner.py``, which re-exports it).
* :class:`CoreState` — the full decode-loop carry shared by every
  XLA-path consumer: LSTM state, hypothesis/token buffers, beam
  scores, finished flags, per-row write positions, optional rng.
* :func:`decode_step` — THE per-step math, in three modes:
  ``beam`` (top-K over score+logp with parent gather and EOS freeze),
  ``greedy`` (argmax) and ``sample`` (temperature-scaled multinomial
  with a pluggable noise source).  ``decoding/beam.py``,
  ``serving/slots.py``, ``CaptionModel._sample_from_cache`` and the
  CST ``SlotRollout`` (``training/cst.py``) all drive their loops
  through this function; a grep-guard test
  (tests/test_decode_core.py) fails the build if a new module
  re-implements the recurrence instead of importing it.
* a **backend registry**: every decode implementation — scan or fused
  Pallas kernel — registers a parity runner here, and ONE shared
  harness (tests/test_decode_core.py) drives all of them through
  identical inputs and pins token/score exactness against their
  declared reference, replacing four bespoke per-backend parity
  copies.

The fused Pallas kernels keep their in-kernel recurrences (a Pallas
body cannot call back into XLA ops) — they participate through the
registry and the shared :func:`finalize` epilogue instead, and the
grep guard allowlists their files explicitly.

Write positions are PER-ROW counters (``CoreState.step``), not the
shared scan index: offline loops advance all rows together (counter ==
scan index, value-identical), while the slot consumers hold rows at
different decode depths in one matrix.  That one generalization is
what lets the same step serve batch-synchronous eval, continuous
serving, and the slot-based CST rollout.

Row-keyed sampling (:func:`row_sample_fn`): the CST slot rollout draws
each row's token from ``fold_in(fold_in(rng, row_id), t)`` — the
row's IDENTITY and its own decode position, never its slot index or
admission order — so which slot a row lands in, and when, cannot
change any sampled token (docs/PARITY.md "slot rollout invariance").
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from cst_captioning_tpu.constants import BOS_ID, EOS_ID, PAD_ID

NEG_INF = -1e30


# ------------------------------------------------- kernel capability table
#
# Which mesh axes each fused decode kernel's fast path survives — THE
# machine-checked source the kernel gates consult (CST-SHD-005 fails the
# analysis pass if a `use_pallas_*` ModelConfig flag has no row here, if
# a row names no declared flag, or if the gate in models/captioner.py
# stops routing through :func:`kernel_supports`).  A literal dict on
# purpose: the jax-free analysis pass reads it straight off the AST.
#
# "model": the kernel (or its shard_map port, ops/shard_decode.py) can
# run with the vocab sharded over the mesh `model` axis — per-shard
# vocab-tile streaming with a cross-shard top-K candidate merge.
# "data": the kernel can run inside a batch-sharded (data > 1) jit —
# none can today (pallas_call has no SPMD partitioning rule and no
# shard_map port exists for the batch axis).
DECODE_KERNEL_CAPS = {
    "use_pallas_lstm": {"model": False, "data": False},
    "use_pallas_attention": {"model": False, "data": False},
    "use_pallas_sampler": {"model": True, "data": False},
    "use_pallas_beam": {"model": True, "data": False},
}


def kernel_supports(flag: str, axis: str) -> bool:
    """True when the fused path behind ``use_pallas_*`` flag ``flag``
    survives sharding over mesh ``axis`` (see DECODE_KERNEL_CAPS)."""
    caps = DECODE_KERNEL_CAPS.get(flag)
    return bool(caps and caps.get(axis, False))


class DecodeState(NamedTuple):
    """Autoregressive decoder carry: per-layer (h, c)."""

    h: jax.Array  # (num_layers, B, H) compute dtype
    c: jax.Array  # (num_layers, B, H) float32


class CoreState(NamedTuple):
    """Carry of the unified decode loop over G row groups of K rows
    each (beam: K = beam width; greedy/sample: K = 1).  The flat row
    axis is ``G*K``.  Optional leaves are ``None`` where a mode does
    not use them (beam: ``lps``/``rng``; row modes: ``scores``)."""

    state: DecodeState            # (layers, G*K, H) LSTM carry
    seqs: jax.Array               # (G, K, L) int32 emitted tokens
    scores: Optional[jax.Array]   # (G, K) f32 cumulative beam log-probs
    lps: Optional[jax.Array]      # (G, K, L) f32 per-token log-probs
    finished: jax.Array           # (G, K) bool
    tokens: jax.Array             # (G*K,) int32 next-step input tokens
    step: jax.Array               # (G,) int32 per-row write position
    rng: Optional[jax.Array]      # PRNG carry (threefry sample stream)


def init_core(
    state: DecodeState,
    G: int,
    K: int,
    L: int,
    *,
    mode: str,
    rng: Optional[jax.Array] = None,
    want_lps: bool = True,
) -> CoreState:
    """Fresh decode-loop carry: BOS inputs, PAD buffers, beam 0 live
    (beam mode), per-row write position 0."""
    if mode == "beam":
        scores = (
            jnp.where(jnp.arange(K)[None, :] == 0, 0.0, NEG_INF)
            * jnp.ones((G, 1))
        ).astype(jnp.float32)
        lps = None
        rng = None
    else:
        scores = None
        lps = jnp.zeros((G, K, L), jnp.float32) if want_lps else None
    return CoreState(
        state=state,
        seqs=jnp.full((G, K, L), PAD_ID, jnp.int32),
        scores=scores,
        lps=lps,
        finished=jnp.zeros((G, K), bool),
        tokens=jnp.full((G * K,), BOS_ID, jnp.int32),
        step=jnp.zeros((G,), jnp.int32),
        rng=rng,
    )


def decode_step(
    step_logits: Callable,
    st: CoreState,
    *,
    mode: str,
    temperature: float = 1.0,
    sample_fn: Optional[Callable] = None,
    topk_fn: Optional[Callable] = None,
    pick_fn: Optional[Callable] = None,
) -> CoreState:
    """One decode step over every row of ``st`` — the single
    definition site of the per-step recurrence.

    ``step_logits(state, tokens) -> (state, logits)`` is the model
    hook: one decoder step returning float32 DECODE-POLICY logits
    (PAD/BOS masked out — ``CaptionModel.mask_decode_logits``).

    Modes:

    * ``"beam"`` — the ``lax.top_k`` beam recurrence over
      ``score + log_softmax(logits)`` with PAD-frozen finished beams,
      parent gather of hypothesis/state, EOS/PAD finish, PAD→EOS feed.
    * ``"greedy"`` — argmax of ``log_softmax(logits)``; finished rows
      emit PAD at zero log-prob.
    * ``"sample"`` — multinomial over ``logits / temperature``.  The
      noise source is pluggable: ``sample_fn(scaled_logits, key, st)
      -> (G,) int32`` (``key`` is the step's split of ``st.rng``, or
      ``None`` when the carry holds no rng — row-keyed callers derive
      their own keys from ``st.step`` and row identity).  ``None``
      uses ``jax.random.categorical`` on ``st.rng`` — the legacy
      threefry batch stream of ``CaptionModel._sample_from_cache``.

    ``topk_fn`` (beam) / ``pick_fn`` (greedy) swap the candidate
    SELECTION for an equivalent implementation — the tensor-parallel
    cross-shard merge (:func:`make_tp_beam_topk` /
    :func:`make_tp_row_pick`) that avoids materializing or gathering
    the full-vocab logits on any one shard.  The recurrence around the
    selection (parent gather, finish update, PAD→EOS feed) stays HERE,
    the single definition site.  ``topk_fn(logits, st) ->
    (top_scores (G, K), top_flat (G, K) flat ``k*V + v`` keys)``;
    ``pick_fn(logits) -> (next_token (G,), its log-prob (G,))``.

    Every op is row-independent, so co-resident rows (and admission
    order, in slot consumers) cannot change any row's numbers — the
    PR-3 parity argument, now made once, here (docs/PARITY.md).
    """
    G, K, L = st.seqs.shape
    write = jnp.arange(L)[None, :] == st.step[:, None]     # (G, L)

    if mode == "beam":
        state, logits = step_logits(st.state, st.tokens)
        V = logits.shape[-1]
        if topk_fn is not None:
            top_scores, top_flat = topk_fn(logits, st)       # (G, K)
        else:
            logp = jax.nn.log_softmax(logits, axis=-1).reshape(G, K, V)
            # Frozen finished beams: only PAD continuation, zero cost.
            pad_only = jnp.full((V,), NEG_INF).at[PAD_ID].set(0.0)
            logp = jnp.where(
                st.finished[..., None], pad_only[None, None, :], logp
            )
            total = st.scores[..., None] + logp             # (G, K, V)
            top_scores, top_flat = jax.lax.top_k(
                total.reshape(G, K * V), K
            )                                                # (G, K)
        parent = top_flat // V                               # (G, K)
        tok = (top_flat % V).astype(jnp.int32)               # (G, K)
        g_ix = jnp.arange(G)[:, None]
        seqs = st.seqs[g_ix, parent]                         # reorder history
        seqs = jnp.where(write[:, None, :], tok[:, :, None], seqs)
        finished = (
            st.finished[g_ix, parent] | (tok == EOS_ID) | (tok == PAD_ID)
        )
        flat_parent = (g_ix * K + parent).reshape(-1)        # (G*K,)
        state = state._replace(
            h=state.h[:, flat_parent], c=state.c[:, flat_parent]
        )
        # Finished beams feed EOS so the next-step embedding is defined.
        next_tok = jnp.where(tok == PAD_ID, EOS_ID, tok).reshape(-1)
        return CoreState(
            state=state, seqs=seqs, scores=top_scores, lps=st.lps,
            finished=finished, tokens=next_tok,
            step=jnp.minimum(st.step + 1, L), rng=st.rng,
        )

    if mode not in ("greedy", "sample"):
        raise ValueError(f"unknown decode mode {mode!r}")
    if K != 1:
        raise ValueError(f"row modes decode K=1 rows per group, got K={K}")
    rng = st.rng
    key = None
    if mode == "sample" and rng is not None:
        rng, key = jax.random.split(rng)
    state, logits = step_logits(st.state, st.tokens)
    if mode == "greedy" and pick_fn is not None:
        nxt, tok_lp = pick_fn(logits)
        nxt = nxt.astype(jnp.int32)
    elif mode == "greedy":
        logp = jax.nn.log_softmax(logits, axis=-1)
        nxt = jnp.argmax(logp, axis=-1).astype(jnp.int32)    # (G,)
        tok_lp = jnp.take_along_axis(logp, nxt[:, None], axis=-1)[:, 0]
    else:
        scaled = logits / jnp.asarray(temperature, jnp.float32)
        logp = jax.nn.log_softmax(scaled, axis=-1)
        if sample_fn is None:
            nxt = jax.random.categorical(key, scaled).astype(jnp.int32)
        else:
            nxt = sample_fn(scaled, key, st).astype(jnp.int32)
        tok_lp = jnp.take_along_axis(logp, nxt[:, None], axis=-1)[:, 0]
    valid = ~st.finished[:, 0]                               # live rows
    out_tok = jnp.where(valid, nxt, PAD_ID)
    out_lp = jnp.where(valid, tok_lp, 0.0)
    finished = st.finished | ((nxt == EOS_ID) | (nxt == PAD_ID))[:, None]
    # Feed EOS (not raw PAD) so the next-step input embedding is defined.
    feed = jnp.where(out_tok == PAD_ID, EOS_ID, out_tok)
    seqs = jnp.where(write[:, None, :], out_tok[:, None, None], st.seqs)
    lps = st.lps
    if lps is not None:
        lps = jnp.where(write[:, None, :], out_lp[:, None, None], lps)
    return CoreState(
        state=state, seqs=seqs, scores=st.scores, lps=lps,
        finished=finished, tokens=feed,
        step=jnp.minimum(st.step + 1, L), rng=rng,
    )


def all_done(st: CoreState) -> jax.Array:
    """Scalar bool: every row of every group has finished."""
    return jnp.all(st.finished)


# ------------------------------------- tensor-parallel candidate merge
#
# The cross-shard top-K that unlocks the fused/TP decode fast path
# (ISSUE 14): with the (rows, V) decode-step logits sharded
# vocab-over-model, the inline `lax.top_k(total.reshape(G, K*V), K)`
# above forces the SPMD partitioner to all-gather the full vocab axis
# onto every shard — O(V) bytes per step on the hottest serving op.
# These factories build drop-in `topk_fn`/`pick_fn` hooks that keep
# every shard on its own vocab tile: per-shard top-K candidates (with
# GLOBAL flat keys), one `jax.lax.all_gather` of the (K, 2)-shaped
# candidate tables — O(shards·K) bytes — and a deterministic
# tie-order-preserving re-top-K of the union.  Selection is exact: any
# global top-K element is necessarily inside its shard's local top-K,
# per-shard `lax.top_k` breaks ties by the lowest local flat index
# (which maps monotonically to the lowest GLOBAL flat key within a
# shard), and the union re-ranks by (value desc, key asc) — precisely
# the inline `lax.top_k` order over the full (G, K*V) array
# (docs/PARITY.md r15).  The residual daylight is the log-softmax
# normalizer: the per-shard partial sums fold through one psum whose
# association differs from the single-pass `jax.nn.log_softmax` sum at
# the last ulp — a per-row constant shift, pinned token-exact in the
# shared harness including exact-tie columns spanning shard boundaries.


def _merge_candidates(values: jax.Array, keys: jax.Array, k: int):
    """Exact top-``k`` of a small candidate union by (value desc, key
    asc) — `jax.lax.top_k`'s tie order over values laid out in
    ascending-key positions.  ``values``/``keys``: (G, W)."""
    order = jnp.lexsort((keys, -values), axis=-1)[:, :k]
    g_ix = jnp.arange(values.shape[0])[:, None]
    return values[g_ix, order], keys[g_ix, order]


def make_tp_beam_topk(mesh, axis: str = "model") -> Callable:
    """Build a beam-mode ``topk_fn`` for :func:`decode_step` that merges
    per-shard top-K candidates over the mesh ``axis`` instead of
    all-gathering the vocab (see the block comment above).  The logits
    handed to it must be the decode-policy (rows, V) float32 logits with
    V divisible by the axis size — callers gate on that."""
    from jax.sharding import PartitionSpec as P

    from cst_captioning_tpu.parallel.mesh import shard_map

    M = mesh.shape[axis]

    def topk(logits: jax.Array, st: CoreState) -> Tuple[jax.Array, jax.Array]:
        G, K = st.finished.shape
        V = logits.shape[-1]

        def body(lg, scores, finished):
            # lg: this shard's (G*K, Vloc) logits tile.
            Vloc = lg.shape[-1]
            shard = jax.lax.axis_index(axis)
            col0 = shard * Vloc
            # Exact global log-softmax stats: the max is order-invariant
            # across shards; the normalizer folds per-shard partial sums
            # through one psum (fixed association, PARITY r15).
            gmax = jax.lax.pmax(
                jnp.max(lg, axis=-1, keepdims=True), axis
            )
            gsum = jax.lax.psum(
                jnp.sum(jnp.exp(lg - gmax), axis=-1, keepdims=True), axis
            )
            logp = ((lg - gmax) - jnp.log(gsum)).reshape(G, K, Vloc)
            # Frozen finished beams: PAD-only continuation at zero cost.
            # The global PAD column lives on exactly one shard; every
            # other shard's tile collapses to NEG_INF.
            gcol = col0 + jax.lax.broadcasted_iota(
                jnp.int32, (G, K, Vloc), 2
            )
            pad_only = jnp.where(gcol == PAD_ID, 0.0, NEG_INF)
            logp = jnp.where(finished[..., None], pad_only, logp)
            total = scores[..., None] + logp                # (G, K, Vloc)
            loc_sc, loc_flat = jax.lax.top_k(
                total.reshape(G, K * Vloc), K
            )
            # Local flat key k*Vloc + v -> GLOBAL flat key k*V + v_glob
            # (monotone within a shard, so local tie order is preserved).
            lk = loc_flat // Vloc
            gkey = lk * V + (col0 + loc_flat - lk * Vloc)
            # The O(shards*K) collective: (M, G, K) candidate tables.
            cand_sc = jnp.moveaxis(
                jax.lax.all_gather(loc_sc, axis), 0, 1
            ).reshape(G, M * K)
            cand_key = jnp.moveaxis(
                jax.lax.all_gather(gkey, axis), 0, 1
            ).reshape(G, M * K)
            return _merge_candidates(cand_sc, cand_key, K)

        return shard_map(
            body, mesh=mesh,
            in_specs=(P(None, axis), P(), P()),
            out_specs=(P(), P()),
            check_rep=False,   # outputs are replicated by construction
        )(logits, st.scores, st.finished)

    return topk


def make_tp_row_pick(mesh, axis: str = "model") -> Callable:
    """Greedy-mode ``pick_fn`` twin of :func:`make_tp_beam_topk`: each
    shard takes the argmax of its local log-softmax tile, and one
    all-gather of the (value, global id) pairs picks the global winner
    by (value desc, id asc) — `jnp.argmax`'s lowest-index tie order."""
    from jax.sharding import PartitionSpec as P

    from cst_captioning_tpu.parallel.mesh import shard_map

    def pick(logits: jax.Array) -> Tuple[jax.Array, jax.Array]:
        def body(lg):
            # lg: (G, Vloc) local tile of the decode-policy logits.
            Vloc = lg.shape[-1]
            shard = jax.lax.axis_index(axis)
            gmax = jax.lax.pmax(
                jnp.max(lg, axis=-1, keepdims=True), axis
            )
            gsum = jax.lax.psum(
                jnp.sum(jnp.exp(lg - gmax), axis=-1, keepdims=True), axis
            )
            logp = (lg - gmax) - jnp.log(gsum)
            loc_arg = jnp.argmax(logp, axis=-1)
            loc_val = jnp.take_along_axis(
                logp, loc_arg[:, None], axis=-1
            )[:, 0]
            gid = shard * Vloc + loc_arg.astype(jnp.int32)
            vals = jnp.moveaxis(jax.lax.all_gather(loc_val, axis), 0, 1)
            ids = jnp.moveaxis(jax.lax.all_gather(gid, axis), 0, 1)
            best_v, best_i = _merge_candidates(vals, ids, 1)
            return best_i[:, 0].astype(jnp.int32), best_v[:, 0]

        return shard_map(
            body, mesh=mesh,
            in_specs=(P(None, axis),),
            out_specs=(P(), P()),
            check_rep=False,
        )(logits)

    return pick


def row_sample_fn(
    base_rng: jax.Array,
    row_id: jax.Array,
    is_sample: Optional[jax.Array] = None,
) -> Callable:
    """Row-keyed multinomial noise for :func:`decode_step` sample mode:
    row ``r`` at its own decode position ``t`` draws from
    ``fold_in(fold_in(base_rng, row_id[r]), t)``.  The key depends on
    the row's IDENTITY and position only — never its slot index,
    admission tick, or which rows share the matrix — so the padded and
    slot rollout layouts produce bit-identical tokens per row
    (docs/PARITY.md "slot rollout invariance").

    ``is_sample`` (optional, (G,) bool): rows marked False take the
    greedy argmax instead — the CST greedy-baseline rows riding in the
    same slot matrix as the multinomial rollout rows."""
    def fn(scaled: jax.Array, key, st: CoreState) -> jax.Array:
        del key  # carries no rng; keys derive from row identity
        keys = jax.vmap(
            lambda r, t: jax.random.fold_in(
                jax.random.fold_in(base_rng, r), t
            )
        )(row_id, st.step)
        drawn = jax.vmap(jax.random.categorical)(keys, scaled)
        if is_sample is None:
            return drawn.astype(jnp.int32)
        greedy = jnp.argmax(scaled, axis=-1)
        return jnp.where(is_sample, drawn, greedy).astype(jnp.int32)

    return fn


# ------------------------------------------------------ backend registry

class ParityCtx(NamedTuple):
    """Everything a registered backend runner needs to decode one fixed
    batch: a model factory (flag overrides pick the backend variant),
    shared params/inputs, and decode knobs.  Built once by the shared
    parity harness (tests/test_decode_core.py)."""

    make_model: Callable          # (**flag overrides) -> CaptionModel
    params: Any
    feats: Any
    masks: Any
    category: Any
    beam_size: int
    max_len: int
    temperature: float
    rng: Any                      # PRNGKey
    video_idx: Any                # (B,) int32 (rollout backends)
    repeat: int                   # rollouts/video (rollout backends)


class Backend(NamedTuple):
    """One registered decode implementation.  ``ref`` names the backend
    whose tokens it must match EXACTLY (None = it IS a reference);
    ``kind`` groups comparable output shapes: "beam" -> best tokens
    (B, L) + scores (B,), "greedy" -> tokens (B, L) + per-token lps,
    "rollout" -> the full (rows, L) CST rollout token matrix."""

    name: str
    kind: str
    ref: Optional[str]
    run: Callable                 # (ParityCtx) -> Dict[str, np.ndarray]


_BACKENDS: Dict[str, Backend] = {}

# Modules that register decode backends at import time; the parity
# harness (and the single-definition-site guard) imports them all.
_BACKEND_MODULES = (
    "cst_captioning_tpu.decoding.beam",
    "cst_captioning_tpu.decoding.speculative",
    "cst_captioning_tpu.models.captioner",
    "cst_captioning_tpu.ops.pallas_beam",
    "cst_captioning_tpu.ops.pallas_sampler",
    "cst_captioning_tpu.ops.shard_decode",
    "cst_captioning_tpu.serving.slots",
    "cst_captioning_tpu.training.cst",
)


def register_backend(
    name: str, run: Callable, *, kind: str, ref: Optional[str] = None
) -> None:
    if kind not in ("beam", "greedy", "rollout"):
        raise ValueError(f"unknown backend kind {kind!r}")
    _BACKENDS[name] = Backend(name=name, kind=kind, ref=ref, run=run)


def get_backend(name: str) -> Backend:
    return _BACKENDS[name]


def load_backends() -> List[str]:
    """Import every consumer module (each registers its backends at
    import bottom) and return the registered names, sorted."""
    import importlib

    for mod in _BACKEND_MODULES:
        importlib.import_module(mod)
    return sorted(_BACKENDS)
