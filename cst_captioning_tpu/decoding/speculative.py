"""Speculative greedy decode: draft-LSTM propose, full-model verify.

Every caption the slot runtime (serving/slots.py) serves pays one full
decode step — one vocab-sized GEMM — per emitted token.  This module
removes that 1:1 coupling for greedy serving: a tiny draft LSTM
(``serving.speculative.draft_hidden`` units, vs the full model's
``rnn_size``) proposes ``draft_k`` greedy tokens from its own cheap
carry, and the full model verifies ALL of them in one round —
``CaptionModel.decode_verify`` chains the k (cheap, hidden-sized)
recurrence steps but batches the k dominant vocab projections into ONE
(k*G, H) @ (H, V) GEMM.  The accepted prefix is the longest run where
the draft's proposals equal the full model's own argmax stream, plus
the model's next token after the first disagreement — the standard
speculative rejection rule, which makes the emitted token sequence
BIT-IDENTICAL to non-speculative greedy decode: every emitted token is
the full model's argmax computed from exactly the decode state the
non-speculative loop would have had (docs/PARITY.md r18; pinned by the
shared harness backends ``greedy_spec_offline`` /
``slot_decoder_greedy_spec`` and the bench's ``spec_token_mismatches``
assert).  The draft can only change HOW MANY rounds a caption takes
(acceptance rate), never which tokens come out.

The draft is deliberately trivial: a single LSTM layer over the word
embedding alone (no attention context, no category — dropping them
costs acceptance rate, not correctness), initialized by TRUNCATING the
full checkpoint (:func:`make_draft_params`): the first ``draft_hidden``
embedding columns, the matching row/column slices of the layer-0 LSTM
gates, the first ``draft_hidden`` rows of the vocab projection.  The
quality path is ``cli/distill_draft.py``, which distills the same
shapes against the full model's greedy stream offline and saves an
``.npz`` the ``serving.speculative.draft_params`` knob points at.

The propose/verify round itself (:func:`spec_round`) is a pure function
over ``decoding/core.py``'s ``CoreState`` plus a (2, G, draft_hidden)
draft carry, so the offline harness backend and the slot runtime share
one definition; the TP cross-shard argmax merge composes through the
same ``pick_fn`` hook ``decode_step`` grew in PR 14 (the verify logits
are flat (k*G, V), exactly the 2-D shape ``make_tp_row_pick`` and the
TP logits sharding constraint already handle).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cst_captioning_tpu.constants import EOS_ID
from cst_captioning_tpu.decoding.core import (
    CoreState,
    DecodeState,
    init_core,
    register_backend,
)
from cst_captioning_tpu.ops import quant
from cst_captioning_tpu.ops.rnn import LSTMWeights, lstm_step

log = logging.getLogger(__name__)

# The draft tree's leaf names.  They must NOT collide (by re.search)
# with any full-model leaf pattern in parallel/partition.py's rule
# table or ops/quant.py's axis rules — "draft_head_w" deliberately
# avoids the "logit_w$" suffix, "draft_cell_w" the "lstm\d+_w$" one.
DRAFT_LEAVES = (
    "draft_embed",
    "draft_cell_w",
    "draft_cell_b",
    "draft_head_w",
    "draft_head_b",
)

DEFAULT_DRAFT_K = 4
DEFAULT_DRAFT_HIDDEN = 128


class SpecConfig(NamedTuple):
    """Parsed/validated ``serving.speculative`` section."""

    draft_k: int                  # proposals verified per round (>= 2)
    draft_hidden: int             # draft LSTM width (< full rnn_size)
    draft_params: str             # optional distilled-.npz path ("")


def spec_config(serving_cfg) -> Optional[SpecConfig]:
    """Parse ``serving.speculative`` (a dict knob like ``chaos`` /
    ``autoscale``: empty = OFF, unknown keys rejected).  Returns None
    when speculation is off."""
    raw = dict(getattr(serving_cfg, "speculative", None) or {})
    if not raw:
        return None
    unknown = set(raw) - {"draft_k", "draft_hidden", "draft_params"}
    if unknown:
        raise ValueError(
            f"unknown serving.speculative key(s) {sorted(unknown)} — "
            "expected draft_k / draft_hidden / draft_params"
        )
    k = int(raw.get("draft_k", DEFAULT_DRAFT_K))
    hidden = int(raw.get("draft_hidden", DEFAULT_DRAFT_HIDDEN))
    path = str(raw.get("draft_params", "") or "")
    if k < 2:
        raise ValueError(
            f"serving.speculative.draft_k = {k} — speculation needs at "
            "least 2 (1 draft proposal + the model's own next token); "
            "use an empty dict to disable"
        )
    if hidden < 1:
        raise ValueError(
            f"serving.speculative.draft_hidden = {hidden} must be >= 1"
        )
    return SpecConfig(draft_k=k, draft_hidden=hidden, draft_params=path)


# ------------------------------------------------------------ draft init

def _host_f32(p: Dict[str, Any], name: str) -> np.ndarray:
    """A full-model leaf as host float32 — dequantized first when the
    tree is the int8w serving tree (draft init must see real weights)."""
    leaf = p[name]
    axis = quant.quant_axis(name)
    if axis is not None and jnp.dtype(
        getattr(leaf, "dtype", np.float32)
    ) == jnp.int8:
        leaf = quant.dequantize(leaf, p[name + quant.SCALE_SUFFIX], axis)
    return np.asarray(jax.device_get(leaf), np.float32)


def make_draft_params(params, draft_hidden: int) -> Dict[str, np.ndarray]:
    """Truncation init of the draft tree from the FULL checkpoint: keep
    the first ``draft_hidden`` units of the embedding, the layer-0 LSTM
    (input-slice + hidden-slice rows; the matching per-gate column
    slices of the fused i|f|g|o kernel, so gate structure — including
    the forget-bias-1.0 slice — survives), and the vocab head.  Cheap
    and training-free; acceptance rate is what distillation
    (cli/distill_draft.py) buys on top."""
    p = params["params"] if "params" in params else params
    we = _host_f32(p, "word_embed")             # (V, E)
    lw = _host_f32(p, "lstm0_w")                # (in_dim + H, 4H)
    lb = _host_f32(p, "lstm0_b")                # (4H,)
    gw = _host_f32(p, "logit_w")                # (H, V)
    gb = _host_f32(p, "logit_b")                # (V,)
    H = lb.shape[0] // 4
    E = we.shape[1]
    in_dim = lw.shape[0] - H
    d = int(draft_hidden)
    if not 1 <= d <= min(E, H):
        raise ValueError(
            f"serving.speculative.draft_hidden = {d} must lie in "
            f"[1, min(embed_size={E}, rnn_size={H})] for truncation "
            "init from the full checkpoint"
        )
    rows = np.concatenate([lw[:d], lw[in_dim : in_dim + d]], axis=0)
    cell_w = np.concatenate(
        [rows[:, g * H : g * H + d] for g in range(4)], axis=1
    )
    cell_b = np.concatenate(
        [lb[g * H : g * H + d] for g in range(4)], axis=0
    )
    return {
        "draft_embed": np.ascontiguousarray(we[:, :d]),
        "draft_cell_w": cell_w,                 # (2d, 4d), gates i|f|g|o
        "draft_cell_b": cell_b,                 # (4d,)
        "draft_head_w": np.ascontiguousarray(gw[:d]),   # (d, V)
        "draft_head_b": gb,                     # (V,)
    }


def save_draft_params(path: str, dp: Dict[str, Any]) -> None:
    """Persist a draft tree (cli/distill_draft.py's output format — the
    file ``serving.speculative.draft_params`` points at)."""
    np.savez(
        path,
        **{k: np.asarray(jax.device_get(dp[k]), np.float32)
           for k in DRAFT_LEAVES},
    )


def load_draft_params(path: str) -> Dict[str, np.ndarray]:
    """Load a distilled draft tree; key set is validated so a stale or
    foreign .npz fails loudly at boot, not as a shape error mid-trace."""
    with np.load(path) as z:
        missing = set(DRAFT_LEAVES) - set(z.files)
        if missing:
            raise ValueError(
                f"draft params {path!r} missing leaves {sorted(missing)}"
            )
        return {k: np.asarray(z[k], np.float32) for k in DRAFT_LEAVES}


# ---------------------------------------------------------- draft step

def draft_logits(
    draft_params, carry, tok, suppress_unk: bool = False
):
    """One draft forward step → ``(carry', masked logits)``.  The
    differentiable core ``draft_step`` argmaxes over and
    ``cli/distill_draft.py`` trains through (same decode-policy mask in
    both places, so the distillation target distribution IS the
    proposal distribution)."""
    from cst_captioning_tpu.models.captioner import CaptionModel

    emb = draft_params["draft_embed"][tok]
    h_new, c_new = lstm_step(
        LSTMWeights(
            draft_params["draft_cell_w"], draft_params["draft_cell_b"]
        ),
        emb,
        carry[0],
        carry[1],
    )
    logits = jnp.matmul(
        h_new, draft_params["draft_head_w"],
        preferred_element_type=jnp.float32,
    ) + draft_params["draft_head_b"]
    logits = CaptionModel.mask_decode_logits(logits, suppress_unk)
    return jnp.stack([h_new, c_new]), logits


def draft_step(
    draft_params, carry, tok, suppress_unk: bool = False
):
    """One greedy draft step.  ``carry``: (2, G, Hd) float32 (h row 0,
    c row 1 — one stacked leaf keeps the slot matrix's draft column a
    single array); ``tok``: (G,) int32.  Returns ``(carry', proposal)``.
    All-float32 compute: the draft's job is agreeing with the full
    model's argmax, so it gets no low-precision fast path; its entire
    cost is already ~(Hd/H)^2 of a full step.  The proposal policy
    masks PAD/BOS (and UNK when the model does) exactly like the full
    decode policy — proposing a token the verifier can never emit would
    only burn acceptance."""
    carry_new, logits = draft_logits(draft_params, carry, tok, suppress_unk)
    prop = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return carry_new, prop


# ------------------------------------------------------------ the round

def spec_round(
    verify_fn: Callable,
    draft_fn: Callable,
    st: CoreState,
    carry,
    k: int,
    *,
    pick_fn: Optional[Callable] = None,
) -> Tuple[CoreState, Any, jax.Array]:
    """One speculative greedy round over ``CoreState`` ``st`` (K == 1
    slot rows, like ``decode_step``'s greedy mode).

    ``draft_fn(carry, tok) -> (carry', proposal)`` is one draft step;
    ``verify_fn(state, tokens_k) -> (h_all, c_all, logits)`` is the
    full model's ``decode_verify`` (plus any sharding constraint), with
    ``tokens_k`` (k, G) and flat ``logits`` (k*G, V); ``pick_fn`` is
    the TP cross-shard row pick (``make_tp_row_pick``) or None for the
    replicated log-softmax argmax — both EXACTLY the decision rule
    ``decode_step`` applies, which is what makes acceptance exact.

    Returns ``(st', carry', stats)`` where ``stats`` is a (2,) float32
    ``[tokens emitted this round, live rows this round]`` — the
    acceptance-rate numerator/denominator the slot decoder accumulates
    without a host sync.

    Exactness argument (docs/PARITY.md r18): row j of the verify batch
    computes the model's argmax after consuming ``[tok0, p_0..p_{j-1}]``.
    Accepting while ``p_j == m_j`` means every consumed proposal WAS the
    model's own argmax, i.e. the non-speculative loop would have fed the
    identical prefix — so each emitted ``m_j`` is the token it would
    have emitted.  The first disagreeing position still emits the
    MODEL's token (never the draft's), EOS truncates the accepted
    prefix exactly where the non-speculative loop would have stopped
    (positions after an accepted EOS stay PAD), and rows that are
    finished or out of length emit nothing.
    """
    G = st.tokens.shape[0]
    L = st.seqs.shape[-1]
    # ---- draft: k proposals, one carry snapshot per consumed input.
    # snaps[j] = carry after consuming [tok0, p_0..p_{j-1}] — the state
    # to resume from when j+1 tokens get accepted.
    props, snaps = [], []
    tok = st.tokens
    c = carry
    for _ in range(k):
        c, tok = draft_fn(c, tok)
        snaps.append(c)
        props.append(tok)
    p = jnp.stack(props, axis=1)                       # (G, k)
    snap = jnp.stack(snaps, axis=0)                    # (k, 2, G, Hd)
    # ---- verify: current token then the first k-1 proposals.
    vin = jnp.concatenate(
        [st.tokens[None, :], p[:, : k - 1].T], axis=0
    )                                                  # (k, G)
    h_all, c_all, logits = verify_fn(st.state, vin)
    if pick_fn is not None:
        nxt, tok_lp = pick_fn(logits)
    else:
        logp = jax.nn.log_softmax(logits, axis=-1)
        nxt = jnp.argmax(logp, axis=-1)
        tok_lp = jnp.take_along_axis(logp, nxt[:, None], axis=-1)[:, 0]
    m = nxt.astype(jnp.int32).reshape(k, G).T          # (G, k)
    lp = tok_lp.reshape(k, G).T                        # (G, k)
    # ---- the rejection rule: longest draft/model agreement + the
    # model's next token, truncated at the model's own EOS and at the
    # row's remaining length.
    match = (p[:, : k - 1] == m[:, : k - 1]).astype(jnp.int32)
    n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)    # [0, k-1]
    pos = jnp.arange(1, k + 1, dtype=jnp.int32)
    eos_pos = jnp.min(
        jnp.where(m == EOS_ID, pos[None, :], k + 1), axis=1
    )                                                  # [1, k+1]
    finished0 = st.finished[:, 0]
    valid = (~finished0) & (st.step < L)
    room = jnp.maximum(L - st.step, 1)
    n_emit = jnp.minimum(jnp.minimum(n_acc + 1, eos_pos), room)
    n_emit = jnp.where(valid, n_emit, 0)               # (G,) in [0, k]
    eos_hit = valid & (eos_pos <= n_emit)
    finished = st.finished | eos_hit[:, None]
    step = jnp.minimum(st.step + n_emit, L)
    idx = jnp.clip(n_emit - 1, 0, k - 1)               # (G,)
    last_tok = jnp.take_along_axis(m, idx[:, None], axis=1)[:, 0]
    tokens = jnp.where(valid, last_tok, st.tokens)
    # ---- scatter the accepted prefix into seqs (and lps) rows.
    write_pos = st.step[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
    emit = (jnp.arange(k)[None, :] < n_emit[:, None]) & (write_pos < L)
    onehot = (
        write_pos[:, :, None]
        == jnp.arange(L, dtype=jnp.int32)[None, None, :]
    ) & emit[:, :, None]                               # (G, k, L)
    written = jnp.any(onehot, axis=1)                  # (G, L)
    upd = jnp.sum(jnp.where(onehot, m[:, :, None], 0), axis=1)
    seqs = jnp.where(written, upd, st.seqs[:, 0, :])[:, None, :]
    lps = st.lps
    if lps is not None:
        lp_upd = jnp.sum(jnp.where(onehot, lp[:, :, None], 0.0), axis=1)
        lps = jnp.where(written, lp_upd, st.lps[:, 0, :])[:, None, :]
    # ---- resume state: the snapshot after the last ACCEPTED input
    # (frozen rows select snapshot 0 — harmless drift: their emissions
    # are suppressed for good and admission resets every leaf).
    sel = idx[None, None, :, None]
    state = DecodeState(
        h=jnp.take_along_axis(h_all, sel, axis=0)[0],
        c=jnp.take_along_axis(c_all, sel, axis=0)[0],
    )
    carry_new = jnp.take_along_axis(snap, sel, axis=0)[0]
    stats = jnp.stack([
        jnp.sum(n_emit.astype(jnp.float32)),
        jnp.sum(valid.astype(jnp.float32)),
    ])
    new_st = st._replace(
        state=state,
        seqs=seqs,
        lps=lps,
        finished=finished,
        tokens=tokens,
        step=step,
    )
    return new_st, carry_new, stats


# --------------------------------------------------- offline backend

def _greedy_spec_runner(ctx) -> Dict[str, np.ndarray]:
    """``greedy_spec_offline``: the speculative round driven to
    completion on the harness's fixed batch — must match
    ``scan_greedy`` token-for-token (and log-prob-for-log-prob)."""
    model = ctx.make_model()
    B = ctx.feats[next(iter(ctx.feats))].shape[0]
    k = 3
    hidden = 8
    dp = make_draft_params(ctx.params, hidden)
    state, cache = model.apply(
        ctx.params, ctx.feats, ctx.masks, ctx.category,
        method="init_decode",
    )
    st = init_core(state, B, 1, ctx.max_len, mode="greedy")
    suppress = bool(model.decode_suppress_unk)

    @jax.jit
    def round_fn(params, dparams, cache, st, carry):
        def verify_fn(state, vin):
            return model.apply(
                params, state, cache, vin, method="decode_verify"
            )

        def draft_fn(c, tok):
            return draft_step(dparams, c, tok, suppress)

        return spec_round(verify_fn, draft_fn, st, carry, k)

    carry = jnp.zeros((2, B, hidden), jnp.float32)
    # Every round advances each live row by >= 1 token, so max_len
    # rounds always drain the batch.
    for _ in range(ctx.max_len):
        st, carry, _ = round_fn(ctx.params, dp, cache, st, carry)
        fin = np.asarray(jax.device_get(st.finished))[:, 0]
        stp = np.asarray(jax.device_get(st.step))
        if bool(np.all(fin | (stp >= ctx.max_len))):
            break
    return {
        "tokens": np.asarray(jax.device_get(st.seqs))[:, 0, :],
        "lps": np.asarray(jax.device_get(st.lps))[:, 0, :],
    }


register_backend(
    "greedy_spec_offline", _greedy_spec_runner, kind="greedy",
    ref="scan_greedy",
)
