"""Decoding layer: fixed-shape beam search (reference ``sample.py``).

Greedy and multinomial sampling live on the model itself
(``CaptionModel.sample``); beam search composes the model's
``init_decode`` / ``decode_one`` hooks into a ``lax.scan`` with a static
beam dimension — no dynamic shapes, runs under ``jit``/``pjit``
(SURVEY.md §7 hard part #2).
"""

# core first: models.captioner imports decoding.core, which runs this
# __init__ — beam (below) must not re-enter a partially-built captioner.
from cst_captioning_tpu.decoding.core import (  # noqa: F401
    CoreState,
    DecodeState,
    decode_step,
    get_backend,
    init_core,
    load_backends,
    register_backend,
    row_sample_fn,
)
from cst_captioning_tpu.decoding.beam import (  # noqa: F401
    BeamResult,
    beam_search,
    finalize_beams,
    fused_beam_engaged,
    make_beam_search_fn,
)
