"""AOT serving artifacts: kill cold start, make replicas disposable.

A replica is born today by warmup-compiling every (slot-bank,
admit-bucket, transition) tick variant — PR 7 made *regrow* free but
left *boot* paying the full trace+compile ladder, which is exactly what
makes elastic fleets unreal: autoscaling only works when adding a
replica is cheap.  This module turns the compiled decode ladder into a
**versioned on-disk deploy unit** (the "Compiler-First State Space
Duality and Portable O(1) Autoregressive Caching" framing in PAPERS.md:
compiled decode state as a portable artifact):

```
<root>/<artifact_version>/          (published atomically: tmp + rename)
  manifest.json     schema version, fingerprint block (params_tag /
                    mesh_shape / preset / package version), jax/jaxlib
                    versions + device kind, per-variant cache keys
                    (sha256 of the lowered HLO), the full Config
  params/           orbax params item (the cli/test.py restore format —
                    an artifact IS a loadable checkpoint)
  vocab.json        the engine's vocabulary
  executables.pkl   {variant key -> serialized compiled executable}
  xla_cache/        the persistent compilation cache populated by the
                    build's `.lower().compile()` calls
                    (jax_compilation_cache_dir)
```

**Build** (:func:`build_artifact`, ``cli/build_artifact.py``): every
variant ``warmup()`` would compile is enumerated by the SAME ladder code
(``SlotDecoder.aot_variant_keys`` / ``aot_lower`` +
``aot_encode_buckets`` for the admission encode), lowered at its exact
runtime shapes, compiled through the persistent compilation cache
pointed INTO the artifact, and serialized
(``jax.experimental.serialize_executable``).  The artifact version is a
content hash over (fingerprint, environment, per-variant HLO keys), so
rebuilding an unchanged engine is a no-op and two hosts building the
same deploy agree on the version string.

**Sharded executables (ISSUE 14).**  A model-sharded engine's tick
variants contain the shard_map candidate-merge collectives and are
compiled against its (1, M) submesh, so the executables only make
sense on the same topology.  The manifest already carries the gate:
``fingerprint.mesh_shape`` ("1x2"-style) participates in the content
hash AND in the field-by-field load validation, so a sharded artifact
refuses to boot a differently-sharded (or unsharded) engine with a
named mismatch instead of deserializing collectives onto the wrong
device set — the same refusal-not-adaptation contract as every other
manifest field (docs/PARITY.md r14).

**Load** (:func:`load_engine`, ``InferenceEngine.from_artifact``): the
manifest is validated FIELD BY FIELD against the live environment —
any mismatch raises :class:`ArtifactMismatchError` naming every
divergent field (a refusal, never a silent retrace) — then params
restore via orbax, the variant key set is re-derived from the live
ladder code and checked against the manifest (drift refusal), and every
executable is deserialized and installed.  The booted engine's slot
decoder has ``compile_count == 0``: zero tick-ladder traces, zero XLA
compiles, second-scale replica birth (the paired ``coldstart_*`` bench
rows measure it).  Loading also garbage-collects artifact versions
beyond ``serving.artifact_keep`` — the active version is never
collected (:func:`prune_artifacts`).

Parity (docs/PARITY.md): an artifact-booted replica cannot change any
token — the installed executables ARE the programs warmup would have
compiled (same lowering, same shapes, same XLA pipeline); only the
compilation moved in time.  Pinned by the ``slot_decoder_beam_aot``
shared-harness backend and the warm-vs-artifact token test in
tests/test_artifact.py.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import pickle
import shutil
import time
from typing import Any, Dict, List, Optional, Tuple

import jax

_log = logging.getLogger("cst_captioning_tpu.serving")

ARTIFACT_SCHEMA_VERSION = 1
MANIFEST_NAME = "manifest.json"
_EXEC_NAME = "executables.pkl"
_CACHE_DIR = "xla_cache"
_VOCAB_NAME = "vocab.json"
_TMP_PREFIX = ".tmp-"

# The manifest fields compared field-by-field against the live
# environment before anything is deserialized.
_ENV_FIELDS = ("jax_version", "jaxlib_version", "platform", "device_kind")


class ArtifactError(ValueError):
    """Malformed or unreadable artifact (missing manifest, bad schema
    payload, truncated executables)."""


class ArtifactMismatchError(ArtifactError):
    """The refusal contract: the manifest does not match the live
    environment/engine.  Carries every divergent field as
    ``(field, artifact_value, live_value)`` — the loader never guesses,
    never retraces, and the error names exactly what moved."""

    def __init__(self, mismatches: List[Tuple[str, Any, Any]]):
        self.mismatches = list(mismatches)
        detail = "; ".join(
            f"{f}: artifact={a!r} live={b!r}"
            for f, a, b in self.mismatches
        )
        super().__init__(
            f"artifact refused — {len(self.mismatches)} manifest field(s) "
            f"mismatch the live environment: {detail}"
        )


def environment_block() -> Dict[str, str]:
    """The live-environment half of the refusal contract."""
    dev = jax.devices()[0]
    return {
        "jax_version": jax.__version__,
        "jaxlib_version": getattr(jax.lib, "__version__", jax.__version__),
        "platform": dev.platform,
        "device_kind": dev.device_kind,
    }


@contextlib.contextmanager
def _compilation_cache(path: str):
    """Point jax's persistent compilation cache at ``path`` for the
    duration (min-compile-time/entry-size floors dropped so every ladder
    variant lands on disk), restoring the previous configuration after —
    builds and loads must not leave a global cache redirect behind."""
    old_dir = jax.config.jax_compilation_cache_dir
    old_min_t = jax.config.jax_persistent_cache_min_compile_time_secs
    old_min_b = jax.config.jax_persistent_cache_min_entry_size_bytes
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", old_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", old_min_t
        )
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", old_min_b
        )


def _hlo_key(lowered) -> str:
    """Per-variant cache key: sha256 of the lowered (pre-optimization)
    HLO text — stable across processes for an unchanged program, so the
    manifest records WHAT each executable computes, not where."""
    return hashlib.sha256(lowered.as_text().encode()).hexdigest()[:16]


def artifact_bytes(path: str) -> int:
    """Total on-disk bytes of one artifact version (the bench
    ``coldstart_artifact_bytes`` row)."""
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            total += os.path.getsize(os.path.join(root, f))
    return total


# ------------------------------------------------------------------ build

def build_artifact(engine, out_root: str) -> Dict[str, Any]:
    """Precompile ``engine``'s entire tick ladder ahead of time and
    publish it as a versioned artifact under ``out_root`` (see module
    doc for the layout).  Atomic: everything is written into a
    ``.tmp-*`` sibling and ``os.replace``d into place, so a crashed
    build leaves no half-artifact a loader could trust.  Rebuilding an
    unchanged engine finds its content-hash version already published
    and returns without writing (``rebuilt: False``)."""
    import orbax.checkpoint as ocp

    from jax.experimental import serialize_executable as se

    t0 = time.perf_counter()
    decoder = engine.slot_decoder()
    lowered = decoder.aot_lower()
    lowered += engine.aot_lower_encode(decoder.aot_encode_buckets())
    variant_keys = {
        k: _hlo_key(low) for k, low in lowered
        if not k.startswith("encode:")
    }
    encode_keys = {
        k: _hlo_key(low) for k, low in lowered if k.startswith("encode:")
    }
    from cst_captioning_tpu.ops import quant

    fp = dict(engine.fingerprint())
    fp.pop("artifact_version", None)  # the artifact NAMES the version
    core = {
        "schema": ARTIFACT_SCHEMA_VERSION,
        "fingerprint": fp,
        "env": environment_block(),
        "variants": variant_keys,
        "encode_variants": encode_keys,
        # Low-precision provenance (ISSUE 16): the engine's serving
        # dtype and — for int8w builds — a content hash per dequant
        # scale vector.  In `core`, so a dtype or scale change names a
        # NEW artifact version, and the loader refuses divergence
        # field-by-field like every other manifest field.  f32/bf16
        # builds carry no scale leaves: scale_hashes is {}.
        "serving_dtype": fp.get("serving_dtype", "f32"),
        "scale_hashes": quant.scale_hashes(engine.params),
    }
    version = "v" + hashlib.sha256(
        json.dumps(core, sort_keys=True).encode()
    ).hexdigest()[:12]
    os.makedirs(out_root, exist_ok=True)
    final = os.path.join(out_root, version)
    if os.path.exists(os.path.join(final, MANIFEST_NAME)):
        _log.info("artifact %s already published — reusing", final)
        return {
            "path": final,
            "artifact_version": version,
            "rebuilt": False,
            "build_s": time.perf_counter() - t0,
            "artifact_bytes": artifact_bytes(final),
            "variants": len(variant_keys),
            "encode_variants": len(encode_keys),
        }
    tmp = os.path.join(out_root, f"{_TMP_PREFIX}{version}-{os.getpid()}")
    try:
        os.makedirs(tmp)
        # Compile every variant THROUGH the persistent cache pointed
        # into the artifact: the cache dir ships with it, so any
        # residual compile at load is a disk hit, not a fresh XLA run.
        with _compilation_cache(os.path.join(tmp, _CACHE_DIR)):
            compiled = {k: low.compile() for k, low in lowered}
        payloads = {k: se.serialize(c) for k, c in compiled.items()}
        with open(os.path.join(tmp, _EXEC_NAME), "wb") as f:
            pickle.dump(payloads, f)
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(
            os.path.abspath(os.path.join(tmp, "params")),
            engine.params,
            force=True,
        )
        ckptr.wait_until_finished()
        engine.vocab.save(os.path.join(tmp, _VOCAB_NAME))
        manifest = dict(
            core,
            artifact_version=version,
            config=engine.cfg.to_dict(),
            built_utc=time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
        )
        with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        os.replace(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    build_s = time.perf_counter() - t0
    _log.info(
        "artifact %s published: %d tick + %d encode variants, %.1fs",
        final, len(variant_keys), len(encode_keys), build_s,
    )
    return {
        "path": final,
        "artifact_version": version,
        "rebuilt": True,
        "build_s": build_s,
        "artifact_bytes": artifact_bytes(final),
        "variants": len(variant_keys),
        "encode_variants": len(encode_keys),
    }


# ------------------------------------------------------------------- load

def _resolve_version_dir(path: str) -> str:
    """``path`` may be a version dir (manifest present) or an artifact
    root — then the NEWEST published version is picked."""
    if os.path.exists(os.path.join(path, MANIFEST_NAME)):
        return path
    if not os.path.isdir(path):
        raise ArtifactError(f"no artifact at {path!r}")
    versions = [
        os.path.join(path, d) for d in os.listdir(path)
        if not d.startswith(_TMP_PREFIX)
        and os.path.exists(os.path.join(path, d, MANIFEST_NAME))
    ]
    if not versions:
        raise ArtifactError(
            f"{path!r} holds no published artifact version (a crashed "
            "build leaves only .tmp-* dirs, which are never loaded)"
        )
    return max(versions, key=os.path.getmtime)


def load_manifest(version_dir: str) -> Dict[str, Any]:
    p = os.path.join(version_dir, MANIFEST_NAME)
    try:
        with open(p) as f:
            man = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ArtifactError(f"unreadable manifest {p!r}: {e}") from e
    for key in (
        "schema", "fingerprint", "env", "variants", "encode_variants",
        "artifact_version", "config",
    ):
        if key not in man:
            raise ArtifactError(f"manifest {p!r} missing key {key!r}")
    return man


def _check_environment(man: Dict[str, Any]) -> None:
    """Pre-deserialization refusal: schema, toolchain, device, package
    version — every divergent field reported at once."""
    from cst_captioning_tpu import __version__

    mm: List[Tuple[str, Any, Any]] = []
    if man["schema"] != ARTIFACT_SCHEMA_VERSION:
        mm.append(("schema", man["schema"], ARTIFACT_SCHEMA_VERSION))
    env = environment_block()
    for f in _ENV_FIELDS:
        if man["env"].get(f) != env[f]:
            mm.append((f"env.{f}", man["env"].get(f), env[f]))
    if man["fingerprint"].get("version") != __version__:
        mm.append((
            "fingerprint.version",
            man["fingerprint"].get("version"),
            __version__,
        ))
    if mm:
        raise ArtifactMismatchError(mm)


def load_engine(path: str, engine_cls=None, replica_id=None):
    """Boot an :class:`InferenceEngine` from an artifact with ZERO fresh
    tick-ladder traces or compiles (see module doc).  The engine's slot
    decoder reports ``compile_count == 0`` after this returns — the
    tier-1 pin that the boot really was ahead-of-time."""
    from jax.experimental import serialize_executable as se

    from cst_captioning_tpu.config import Config
    from cst_captioning_tpu.data.vocab import Vocabulary

    if engine_cls is None:
        from cst_captioning_tpu.serving.engine import InferenceEngine

        engine_cls = InferenceEngine
    vdir = _resolve_version_dir(path)
    man = load_manifest(vdir)
    _check_environment(man)
    cfg = Config.from_dict(man["config"])
    # The ladder is installed, not warmed — ctor warmup would rebuild
    # (and recompile) what the artifact already carries.
    cfg.serving.warmup = False
    vocab = Vocabulary.load(os.path.join(vdir, _VOCAB_NAME))
    fp = man["fingerprint"]
    with _compilation_cache(os.path.join(vdir, _CACHE_DIR)):
        engine = engine_cls(cfg, checkpoint=vdir, vocab=vocab)
        # The artifact serves ONE logical model: replicas booted from it
        # share the build-time params_tag (exactly the clone_for_device
        # contract), so tier-1/2 cache entries hit across provenance.
        engine.params_tag = fp["params_tag"]
        engine.replica_id = replica_id
        mm: List[Tuple[str, Any, Any]] = []
        if engine._mesh_shape_str() != fp.get("mesh_shape"):
            mm.append((
                "fingerprint.mesh_shape",
                fp.get("mesh_shape"),
                engine._mesh_shape_str(),
            ))
        if cfg.name != fp.get("preset"):
            mm.append(("fingerprint.preset", fp.get("preset"), cfg.name))
        # Low-precision refusal (ISSUE 16): a manifest whose recorded
        # serving_dtype diverges from the engine the config builds, or
        # whose dequant scales no longer hash to what was published, is
        # a named mismatch — never a silent parity change.
        built_dtype = man.get("serving_dtype", "f32")
        if engine.serving_dtype != built_dtype:
            mm.append((
                "serving_dtype", built_dtype, engine.serving_dtype,
            ))
        from cst_captioning_tpu.ops import quant

        live_hashes = quant.scale_hashes(engine.params)
        built_hashes = man.get("scale_hashes", {})
        drifted = sorted(
            k for k in set(live_hashes) | set(built_hashes)
            if live_hashes.get(k) != built_hashes.get(k)
        )
        if drifted:
            mm.append((
                "scale_hashes",
                {k: built_hashes.get(k) for k in drifted},
                {k: live_hashes.get(k) for k in drifted},
            ))
        decoder = engine.slot_decoder()
        # Drift refusal: the variant set is RE-DERIVED from the live
        # ladder code and must equal the manifest's — a ladder change
        # since build is a named refusal, never a silent retrace.
        live = set(decoder.aot_variant_keys())
        built = set(man["variants"])
        if live != built:
            mm.append((
                "variants",
                sorted(built - live),
                sorted(live - built),
            ))
        live_enc = {f"encode:B{b}" for b in decoder.aot_encode_buckets()}
        built_enc = set(man["encode_variants"])
        if live_enc != built_enc:
            mm.append((
                "encode_variants",
                sorted(built_enc - live_enc),
                sorted(live_enc - built_enc),
            ))
        if mm:
            raise ArtifactMismatchError(mm)
        try:
            with open(os.path.join(vdir, _EXEC_NAME), "rb") as f:
                payloads = pickle.load(f)
        except (OSError, pickle.UnpicklingError, EOFError) as e:
            raise ArtifactError(
                f"unreadable executables in {vdir!r}: {e}"
            ) from e
        tick_exec: Dict[str, Any] = {}
        enc_exec: Dict[str, Any] = {}
        for key, (payload, in_tree, out_tree) in payloads.items():
            fn = se.deserialize_and_load(payload, in_tree, out_tree)
            (enc_exec if key.startswith("encode:") else tick_exec)[key] = fn
        decoder.aot_install(tick_exec)
        engine.aot_install_encode(enc_exec)
    engine.artifact_version = man["artifact_version"]
    prune_artifacts(
        os.path.dirname(os.path.abspath(vdir)),
        keep=int(getattr(cfg.serving, "artifact_keep", 2)),
        active=vdir,
    )
    _log.info(
        "artifact boot %s: %d tick + %d encode executables installed, "
        "0 fresh compiles",
        man["artifact_version"], len(tick_exec), len(enc_exec),
    )
    return engine


# --------------------------------------------------------------- hygiene

def prune_artifacts(
    root: str, keep: int = 2, active: Optional[str] = None
) -> List[str]:
    """Directory hygiene: drop artifact versions beyond the ``keep``
    newest (by mtime) plus any ``.tmp-*`` leftovers from crashed
    builds.  The ACTIVE version (the one just loaded) is never
    collected, regardless of age or ``keep``.  Returns the removed
    paths."""
    keep = max(1, int(keep))
    if not os.path.isdir(root):
        return []
    active_real = os.path.realpath(active) if active else None
    removed: List[str] = []
    versions: List[str] = []
    for d in sorted(os.listdir(root)):
        p = os.path.join(root, d)
        if not os.path.isdir(p):
            continue
        if d.startswith(_TMP_PREFIX):
            shutil.rmtree(p, ignore_errors=True)
            removed.append(p)
        elif os.path.exists(os.path.join(p, MANIFEST_NAME)):
            versions.append(p)
    versions.sort(key=os.path.getmtime, reverse=True)
    for p in versions[keep:]:
        if active_real is not None and os.path.realpath(p) == active_real:
            continue
        shutil.rmtree(p, ignore_errors=True)
        removed.append(p)
        _log.info("pruned stale artifact version %s", p)
    return removed
