"""Deterministic fault injection + recorded-trace chaos soak for the
serving fleet.

Every failure the fleet tolerates today was injected BY HAND in a test.
This module makes fault injection a first-class, seeded, schedule-driven
subsystem so the bench suite (and tier-1) can rehearse production
failure modes — replica death, tick stalls, admission bursts, cache-miss
storms, deadline-adjacent arrivals — and prove the degradation ladder
(docs/SERVING.md "Failure modes & degradation ladder") holds instead of
hoping it does.

Three parts:

* :data:`FAULT_SITES` — the injection-point catalogue, the
  ``METRIC_FAMILIES`` / ``SPAN_CATALOGUE`` discipline applied to chaos:
  every ``chaos.fire("<site>")`` call anywhere in ``serving/`` must name
  a registered site (CST-RES-001, runtime-checked here too), must be
  guarded so chaos-off costs nothing (CST-RES-002), and must be
  unreachable from jit-traced code (CST-RES-003) — see
  ``analysis/resilience.py`` and docs/ANALYSIS.md.
* :class:`ChaosEngine` — the seeded decision oracle.  Serving code asks
  it at registered sites; it answers from a declarative schedule
  (``serving.chaos`` config).  Same seed + same schedule + same call
  sequence => the identical fault schedule, byte for byte — the
  determinism the soak replay test pins.  **Off by default**: the
  ``serving.chaos`` config dict defaults empty, ``from_config`` returns
  ``None``, and every injection site is behind an ``is not None`` guard,
  so the default serving path is byte-identical to a tree without this
  module (pinned by the no-chaos parity test).
* :func:`run_soak` — the recorded-request replay harness: a virtual-time
  (tick-driven, single-threaded) drive of a REAL :class:`ReplicaSet`'s
  routing/admission/shed/requeue/resolve machinery against a recorded
  arrival trace (:func:`make_diurnal_trace` synthesizes diurnal-burst
  traces), with ChaosEngine faults applied at tick boundaries.  Being
  single-threaded makes every per-request decision (shed, requeue,
  expiry, serving replica) a deterministic function of (trace, seed) —
  so the soak can assert "same seed => identical decision log" exactly,
  which a thread-scheduled run never could.  bench.py replays the same
  scenarios as ``slo_*`` rows and gates regressions (the SLO gate).

Stdlib-only on purpose (like ``serving/metrics.py`` and
``observability/trace.py``): the analysis pass imports the catalogue
without dragging jax in, and the engine itself never touches device
state — chaos is a HOST-side decision layer, which is exactly what
CST-RES-003 enforces.
"""

from __future__ import annotations

import math
import random
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

# --------------------------------------------------------------------------
# The injection-point catalogue.  Every entry names a fault the serving
# stack can absorb, the module(s) that host its ``fire`` call sites, and
# what a truthy decision means there.  CST-RES-001 enforces that (a)
# every ``chaos.fire`` literal matches an entry, (b) every entry has at
# least one live call site, and (c) every entry is documented in
# docs/SERVING.md.
FAULT_SITES: List[Tuple[str, str, str]] = [
    ("replica_kill", "serving/replicas.py",
     "kill this replica at the tick boundary: the worker raises its "
     "death path, the replica drains from routing, queued + in-flight "
     "work requeues onto survivors bounded by original deadlines"),
    ("tick_stall", "serving/batcher.py, serving/replicas.py",
     "stall the scheduler for the returned number of seconds before the "
     "tick dispatch (a slow/hung device step; in the virtual-time soak "
     "the value converts to skipped ticks)"),
    ("queue_burst", "serving/batcher.py, serving/replicas.py",
     "inflate the queue-pressure signal fed to the elastic slot-bank "
     "resize by the returned count (a synthetic admission burst at a "
     "grow boundary)"),
    ("cache_miss", "serving/batcher.py",
     "force this request to miss BOTH cache tiers (tier-1 caption hit "
     "suppressed, tier-2 encoder row dropped) — a cache-hostile key "
     "storm; token-exactness is unaffected, the request just pays the "
     "full decode"),
    ("deadline_skew", "serving/batcher.py",
     "clamp this arriving request's deadline to the returned number of "
     "seconds from now (deadline-adjacent arrivals that expire in the "
     "queue or at admission)"),
]

_SITE_NAMES = {name for name, _, _ in FAULT_SITES}

_TRIGGER_KEYS = ("at", "every", "p")


def _uniform(seed: int, site: str, replica: Any, n: int) -> float:
    """Deterministic uniform [0, 1) for probabilistic schedule entries —
    crc32-keyed so it never depends on ``PYTHONHASHSEED`` or call-order
    across sites."""
    h = zlib.crc32(f"{seed}|{site}|{replica}|{n}".encode())
    return (h & 0xFFFFFFFF) / 4294967296.0


@dataclass(frozen=True)
class _Entry:
    """One validated schedule entry."""

    site: str
    at: Optional[int] = None       # fire when the site counter == at
    every: Optional[int] = None    # fire when counter % every == 0 (>0)
    p: Optional[float] = None      # fire with seeded probability p
    replica: Optional[int] = None  # only at this replica id (None = any)
    value: Any = True              # what fire() returns on a hit


class ChaosEngine:
    """Seeded, schedule-driven fault oracle (see module doc).

    ``fire(site, replica=...)`` advances a per-``(site, replica)``
    counter and answers the first matching schedule entry's value (falsy
    when nothing matches).  Counters index ACTIVE scheduler events —
    tick iterations for tick sites, arriving requests for admission
    sites — so a schedule reads as "kill replica 0 at its 6th tick",
    "stall every 4th tick for 50 ms", "skew the deadline of the 3rd
    arrival".  Every hit is appended to :attr:`log` (the decision record
    the determinism test compares byte-for-byte across replays).

    Thread-safe: live schedulers fire from worker AND submit threads.
    Per-key counter sequences are deterministic whenever each key is
    owned by one thread (replica-keyed sites under the threaded
    schedulers) or everything runs single-threaded (the soak replay —
    where full cross-site determinism is pinned).
    """

    def __init__(self, seed: int = 0, schedule: Sequence[Dict[str, Any]] = ()):
        self.seed = int(seed)
        self._entries: List[_Entry] = []
        for i, raw in enumerate(schedule):
            self._entries.append(self._validate(i, raw))
        self._by_site: Dict[str, List[_Entry]] = {}
        for e in self._entries:
            self._by_site.setdefault(e.site, []).append(e)
        self._counters: Dict[Tuple[str, Any], int] = {}
        self._lock = threading.Lock()
        # The decision record: (site, replica, counter, value) per hit.
        self.log: List[Tuple[str, Any, int, Any]] = []

    @staticmethod
    def _validate(i: int, raw: Any) -> _Entry:
        where = f"serving.chaos.schedule[{i}]"
        if not isinstance(raw, dict):
            raise ValueError(f"{where} must be an object, got {raw!r}")
        site = raw.get("site")
        if site not in _SITE_NAMES:
            raise ValueError(
                f"{where}.site {site!r} is not registered in "
                f"serving/chaos.py::FAULT_SITES (have {sorted(_SITE_NAMES)})"
            )
        triggers = [k for k in _TRIGGER_KEYS if raw.get(k) is not None]
        if len(triggers) != 1:
            raise ValueError(
                f"{where} must set exactly one of {_TRIGGER_KEYS}, "
                f"got {triggers}"
            )
        at = raw.get("at")
        every = raw.get("every")
        p = raw.get("p")
        if at is not None and (isinstance(at, bool) or not isinstance(at, int) or at < 0):
            raise ValueError(f"{where}.at must be a non-negative int")
        if every is not None and (
            isinstance(every, bool) or not isinstance(every, int) or every < 1
        ):
            raise ValueError(f"{where}.every must be a positive int")
        if p is not None and not (
            isinstance(p, (int, float)) and not isinstance(p, bool)
            and 0.0 <= p <= 1.0
        ):
            raise ValueError(f"{where}.p must be a probability in [0, 1]")
        rep = raw.get("replica")
        if rep is not None and (isinstance(rep, bool) or not isinstance(rep, int)):
            raise ValueError(f"{where}.replica must be an int replica id")
        return _Entry(
            site=site, at=at, every=every, p=p, replica=rep,
            value=raw.get("value", True),
        )

    @classmethod
    def from_config(cls, serving_cfg: Any) -> Optional["ChaosEngine"]:
        """Build from ``cfg.serving.chaos`` — ``None`` (chaos fully off,
        zero overhead, byte-identical serving) when the dict is empty or
        absent.  Keys: ``seed`` (int), ``schedule`` (list of entries,
        see :meth:`fire`)."""
        raw = getattr(serving_cfg, "chaos", None)
        if not raw:
            return None
        if not isinstance(raw, dict):
            raise ValueError(
                f"serving.chaos must be a dict, got {type(raw).__name__}"
            )
        unknown = set(raw) - {"seed", "schedule"}
        if unknown:
            raise ValueError(
                f"unknown serving.chaos key(s) {sorted(unknown)}; "
                "have: seed, schedule"
            )
        return cls(
            seed=int(raw.get("seed", 0)),
            schedule=raw.get("schedule", ()),
        )

    # ------------------------------------------------------------- firing
    def fire(self, site: str, replica: Optional[int] = None) -> Any:
        """Ask whether the fault at ``site`` (for ``replica``, when the
        site is replica-scoped) fires at this event.  Returns the
        matching entry's ``value`` (truthy) or ``False``.  Unregistered
        sites raise — the runtime twin of CST-RES-001."""
        if site not in _SITE_NAMES:
            raise ValueError(
                f"chaos site {site!r} is not registered in "
                "serving/chaos.py::FAULT_SITES — register and document "
                "it (docs/SERVING.md) before injecting"
            )
        with self._lock:
            key = (site, replica)
            n = self._counters.get(key, 0)
            self._counters[key] = n + 1
            for e in self._by_site.get(site, ()):
                if e.replica is not None and e.replica != replica:
                    continue
                hit = (
                    (e.at is not None and n == e.at)
                    or (e.every is not None and n > 0 and n % e.every == 0)
                    or (e.p is not None
                        and _uniform(self.seed, site, replica, n) < e.p)
                )
                if hit:
                    self.log.append((site, replica, n, e.value))
                    return e.value
            return False

    @property
    def fired(self) -> int:
        with self._lock:
            return len(self.log)

    def decision_log(self) -> List[Tuple[str, Any, int, Any]]:
        with self._lock:
            return list(self.log)

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "seed": self.seed,
                "entries": len(self._entries),
                "fired": len(self.log),
                "sites": sorted({e.site for e in self._entries}),
            }


# --------------------------------------------------------------------------
# Recorded-request traces.


@dataclass(frozen=True)
class RecordedRequest:
    """One recorded arrival: virtual arrival tick, feature-pool key,
    priority class, and the wall deadline it carried (the PR-10 trace
    fields an operator would capture: arrival time + feature id + beam
    config, with the beam config implied by the serving preset)."""

    rid: int
    t_tick: int
    key: int
    priority: str = "interactive"
    deadline_ms: float = 120_000.0


def make_diurnal_trace(
    seed: int,
    n_requests: int,
    n_keys: int,
    *,
    base_per_tick: float = 0.5,
    burst_factor: float = 4.0,
    period_ticks: int = 64,
    priority_mix: Sequence[Tuple[str, float]] = (
        ("interactive", 0.5), ("batch", 0.25), ("best_effort", 0.25),
    ),
    deadline_ms: float = 120_000.0,
) -> List[RecordedRequest]:
    """Synthesize a deterministic diurnal-burst arrival trace: the
    offered rate swings sinusoidally between ``base_per_tick`` and
    ``base_per_tick * burst_factor`` requests/tick over ``period_ticks``
    — the "millions of users don't arrive Poisson-uniform" shape the
    ROADMAP's rehearsal item names.  Same seed => byte-identical
    trace."""
    rng = random.Random(seed)
    names = [p for p, _ in priority_mix]
    weights = [w for _, w in priority_mix]
    out: List[RecordedRequest] = []
    tick = 0
    acc = 0.0
    while len(out) < n_requests:
        swing = 0.5 * (1.0 + math.sin(2.0 * math.pi * tick / period_ticks))
        rate = base_per_tick * (1.0 + (burst_factor - 1.0) * swing)
        acc += rate
        k = int(acc)
        acc -= k
        for _ in range(k):
            if len(out) >= n_requests:
                break
            out.append(RecordedRequest(
                rid=len(out),
                t_tick=tick,
                key=rng.randrange(n_keys),
                priority=rng.choices(names, weights=weights)[0],
                deadline_ms=deadline_ms,
            ))
        tick += 1
    return out


# --------------------------------------------------------------------------
# The replay/soak harness.


@dataclass
class SoakReport:
    """Outcome of one :func:`run_soak` replay."""

    outcomes: Dict[int, Dict[str, Any]] = field(default_factory=dict)
    decisions: List[Tuple] = field(default_factory=list)
    chaos_log: List[Tuple] = field(default_factory=list)
    ticks: int = 0
    kills: int = 0
    stall_ticks: int = 0
    completed: bool = False

    def count(self, outcome: str) -> int:
        return sum(
            1 for o in self.outcomes.values() if o["outcome"] == outcome
        )

    @property
    def served(self) -> int:
        return self.count("served") + self.count("served_cached")

    @property
    def lost(self) -> int:
        """Requests that never reached a terminal outcome — the
        zero-loss acceptance bar."""
        return sum(
            1 for o in self.outcomes.values() if o["outcome"] == "lost"
        )

    def attainment(self, slo_ticks: int) -> Dict[str, float]:
        """Fraction of requests that completed within ``slo_ticks`` of
        arrival, per priority class plus ``overall``.  Shed / expired /
        failed requests count as missed."""
        tot: Dict[str, int] = {}
        ok: Dict[str, int] = {}
        for o in self.outcomes.values():
            p = o["priority"]
            tot[p] = tot.get(p, 0) + 1
            attained = (
                o["outcome"] in ("served", "served_cached")
                and (o["done_tick"] - o["arrival_tick"]) <= slo_ticks
            )
            ok[p] = ok.get(p, 0) + (1 if attained else 0)
        out = {
            p: (ok.get(p, 0) / tot[p]) if tot[p] else 0.0 for p in tot
        }
        n = sum(tot.values())
        out["overall"] = (sum(ok.values()) / n) if n else 0.0
        return out


def _classify(exc: BaseException) -> str:
    from cst_captioning_tpu.serving.batcher import (
        BackpressureError,
        DeadlineExceededError,
        ShuttingDownError,
    )

    if isinstance(exc, BackpressureError):
        return "shed"
    if isinstance(exc, DeadlineExceededError):
        return "expired"
    if isinstance(exc, ShuttingDownError):
        return "rejected"
    return f"failed:{type(exc).__name__}"


def run_soak(
    rs: Any,
    payloads: Sequence[Dict[str, Any]],
    trace: Sequence[RecordedRequest],
    *,
    chaos: Optional[ChaosEngine] = None,
    autoscaler: Any = None,
    stall_tick_s: float = 0.01,
    max_ticks: int = 20_000,
) -> SoakReport:
    """Replay ``trace`` against an (UNSTARTED) ``ReplicaSet`` in virtual
    time — see the module doc for why single-threaded: it makes every
    shed / requeue / expiry / routing decision a pure function of
    (trace, chaos seed), which is the determinism contract the replay
    test pins.

    Per tick: (0) one autoscaler control-loop step when an
    ``autoscaler`` (serving/autoscaler.py) is passed — scale-ups add
    replicas through the real ``add_replica`` router admission,
    scale-downs run the real kill/drain/requeue path inline (the
    single-threaded twin of the worker's death path), so autoscaling
    decisions are replay-deterministic exactly like the chaos schedule;
    (1) chaos ``replica_kill`` / ``tick_stall`` decisions per
    healthy replica, (2) due arrivals submitted through the real
    admission path (``submit_async`` — priorities, shedding, Retry-After
    and deadline bookkeeping all live), (3) one scheduler iteration per
    healthy un-stalled replica (admission pop with hedge-cancel skip,
    deadline expiry, decoder tick, harvest + resolve through the real
    ``_resolve``).  ``tick_stall`` values (seconds) convert to skipped
    ticks via ``stall_tick_s``.
    """
    report = SoakReport()
    if chaos is not None:
        # One engine for the WHOLE stack: the harness drives the
        # tick-boundary sites itself, while the admission-path sites
        # (cache_miss, deadline_skew) fire inside the batcher's own
        # submit_async — same oracle, one decision log.
        rs.chaos = chaos
    clock = {"t": 0}
    arrivals = sorted(trace, key=lambda r: (r.t_tick, r.rid))
    unresolved: Dict[int, Any] = {}
    stalled = {rep.rid: 0 for rep in rs.replicas}

    def _settle(rid: int, outcome: str, arrival: int, **extra: Any) -> None:
        report.outcomes[rid] = {
            "outcome": outcome,
            "priority": extra.pop("priority"),
            "arrival_tick": arrival,
            "done_tick": clock["t"],
            **extra,
        }
        report.decisions.append(
            (rid, outcome, arrival, clock["t"],
             extra.get("replica"), extra.get("requeues"))
        )
        unresolved.pop(rid, None)

    def _callback(req: RecordedRequest, pending: Any):
        def cb(fut) -> None:
            exc = fut.exception()
            if exc is None:
                res = fut.result()
                _settle(
                    req.rid, "served", req.t_tick,
                    priority=req.priority,
                    replica=res.get("replica"),
                    requeues=pending.requeues,
                )
            else:
                _settle(
                    req.rid, _classify(exc), req.t_tick,
                    priority=req.priority,
                    replica=pending.rid,
                    requeues=pending.requeues,
                )
        return cb

    def _step_replica(rep: Any) -> None:
        decoder = rep.decoder
        admits: List[Any] = []
        with rs._cond:
            burst = 0
            if chaos is not None:
                b = chaos.fire("queue_burst", replica=rep.rid)
                if b:
                    burst = int(b)
                    rs.metrics.chaos_faults.inc()
            decoder.maybe_resize(len(rep.q) + burst)
            cap = min(
                len(decoder.free), min(decoder.admit_cap, decoder.S)
            )
            while rep.q and len(admits) < cap:
                p = rep.q.popleft()
                if p.future.done():
                    rs.metrics.hedge_cancelled.inc()
                    continue
                admits.append(p)
        now = time.monotonic()
        live = []
        for p in admits:
            if now > p.deadline:
                rs._expire(p, now, flight=rep.flight)
            else:
                live.append(p)
        handle = decoder.tick_begin([p.prepared for p in live], live)
        t_admit = time.monotonic()
        for p in live:
            p.t_admit = t_admit
        if handle is None:
            return
        done = decoder.tick_wait(handle)
        if done:
            rs._resolve(
                rep, rs.metrics.replica(rep.rid),
                decoder.harvest_from(handle, done),
            )

    i = 0
    for tick in range(max_ticks):
        clock["t"] = tick
        report.ticks = tick + 1
        # (0) autoscaler control-loop step (scale-downs drain inline —
        # there are no worker threads in virtual time)
        if autoscaler is not None:
            autoscaler.step(rs, drain_inline=True)
        # (1) chaos at the tick boundary (replicas the autoscaler just
        # added get a stall counter on first sight)
        for rep in rs.replicas:
            stalled.setdefault(rep.rid, 0)
            if not rep.healthy:
                continue
            if chaos is not None:
                if chaos.fire("replica_kill", replica=rep.rid):
                    rs.metrics.chaos_faults.inc()
                    rep.flight.event("chaos_fault", site="replica_kill")
                    report.kills += 1
                    rs.kill_replica(rep.rid)
                    rs._drain_replica(rep, "chaos replica_kill")
                    continue
                st = chaos.fire("tick_stall", replica=rep.rid)
                if st:
                    rs.metrics.chaos_faults.inc()
                    rep.flight.event(
                        "chaos_fault", site="tick_stall",
                        stall_s=float(st),
                    )
                    stalled[rep.rid] += max(
                        1, int(round(float(st) / stall_tick_s))
                    )
        # (2) due arrivals through the real admission path
        while i < len(arrivals) and arrivals[i].t_tick <= tick:
            req = arrivals[i]
            i += 1
            try:
                out = rs.submit_async(
                    dict(payloads[req.key]),
                    deadline_ms=req.deadline_ms,
                    priority=req.priority,
                )
            except Exception as e:  # noqa: BLE001 — classified outcome
                _settle(
                    req.rid, _classify(e), req.t_tick,
                    priority=req.priority, replica=None, requeues=0,
                )
                continue
            if isinstance(out, dict):
                _settle(
                    req.rid, "served_cached", req.t_tick,
                    priority=req.priority, replica=None, requeues=0,
                )
                continue
            unresolved[req.rid] = out
            out.future.add_done_callback(_callback(req, out))
        # (3) one scheduler iteration per healthy, un-stalled replica
        for rep in rs.replicas:
            if not rep.healthy:
                continue
            if stalled[rep.rid] > 0:
                stalled[rep.rid] -= 1
                report.stall_ticks += 1
                continue
            _step_replica(rep)
        if i >= len(arrivals) and not unresolved:
            report.completed = True
            break
    # Anything still pending at the tick cap is LOST — the exact failure
    # the zero-loss bar exists to catch.
    for rid, p in list(unresolved.items()):
        report.outcomes[rid] = {
            "outcome": "lost",
            "priority": "unknown",
            "arrival_tick": -1,
            "done_tick": clock["t"],
        }
        report.decisions.append((rid, "lost", -1, clock["t"], None, None))
    if chaos is not None:
        report.chaos_log = chaos.decision_log()
    return report
