"""Continuous in-flight batching: the persistent slot-based decode loop.

The PR-2 engine decodes batch-at-a-time: a coalesced micro-batch runs
``max_decode_len`` scan steps to completion while newly arrived requests
wait for the whole batch to drain.  MSR-VTT captions average ~9-12
tokens against a 28-30 cap, so most of that work is PAD-frozen rows and
most of the wait is head-of-line blocking.  This module holds the
standard production fix (continuous batching at decode-step
granularity):

* a fixed matrix of ``S`` decode slots — greedy: 1 row/slot, beam: K
  contiguous rows/slot — whose per-slot state (``DecodeState`` rows,
  projected ``DecodeCache`` rows, emitted tokens, beam scores, finished
  flags, step counter) lives on device as one pytree of static shapes;
* each scheduler iteration (:meth:`SlotDecoder.tick`) is ONE jitted
  call: admit up to ``admit_cap`` pending requests into free slots via
  ``jax.lax.dynamic_update_slice`` on every leaf of the state pytree,
  then run ``slot_block_steps`` decode steps over all ``S*K`` rows —
  so a new request starts decoding at the next STEP boundary instead
  of the next batch boundary;
* slots whose rows all hit EOS — or the length cap — are harvested
  (host-side, from the tick's own outputs — no extra device call) and
  freed, so a short caption exits in ~its-own-length steps.

Host-overhead discipline: with short captions, admissions and harvests
happen roughly once per caption, so per-request device dispatches would
dominate the step loop.  The loop therefore pays a CONSTANT number of
dispatches per iteration: admission is batched (one padded-bucket
encode, scatter fused into the step call, one compiled variant per
admission-count bucket) and harvest reads the (tiny) token/score
matrices the tick already returned.

Parity argument (the bar: slot-decoded captions are token-exact vs the
offline ``evaluation.py`` path, pinned by tests/test_serving.py):

* The per-step math IS the unified decode core — the very same
  ``decoding/core.py::decode_step`` the offline scan beam
  (``decoding/beam.py``) and ``CaptionModel._sample_from_cache`` drive:
  same PAD-freeze of finished beams, same top-K / argmax selection,
  same parent gather — only the batch axis is the slot axis and the
  sequence-write position is the per-slot step counter instead of the
  shared scan index.  Every op is row-independent, so which OTHER
  requests share the matrix (or arrive later — admission order) cannot
  change any row's numbers.
* A finished slot that keeps riding (until harvest, or the remainder of
  a step block) is frozen exactly like the offline scan's finished
  beams: its only continuation is PAD at zero cost, a no-op on
  (tokens, scores).
* The admission encode is the same jitted ``init_decode`` the offline
  paths run, at a padded shape-ladder bucket (row-independent padding,
  the PR-2 discipline); admission-batch padding rows re-write the last
  real slot's rows — idempotent by construction.
* The host epilogue mirrors :func:`decoding.beam.finalize_beams` in
  numpy with a stable argsort — the same tie behavior as the offline
  jnp epilogue.

Double-buffered dispatch (``serving/replicas.py`` workers): the tick is
split into :meth:`SlotDecoder.tick_begin` (admission scatter + step
block DISPATCHED, no host sync — returns a :class:`TickHandle` holding
the tick's output arrays) and :meth:`SlotDecoder.tick_wait` /
:meth:`SlotDecoder.harvest_from` (sync + extract against a specific
handle).  A worker that dispatches tick *t+1* before waiting on tick
*t* overlaps its host-side harvest/detokenize/admission work with
device compute.  Two guards keep that reordering exact:

* every handle carries the tick's OWN functional outputs (``done`` /
  ``seqs`` / ``scores`` are fresh arrays per jitted call), so
  harvesting tick *t* after tick *t+1* was dispatched reads tick *t*'s
  numbers, not *t+1*'s;
* ``admit_tick`` records the tick at which each slot's occupant was
  admitted, and ``tick_wait(handle)`` only reports slots admitted at or
  before ``handle.seq`` — a slot harvested-then-refilled between
  dispatch and wait can never be harvested from a stale handle.

A finished slot rides frozen for the extra buffered tick (PAD-only
continuation, a no-op on tokens/scores — the same parity argument as
``slot_block_steps`` > 1), so double buffering cannot change any
caption.  The synchronous :meth:`SlotDecoder.tick` is the composition
``tick_begin`` + ``tick_wait`` and keeps the PR-3 behavior exactly.

Threading: a ``SlotDecoder`` is owned by exactly one scheduler thread
(``serving.batcher.ContinuousBatcher`` or one ``ReplicaSet`` worker);
nothing here locks.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cst_captioning_tpu.constants import BOS_ID, PAD_ID
from cst_captioning_tpu.decoding.core import (
    NEG_INF,
    CoreState,
    DecodeState,
    decode_step,
    register_backend,
)
from cst_captioning_tpu.models.captioner import DecodeCache

_log = logging.getLogger("cst_captioning_tpu.serving")


def _buckets(top: int) -> List[int]:
    out, b = [], 1
    while b < top:
        out.append(b)
        b *= 2
    out.append(top)
    return out


class TickHandle(NamedTuple):
    """One dispatched (possibly un-synced) tick: its sequence number and
    its own output arrays.  ``done``/``seqs``/``scores`` are the jitted
    call's functional outputs — later ticks never mutate them."""

    seq: int
    done: Any             # (S,) bool device array
    seqs: Any             # (S, K, L) int32 device array
    scores: Any           # (S, K) float32 device array


class SlotState(NamedTuple):
    """Device-resident state of all S decode slots: the unified decode
    carry (``decoding/core.py::CoreState``, per-slot axes ``(S, K,
    ...)``, flat row axis ``S*K``) plus the projected ``DecodeCache``
    rows the step closes over."""

    core: CoreState       # seqs/scores/finished/tokens/step + (h, c)
    cache: DecodeCache    # leaves lead with S*K


class SlotDecoder:
    """See module doc.  Built by ``InferenceEngine.slot_decoder()``."""

    def __init__(self, engine):
        self.engine = engine
        cfg = engine.cfg
        sv = cfg.serving
        self.greedy = engine.decode_mode == "greedy"
        self.K = 1 if self.greedy else cfg.eval.beam_size
        self.L = cfg.eval.max_decode_len
        self.S = int(sv.num_slots or engine.max_batch)
        if self.S < 1:
            raise ValueError(f"num_slots {self.S} < 1")
        self.block = max(1, int(sv.slot_block_steps))
        self.length_normalize = cfg.eval.length_normalize
        self.model = engine.model
        self.V = self.model.vocab_size
        # Admissions per tick are capped so the padded admission-encode
        # bucket stays within the engine's compiled shape discipline.
        self.admit_cap = min(self.S, engine.max_batch)
        self._admit_buckets = _buckets(self.admit_cap)
        if getattr(self.model, "use_pallas_beam", False):
            # The fused whole-recurrence kernel decodes run-to-completion
            # by construction; the slot loop needs step granularity.
            _log.info(
                "continuous slot loop uses the per-step scan math; the "
                "fused beam kernel (use_pallas_beam) applies to the "
                "ladder/offline paths only"
            )
        # Host-side slot bookkeeping (scheduler thread only).
        self.free: List[int] = list(range(self.S))
        self.occupied: Dict[int, Any] = {}      # slot -> caller's data
        self.admit_tick: Dict[int, int] = {}    # slot -> admission seq
        self._tick_fns: Dict[int, Any] = {}
        self._seq = 0                           # dispatched-tick counter
        # Last dispatched handle (sync-path harvest target) and a host
        # snapshot cache keyed by handle seq (fetched lazily, at most
        # once per handle).
        self._last_handle: Optional[TickHandle] = None
        self._np_seq = -1
        self._seqs_np: Optional[np.ndarray] = None
        self._scores_np: Optional[np.ndarray] = None
        self._build_step()
        self._st = self._init_state()

    # ------------------------------------------------------------- device
    def _init_state(self) -> SlotState:
        model, S, K, L = self.model, self.S, self.K, self.L
        cdt = jnp.dtype(model.compute_dtype)
        n = S * K
        d = self.engine.cfg.data
        # Zero cache rows shaped exactly like one encode output: let
        # eval_shape infer the (S*K, ...) DecodeCache leaf shapes.
        feats = {
            m: jnp.zeros((n, d.max_frames, d.feature_dims[m]))
            for m in d.feature_modalities
        }
        masks = {m: jnp.ones((n, d.max_frames)) for m in feats}
        cat = (
            jnp.zeros((n,), jnp.int32) if model.use_category else None
        )
        cache_shape = jax.eval_shape(
            lambda f, mk, c: model.apply(
                self.engine.params, f, mk, c, method="init_decode"
            )[1],
            feats, masks, cat,
        )
        cache = jax.tree.map(
            lambda sds: jnp.zeros(sds.shape, sds.dtype), cache_shape
        )
        core = CoreState(
            state=DecodeState(
                h=jnp.zeros((model.num_layers, n, model.rnn_size), cdt),
                c=jnp.zeros(
                    (model.num_layers, n, model.rnn_size), jnp.float32
                ),
            ),
            seqs=jnp.full((S, K, L), PAD_ID, jnp.int32),
            scores=None if self.greedy else jnp.zeros((S, K), jnp.float32),
            lps=None,
            # Empty slots ride as finished/step=L: done, frozen, harmless.
            finished=jnp.ones((S, K), bool),
            tokens=jnp.full((n,), BOS_ID, jnp.int32),
            step=jnp.full((S,), L, jnp.int32),
            rng=None,
        )
        st = SlotState(core=core, cache=cache)
        # Replica engines pin their slot matrix to their device so the
        # first tick doesn't silently run on the default device.
        dev = getattr(self.engine, "device", None)
        return st if dev is None else jax.device_put(st, dev)

    def _build_step(self) -> None:
        model, K = self.model, self.K
        mode = "greedy" if self.greedy else "beam"

        def step_once(params, st: SlotState) -> SlotState:
            # The per-step recurrence is the unified decode core
            # (decoding/core.py::decode_step) — identical math to the
            # offline scan paths, only the batch axis is the slot axis
            # and write positions are the per-slot step counters.
            def step_logits(state, tokens):
                return model.apply(
                    params, state, st.cache, tokens,
                    method="decode_logits",
                )

            core = decode_step(step_logits, st.core, mode=mode)
            return SlotState(core=core, cache=st.cache)

        self._step_once = step_once
        self._scores0 = jnp.where(
            jnp.arange(K) == 0, 0.0, NEG_INF
        ).astype(jnp.float32)[None, :]                          # (1, K)

    def _tick_fn(self, A: int):
        """One compiled scheduler iteration: scatter A admissions into
        their slots (A static per variant, 0 = pure step), then run the
        step block.  Returns the new state plus everything the host
        needs — done flags and the token/score matrices — so harvests
        cost no extra device call."""
        if A in self._tick_fns:
            return self._tick_fns[A]
        model, K, L = self.model, self.K, self.L
        cdt = jnp.dtype(model.compute_dtype)
        scores0 = self._scores0
        step_once, block = self._step_once, self.block

        def admit_one(st: SlotState, slot, rows_k: DecodeCache):
            """Scatter one request's K beam rows into ``slot``."""
            row0 = slot * K
            cache = jax.tree.map(
                lambda leaf, r: jax.lax.dynamic_update_slice(
                    leaf, r.astype(leaf.dtype),
                    (row0,) + (jnp.int32(0),) * (leaf.ndim - 1),
                ),
                st.cache, rows_k,
            )
            co = st.core
            core = co._replace(
                state=DecodeState(
                    h=jax.lax.dynamic_update_slice(
                        co.state.h,
                        jnp.zeros(
                            (model.num_layers, K, model.rnn_size), cdt
                        ),
                        (jnp.int32(0), row0, jnp.int32(0)),
                    ),
                    c=jax.lax.dynamic_update_slice(
                        co.state.c,
                        jnp.zeros(
                            (model.num_layers, K, model.rnn_size),
                            jnp.float32,
                        ),
                        (jnp.int32(0), row0, jnp.int32(0)),
                    ),
                ),
                seqs=jax.lax.dynamic_update_slice(
                    co.seqs,
                    jnp.full((1, K, L), PAD_ID, jnp.int32),
                    (slot, jnp.int32(0), jnp.int32(0)),
                ),
                scores=(
                    None if co.scores is None
                    else jax.lax.dynamic_update_slice(
                        co.scores, scores0, (slot, jnp.int32(0))
                    )
                ),
                finished=jax.lax.dynamic_update_slice(
                    co.finished,
                    jnp.zeros((1, K), bool),
                    (slot, jnp.int32(0)),
                ),
                tokens=jax.lax.dynamic_update_slice(
                    co.tokens,
                    jnp.full((K,), BOS_ID, jnp.int32),
                    (row0,),
                ),
                step=jax.lax.dynamic_update_slice(
                    co.step, jnp.zeros((1,), jnp.int32), (slot,)
                ),
            )
            return SlotState(core=core, cache=cache)

        @jax.jit
        def tick(params, st: SlotState, slots, rows: DecodeCache):
            if A:
                # (A, ...) request rows -> (A*K, ...) beam rows, once.
                rows = jax.tree.map(
                    lambda x: jnp.repeat(x, K, axis=0), rows
                )
                for i in range(A):
                    rows_k = jax.tree.map(
                        lambda r: jax.lax.dynamic_slice(
                            r,
                            (i * K,) + (0,) * (r.ndim - 1),
                            (K,) + r.shape[1:],
                        ),
                        rows,
                    )
                    st = admit_one(
                        st, slots[i].astype(jnp.int32), rows_k
                    )
            for _ in range(block):
                st = step_once(params, st)
            done = jnp.all(st.core.finished, axis=-1) | (
                st.core.step >= L
            )
            return st, done, st.core.seqs, st.core.scores

        self._tick_fns[A] = tick
        return tick

    def _pad_bucket(self, n: int) -> int:
        for b in self._admit_buckets:
            if b >= n:
                return b
        return self._admit_buckets[-1]

    # --------------------------------------------------------------- host
    @property
    def n_occupied(self) -> int:
        return len(self.occupied)

    def tick_begin(
        self,
        prepared: Sequence[Any] = (),
        datas: Sequence[Any] = (),
    ) -> Optional[TickHandle]:
        """Dispatch one scheduler iteration WITHOUT a host sync: admit
        ``prepared`` (up to ``admit_cap``; caller gates on ``free``) and
        launch one step block over all slots.  Returns a
        :class:`TickHandle` to pass to :meth:`tick_wait` /
        :meth:`harvest_from`, or ``None`` when there is nothing to do
        (no admissions, no occupied slots — no device work launched)."""
        n = len(prepared)
        if n == 0 and not self.occupied:
            return None
        if n > len(self.free) or n > self.admit_cap:
            raise RuntimeError(
                f"tick admitting {n} exceeds free={len(self.free)} "
                f"cap={self.admit_cap}"
            )
        if n:
            A = self._pad_bucket(n)
            # Pad the admission batch by replicating the LAST request:
            # padding rows re-scatter into the same slot (idempotent).
            # Encode BEFORE claiming slots so a failed encode (bad row,
            # OOM) leaks nothing.
            reqs = list(prepared) + [prepared[-1]] * (A - n)
            rows = self.engine.encode_prepared_rows(reqs)
            slots = [self.free.pop() for _ in range(n)]
            for s in slots:
                if s in self.occupied:  # pragma: no cover — invariant
                    raise RuntimeError(f"slot {s} double-assigned")
            slot_arr = jnp.asarray(
                np.asarray(slots + [slots[-1]] * (A - n), np.int32)
            )
        else:
            A = 0
            slots = []
            slot_arr = rows = None
        self._seq += 1
        for s, d in zip(slots, datas):
            self.occupied[s] = d
            self.admit_tick[s] = self._seq
        self._st, done, seqs_d, scores_d = self._tick_fn(A)(
            self.engine.params, self._st, slot_arr, rows
        )
        handle = TickHandle(self._seq, done, seqs_d, scores_d)
        self._last_handle = handle
        return handle

    def tick_wait(self, handle: TickHandle) -> List[int]:
        """Sync on ``handle``'s tick and return the occupied slots that
        finished by it (all beams EOS, or length cap).  Slots whose
        occupant was admitted AFTER the handle's tick are excluded —
        their done flags in this handle describe the PREVIOUS occupant
        (double-buffered dispatch admits into freed slots before the
        older tick is waited on)."""
        done_np = np.asarray(jax.device_get(handle.done))
        return [
            s for s in self.occupied
            if bool(done_np[s]) and self.admit_tick[s] <= handle.seq
        ]

    def tick(
        self,
        prepared: Sequence[Any] = (),
        datas: Sequence[Any] = (),
    ) -> List[int]:
        """One synchronous scheduler iteration (dispatch + sync):
        ``tick_begin`` composed with ``tick_wait``.  Returns the
        occupied slots that are now done."""
        handle = self.tick_begin(prepared, datas)
        if handle is None:
            return []
        return self.tick_wait(handle)

    def harvest_many(
        self, slots: Sequence[int]
    ) -> List[Tuple[Any, np.ndarray, float, int]]:
        """Extract done slots from the LAST dispatched tick's outputs
        (the synchronous-loop path)."""
        if not slots:
            return []
        if self._last_handle is None:
            raise RuntimeError("harvest before any tick")
        return self.harvest_from(self._last_handle, slots)

    def harvest_from(
        self, handle: TickHandle, slots: Sequence[int]
    ) -> List[Tuple[Any, np.ndarray, float, int]]:
        """Extract done slots' best hypotheses from ``handle``'s tick
        outputs (no device call beyond fetching them once per handle)
        and free the slots.  Returns ``[(data, tokens (L,) int32,
        score, steps), ...]`` in ``slots`` order."""
        if not slots:
            return []
        for s in slots:
            if s not in self.occupied:
                raise RuntimeError(f"harvest of unoccupied slot {s}")
            if self.admit_tick[s] > handle.seq:  # pragma: no cover
                raise RuntimeError(
                    f"slot {s} admitted at tick {self.admit_tick[s]} > "
                    f"harvest handle tick {handle.seq}"
                )
        if self._np_seq != handle.seq:
            self._seqs_np = np.asarray(jax.device_get(handle.seqs))
            # Greedy slots carry no beam scores (CoreState.scores=None).
            self._scores_np = (
                None if handle.scores is None
                else np.asarray(jax.device_get(handle.scores))
            )
            self._np_seq = handle.seq
        seqs = self._seqs_np[list(slots)]                 # (n, K, L)
        if self.greedy:
            best = np.zeros((len(slots),), int)
            final = np.zeros((len(slots), 1), np.float32)
        else:
            scores = self._scores_np[list(slots)]         # (n, K)
            if self.length_normalize:
                lengths = np.maximum((seqs != PAD_ID).sum(-1), 1)
                final = scores / lengths.astype(np.float32)
            else:
                final = scores
            best = np.argsort(-final, axis=-1, kind="stable")[:, 0]
        out = []
        for i, slot in enumerate(slots):
            data = self.occupied.pop(slot)
            # Device steps the caption paid: every dispatched tick from
            # its admission tick through the handle's tick ran `block`
            # steps over its rows.
            paid = (handle.seq - self.admit_tick.pop(slot) + 1) * self.block
            self.free.append(slot)
            out.append((
                data,
                seqs[i, best[i]],
                float(final[i, best[i]]),
                min(paid, self.L),
            ))
        return out

    def harvest(self, slot: int) -> Tuple[np.ndarray, float, int]:
        """Single-slot harvest (tests / tools)."""
        _, tokens, score, steps = self.harvest_many([slot])[0]
        return tokens, score, steps

    def evict(self, slot: int) -> Any:
        """Free a slot WITHOUT extracting (drain-deadline abandonment).
        Returns the caller data so its future can be failed."""
        data = self.occupied.pop(slot)
        self.admit_tick.pop(slot, None)
        self.free.append(slot)
        return data

    def drain(self) -> List[Tuple[Any, np.ndarray, float, int]]:
        """Run the loop with no admissions until every occupied slot
        finishes; harvest everything.  (Tests / shutdown convenience.)"""
        out = []
        while self.occupied:
            done = self.tick()
            out.extend(self.harvest_many(done))
        return out

    def warmup(self) -> None:
        """Compile every tick variant (one per admission bucket, plus
        the pure-step variant) so the first served request never pays
        XLA compile latency."""
        req = self.engine.template_prepared()
        for A in self._admit_buckets:
            done = self.tick([req] * A, [None] * A)
            self.harvest_many(done)
            self.drain()
        assert not self.occupied and len(self.free) == self.S

    def describe(self) -> Dict[str, Any]:
        return {
            "slots": self.S,
            "rows_per_slot": self.K,
            "block_steps": self.block,
            "max_steps": self.L,
            "mode": "greedy" if self.greedy else "beam",
            "admit_cap": self.admit_cap,
        }


# ------------------------------------------------ parity-harness backends

class _ParityEngine:
    """The minimal engine surface a :class:`SlotDecoder` needs, built
    straight from a :class:`~cst_captioning_tpu.decoding.core.ParityCtx`
    — so the shared parity harness (tests/test_decode_core.py) can
    drive the slot loop without the HTTP/batcher/cache stack.
    "Prepared requests" are plain video indices into the ctx batch."""

    def __init__(self, ctx, *, mode: str, num_slots: int, block: int):
        from types import SimpleNamespace

        self.model = ctx.make_model()
        self.params = ctx.params
        self.decode_mode = mode
        self.max_batch = num_slots
        self.device = None
        self._feats, self._masks, self._cat = (
            ctx.feats, ctx.masks, ctx.category,
        )
        d0 = next(iter(ctx.feats.values()))
        self.cfg = SimpleNamespace(
            serving=SimpleNamespace(
                num_slots=num_slots, slot_block_steps=block
            ),
            eval=SimpleNamespace(
                beam_size=ctx.beam_size, max_decode_len=ctx.max_len,
                length_normalize=True,
            ),
            data=SimpleNamespace(
                max_frames=d0.shape[1],
                feature_modalities=list(ctx.feats),
                feature_dims={
                    m: a.shape[-1] for m, a in ctx.feats.items()
                },
            ),
        )

    def encode_prepared_rows(self, reqs):
        ids = jnp.asarray(np.asarray(reqs, np.int32))
        feats = {m: a[ids] for m, a in self._feats.items()}
        masks = {m: a[ids] for m, a in self._masks.items()}
        cat = self._cat[ids] if self._cat is not None else None
        _, cache = self.model.apply(
            self.params, feats, masks, cat, method="init_decode"
        )
        return cache

    def template_prepared(self):
        return 0


def _slot_runner(ctx, mode: str):
    """Decode every ctx row through a small slot matrix with staggered
    admissions (slots hold rows at different decode depths), then map
    harvests back to row order."""
    B = next(iter(ctx.feats.values())).shape[0]
    eng = _ParityEngine(
        ctx, mode=mode, num_slots=max(2, B // 2), block=1
    )
    dec = SlotDecoder(eng)
    got_tok: Dict[int, np.ndarray] = {}
    got_score: Dict[int, float] = {}
    pending = list(range(B))
    stagger = 0
    while pending or dec.occupied:
        n = min(1 + stagger % 2, len(pending), len(dec.free),
                dec.admit_cap)
        adm = [pending.pop(0) for _ in range(n)]
        stagger += 1
        done = dec.tick(adm, adm)
        for i, tokens, score, steps in dec.harvest_many(done):
            got_tok[i], got_score[i] = tokens, score
            assert 0 < steps <= dec.L
    return {
        "tokens": np.stack([got_tok[i] for i in range(B)]),
        "scores": (
            np.asarray([got_score[i] for i in range(B)], np.float32)
            if mode == "beam" else None
        ),
    }


register_backend(
    "slot_decoder_beam",
    lambda ctx: _slot_runner(ctx, "beam"),
    kind="beam",
    ref="scan_beam",
)
register_backend(
    "slot_decoder_greedy",
    lambda ctx: _slot_runner(ctx, "greedy"),
    kind="greedy",
    ref="scan_greedy",
)
