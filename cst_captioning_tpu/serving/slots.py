"""Continuous in-flight batching: the persistent slot-based decode loop.

The PR-2 engine decodes batch-at-a-time: a coalesced micro-batch runs
``max_decode_len`` scan steps to completion while newly arrived requests
wait for the whole batch to drain.  MSR-VTT captions average ~9-12
tokens against a 28-30 cap, so most of that work is PAD-frozen rows and
most of the wait is head-of-line blocking.  This module holds the
standard production fix (continuous batching at decode-step
granularity):

* a matrix of ``S`` decode slots — greedy: 1 row/slot, beam: K
  contiguous rows/slot — whose per-slot state (``DecodeState`` rows,
  projected ``DecodeCache`` rows, emitted tokens, beam scores, finished
  flags, step counter) lives on device as one pytree of static shapes;
* each scheduler iteration (:meth:`SlotDecoder.tick`) is ONE jitted
  call: admit up to ``admit_cap`` pending requests into free slots via
  ``jax.lax.dynamic_update_slice`` on every leaf of the state pytree,
  then run ``slot_block_steps`` decode steps over all ``S*K`` rows —
  so a new request starts decoding at the next STEP boundary instead
  of the next batch boundary;
* slots whose rows all hit EOS — or the length cap — are harvested
  (host-side, from the tick's own outputs — no extra device call) and
  freed, so a short caption exits in ~its-own-length steps.

Decode-state memory (PR 7).  The projected ``DecodeCache`` is READ-ONLY
across decode steps, and a beam slot's K rows decode the SAME video —
the replicated ``(S*K, ...)`` cache layout stored K byte-identical
copies per request.  With ``serving.dedup_cache`` (default) the cache
is stored ONCE per slot (``(S, ...)`` leaves) and the jitted step
gathers per-row cache views via the row→slot index ``row // K`` before
calling ``decode_logits`` — the gather is transient scratch inside the
step, while the PERSISTENT decode-state HBM per in-flight beam request
drops ~K× (exact byte arithmetic: :meth:`SlotDecoder.state_bytes` /
:meth:`SlotDecoder.expected_state_bytes`, machine-checked in tier-1).
The cache rows were identical copies, and every decode op is
row-independent, so reading the shared copy cannot change any token
(docs/PARITY.md).  ``dedup_cache=false`` keeps the replicated layout —
the paired ``slot_mem_*`` bench rows measure both pytrees honestly, and
both layouts register in the shared parity harness.

Elastic slot banks (PR 7).  ``serving.slot_bank_min > 0`` pages the
slot matrix through a small pre-jitted doubling LADDER of bank shapes
(``[min, 2·min, ..., num_slots]`` — the PR-2 batch-ladder pattern): at
tick boundaries :meth:`SlotDecoder.maybe_resize` grows the bank while
queue pressure exceeds free slots and shrinks it after
``slot_shrink_idle_ticks`` consecutive underfull ticks.  Admission
fills the LOWEST free slot first, so high banks drain naturally and a
shrink only ever drops FREE slots (occupied rows are never moved —
resizing copies the surviving prefix, so it cannot change any row's
numbers).  Every tick variant and bank transition is compiled at
:meth:`warmup`, so a regrow under traffic is a pre-jitted ladder hit —
no cold-retrace stall on the request path
(``SlotDecoder.compile_count`` pins this in tier-1).  Capacity ``S``
becomes a knob that follows traffic instead of a deploy-time constant
(ROADMAP open item 3).

Freed/evicted slots have their cache and carry rows ZEROED at free time
(``serving.zero_freed_slots``, one fused mask-select per harvest
batch), so the live-byte gauges (``caption_decode_state_bytes``,
``caption_slot_bank_size``) report what is actually resident, not
stale rows riding dead in the bank.

Host-overhead discipline: with short captions, admissions and harvests
happen roughly once per caption, so per-request device dispatches would
dominate the step loop.  The loop therefore pays a CONSTANT number of
dispatches per iteration: admission is batched (one padded-bucket
encode, scatter fused into the step call, one compiled variant per
admission-count bucket per bank) and harvest reads the (tiny)
token/score matrices the tick already returned.

Parity argument (the bar: slot-decoded captions are token-exact vs the
offline ``evaluation.py`` path, pinned by tests/test_serving.py and the
shared harness in tests/test_decode_core.py):

* The per-step math IS the unified decode core — the very same
  ``decoding/core.py::decode_step`` the offline scan beam
  (``decoding/beam.py``) and ``CaptionModel._sample_from_cache`` drive:
  same PAD-freeze of finished beams, same top-K / argmax selection,
  same parent gather — only the batch axis is the slot axis and the
  sequence-write position is the per-slot step counter instead of the
  shared scan index.  Every op is row-independent, so which OTHER
  requests share the matrix (or arrive later — admission order) cannot
  change any row's numbers.
* The deduped cache read ``cache[row // K]`` yields bitwise the same
  per-row tensors the replicated layout stored — K identical copies
  collapse to one — so dedup cannot change any logit, and neither can
  a bank resize (prefix copy) or a freed-row zeroing (dead rows only).
* A finished slot that keeps riding (until harvest, or the remainder of
  a step block) is frozen exactly like the offline scan's finished
  beams: its only continuation is PAD at zero cost, a no-op on
  (tokens, scores).
* The admission encode is the same jitted ``init_decode`` the offline
  paths run, at a padded shape-ladder bucket (row-independent padding,
  the PR-2 discipline); admission-batch padding rows re-write the last
  real slot's rows — idempotent by construction.
* The host epilogue mirrors :func:`decoding.beam.finalize_beams` in
  numpy with a stable argsort — the same tie behavior as the offline
  jnp epilogue.

Double-buffered dispatch (``serving/replicas.py`` workers): the tick is
split into :meth:`SlotDecoder.tick_begin` (admission scatter + step
block DISPATCHED, no host sync — returns a :class:`TickHandle` holding
the tick's output arrays) and :meth:`SlotDecoder.tick_wait` /
:meth:`SlotDecoder.harvest_from` (sync + extract against a specific
handle).  A worker that dispatches tick *t+1* before waiting on tick
*t* overlaps its host-side harvest/detokenize/admission work with
device compute.  Two guards keep that reordering exact:

* every handle carries the tick's OWN functional outputs (``done`` /
  ``seqs`` / ``scores`` are fresh arrays per jitted call), so
  harvesting tick *t* after tick *t+1* was dispatched reads tick *t*'s
  numbers, not *t+1*'s;
* ``admit_tick`` records the tick at which each slot's occupant was
  admitted, and ``tick_wait(handle)`` only reports slots admitted at or
  before ``handle.seq`` — a slot harvested-then-refilled between
  dispatch and wait can never be harvested from a stale handle.  (The
  same guard makes bank resizes safe between dispatch and wait: a slot
  admitted into a freshly-grown bank carries a later ``admit_tick``
  than any outstanding handle, and a shrink only drops free slots.)

A finished slot rides frozen for the extra buffered tick (PAD-only
continuation, a no-op on tokens/scores — the same parity argument as
``slot_block_steps`` > 1), so double buffering cannot change any
caption.  The synchronous :meth:`SlotDecoder.tick` is the composition
``tick_begin`` + ``tick_wait`` and keeps the PR-3 behavior exactly.

Threading: a ``SlotDecoder`` is owned by exactly one scheduler thread
(``serving.batcher.ContinuousBatcher`` or one ``ReplicaSet`` worker);
nothing here locks.
"""

from __future__ import annotations

import bisect
import logging
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cst_captioning_tpu.constants import BOS_ID, PAD_ID
from cst_captioning_tpu.decoding.core import (
    NEG_INF,
    CoreState,
    DecodeState,
    decode_step,
    register_backend,
)
from cst_captioning_tpu.decoding.speculative import (
    draft_step,
    make_draft_params,
    spec_config,
    spec_round,
)
from cst_captioning_tpu.models.captioner import DecodeCache
from cst_captioning_tpu.observability.trace import get_tracer, null_tracer

_log = logging.getLogger("cst_captioning_tpu.serving")


def _buckets(top: int) -> List[int]:
    out, b = [], 1
    while b < top:
        out.append(b)
        b *= 2
    out.append(top)
    return out


def _bank_ladder(lo: int, hi: int) -> List[int]:
    """Doubling ladder of slot-bank sizes ``[lo, 2·lo, ..., hi]``."""
    lo = max(1, min(int(lo), int(hi)))
    out, b = [lo], lo
    while b < hi:
        b = min(b * 2, hi)
        out.append(b)
    return out


class TickHandle(NamedTuple):
    """One dispatched (possibly un-synced) tick: its sequence number and
    its own output arrays.  ``done``/``seqs``/``scores`` are the jitted
    call's functional outputs — later ticks never mutate them."""

    seq: int
    done: Any             # (S,) bool device array
    seqs: Any             # (S, K, L) int32 device array
    scores: Any           # (S, K) float32 device array


class SlotState(NamedTuple):
    """Device-resident state of all S decode slots: the unified decode
    carry (``decoding/core.py::CoreState``, per-slot axes ``(S, K,
    ...)``, flat row axis ``S*K``) plus the projected ``DecodeCache``
    rows the step closes over — deduped to ONE row per slot (leaves
    lead with S) under ``serving.dedup_cache``, or the legacy
    replicated per-beam-row layout (leaves lead with S*K)."""

    core: CoreState       # seqs/scores/finished/tokens/step + (h, c)
    cache: DecodeCache    # leaves lead with S (dedup) or S*K
    # Speculative decode only (serving.speculative): the draft LSTM's
    # (2, S, draft_hidden) f32 carry — h row 0, c row 1, one column per
    # slot.  None (an empty pytree leaf) on non-speculative decoders,
    # so their slot-state layout is byte-identical to pre-spec builds.
    draft: Any = None


class SlotDecoder:
    """See module doc.  Built by ``InferenceEngine.slot_decoder()``."""

    def __init__(self, engine):
        self.engine = engine
        cfg = engine.cfg
        sv = cfg.serving
        self.greedy = engine.decode_mode == "greedy"
        self.K = 1 if self.greedy else cfg.eval.beam_size
        self.L = cfg.eval.max_decode_len
        self.S_max = int(sv.num_slots or engine.max_batch)
        if self.S_max < 1:
            raise ValueError(f"num_slots {self.S_max} < 1")
        self.dedup = bool(getattr(sv, "dedup_cache", True))
        self.zero_freed = bool(getattr(sv, "zero_freed_slots", True))
        bank_min = int(getattr(sv, "slot_bank_min", 0) or 0)
        self.bank_ladder = (
            _bank_ladder(bank_min, self.S_max)
            if bank_min > 0 else [self.S_max]
        )
        self.shrink_after = max(
            1, int(getattr(sv, "slot_shrink_idle_ticks", 8))
        )
        # Elastic mode starts at the smallest bank — capacity follows
        # traffic; warmup pre-compiles every bank and transition.
        self.S = self.bank_ladder[0]
        self.block = max(1, int(sv.slot_block_steps))
        self.length_normalize = cfg.eval.length_normalize
        self.model = engine.model
        self.V = self.model.vocab_size
        # Speculative decode (serving.speculative; decoding/
        # speculative.py): each tick "round" proposes draft_k tokens
        # from the draft LSTM and verifies them in one batched full-
        # model step — 1..draft_k tokens emitted per slot per round,
        # token-exact vs the non-speculative loop by the rejection
        # rule.  The draft tree itself lives on the ENGINE
        # (engine.draft_params, built once at boot) and is passed into
        # the jitted tick as an ARGUMENT — closure-capturing it would
        # bake device buffers into AOT-serialized executables.
        self.spec = spec_config(sv)
        if self.spec is not None and not self.greedy:
            raise ValueError(
                "serving.speculative requires decode_mode='greedy' — "
                "the rejection rule accepts against the model's argmax "
                "stream, which beam search does not have"
            )
        self.spec_k = self.spec.draft_k if self.spec else 0
        if self.spec is not None:
            if getattr(engine, "draft_params", None) is None:
                raise ValueError(
                    "serving.speculative is on but the engine carries "
                    "no draft_params — InferenceEngine builds them at "
                    "boot; a custom engine surface must too"
                )
            # Acceptance accounting: a running (2,) device total of
            # [emitted tokens, live slot-rounds] accumulated with an
            # async device add per tick (no host sync, O(1) memory);
            # spec_stats() fetches it on demand.
            self._spec_totals = jnp.zeros((2,), jnp.float32)
        # Admissions per tick are capped so the padded admission-encode
        # bucket stays within the engine's compiled shape discipline.
        self.admit_cap = min(self.S_max, engine.max_batch)
        self._admit_buckets = _buckets(self.admit_cap)
        if getattr(self.model, "use_pallas_beam", False):
            # The fused whole-recurrence kernel decodes run-to-completion
            # by construction; the slot loop needs step granularity.
            # Whether the slot step itself gets the tensor-parallel fast
            # path is a CAPABILITY question, not a hardcoded refusal
            # (decoding/core.py::DECODE_KERNEL_CAPS, ISSUE 14).
            from cst_captioning_tpu.decoding.core import kernel_supports

            shards = int(getattr(self.model, "decode_shards", 1) or 1)
            _log.info(
                "continuous slot loop uses the per-step decode core; %s",
                "the cross-shard fused top-K merge covers the "
                "model-sharded step (shard_fused_decode)"
                if shards > 1 and kernel_supports("use_pallas_beam", "model")
                else "the fused beam kernel (use_pallas_beam) applies "
                "to the ladder/offline paths only",
            )
        # Host-side slot bookkeeping (scheduler thread only).  ``free``
        # stays SORTED and admission takes the LOWEST index, so high
        # slots drain first and a bank shrink only drops free slots.
        self.free: List[int] = list(range(self.S))
        self.occupied: Dict[int, Any] = {}      # slot -> caller's data
        self.admit_tick: Dict[int, int] = {}    # slot -> admission seq
        self._tick_fns: Dict[Tuple[int, int], Any] = {}   # (S, A) -> fn
        self._resize_fns: Dict[Tuple[int, int], Any] = {}
        self._free_fns: Dict[int, Any] = {}               # S -> fn
        self._seq = 0                           # dispatched-tick counter
        # Compiled-variant builds (tick/resize/free fns): warmup builds
        # them all, so post-warmup traffic — including bank regrows —
        # must never build a new one (the pre-jitted-ladder pin).
        self.compile_count = 0
        # Bank-resize accounting (metrics / bench regrow rows).
        self.resize_count = 0
        self.last_resize_ms = 0.0
        self.worst_resize_ms = 0.0
        self._shrink_streak = 0
        # Host-side span tracing (observability/trace.py): the loop's
        # dispatch/wait/harvest split is recorded around the HOST calls
        # only — zero tracing inside jitted code (CST-OBS-003); the
        # async tick handles are what make the host-vs-device split
        # honest.  Replica engines tag every span with their id.
        self.tracer = (
            get_tracer() if getattr(sv, "tracing", True) else null_tracer()
        )
        rid = getattr(engine, "replica_id", None)
        self.span_tags: Dict[str, Any] = (
            {} if rid is None else {"replica": rid}
        )
        # Last dispatched handle (sync-path harvest target) and a host
        # snapshot cache keyed by handle seq (fetched lazily, at most
        # once per handle).
        self._last_handle: Optional[TickHandle] = None
        self._np_seq = -1
        self._seqs_np: Optional[np.ndarray] = None
        self._scores_np: Optional[np.ndarray] = None
        self._build_step()
        self._st = self._init_state(self.S)

    # ------------------------------------------------------------- device
    def _cache_rows(self, S: int) -> int:
        """Leading dim of the stored DecodeCache: one row per slot when
        deduped, one per beam row in the legacy replicated layout."""
        return S if self.dedup else S * self.K

    def _cache_avals(self, rows: int) -> DecodeCache:
        """Shape/dtype structs of a ``rows``-row projected DecodeCache —
        exactly one encode output's leaves with a ``rows`` leading dim.
        The ONE shape source for slot-state init AND the AOT artifact
        lowering (serving/artifact.py), so the two can never drift."""
        model = self.model
        d = self.engine.cfg.data
        feats = {
            m: jnp.zeros((rows, d.max_frames, d.feature_dims[m]))
            for m in d.feature_modalities
        }
        masks = {m: jnp.ones((rows, d.max_frames)) for m in feats}
        cat = (
            jnp.zeros((rows,), jnp.int32) if model.use_category else None
        )
        return jax.eval_shape(
            lambda f, mk, c: model.apply(
                self.engine.params, f, mk, c, method="init_decode"
            )[1],
            feats, masks, cat,
        )

    def _init_state(self, S: int) -> SlotState:
        model, K, L = self.model, self.K, self.L
        cdt = jnp.dtype(model.compute_dtype)
        n = S * K
        # Zero cache rows shaped exactly like one encode output: let
        # eval_shape infer the DecodeCache leaf shapes.
        cache_shape = self._cache_avals(self._cache_rows(S))
        cache = jax.tree.map(
            lambda sds: jnp.zeros(sds.shape, sds.dtype), cache_shape
        )
        core = CoreState(
            state=DecodeState(
                h=jnp.zeros((model.num_layers, n, model.rnn_size), cdt),
                c=jnp.zeros(
                    (model.num_layers, n, model.rnn_size), jnp.float32
                ),
            ),
            seqs=jnp.full((S, K, L), PAD_ID, jnp.int32),
            scores=None if self.greedy else jnp.zeros((S, K), jnp.float32),
            lps=None,
            # Empty slots ride as finished/step=L: done, frozen, harmless.
            finished=jnp.ones((S, K), bool),
            tokens=jnp.full((n,), BOS_ID, jnp.int32),
            step=jnp.full((S,), L, jnp.int32),
            rng=None,
        )
        draft = (
            None if self.spec is None
            else jnp.zeros((2, S, self.spec.draft_hidden), jnp.float32)
        )
        st = SlotState(core=core, cache=cache, draft=draft)
        # Replica engines pin their slot matrix to their device so the
        # first tick doesn't silently run on the default device.
        dev = getattr(self.engine, "device", None)
        if dev is not None:
            return jax.device_put(st, dev)
        # Mesh-carrying engines: slot state is activation-shaped, so it
        # commits with the data-axis sharding on its slot/row axes —
        # which on the (data=1, model=N) serving submesh degenerates to
        # replication across the shard group, and on a serving mesh
        # that carries data > 1 actually shards the slot rows (ISSUE
        # 14: activation-sharded slot state).  Committing it explicitly
        # keeps the first tick from running single-device against
        # mesh-sharded params.
        tp = getattr(self.engine, "tp_mesh", None)
        if tp is not None:
            return jax.device_put(st, self._slot_shardings(st, tp))
        return st

    def _slot_shardings(self, st: SlotState, mesh):
        """Per-leaf NamedShardings for the slot-state pytree on a
        serving mesh: the slot/row axis (axis 1 for the (layers, rows,
        H) LSTM carry, axis 0 everywhere else) shards over ``data``
        when the mesh carries data > 1 AND the axis divides it; every
        other case — including the whole (data=1, model=N) submesh
        grid — is replication, byte-identical to the PR-9 layout.
        The spec rule itself lives beside the param rule table
        (parallel/partition.py::rows_sharding)."""
        from jax.sharding import NamedSharding

        from cst_captioning_tpu.parallel.partition import rows_sharding

        carry = jax.tree.map(
            lambda x: rows_sharding(mesh, x.shape, 1), st.core.state
        )
        core = st.core._replace(state=carry)
        core = jax.tree.map(
            lambda x: x if isinstance(x, NamedSharding)
            else rows_sharding(mesh, x.shape, 0),
            core,
        )
        cache = jax.tree.map(
            lambda x: rows_sharding(mesh, x.shape, 0), st.cache
        )
        draft = (
            None if st.draft is None
            else rows_sharding(mesh, st.draft.shape, 1)
        )
        return SlotState(core=core, cache=cache, draft=draft)

    def _build_step(self) -> None:
        model, K, dedup = self.model, self.K, self.dedup
        mode = "greedy" if self.greedy else "beam"
        # Model-sharded engine: pin the (rows, V) decode-step logits
        # vocab-over-model so XLA keeps the logit matmul sharded through
        # the step instead of all-gathering before the top-K/argmax —
        # the serving twin of the training-side logits constraint
        # (parallel/partition.py::logits_spec, docs/PERF.md r12) — and,
        # with ``serving.shard_fused_decode`` (default), swap the
        # inline top-K/argmax for the cross-shard candidate merge
        # (decoding/core.py::make_tp_beam_topk / make_tp_row_pick):
        # each shard top-Ks its own vocab tile and one O(shards*K)
        # candidate all-gather replaces the O(V) full-vocab gather the
        # SPMD partitioner otherwise inserts on the hottest serving op
        # (docs/PERF.md r14; token-exact incl. tie order, PARITY r15,
        # pinned by the *_tp2_fused backends in the shared harness).
        tp_logits = None
        tp_topk = tp_pick = None
        tp = getattr(self.engine, "tp_mesh", None)
        if tp is not None and tp.shape.get("model", 1) > 1:
            from cst_captioning_tpu.parallel import partition

            tp_logits = partition.logits_sharding(tp, ndim=2)
            M = tp.shape["model"]
            sv = self.engine.cfg.serving
            if bool(getattr(sv, "shard_fused_decode", True)):
                if self.V % M == 0:
                    from cst_captioning_tpu.decoding.core import (
                        make_tp_beam_topk,
                        make_tp_row_pick,
                    )

                    if self.greedy:
                        tp_pick = make_tp_row_pick(tp)
                    else:
                        tp_topk = make_tp_beam_topk(tp)
                else:
                    _log.warning(
                        "serving.shard_fused_decode requested but vocab "
                        "%d does not tile over the %d-way model axis — "
                        "keeping the full-vocab-gather top-K (pad the "
                        "vocab to a multiple of model_shards)",
                        self.V, M,
                    )

        def step_once(params, st: SlotState) -> SlotState:
            # The per-step recurrence is the unified decode core
            # (decoding/core.py::decode_step) — identical math to the
            # offline scan paths, only the batch axis is the slot axis
            # and write positions are the per-slot step counters.
            def step_logits(state, tokens):
                cache = st.cache
                if dedup and K > 1:
                    # Shared-copy read: row r of slot s sees cache[s].
                    # The gather is scratch inside the step; the stored
                    # state keeps ONE row per slot.
                    row_slot = jnp.arange(state.h.shape[1]) // K
                    cache = jax.tree.map(
                        lambda x: x[row_slot], cache
                    )
                new_state, logits = model.apply(
                    params, state, cache, tokens,
                    method="decode_logits",
                )
                if tp_logits is not None:
                    logits = jax.lax.with_sharding_constraint(
                        logits, tp_logits
                    )
                return new_state, logits

            core = decode_step(
                step_logits, st.core, mode=mode,
                topk_fn=tp_topk, pick_fn=tp_pick,
            )
            return SlotState(core=core, cache=st.cache, draft=st.draft)

        self._step_once = step_once

        # Speculative round (decoding/speculative.py::spec_round): the
        # verify closure is step_once's twin — the model's batched
        # k-step verify plus the SAME TP logits constraint and the SAME
        # cross-shard row pick, which is what keeps TP speculative
        # decode token-exact through the one pick definition.  Greedy
        # implies K == 1, so the dedup row->slot gather degenerates to
        # the identity and the stored cache feeds the verify directly.
        if self.spec is not None:
            spec_k = self.spec.draft_k
            suppress = bool(getattr(model, "decode_suppress_unk", False))

            def spec_once(params, dparams, st: SlotState):
                def verify_fn(state, vin):
                    h_all, c_all, logits = model.apply(
                        params, state, st.cache, vin,
                        method="decode_verify",
                    )
                    if tp_logits is not None:
                        logits = jax.lax.with_sharding_constraint(
                            logits, tp_logits
                        )
                    return h_all, c_all, logits

                def draft_fn(c, tok):
                    return draft_step(dparams, c, tok, suppress)

                core, draft, stats = spec_round(
                    verify_fn, draft_fn, st.core, st.draft, spec_k,
                    pick_fn=tp_pick,
                )
                return (
                    SlotState(core=core, cache=st.cache, draft=draft),
                    stats,
                )

            self._spec_once = spec_once

        self._scores0 = jnp.where(
            jnp.arange(K) == 0, 0.0, NEG_INF
        ).astype(jnp.float32)[None, :]                          # (1, K)

    def _tick_fn(self, A: int, S: Optional[int] = None):
        """One compiled scheduler iteration at bank size ``S`` (default:
        the CURRENT bank): scatter A admissions into their slots (A
        static per variant, 0 = pure step), then run the step block.
        Returns the new state plus everything the host needs — done
        flags and the token/score matrices — so harvests cost no extra
        device call.  ``S`` only keys the variant cache (the traced fn
        takes its shapes from its arguments); the AOT artifact builder
        passes it explicitly to lower every bank's variant."""
        key = ((self.S if S is None else S), A)
        if key in self._tick_fns:
            return self._tick_fns[key]
        self.compile_count += 1
        model, K, L = self.model, self.K, self.L
        dedup = self.dedup
        cdt = jnp.dtype(model.compute_dtype)
        scores0 = self._scores0
        step_once, block = self._step_once, self.block

        def admit_one(st: SlotState, slot, req_rows: DecodeCache):
            """Scatter one request's cache rows — (1, ...) deduped, or
            (K, ...) replicated — plus fresh carry into ``slot``."""
            row0 = slot * K
            cache_off = slot if dedup else row0
            cache = jax.tree.map(
                lambda leaf, r: jax.lax.dynamic_update_slice(
                    leaf, r.astype(leaf.dtype),
                    (cache_off,) + (jnp.int32(0),) * (leaf.ndim - 1),
                ),
                st.cache, req_rows,
            )
            co = st.core
            core = co._replace(
                state=DecodeState(
                    h=jax.lax.dynamic_update_slice(
                        co.state.h,
                        jnp.zeros(
                            (model.num_layers, K, model.rnn_size), cdt
                        ),
                        (jnp.int32(0), row0, jnp.int32(0)),
                    ),
                    c=jax.lax.dynamic_update_slice(
                        co.state.c,
                        jnp.zeros(
                            (model.num_layers, K, model.rnn_size),
                            jnp.float32,
                        ),
                        (jnp.int32(0), row0, jnp.int32(0)),
                    ),
                ),
                seqs=jax.lax.dynamic_update_slice(
                    co.seqs,
                    jnp.full((1, K, L), PAD_ID, jnp.int32),
                    (slot, jnp.int32(0), jnp.int32(0)),
                ),
                scores=(
                    None if co.scores is None
                    else jax.lax.dynamic_update_slice(
                        co.scores, scores0, (slot, jnp.int32(0))
                    )
                ),
                finished=jax.lax.dynamic_update_slice(
                    co.finished,
                    jnp.zeros((1, K), bool),
                    (slot, jnp.int32(0)),
                ),
                tokens=jax.lax.dynamic_update_slice(
                    co.tokens,
                    jnp.full((K,), BOS_ID, jnp.int32),
                    (row0,),
                ),
                step=jax.lax.dynamic_update_slice(
                    co.step, jnp.zeros((1,), jnp.int32), (slot,)
                ),
            )
            draft = st.draft
            if draft is not None:
                # Fresh draft carry for the admitted slot's column.
                draft = jax.lax.dynamic_update_slice(
                    draft,
                    jnp.zeros((2, 1, draft.shape[-1]), jnp.float32),
                    (jnp.int32(0), slot, jnp.int32(0)),
                )
            return SlotState(core=core, cache=cache, draft=draft)

        def admit_all(st: SlotState, slots, rows: DecodeCache):
            if not dedup:
                # Legacy replicated layout only: fan each request's
                # row out to its K beam rows before the scatter.
                rows = jax.tree.map(
                    lambda x: jnp.repeat(x, K, axis=0), rows
                )
            R = 1 if dedup else K
            for i in range(A):
                req_rows = jax.tree.map(
                    lambda r: jax.lax.dynamic_slice(
                        r,
                        (i * R,) + (0,) * (r.ndim - 1),
                        (R,) + r.shape[1:],
                    ),
                    rows,
                )
                st = admit_one(
                    st, slots[i].astype(jnp.int32), req_rows
                )
            return st

        @jax.jit
        def tick(params, st: SlotState, slots, rows: DecodeCache):
            if A:
                st = admit_all(st, slots, rows)
            for _ in range(block):
                st = step_once(params, st)
            done = jnp.all(st.core.finished, axis=-1) | (
                st.core.step >= L
            )
            return st, done, st.core.seqs, st.core.scores

        spec_once = getattr(self, "_spec_once", None)

        @jax.jit
        def tick_spec(
            params, dparams, st: SlotState, slots, rows: DecodeCache
        ):
            # The speculative tick: identical admissions, but each of
            # the `block` iterations is a propose/verify ROUND emitting
            # 1..draft_k tokens per live slot; the (2,) stats vector
            # sums [emitted, live] over the block so the host can
            # accumulate acceptance accounting without a sync.
            if A:
                st = admit_all(st, slots, rows)
            stats = jnp.zeros((2,), jnp.float32)
            for _ in range(block):
                st, s = spec_once(params, dparams, st)
                stats = stats + s
            done = jnp.all(st.core.finished, axis=-1) | (
                st.core.step >= L
            )
            return st, done, st.core.seqs, st.core.scores, stats

        fn = tick if self.spec is None else tick_spec
        self._tick_fns[key] = fn
        return fn

    def _free_fn(self, S: int):
        """Compiled freed-slot blanking: reset the masked slots' cache
        and carry rows to the empty-slot pattern (zeros / PAD / frozen)
        so live decode-state bytes are honest.  One variant per bank —
        the mask is a traced argument, not a shape."""
        if S in self._free_fns:
            return self._free_fns[S]
        self.compile_count += 1
        K, L = self.K, self.L
        dedup = self.dedup

        def bmask(mask, leaf):
            return mask.reshape(mask.shape + (1,) * (leaf.ndim - 1))

        @jax.jit
        def free_rows(st: SlotState, mask):       # mask: (S,) bool
            mask_n = jnp.reshape(
                jnp.broadcast_to(mask[:, None], (S, K)), (S * K,)
            )
            mask_c = mask if dedup else mask_n
            cache = jax.tree.map(
                lambda x: jnp.where(
                    bmask(mask_c, x), jnp.zeros((), x.dtype), x
                ),
                st.cache,
            )
            co = st.core
            core = co._replace(
                state=DecodeState(
                    h=jnp.where(mask_n[None, :, None], 0.0, co.state.h),
                    c=jnp.where(mask_n[None, :, None], 0.0, co.state.c),
                ),
                seqs=jnp.where(
                    mask[:, None, None], jnp.int32(PAD_ID), co.seqs
                ),
                scores=(
                    None if co.scores is None
                    else jnp.where(mask[:, None], 0.0, co.scores)
                ),
                finished=co.finished | mask[:, None],
                tokens=jnp.where(mask_n, jnp.int32(BOS_ID), co.tokens),
                step=jnp.where(mask, jnp.int32(L), co.step),
            )
            draft = st.draft
            if draft is not None:
                draft = jnp.where(mask[None, :, None], 0.0, draft)
            return SlotState(core=core, cache=cache, draft=draft)

        self._free_fns[S] = free_rows
        return free_rows

    def _zero_slots(self, slots: Sequence[int]) -> None:
        if not self.zero_freed or not slots:
            return
        mask = np.zeros((self.S,), bool)
        mask[list(slots)] = True
        self._st = self._free_fn(self.S)(self._st, jnp.asarray(mask))

    def _resize_fn(self, S_from: int, S_to: int):
        """Compiled bank transition ``S_from -> S_to``: grow pads with
        empty slots (finished / step=L / zero rows) after the surviving
        prefix; shrink slices the prefix (callers guarantee slots >=
        S_to are free).  Prefix rows are COPIED, never recomputed, so a
        resize cannot change any in-flight row's numbers."""
        key = (S_from, S_to)
        if key in self._resize_fns:
            return self._resize_fns[key]
        self.compile_count += 1
        K, L = self.K, self.L
        grow = S_to > S_from

        def scale(leaf, fill, axis=0):
            shape = list(leaf.shape)
            shape[axis] = (shape[axis] // S_from) * S_to
            if grow:
                big = jnp.full(tuple(shape), fill, leaf.dtype)
                return jax.lax.dynamic_update_slice(
                    big, leaf, (jnp.int32(0),) * leaf.ndim
                )
            ix = [slice(None)] * leaf.ndim
            ix[axis] = slice(0, shape[axis])
            return leaf[tuple(ix)]

        @jax.jit
        def resize(st: SlotState) -> SlotState:
            co = st.core
            cache = jax.tree.map(lambda x: scale(x, 0), st.cache)
            core = co._replace(
                state=DecodeState(
                    h=scale(co.state.h, 0, axis=1),
                    c=scale(co.state.c, 0, axis=1),
                ),
                seqs=scale(co.seqs, PAD_ID),
                scores=(
                    None if co.scores is None else scale(co.scores, 0.0)
                ),
                finished=scale(co.finished, True),
                tokens=scale(co.tokens, BOS_ID),
                step=scale(co.step, L),
            )
            draft = (
                None if st.draft is None
                else scale(st.draft, 0.0, axis=1)
            )
            return SlotState(core=core, cache=cache, draft=draft)

        self._resize_fns[key] = resize
        return resize

    def _pad_bucket(self, n: int) -> int:
        for b in self._admit_buckets:
            if b >= n:
                return b
        return self._admit_buckets[-1]

    # ------------------------------------------------------ elastic banks
    def _set_bank(self, S_to: int) -> None:
        S_from = self.S
        if S_to == S_from:
            return
        if S_to < S_from:
            busy = [s for s in self.occupied if s >= S_to]
            if busy:  # pragma: no cover — callers check first
                raise RuntimeError(
                    f"bank shrink {S_from}->{S_to} with occupied slots "
                    f"{busy}"
                )
        t0 = time.perf_counter()
        self._st = self._resize_fn(S_from, S_to)(self._st)
        if S_to > S_from:
            self.free.extend(range(S_from, S_to))
        else:
            self.free = [s for s in self.free if s < S_to]
        self.free.sort()
        self.S = S_to
        self.resize_count += 1
        self.last_resize_ms = (time.perf_counter() - t0) * 1e3
        self.worst_resize_ms = max(
            self.worst_resize_ms, self.last_resize_ms
        )
        _log.info(
            "slot bank %d -> %d (%.2fms dispatch)",
            S_from, S_to, self.last_resize_ms,
        )

    def maybe_resize(self, pending: int = 0) -> int:
        """Elastic-bank policy, called by the scheduler at tick
        boundaries with its queue depth.  Grows (possibly several rungs)
        while pending work exceeds free slots; shrinks one rung after
        ``slot_shrink_idle_ticks`` consecutive ticks in which the
        occupancy + queue fits the next bank down.  Returns the
        (possibly new) bank size.  All transitions are pre-jitted by
        :meth:`warmup` — a resize is a ladder hit, never a retrace."""
        if len(self.bank_ladder) == 1:
            return self.S
        i = self.bank_ladder.index(self.S)
        grew = False
        while (
            pending > len(self.free)
            and i + 1 < len(self.bank_ladder)
        ):
            i += 1
            self._set_bank(self.bank_ladder[i])
            grew = True
        if grew:
            self._shrink_streak = 0
            return self.S
        if i > 0:
            lower = self.bank_ladder[i - 1]
            fits = (
                self.n_occupied + pending <= lower
                and all(s < lower for s in self.occupied)
            )
            if fits:
                self._shrink_streak += 1
                if self._shrink_streak >= self.shrink_after:
                    self._set_bank(lower)
                    self._shrink_streak = 0
            else:
                self._shrink_streak = 0
        return self.S

    # ------------------------------------------------------ byte accounting
    def state_bytes(self) -> int:
        """Exact bytes of the resident decode-state pytree (allocated
        bank), measured from the arrays themselves."""
        return int(sum(
            x.size * jnp.dtype(x.dtype).itemsize
            for x in jax.tree.leaves(self._st)
        ))

    def cache_bytes(self) -> int:
        """Bytes of the stored (read-only) DecodeCache leaves — the
        component the dedup collapses exactly K×."""
        return int(sum(
            x.size * jnp.dtype(x.dtype).itemsize
            for x in jax.tree.leaves(self._st.cache)
        ))

    def carry_bytes(self) -> int:
        """Bytes of the genuinely per-row carry (h/c, seqs, scores,
        finished, tokens, counters) — unchanged by the dedup."""
        return self.state_bytes() - self.cache_bytes()

    def per_slot_bytes(self) -> int:
        """Decode-state bytes per in-flight request.  Every leaf's
        slot/row axis scales linearly with S, so this is exact integer
        arithmetic, not an estimate."""
        return self.state_bytes() // self.S

    def live_state_bytes(self) -> int:
        """Bytes attributable to OCCUPIED slots (freed rows are zeroed
        at free time, so this is what is live, honestly)."""
        return self.per_slot_bytes() * self.n_occupied

    def expected_state_bytes(self, S: Optional[int] = None) -> int:
        """Closed-form bytes-per-bank formula from config shapes — the
        machine-checked twin of :meth:`state_bytes` (tier-1 asserts
        they agree exactly, so a layout regression fails the build).

        cache (per stored row): E·cdt  (ctx_static)
                              + F·E·cdt (att_vals) + F·A·cdt (att_proj)
                              + F·4    (att_mask, f32)
                              + C·cdt  (cat_emb)
          × S stored rows deduped, S·K replicated;
        carry (per slot):  layers·K·H·(cdt+4)   (h compute-dtype, c f32)
                         + K·L·4 (seqs) + K·4 (beam scores)
                         + K (finished bool) + K·4 (tokens) + 4 (step)
                         + 2·draft_hidden·4 (speculative draft carry).
        """
        S = self.S if S is None else S
        m, d = self.model, self.engine.cfg.data
        K, L = self.K, self.L
        cdt = jnp.dtype(m.compute_dtype).itemsize
        E, H = m.embed_size, m.rnn_size
        F = d.max_frames * len(d.feature_modalities)
        A = m.att_hidden_size if m.fusion == "attention" else 0
        C = m.category_embed_size if m.use_category else 0
        cache_row = E * cdt + F * E * cdt + F * A * cdt + F * 4 + C * cdt
        cache = self._cache_rows(S) * cache_row
        carry = S * (
            m.num_layers * K * H * (cdt + 4)
            + K * L * 4
            + (0 if self.greedy else K * 4)
            + K
            + K * 4
            + 4
            + (0 if self.spec is None else 2 * self.spec.draft_hidden * 4)
        )
        return cache + carry

    # --------------------------------------------------------------- host
    @property
    def n_occupied(self) -> int:
        return len(self.occupied)

    def tick_begin(
        self,
        prepared: Sequence[Any] = (),
        datas: Sequence[Any] = (),
    ) -> Optional[TickHandle]:
        """Dispatch one scheduler iteration WITHOUT a host sync: admit
        ``prepared`` (up to ``admit_cap``; caller gates on ``free``) and
        launch one step block over all slots.  Returns a
        :class:`TickHandle` to pass to :meth:`tick_wait` /
        :meth:`harvest_from`, or ``None`` when there is nothing to do
        (no admissions, no occupied slots — no device work launched)."""
        n = len(prepared)
        if n == 0 and not self.occupied:
            return None
        t_begin = time.monotonic()
        if n > len(self.free) or n > self.admit_cap:
            raise RuntimeError(
                f"tick admitting {n} exceeds free={len(self.free)} "
                f"cap={self.admit_cap}"
            )
        if n:
            A = self._pad_bucket(n)
            # Pad the admission batch by replicating the LAST request:
            # padding rows re-scatter into the same slot (idempotent).
            # Encode BEFORE claiming slots so a failed encode (bad row,
            # OOM) leaks nothing.
            reqs = list(prepared) + [prepared[-1]] * (A - n)
            rows = self.engine.encode_prepared_rows(reqs)
            # Lowest-index slots first: keeps occupancy packed toward
            # the bank prefix so elastic shrinks stay possible.
            slots = [self.free.pop(0) for _ in range(n)]
            for s in slots:
                if s in self.occupied:  # pragma: no cover — invariant
                    raise RuntimeError(f"slot {s} double-assigned")
            slot_arr = jnp.asarray(
                np.asarray(slots + [slots[-1]] * (A - n), np.int32)
            )
        else:
            A = 0
            slots = []
            slot_arr = rows = None
        self._seq += 1
        for s, d in zip(slots, datas):
            self.occupied[s] = d
            self.admit_tick[s] = self._seq
        if self.spec is not None:
            self._st, done, seqs_d, scores_d, stats = self._tick_fn(A)(
                self.engine.params, self.engine.draft_params,
                self._st, slot_arr, rows,
            )
            # Async device add: the totals stay a lazy device value,
            # never forcing a sync on the dispatch path.
            self._spec_totals = self._spec_totals + stats
        else:
            self._st, done, seqs_d, scores_d = self._tick_fn(A)(
                self.engine.params, self._st, slot_arr, rows
            )
        handle = TickHandle(self._seq, done, seqs_d, scores_d)
        self._last_handle = handle
        # Host side of the tick only: the dispatch returns before the
        # device work completes; tick_wait's span carries the exposed
        # device residual.
        self.tracer.record(
            "tick_dispatch", t_begin, time.monotonic(),
            tags=dict(self.span_tags, seq=self._seq, admits=n),
        )
        return handle

    def tick_wait(self, handle: TickHandle) -> List[int]:
        """Sync on ``handle``'s tick and return the occupied slots that
        finished by it (all beams EOS, or length cap).  Slots whose
        occupant was admitted AFTER the handle's tick are excluded —
        their done flags in this handle describe the PREVIOUS occupant
        (double-buffered dispatch admits into freed slots before the
        older tick is waited on; the admit-tick check also keeps slot
        indices within the handle's own bank shape across resizes)."""
        t0 = time.monotonic()
        done_np = np.asarray(jax.device_get(handle.done))
        self.tracer.record(
            "tick_wait", t0, time.monotonic(),
            tags=dict(self.span_tags, seq=handle.seq),
        )
        return [
            s for s in self.occupied
            if self.admit_tick[s] <= handle.seq and bool(done_np[s])
        ]

    def tick(
        self,
        prepared: Sequence[Any] = (),
        datas: Sequence[Any] = (),
    ) -> List[int]:
        """One synchronous scheduler iteration (dispatch + sync):
        ``tick_begin`` composed with ``tick_wait``.  Returns the
        occupied slots that are now done."""
        handle = self.tick_begin(prepared, datas)
        if handle is None:
            return []
        return self.tick_wait(handle)

    def harvest_many(
        self, slots: Sequence[int]
    ) -> List[Tuple[Any, np.ndarray, float, int]]:
        """Extract done slots from the LAST dispatched tick's outputs
        (the synchronous-loop path)."""
        if not slots:
            return []
        if self._last_handle is None:
            raise RuntimeError("harvest before any tick")
        return self.harvest_from(self._last_handle, slots)

    def harvest_from(
        self, handle: TickHandle, slots: Sequence[int]
    ) -> List[Tuple[Any, np.ndarray, float, int]]:
        """Extract done slots' best hypotheses from ``handle``'s tick
        outputs (no device call beyond fetching them once per handle)
        and free the slots — zeroing their cache/carry rows so the
        live-byte gauges stay honest.  Returns ``[(data, tokens (L,)
        int32, score, steps), ...]`` in ``slots`` order."""
        if not slots:
            return []
        t_harvest = time.monotonic()
        for s in slots:
            if s not in self.occupied:
                raise RuntimeError(f"harvest of unoccupied slot {s}")
            if self.admit_tick[s] > handle.seq:  # pragma: no cover
                raise RuntimeError(
                    f"slot {s} admitted at tick {self.admit_tick[s]} > "
                    f"harvest handle tick {handle.seq}"
                )
        if self._np_seq != handle.seq:
            self._seqs_np = np.asarray(jax.device_get(handle.seqs))
            # Greedy slots carry no beam scores (CoreState.scores=None).
            self._scores_np = (
                None if handle.scores is None
                else np.asarray(jax.device_get(handle.scores))
            )
            self._np_seq = handle.seq
        seqs = self._seqs_np[list(slots)]                 # (n, K, L)
        if self.greedy:
            best = np.zeros((len(slots),), int)
            final = np.zeros((len(slots), 1), np.float32)
        else:
            scores = self._scores_np[list(slots)]         # (n, K)
            if self.length_normalize:
                lengths = np.maximum((seqs != PAD_ID).sum(-1), 1)
                final = scores / lengths.astype(np.float32)
            else:
                final = scores
            best = np.argsort(-final, axis=-1, kind="stable")[:, 0]
        out = []
        for i, slot in enumerate(slots):
            data = self.occupied.pop(slot)
            # Device steps the caption paid: every dispatched tick from
            # its admission tick through the handle's tick ran `block`
            # steps over its rows.  Speculative rounds emit up to
            # draft_k tokens each, so the per-caption charge scales by
            # k (an upper bound — min(·, L) below keeps it honest).
            paid = (
                (handle.seq - self.admit_tick.pop(slot) + 1)
                * self.block * max(1, self.spec_k)
            )
            bisect.insort(self.free, slot)
            out.append((
                data,
                seqs[i, best[i]],
                float(final[i, best[i]]),
                min(paid, self.L),
            ))
        self._zero_slots(list(slots))
        self.tracer.record(
            "harvest", t_harvest, time.monotonic(),
            tags=dict(self.span_tags, seq=handle.seq, slots=len(slots)),
        )
        return out

    def harvest(self, slot: int) -> Tuple[np.ndarray, float, int]:
        """Single-slot harvest (tests / tools)."""
        _, tokens, score, steps = self.harvest_many([slot])[0]
        return tokens, score, steps

    def evict(self, slot: int) -> Any:
        """Free a slot WITHOUT extracting (drain-deadline abandonment).
        Returns the caller data so its future can be failed."""
        data = self.occupied.pop(slot)
        self.admit_tick.pop(slot, None)
        bisect.insort(self.free, slot)
        self._zero_slots([slot])
        return data

    def drain(self) -> List[Tuple[Any, np.ndarray, float, int]]:
        """Run the loop with no admissions until every occupied slot
        finishes; harvest everything.  (Tests / shutdown convenience.)"""
        out = []
        while self.occupied:
            done = self.tick()
            out.extend(self.harvest_many(done))
        return out

    def warmup(self) -> None:
        """Compile EVERY variant the loop can hit — tick fns per
        admission bucket per bank (plus the pure-step variant), the
        freed-slot blanking fn per bank, and both directions of every
        bank transition — so neither the first served request nor a
        bank regrow under traffic ever pays XLA compile latency."""
        req = self.engine.template_prepared()
        for bank in self.bank_ladder:
            if bank != self.S:
                self._set_bank(bank)          # compiles the grow fns
            warm_As = [
                a for a in self.warm_admit_counts(bank) if a > 0
            ]
            for A in warm_As:
                n = min(A, bank)
                done = self.tick([req] * n, [None] * n)
                self.harvest_many(done)
                self.drain()
            # The pure-step variant (A=0) may not be hit above when the
            # template caption finishes within one block: compile it
            # explicitly.  Empty slots are frozen, so stepping them is
            # a no-op on every harvested number.
            if self.spec is not None:
                self._st, *_ = self._tick_fn(0)(
                    self.engine.params, self.engine.draft_params,
                    self._st, None, None,
                )
            else:
                self._st, *_ = self._tick_fn(0)(
                    self.engine.params, self._st, None, None
                )
            if self.zero_freed:
                self._free_fn(bank)(
                    self._st, jnp.zeros((bank,), bool)
                )
        # Walk back down so the shrink transitions compile too, ending
        # at the smallest bank (elastic capacity follows traffic up).
        for bank in reversed(self.bank_ladder[:-1]):
            self._set_bank(bank)
        self.resize_count = 0
        self.last_resize_ms = self.worst_resize_ms = 0.0
        if self.spec is not None:
            # Warmup traffic must not count toward served acceptance.
            self._spec_totals = jnp.zeros((2,), jnp.float32)
        assert not self.occupied and len(self.free) == self.S

    # ----------------------------------------------- AOT artifact ladder
    # The artifact subsystem (serving/artifact.py) precompiles EVERY
    # variant warmup() builds — enumerated HERE, from the same
    # bank-ladder/admit-bucket code warmup() walks, so the artifact and
    # the live ladder can never drift (the loader refuses on a key-set
    # mismatch, and tier-1 pins warmup's built keys == aot_variant_keys).

    def warm_admit_counts(self, bank: int) -> List[int]:
        """Admission-count variants reachable at ``bank`` (including the
        pure-step A=0 tick): every A ``tick_begin`` can dispatch is the
        pad bucket of some n <= min(bank, admit_cap), and each such
        bucket equals ``_pad_bucket(min(b, bank))`` for a ladder bucket
        b — the exact set warmup() compiles."""
        return sorted({
            self._pad_bucket(min(b, bank)) for b in self._admit_buckets
        } | {0})

    def aot_variant_keys(self) -> List[str]:
        """Stable string keys of every compiled variant the loop can
        hit post-warmup: tick fns per (bank, admit bucket), the
        freed-slot blanking fn per bank, and both directions of every
        adjacent bank transition."""
        # Speculative ticks are a distinct variant family: the traced
        # program embeds draft_k, so the key carries it — an artifact
        # built without speculation (or at another k) fails the
        # loader's key-set equality check instead of mis-installing.
        spec_sfx = f":k{self.spec_k}" if self.spec is not None else ""
        keys: List[str] = []
        for bank in self.bank_ladder:
            for A in self.warm_admit_counts(bank):
                keys.append(f"tick:S{bank}:A{A}{spec_sfx}")
            if self.zero_freed:
                keys.append(f"free:S{bank}")
        for a, b in zip(self.bank_ladder, self.bank_ladder[1:]):
            keys.append(f"resize:{a}->{b}")
            keys.append(f"resize:{b}->{a}")
        return keys

    def _state_avals(self, S: int) -> SlotState:
        """Shape/dtype structs of the slot-state pytree at bank ``S``
        (the lowering templates for that bank's variants)."""
        st = self._st if S == self.S else self._init_state(S)
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, jnp.dtype(x.dtype)),
            st,
        )

    def aot_lower(self):
        """Builder half of the AOT artifact contract: lower every
        :meth:`aot_variant_keys` variant against its exact runtime
        shapes.  Returns ``[(key, lowered), ...]`` in key order — the
        caller compiles each (``serving/artifact.py``, through the
        persistent compilation cache) and serializes the executables.
        Builds the underlying jitted fns, so this counts toward
        ``compile_count`` like warmup does; the LOADER side
        (:meth:`aot_install`) builds nothing."""
        sds = jax.ShapeDtypeStruct
        p_avals = jax.tree.map(
            lambda x: sds(x.shape, x.dtype), self.engine.params
        )
        dp_avals = (
            None if self.spec is None
            else jax.tree.map(
                lambda x: sds(jnp.shape(x), jnp.asarray(x).dtype),
                dict(self.engine.draft_params),
            )
        )
        spec_sfx = f":k{self.spec_k}" if self.spec is not None else ""
        out = []
        for bank in self.bank_ladder:
            st_avals = self._state_avals(bank)
            for A in self.warm_admit_counts(bank):
                fn = self._tick_fn(A, S=bank)
                if A:
                    # The encode emits A rows regardless of layout; the
                    # legacy replicated tick fans out to K inside.
                    rows = self._cache_avals(A)
                    slots = sds((A,), jnp.int32)
                else:
                    rows = slots = None
                if self.spec is not None:
                    low = fn.lower(
                        p_avals, dp_avals, st_avals, slots, rows
                    )
                else:
                    low = fn.lower(p_avals, st_avals, slots, rows)
                out.append((f"tick:S{bank}:A{A}{spec_sfx}", low))
            if self.zero_freed:
                mask = sds((bank,), jnp.bool_)
                out.append((
                    f"free:S{bank}",
                    self._free_fn(bank).lower(st_avals, mask),
                ))
        for a, b in zip(self.bank_ladder, self.bank_ladder[1:]):
            out.append((
                f"resize:{a}->{b}",
                self._resize_fn(a, b).lower(self._state_avals(a)),
            ))
            out.append((
                f"resize:{b}->{a}",
                self._resize_fn(b, a).lower(self._state_avals(b)),
            ))
        return out

    def aot_encode_buckets(self) -> List[int]:
        """Every admission-encode batch shape
        ``InferenceEngine.encode_prepared_rows`` can dispatch: the admit
        buckets (full-miss batches encode at the tick's padded bucket)
        plus the power-of-two mixed-miss buckets up to the next power of
        two >= ``admit_cap`` — the artifact builder precompiles the
        encode at each."""
        p = 1
        while p < self.admit_cap:
            p *= 2
        pow2, b = [], 1
        while b <= p:
            pow2.append(b)
            b *= 2
        return sorted(set(self._admit_buckets) | set(pow2))

    def aot_install(self, executables: Dict[str, Any]) -> None:
        """Loader half: place ready-to-call compiled executables (keyed
        by :meth:`aot_variant_keys` strings) into the variant caches
        WITHOUT building anything — post-install traffic is hit-only and
        ``compile_count`` stays exactly where it was (0 on an
        artifact-booted decoder, the tier-1 pin).  Unknown keys raise:
        the artifact loader checks set equality first, so a reject here
        means ladder drift."""
        for key, fn in executables.items():
            kind, _, rest = key.partition(":")
            if kind == "tick":
                parts = rest.split(":")           # S..:A..[:k..]
                if len(parts) == 3 and int(parts[2][1:]) != self.spec_k:
                    raise ValueError(
                        f"AOT tick variant {key!r} was built at "
                        f"draft_k={parts[2][1:]} but this decoder runs "
                        f"draft_k={self.spec_k}"
                    )
                if len(parts) == 2 and self.spec is not None:
                    raise ValueError(
                        f"AOT tick variant {key!r} was built without "
                        "speculation but serving.speculative is on"
                    )
                self._tick_fns[(int(parts[0][1:]), int(parts[1][1:]))] = fn
            elif kind == "free":
                self._free_fns[int(rest[1:])] = fn
            elif kind == "resize":
                a, _, b = rest.partition("->")
                self._resize_fns[(int(a), int(b))] = fn
            else:
                raise ValueError(f"unknown AOT variant key {key!r}")

    def spec_stats(self) -> Dict[str, float]:
        """Speculation accounting since warmup (one device fetch of the
        running (2,) total): emitted tokens, live slot-rounds, the
        draft acceptance rate ((emitted - rounds) / (rounds * (k - 1))
        — the fraction of offered draft tokens the verifier accepted),
        and mean tokens emitted per live slot-round (the speedup
        headline: 1.0 is the non-speculative floor, k the ceiling)."""
        if self.spec is None:
            return {}
        tot = np.asarray(jax.device_get(self._spec_totals))
        emitted, rounds = float(tot[0]), float(tot[1])
        k = self.spec_k
        acc = (
            min(1.0, max(0.0, (emitted - rounds) / (rounds * (k - 1))))
            if rounds > 0 and k > 1 else 0.0
        )
        return {
            "draft_k": float(k),
            "emitted_tokens": emitted,
            "live_slot_rounds": rounds,
            "acceptance_rate": acc,
            "tokens_per_round": emitted / rounds if rounds > 0 else 0.0,
        }

    def describe(self) -> Dict[str, Any]:
        return {
            "slots": self.S,
            "max_slots": self.S_max,
            "bank_ladder": list(self.bank_ladder),
            "rows_per_slot": self.K,
            "block_steps": self.block,
            "max_steps": self.L,
            "mode": "greedy" if self.greedy else "beam",
            "admit_cap": self.admit_cap,
            "dedup_cache": self.dedup,
            # Low-precision serving (serving.dtype): the tick lattice
            # runs at the model's compute dtype, so every byte gauge
            # below is already honest under bf16/int8w — state_bytes
            # measures the live leaves and expected_state_bytes uses
            # the same cdt itemsize.  Quantized WEIGHT bytes live on
            # the engine (param_bytes_per_shard), not in decode state.
            "serving_dtype": getattr(
                self.engine, "serving_dtype", "f32"
            ),
            "state_bytes": self.state_bytes(),
            "live_state_bytes": self.live_state_bytes(),
            "bytes_per_request": self.per_slot_bytes(),
            "bank_resizes": self.resize_count,
            "speculative": (
                {} if self.spec is None else {
                    "draft_k": self.spec.draft_k,
                    "draft_hidden": self.spec.draft_hidden,
                }
            ),
        }


# ------------------------------------------------ parity-harness backends

class _ParityEngine:
    """The minimal engine surface a :class:`SlotDecoder` needs, built
    straight from a :class:`~cst_captioning_tpu.decoding.core.ParityCtx`
    — so the shared parity harness (tests/test_decode_core.py) can
    drive the slot loop without the HTTP/batcher/cache stack.
    "Prepared requests" are plain video indices into the ctx batch."""

    def __init__(
        self, ctx, *, mode: str, num_slots: int, block: int,
        dedup: bool = True, bank_min: int = 0, model_shards: int = 1,
        shard_fused: bool = True, speculative: Optional[dict] = None,
    ):
        from types import SimpleNamespace

        self.model = ctx.make_model()
        self.params = ctx.params
        self.decode_mode = mode
        self.max_batch = num_slots
        self.device = None
        # Model-sharded parity variant: vocab params over a (1, N) mesh
        # exactly like the real engine's serving.model_shards path, so
        # the shared harness pins TP decode token-exact vs every other
        # backend through identical inputs.
        self.tp_mesh = None
        if model_shards > 1:
            import jax as _jax

            from cst_captioning_tpu.parallel import make_mesh, shard_params

            if len(_jax.devices()) < model_shards:
                _log.info(
                    "parity engine: %d devices < model_shards=%d — "
                    "running the replicated layout",
                    len(_jax.devices()), model_shards,
                )
            else:
                self.tp_mesh = make_mesh(
                    {"data": 1, "model": model_shards},
                    devices=_jax.devices()[:model_shards],
                )
                self.params = shard_params(self.params, self.tp_mesh)
        self._feats, self._masks, self._cat = (
            ctx.feats, ctx.masks, ctx.category,
        )
        # Draft tree from the SAME params the slot loop decodes with —
        # built after any TP sharding, exactly like the real engine.
        self.draft_params = (
            make_draft_params(
                self.params, int(speculative["draft_hidden"])
            ) if speculative else None
        )
        d0 = next(iter(ctx.feats.values()))
        self.cfg = SimpleNamespace(
            serving=SimpleNamespace(
                num_slots=num_slots, slot_block_steps=block,
                dedup_cache=dedup, slot_bank_min=bank_min,
                slot_shrink_idle_ticks=4, zero_freed_slots=True,
                shard_fused_decode=shard_fused,
                speculative=dict(speculative or {}),
            ),
            eval=SimpleNamespace(
                beam_size=ctx.beam_size, max_decode_len=ctx.max_len,
                length_normalize=True,
            ),
            data=SimpleNamespace(
                max_frames=d0.shape[1],
                feature_modalities=list(ctx.feats),
                feature_dims={
                    m: a.shape[-1] for m, a in ctx.feats.items()
                },
            ),
        )

    def encode_prepared_rows(self, reqs):
        ids = jnp.asarray(np.asarray(reqs, np.int32))
        feats = {m: a[ids] for m, a in self._feats.items()}
        masks = {m: a[ids] for m, a in self._masks.items()}
        cat = self._cat[ids] if self._cat is not None else None
        _, cache = self.model.apply(
            self.params, feats, masks, cat, method="init_decode"
        )
        return cache

    def template_prepared(self):
        return 0


def _slot_runner(ctx, mode: str, dedup: bool = True, bank_min: int = 0,
                 model_shards: int = 1, aot: bool = False,
                 shard_fused: bool = True,
                 speculative: Optional[dict] = None):
    """Decode every ctx row through a small slot matrix with staggered
    admissions (slots hold rows at different decode depths), then map
    harvests back to row order.  ``dedup`` selects the per-slot vs the
    legacy replicated cache layout; ``bank_min`` > 0 exercises the
    elastic bank ladder mid-traffic; ``model_shards`` > 1 runs the
    model-sharded (data=1, model=N) engine layout (``shard_fused``
    selects the cross-shard fused candidate merge vs the PR-9
    full-vocab-gather top-K); ``aot`` runs the artifact boot path —
    every variant ``.lower().compile()``d by a builder decoder and
    installed into a FRESH decoder that must build zero variants of
    its own (``compile_count == 0``, the PR-13 pin)."""
    B = next(iter(ctx.feats.values())).shape[0]
    eng = _ParityEngine(
        ctx, mode=mode, num_slots=max(2, B // 2), block=1,
        dedup=dedup, bank_min=bank_min, model_shards=model_shards,
        shard_fused=shard_fused, speculative=speculative,
    )
    dec = SlotDecoder(eng)
    if aot:
        # Builder decoder lowers+compiles the ladder; the serving
        # decoder only installs executables — zero fresh traces.
        builder = SlotDecoder(eng)
        compiled = {
            key: low.compile() for key, low in builder.aot_lower()
        }
        assert set(compiled) == set(dec.aot_variant_keys())
        dec.aot_install(compiled)
        assert dec.compile_count == 0
    got_tok: Dict[int, np.ndarray] = {}
    got_score: Dict[int, float] = {}
    pending = list(range(B))
    stagger = 0
    while pending or dec.occupied:
        dec.maybe_resize(len(pending))
        n = min(1 + stagger % 2, len(pending), len(dec.free),
                dec.admit_cap)
        adm = [pending.pop(0) for _ in range(n)]
        stagger += 1
        done = dec.tick(adm, adm)
        for i, tokens, score, steps in dec.harvest_many(done):
            got_tok[i], got_score[i] = tokens, score
            assert 0 < steps <= dec.L
    if aot:
        assert dec.compile_count == 0, (
            "artifact-booted decoder built a fresh tick variant under "
            "traffic — the AOT ladder drifted from warmup's"
        )
    return {
        "tokens": np.stack([got_tok[i] for i in range(B)]),
        "scores": (
            np.asarray([got_score[i] for i in range(B)], np.float32)
            if mode == "beam" else None
        ),
    }


register_backend(
    "slot_decoder_beam",
    lambda ctx: _slot_runner(ctx, "beam"),
    kind="beam",
    ref="scan_beam",
)
register_backend(
    "slot_decoder_greedy",
    lambda ctx: _slot_runner(ctx, "greedy"),
    kind="greedy",
    ref="scan_greedy",
)
# The legacy replicated-cache layout stays registered so the deduped
# default is pinned token-exact against it (and both against the scan
# reference) through the one shared harness.
register_backend(
    "slot_decoder_beam_replicated",
    lambda ctx: _slot_runner(ctx, "beam", dedup=False),
    kind="beam",
    ref="scan_beam",
)
# Elastic-bank variant: banks grow/shrink mid-traffic and tokens must
# not move (prefix-copy resizes, row-independent steps).
register_backend(
    "slot_decoder_beam_elastic",
    lambda ctx: _slot_runner(ctx, "beam", bank_min=2),
    kind="beam",
    ref="scan_beam",
)
# AOT artifact-boot variant (PR 13): every tick/free/resize variant is
# `.lower().compile()`d ahead of time by a builder decoder and installed
# into a fresh decoder that never builds (or traces) a variant itself —
# compile_count stays 0 and tokens must match the scan reference
# exactly, which is the docs/PARITY.md argument for why an
# artifact-booted replica cannot change any caption: the executables ARE
# the warmup-compiled programs, only their compilation moved in time.
register_backend(
    "slot_decoder_beam_aot",
    lambda ctx: _slot_runner(ctx, "beam", aot=True),
    kind="beam",
    ref="scan_beam",
)
# Model-sharded variant (serving.model_shards): vocab params + decode
# logits over a 2-way model axis; the column-sharded logit matmul keeps
# every column's reduction order, so tokens AND scores must match the
# replicated layout exactly (the docs/PARITY.md r12 serving contract).
# shard_fused=False pins the PR-9 full-vocab-gather top-K path; the
# *_tp2_fused twins below pin the ISSUE-14 cross-shard candidate merge
# against the same scan reference — both through the one harness.
# On a 1-device host the _ParityEngine degrades to the replicated
# layout with a log line (device counting at import would force backend
# init, which the bench probe must control) — tier-1's virtual 8-CPU
# platform always runs the real sharded path.
register_backend(
    "slot_decoder_beam_tp2",
    lambda ctx: _slot_runner(ctx, "beam", model_shards=2,
                             shard_fused=False),
    kind="beam",
    ref="scan_beam",
)
# Cross-shard FUSED top-K merge (ISSUE 14): per-shard vocab-tile top-K
# + O(shards*K) candidate all-gather instead of the O(V) gather —
# token-exact vs the scan path including tie order at the vocab-tile
# shard boundary (decoding/core.py::make_tp_beam_topk; PARITY r15).
register_backend(
    "slot_decoder_beam_tp2_fused",
    lambda ctx: _slot_runner(ctx, "beam", model_shards=2,
                             shard_fused=True),
    kind="beam",
    ref="scan_beam",
)
# The sampler-side twin: the slot loop's greedy mode under the same
# 2-way model sharding, argmax via the cross-shard (value, id) merge.
register_backend(
    "slot_decoder_greedy_tp2_fused",
    lambda ctx: _slot_runner(ctx, "greedy", model_shards=2,
                             shard_fused=True),
    kind="greedy",
    ref="scan_greedy",
)
# Speculative decode on the slot runtime (decoding/speculative.py):
# draft-LSTM propose, full-model batched verify, standard rejection —
# the emitted stream must be BIT-IDENTICAL to scan_greedy even though
# slots advance 1..draft_k tokens per tick at staggered depths
# (docs/PARITY.md r18).
register_backend(
    "slot_decoder_greedy_spec",
    lambda ctx: _slot_runner(
        ctx, "greedy", speculative={"draft_k": 3, "draft_hidden": 8},
    ),
    kind="greedy",
    ref="scan_greedy",
)
# Artifact boot WITH speculation (the ISSUE-18 acceptance pin): the
# :k-suffixed tick variants are lowered/compiled by a builder decoder
# and installed into a fresh one that must trace nothing itself —
# compile_count stays 0 AND the spec stream stays exact.
register_backend(
    "slot_decoder_greedy_spec_aot",
    lambda ctx: _slot_runner(
        ctx, "greedy", aot=True,
        speculative={"draft_k": 3, "draft_hidden": 8},
    ),
    kind="greedy",
    ref="scan_greedy",
)
