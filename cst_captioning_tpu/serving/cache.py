"""Two-tier serving cache with hit/miss accounting.

Tier 1 (``captions``) maps a request content hash — feature bytes +
decode parameters — to the finished caption, so an identical request
never reaches the queue at all.  Tier 2 (``features``) maps a
client-supplied ``feature_id`` to the request's preprocessed feature
rows AND (after the first decode) the projected encoder state
(:class:`~cst_captioning_tpu.models.captioner.DecodeCache` rows), so a
repeat request that only names the id skips both the feature upload and
the encoder projections (``decoding.beam.beam_search_from_state``).

Both tiers are plain LRU over an ``OrderedDict`` under one lock per
tier — the working set is bounded by config
(``ServingConfig.caption_cache_size`` / ``feature_cache_size``) and the
values are host numpy, never device arrays, so eviction frees real
memory immediately.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional

import numpy as np


class LRUCache:
    """Thread-safe LRU mapping with hit/miss counters."""

    def __init__(self, capacity: int):
        if capacity < 0:
            raise ValueError(f"capacity {capacity} < 0")
        self.capacity = capacity
        self._d: "OrderedDict[Any, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0

    def get(self, key) -> Optional[Any]:
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                self._hits += 1
                return self._d[key]
            self._misses += 1
            return None

    def put(self, key, value) -> None:
        if self.capacity == 0:
            return
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
            self._d[key] = value
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def __contains__(self, key) -> bool:
        # Membership probe without touching recency or counters.
        with self._lock:
            return key in self._d

    def clear(self) -> None:
        with self._lock:
            self._d.clear()

    def stats(self) -> Dict[str, float]:
        with self._lock:
            hits, misses, size = self._hits, self._misses, len(self._d)
        total = hits + misses
        return {
            "hits": hits,
            "misses": misses,
            "size": size,
            "capacity": self.capacity,
            "hit_rate": round(hits / total, 4) if total else 0.0,
        }


def content_key(
    feats: Dict[str, np.ndarray], params_tag: str
) -> str:
    """Tier-1 key: sha1 over the (float32, contiguous) feature bytes of
    every modality in sorted order, plus a decode-parameter tag (beam
    size / max len / mode / checkpoint id) so a reconfigured engine
    never serves a stale caption."""
    h = hashlib.sha1()
    h.update(params_tag.encode())
    for m in sorted(feats):
        a = np.ascontiguousarray(np.asarray(feats[m], np.float32))
        h.update(m.encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


class TwoTierCache:
    """``captions`` (tier 1) + ``features`` (tier 2); see module doc."""

    def __init__(self, caption_capacity: int, feature_capacity: int):
        self.captions = LRUCache(caption_capacity)
        self.features = LRUCache(feature_capacity)

    def stats(self) -> Dict[str, Dict[str, float]]:
        return {
            "captions": self.captions.stats(),
            "features": self.features.stats(),
        }
