"""Two-tier serving cache with hit/miss accounting.

Tier 1 (``captions``) maps a request content hash — feature bytes +
decode parameters — to the finished caption, so an identical request
never reaches the queue at all.  Tier 2 (``features``) maps a
client-supplied ``feature_id`` to the request's preprocessed feature
rows AND (after the first decode) the projected encoder state
(:class:`~cst_captioning_tpu.models.captioner.DecodeCache` rows), so a
repeat request that only names the id skips both the feature upload and
the encoder projections (``decoding.beam.beam_search_from_state`` /
the continuous slot loop's admission encode).

Both tiers are plain LRU over an ``OrderedDict`` under one lock per
tier.  The working set is bounded two ways: by entry count
(``ServingConfig.caption_cache_size`` / ``feature_cache_size``) and —
for tier 2, whose values are multi-KB projected encoder rows, not
strings — by BYTES (``feature_cache_bytes``): every ``put`` sizes the
entry's numpy payload and evicts least-recently-used entries until the
tier fits the budget.  Evictions are counted and exported on
``/metrics`` so an undersized budget is visible, not silent.  Values
are host numpy, never device arrays, so eviction frees real memory
immediately.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional

import numpy as np


def entry_nbytes(value: Any) -> int:
    """Approximate host bytes held by a cache value: numpy arrays count
    their buffers, containers recurse, everything else a flat 64-byte
    floor (keys/str/ints — negligible next to feature rows)."""
    if isinstance(value, np.ndarray):
        return int(value.nbytes)
    if isinstance(value, dict):
        return sum(entry_nbytes(v) for v in value.values()) + 64
    if isinstance(value, (list, tuple)):
        return sum(entry_nbytes(v) for v in value) + 64
    return 64


class LRUCache:
    """Thread-safe LRU mapping with hit/miss/eviction counters.

    ``capacity`` bounds entries; ``max_bytes`` (0 = unbounded)
    additionally bounds the summed :func:`entry_nbytes` of the values —
    the binding constraint for tier 2, where one projected-state entry
    can outweigh thousands of caption strings.
    """

    def __init__(self, capacity: int, max_bytes: int = 0):
        if capacity < 0:
            raise ValueError(f"capacity {capacity} < 0")
        if max_bytes < 0:
            raise ValueError(f"max_bytes {max_bytes} < 0")
        self.capacity = capacity
        self.max_bytes = max_bytes
        self._d: "OrderedDict[Any, Any]" = OrderedDict()
        self._sizes: Dict[Any, int] = {}
        self._bytes = 0
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self._evictions = 0

    def get(self, key) -> Optional[Any]:
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                self._hits += 1
                return self._d[key]
            self._misses += 1
            return None

    def put(self, key, value) -> None:
        if self.capacity == 0:
            return
        size = entry_nbytes(value) if self.max_bytes else 0
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                self._bytes -= self._sizes.get(key, 0)
            self._d[key] = value
            self._sizes[key] = size
            self._bytes += size
            # Evict LRU-first until both bounds hold.  A single entry
            # bigger than the whole byte budget evicts itself — the
            # tier never holds more than max_bytes.
            while self._d and (
                len(self._d) > self.capacity
                or (self.max_bytes and self._bytes > self.max_bytes)
            ):
                k, _ = self._d.popitem(last=False)
                self._bytes -= self._sizes.pop(k, 0)
                self._evictions += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def __contains__(self, key) -> bool:
        # Membership probe without touching recency or counters.
        with self._lock:
            return key in self._d

    def clear(self) -> None:
        with self._lock:
            self._d.clear()
            self._sizes.clear()
            self._bytes = 0

    def stats(self) -> Dict[str, float]:
        with self._lock:
            hits, misses, size = self._hits, self._misses, len(self._d)
            evictions, nbytes = self._evictions, self._bytes
        total = hits + misses
        return {
            "hits": hits,
            "misses": misses,
            "size": size,
            "capacity": self.capacity,
            "bytes": nbytes,
            "max_bytes": self.max_bytes,
            "evictions": evictions,
            "hit_rate": round(hits / total, 4) if total else 0.0,
        }


def content_key(
    feats: Dict[str, np.ndarray], params_tag: str
) -> str:
    """Tier-1 key: sha1 over the (float32, contiguous) feature bytes of
    every modality in sorted order, plus a decode-parameter tag (beam
    size / max len / mode / checkpoint id) so a reconfigured engine
    never serves a stale caption."""
    h = hashlib.sha1()
    h.update(params_tag.encode())
    for m in sorted(feats):
        a = np.ascontiguousarray(np.asarray(feats[m], np.float32))
        h.update(m.encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


class TwoTierCache:
    """``captions`` (tier 1) + ``features`` (tier 2); see module doc.
    ``feature_max_bytes`` byte-bounds tier 2 only — tier-1 values are
    short strings, the entry count is the honest bound there."""

    def __init__(
        self,
        caption_capacity: int,
        feature_capacity: int,
        feature_max_bytes: int = 0,
    ):
        self.captions = LRUCache(caption_capacity)
        self.features = LRUCache(feature_capacity, feature_max_bytes)

    def stats(self) -> Dict[str, Dict[str, float]]:
        return {
            "captions": self.captions.stats(),
            "features": self.features.stats(),
        }
