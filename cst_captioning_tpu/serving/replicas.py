"""Multi-replica data-parallel serving: N warm engines behind one door.

The PR-3 serving stack is pinned to one device: a single
``InferenceEngine`` and one ``SlotDecoder`` tick loop, one host sync
per tick.  This module scales it across all local accelerators the way
Orca/vLLM-class servers do — replicate the decode engine, schedule in
front of it:

* :class:`ReplicaSet` — the multi-replica scheduler (a drop-in batcher
  for :class:`~cst_captioning_tpu.serving.server.CaptionServer`).  It
  builds one :class:`Replica` per device — a warm
  :class:`~cst_captioning_tpu.serving.engine.InferenceEngine` clone
  whose weights were ``device_put`` ONCE onto that device
  (``InferenceEngine.clone_for_device``) plus that engine's persistent
  ``SlotDecoder`` — and runs one worker thread per replica.
* :class:`Router` — in front of the per-replica admission queues:
  ``least_loaded`` routes each accepted request to the replica with the
  most free capacity (free slots minus queued work), breaking ties
  round-robin so equal replicas interleave; ``round_robin`` ignores
  load.  Routing happens at accept time under the shared lock, so a
  request is assigned to exactly one replica (the decoder additionally
  hard-raises on any slot double-assignment).
* **Double-buffered tick dispatch** (``serving.double_buffer``) inside
  each worker: dispatch tick *t+1* (``SlotDecoder.tick_begin``) BEFORE
  waiting on tick *t* (``tick_wait`` + ``harvest_from``), so the
  host-side harvest/detokenize/cache/admission work of tick *t*
  overlaps the device compute of tick *t+1* — and, across replicas,
  every other replica's compute.  The synchronous loop instead pays
  (host work + device step) serially per tick.  Parity: a finished
  slot rides the one extra buffered tick frozen (PAD-only, a no-op on
  tokens/scores — see serving/slots.py), so buffering cannot change
  any caption.
* **Replica failure**: a worker that dies (device error, poisoned
  state) marks its replica unhealthy, drains it from routing, and
  requeues its queued AND in-flight requests onto surviving replicas —
  each bounded by its original deadline (an already-expired request is
  SHED with ``DeadlineExceededError`` + a flight event, never served
  late and never silently) and by the server-side retry budget
  (``serving.requeue_budget``): a request that has already been
  requeued that many times fails outright instead of amplifying a
  requeue storm across a flapping fleet.  Requeued in-flight work
  restarts from step 0 on the survivor; per-step math is
  row-independent, so the survivor's caption is the same caption.
  ``kill_replica`` is the operational handle for the same path.  With
  ZERO healthy replicas, ``submit`` fails with
  :class:`NoHealthyReplicasError` (HTTP 503) and ``/healthz`` degrades.
* **Request hedging** (``serving.hedge_ms``, ISSUE 11): a submitter
  whose request has produced no result after the hedge threshold —
  ``max(hedge_ms, measured p99 of the total-latency histogram)`` —
  enqueues a duplicate copy onto a second healthy replica.  First
  result wins (the future settles exactly once via the internal
  ``_settle_*`` helpers); the losing copy is cancelled at admission if
  still queued, or its harvest is discarded if it was in flight.
  Because every replica holds byte-identical weights and the per-step
  math is row-independent, BOTH copies compute identical rows — hedging
  can change which replica answers, never the tokens (pinned in
  tests/test_replicas.py).  0 disables hedging (the default).
* **Priorities + chaos**: admission shedding (best-effort before
  interactive under overload) and the ChaosEngine injection sites
  (``replica_kill`` at the tick boundary, ``tick_stall``,
  ``queue_burst``) ride the shared batcher machinery — see
  serving/batcher.py and serving/chaos.py.

Token-exactness: every replica holds byte-identical weights
(``device_put`` copies, it does not compute), runs the same jitted
per-step math as the single-replica slot loop, and shares the tier-1/2
cache under the same ``params_tag`` — so WHICH replica decodes a
request cannot change its tokens.  Pinned against offline
``evaluation.py`` by the fuzz tests in tests/test_replicas.py on the
8-device virtual CPU platform.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Deque, List, Optional, Sequence

from concurrent.futures import TimeoutError as FutureTimeoutError

from cst_captioning_tpu.observability.flight import FlightRecorder
from cst_captioning_tpu.observability.trace import get_tracer, null_tracer
from cst_captioning_tpu.serving.batcher import (
    PRIORITY_RANK,
    BackpressureError,
    ShuttingDownError,
    _BatcherBase,
    _Pending,
    _settle_exception,
    _settle_result,
)
from cst_captioning_tpu.serving.metrics import ServingMetrics

_log = logging.getLogger("cst_captioning_tpu.serving")

ROUTER_POLICIES = ("least_loaded", "round_robin")


class NoHealthyReplicasError(ShuttingDownError):
    """Every replica is unhealthy — the server cannot serve (503)."""


class _ReplicaDied(Exception):
    """Internal: raised inside a worker loop when its replica was
    marked unhealthy (kill_replica / external drain)."""


class Router:
    """Replica selection policy.  ``pick`` is called under the
    ReplicaSet lock with the current healthy candidates."""

    def __init__(self, policy: str = "least_loaded"):
        if policy not in ROUTER_POLICIES:
            raise ValueError(
                f"unknown router policy {policy!r}; have {ROUTER_POLICIES}"
            )
        self.policy = policy
        self._rr = 0

    def pick(self, replicas: Sequence["Replica"]) -> "Replica":
        """Pick one of ``replicas`` (non-empty, all healthy)."""
        if not replicas:
            raise ValueError("router.pick with no candidates")
        if self.policy == "round_robin":
            r = replicas[self._rr % len(replicas)]
        else:
            best = max(r.free_capacity() for r in replicas)
            tied = [r for r in replicas if r.free_capacity() == best]
            r = tied[self._rr % len(tied)]
        self._rr += 1
        return r


class Replica:
    """One engine + slot decoder + admission queue + worker thread."""

    def __init__(self, rid: int, engine):
        self.rid = rid
        self.engine = engine
        self.decoder = engine.slot_decoder()
        self.q: Deque[_Pending] = deque()
        self.healthy = True
        self.thread: Optional[threading.Thread] = None
        # Per-replica flight recorder (observability/flight.py): the
        # last ticks + lifecycle events of THIS replica, dumped on
        # worker death / kill_replica / watchdog / SIGTERM drain and
        # readable live at /debug/flight.  Tagged with the replica id
        # so the dump also carries this replica's recent spans.
        sv = engine.cfg.serving
        tracer = (
            get_tracer(int(getattr(sv, "trace_buffer_spans", 0) or 0))
            if getattr(sv, "tracing", True) else null_tracer()
        )
        self.flight = FlightRecorder(
            f"replica{rid}",
            max_events=int(getattr(sv, "flight_events", 256)),
            out_dir=str(getattr(sv, "flight_dir", "") or ""),
            tracer=tracer,
            tags={"replica": rid},
        )

    def free_capacity(self) -> int:
        """Free slots net of already-queued work (can go negative —
        the router just prefers the least oversubscribed replica)."""
        return self.decoder.S - self.decoder.n_occupied - len(self.q)


class ReplicaSet(_BatcherBase):
    """Multi-replica continuous-batching scheduler (see module doc).

    Construct from pre-built engines (``ReplicaSet(engines, ...)`` —
    each engine must be a distinct object with its own slot decoder) or
    from one loaded engine via :meth:`from_engine`, which clones it
    onto local devices.  ``engines[0]`` doubles as the front engine for
    host-side ``prepare``/cache lookups (any replica works: they share
    the cache and the ``params_tag``)."""

    _thread_name = "caption-replicas"

    def __init__(
        self,
        engines: Sequence[Any],
        metrics: Optional[ServingMetrics] = None,
        *,
        router: Optional[str] = None,
        double_buffer: Optional[bool] = None,
        queue_depth: Optional[int] = None,
        default_deadline_ms: Optional[float] = None,
        retry_after_s: Optional[float] = None,
        drain_timeout_s: Optional[float] = None,
        hedge_ms: Optional[float] = None,
        requeue_budget: Optional[int] = None,
    ):
        if not engines:
            raise ValueError("ReplicaSet needs at least one engine")
        super().__init__(
            engines[0],
            metrics,
            queue_depth=queue_depth,
            default_deadline_ms=default_deadline_ms,
            retry_after_s=retry_after_s,
            drain_timeout_s=drain_timeout_s,
        )
        sv = engines[0].cfg.serving
        self.router = Router(router if router is not None else sv.router)
        self.double_buffer = bool(
            sv.double_buffer if double_buffer is None else double_buffer
        )
        # Hedge threshold floor in ms (0 = hedging off) and the
        # server-side requeue budget — see the module doc.
        self.hedge_ms = float(
            getattr(sv, "hedge_ms", 0.0) if hedge_ms is None else hedge_ms
        )
        self.requeue_budget = int(
            getattr(sv, "requeue_budget", 3)
            if requeue_budget is None else requeue_budget
        )
        self.replicas = [Replica(i, e) for i, e in enumerate(engines)]
        self._threads: List[threading.Thread] = []
        for rep in self.replicas:
            rm = self.metrics.replica(rep.rid)
            rm.healthy.set(1)
            rm.slots_occupied.set(0)
            rm.queue_depth.set(0)
        self.metrics.slots_total.set(
            sum(r.decoder.S for r in self.replicas)
        )

    @classmethod
    def from_engine(
        cls,
        engine,
        metrics: Optional[ServingMetrics] = None,
        *,
        n_replicas: Optional[int] = None,
        devices: Optional[Sequence[Any]] = None,
        **kw,
    ) -> "ReplicaSet":
        """Clone ``engine`` into N replicas over local devices
        (``serving.replicas``; 0 = one per device).  More replicas than
        devices wrap round-robin onto the same devices (useful on a
        single-device host: the workers still overlap their host-side
        work).

        A model-sharded engine (``serving.model_shards = M > 1``) turns
        this into the (R, M) serving GRID: devices are id-sorted (the
        ``make_mesh`` determinism contract) and partitioned into R
        contiguous groups of M — replica i always lands on devices
        [i*M, (i+1)*M), so the fleet layout is a pure function of the
        config and the device enumeration — and each replica is a
        ``clone_for_submesh`` engine on its own (1, M) mesh.  0 = one
        sharded replica per M devices; R*M must fit the device count
        (validated here and at engine boot, message-pinned)."""
        import jax

        sv = engine.cfg.serving
        n = sv.replicas if n_replicas is None else n_replicas
        devs = list(devices if devices is not None else jax.devices())
        tp = getattr(engine, "tp_mesh", None)
        M = tp.shape.get("model", 1) if tp is not None else 1
        if M > 1:
            from cst_captioning_tpu.parallel.mesh import submesh_groups

            groups = submesh_groups(devs, M)
            if n <= 0:
                n = len(groups)
            if n < 1 or n > len(groups):
                raise ValueError(
                    f"serving grid replicas={n} x model_shards={M} "
                    f"needs {max(n, 0) * M} local devices, have "
                    f"{len(devs)} — shrink an axis"
                )
            engines = [
                engine.clone_for_submesh(groups[i], replica_id=i)
                for i in range(n)
            ]
            return cls(engines, metrics, **kw)
        if n <= 0:
            n = len(devs)
        engines = [
            engine.clone_for_device(devs[i % len(devs)], replica_id=i)
            for i in range(n)
        ]
        return cls(engines, metrics, **kw)

    # ----------------------------------------------------------- lifecycle
    def _running(self) -> bool:
        return bool(self._threads)

    def start(self) -> "ReplicaSet":
        if self._threads:
            return self
        self._stop = False
        self._draining = False
        for rep in self.replicas:
            t = threading.Thread(
                target=self._worker,
                args=(rep,),
                name=f"caption-replica-{rep.rid}",
                daemon=True,
            )
            rep.thread = t
            self._threads.append(t)
            t.start()
        return self

    def flight_snapshot(self):
        """Live ``/debug/flight`` view: one ring per replica."""
        return {
            rep.flight.name: rep.flight.snapshot()
            for rep in self.replicas
        }

    def begin_drain(self) -> None:
        with self._cond:
            self._draining = True
            evented, self._drain_evented = self._drain_evented, True
            depths = [len(r.q) for r in self.replicas]
            self._cond.notify_all()
        if not evented:
            for rep, d in zip(self.replicas, depths):
                rep.flight.event("drain_start", queued=d)

    def stop(self, drain: bool = True) -> None:
        with self._cond:
            self._draining = True
            self._drain = drain
            self._stop = True
            threads = list(self._threads)
            evented, self._drain_evented = self._drain_evented, True
            depths = [len(r.q) for r in self.replicas]
            self._cond.notify_all()
        if not evented:
            for rep, d in zip(self.replicas, depths):
                rep.flight.event("drain_start", queued=d, drain=drain)
        # Join OUTSIDE the lock — workers need _cond to observe the
        # stop and drain out.
        for t in threads:
            t.join(timeout=self.drain_timeout_s + 60.0)
        # Fail anything still queued anywhere (drain disabled, drain
        # deadline blown, or worker death) so no submitter blocks.
        with self._cond:
            self._threads = []
            for rep in self.replicas:
                while rep.q:
                    _settle_exception(
                        rep.q.popleft(),
                        RuntimeError("replica set stopped"),
                    )
                self.metrics.replica(rep.rid).queue_depth.set(0)

    @property
    def healthy_replicas(self) -> int:
        return sum(1 for r in self.replicas if r.healthy)

    @property
    def depth(self) -> int:
        with self._cond:
            return sum(len(r.q) for r in self.replicas)

    def kill_replica(self, rid: int) -> None:
        """Operational drain of one replica: mark it unhealthy and stop
        routing to it; its worker requeues the replica's queued and
        in-flight requests onto survivors (deadline-bounded)."""
        self.replicas[rid].flight.event("kill")
        with self._cond:
            self.replicas[rid].healthy = False
            self._cond.notify_all()

    def add_replica(self, engine) -> int:
        """Admit a freshly-booted engine (artifact boot or clone) to the
        router as a new replica — the autoscaler's scale-up primitive.
        Replica ids only ever grow (dead replicas keep their slot in
        ``self.replicas``), so metrics labels and flight-ring names stay
        stable across the fleet's whole life.  If the scheduler is
        running, the new replica's worker thread starts immediately;
        otherwise it starts with the next :meth:`start` (or is stepped
        by the virtual-time soak harness)."""
        with self._cond:
            rid = len(self.replicas)
            try:
                engine.replica_id = rid
            except AttributeError:  # engine doubles without the field
                pass
            rep = Replica(rid, engine)
            self.replicas.append(rep)
            rm = self.metrics.replica(rid)
            rm.healthy.set(1)
            rm.slots_occupied.set(0)
            rm.queue_depth.set(0)
            self.metrics.slots_total.set(sum(
                r.decoder.S for r in self.replicas if r.healthy
            ))
            running = bool(self._threads)
            self._cond.notify_all()
        if running:
            t = threading.Thread(
                target=self._worker,
                args=(rep,),
                name=f"caption-replica-{rid}",
                daemon=True,
            )
            rep.thread = t
            with self._cond:
                self._threads.append(t)
            t.start()
        return rid

    # ------------------------------------------------------------- routing
    def _depth_locked(self) -> int:
        return sum(len(r.q) for r in self.replicas)

    def _shed_lower_priority(self, incoming: _Pending) -> bool:
        """Overload shed across EVERY replica queue: evict the oldest
        queued request of the lowest priority class strictly below the
        incoming one (called under ``self._cond``)."""
        rank = PRIORITY_RANK[incoming.priority]
        victim = None
        victim_rep = None
        for rep in self.replicas:
            for p in rep.q:
                if p.future.done():
                    continue
                r = PRIORITY_RANK[p.priority]
                if r < rank and (
                    victim is None or r < PRIORITY_RANK[victim.priority]
                ):
                    victim, victim_rep = p, rep
        if victim is None:
            return False
        victim_rep.q.remove(victim)
        self.metrics.replica(victim_rep.rid).queue_depth.set(
            len(victim_rep.q)
        )
        self._shed_one(
            victim, self._depth_locked(), flight=victim_rep.flight
        )
        return True

    def _enqueue(self, pending: _Pending) -> None:
        healthy = [r for r in self.replicas if r.healthy]
        if not healthy:
            raise NoHealthyReplicasError(
                "no healthy replicas",
                retry_after_s=self._retry_after_value(
                    self._depth_locked(), None
                ),
            )
        if (
            self._depth_locked() >= self.queue_depth
            and not self._shed_lower_priority(pending)
        ):
            self.metrics.requests_rejected.inc()
            raise BackpressureError(
                self._retry_after_value(
                    self._depth_locked(), self._jitter_key(pending)
                )
            )
        rep = self.router.pick(healthy)
        pending.rid = rep.rid
        rep.q.append(pending)
        self.metrics.replica(rep.rid).queue_depth.set(len(rep.q))

    # ------------------------------------------------------------- hedging
    def _hedge_threshold_s(self) -> Optional[float]:
        """Latency hedge threshold in seconds, or None when hedging is
        off.  p99-derived: once the total-latency histogram has enough
        mass, the threshold floats at max(hedge_ms, measured p99) so
        only genuinely slow requests hedge; ``hedge_ms`` is the floor
        and the cold-start value."""
        if self.hedge_ms <= 0:
            return None
        h = self.metrics.stages["total"]
        ms = self.hedge_ms
        if h.count >= 32:
            ms = max(ms, h.percentile(99))
        return ms / 1e3

    def _hedge(self, pending: _Pending) -> None:
        """Dispatch a duplicate copy of a slow request onto a second
        healthy replica (first result wins — both copies share one
        future, settled exactly once)."""
        with self._cond:
            if pending.future.done() or pending.hedged:
                return
            survivors = [
                r for r in self.replicas
                if r.healthy and r.rid != pending.rid
            ]
            if not survivors:
                return
            rep = self.router.pick(survivors)
            pending.hedged = True
            rep.q.append(pending)
            self.metrics.hedges_total.inc()
            self.metrics.replica(rep.rid).queue_depth.set(len(rep.q))
            self._cond.notify_all()
        rep.flight.event("hedge", frm=pending.rid, to=rep.rid)
        if pending.trace is not None:
            t = time.monotonic()
            self.tracer.record(
                "hedge", t, t,
                trace_id=pending.trace[0], parent_id=pending.trace[1],
                tags={"from": pending.rid, "to": rep.rid},
            )

    def _await(self, pending: _Pending, deadline_s: float):
        hedge_s = self._hedge_threshold_s()
        if hedge_s is None or hedge_s >= deadline_s:
            return super()._await(pending, deadline_s)
        try:
            return pending.future.result(timeout=hedge_s)
        except FutureTimeoutError:
            pass
        self._hedge(pending)
        remaining = pending.deadline - time.monotonic()
        return pending.future.result(timeout=max(remaining, 0.0) + 60.0)

    # ------------------------------------------------------------- workers
    def _worker(self, rep: Replica) -> None:
        try:
            self._worker_loop(rep)
        except _ReplicaDied:
            self._drain_replica(rep, f"replica {rep.rid} killed")
        except Exception as e:  # noqa: BLE001 — any worker death drains it
            _log.exception("replica %d worker died", rep.rid)
            rep.flight.event(
                "worker_death", error=f"{type(e).__name__}: {e}"
            )
            self._drain_replica(rep, f"replica {rep.rid} worker died")

    def _worker_loop(self, rep: Replica) -> None:
        decoder = rep.decoder
        rm = self.metrics.replica(rep.rid)
        rm.slot_bank_size.set(decoder.S)
        outstanding = None          # un-waited TickHandle (double buffer)
        drain_deadline: Optional[float] = None
        while True:
            # Chaos site `replica_kill`: die through the REAL death
            # path (unhealthy -> drain from routing -> deadline-bounded
            # requeue onto survivors).  Counted per ACTIVE scheduler
            # iteration of this replica.
            if self.chaos is not None and self.chaos.fire(
                "replica_kill", replica=rep.rid
            ):
                self.metrics.chaos_faults.inc()
                rep.flight.event("chaos_fault", site="replica_kill")
                raise _ReplicaDied()
            admits: List[_Pending] = []
            with self._cond:
                while (
                    not rep.q
                    and not decoder.occupied
                    and outstanding is None
                    and not self._stop
                    and rep.healthy
                ):
                    self._cond.wait(timeout=0.1)
                if not rep.healthy:
                    raise _ReplicaDied()
                if self._stop:
                    if not self._drain:
                        break
                    if (
                        not rep.q
                        and not decoder.occupied
                        and outstanding is None
                    ):
                        rep.flight.event("drain_exit", served_all=True)
                        # SIGTERM/stop drain completed: leave the
                        # post-mortem record (no-op without flight_dir).
                        rep.flight.dump("drain")
                        return
                    if drain_deadline is None:
                        drain_deadline = (
                            time.monotonic() + self.drain_timeout_s
                        )
                # Elastic slot banks per replica: grow under this
                # replica's queue pressure, shrink when idle.  A resize
                # is a pre-jitted prefix copy at the tick boundary;
                # outstanding double-buffered handles stay harvestable
                # (they carry their own output arrays, and the
                # admit-tick guard bounds their slot indices).
                burst = 0
                if self.chaos is not None:
                    b = self.chaos.fire("queue_burst", replica=rep.rid)
                    if b:
                        burst = int(b)
                        self.metrics.chaos_faults.inc()
                before = decoder.resize_count
                decoder.maybe_resize(len(rep.q) + burst)
                if decoder.resize_count != before:
                    self.metrics.slot_bank_resizes.inc(
                        decoder.resize_count - before
                    )
                    rm.slot_bank_size.set(decoder.S)
                    self.metrics.slots_total.set(sum(
                        r.decoder.S for r in self.replicas if r.healthy
                    ))
                cap = min(
                    len(decoder.free),
                    min(decoder.admit_cap, decoder.S),
                )
                while rep.q and len(admits) < cap:
                    p = rep.q.popleft()
                    if p.future.done():
                        # Hedge loser cancellation: the other copy won
                        # (or the request was shed) before this copy
                        # reached a slot — drop it for free.
                        self.metrics.hedge_cancelled.inc()
                        continue
                    admits.append(p)
                rm.queue_depth.set(len(rep.q))
            if (
                drain_deadline is not None
                and time.monotonic() > drain_deadline
            ):
                rep.flight.event(
                    "watchdog",
                    queued=len(admits),
                    occupied=decoder.n_occupied,
                )
                rep.flight.dump("watchdog")
                self._abandon(rep, admits, "drain deadline exceeded")
                rep.flight.event("drain_exit", served_all=False)
                return

            now = time.monotonic()
            live: List[_Pending] = []
            for p in admits:
                if now > p.deadline:
                    self._expire(p, now, flight=rep.flight)
                else:
                    live.append(p)
            # Chaos site `tick_stall`: a slow/hung device step on THIS
            # replica — the worker sleeps the scheduled seconds before
            # dispatching (hedging and the router route around it).
            if self.chaos is not None:
                stall = self.chaos.fire("tick_stall", replica=rep.rid)
                if stall:
                    self.metrics.chaos_faults.inc()
                    rep.flight.event(
                        "chaos_fault", site="tick_stall",
                        stall_s=float(stall),
                    )
                    time.sleep(float(stall))
            # Dispatch tick t+1 FIRST (double buffer) so the harvest of
            # tick t below overlaps its device compute.
            t_tick = time.monotonic()
            try:
                handle = decoder.tick_begin(
                    [p.prepared for p in live], live
                )
            except Exception as e:  # noqa: BLE001
                # A failed admission encode fails those submitters and
                # the replica keeps serving; a failure with nothing to
                # admit is the step itself dying: replica death.
                for p in live:
                    if _settle_exception(p, e):
                        self.metrics.requests_failed.inc()
                if not live:
                    raise
                continue
            t_admit = time.monotonic()
            for p in live:
                p.t_admit = t_admit
                self.metrics.observe_stage(
                    "admission", (t_admit - p.t_enqueue) * 1e3
                )
            self._record_request_spans(
                live, t_tick, t_admit, tags={"replica": rep.rid}
            )
            if live:
                self.metrics.slots_admitted_total.inc(len(live))
                rm.admitted_total.inc(len(live))
            if handle is not None:
                self.metrics.slot_steps_total.inc(decoder.block)
                rm.steps_total.inc(decoder.block)
                rep.flight.event(
                    "tick",
                    # stub decoders in tests hand back bare tuples
                    seq=getattr(handle, "seq", None),
                    admits=len(live),
                    occupied=decoder.n_occupied,
                )
            rm.slots_occupied.set(decoder.n_occupied)
            self.metrics.slots_occupied.set(
                sum(r.decoder.n_occupied for r in self.replicas)
            )
            if self.double_buffer:
                to_wait, outstanding = outstanding, handle
            else:
                to_wait, outstanding = handle, None
            if to_wait is not None:
                done = decoder.tick_wait(to_wait)
                if done:
                    self._resolve(
                        rep, rm, decoder.harvest_from(to_wait, done)
                    )
                    rm.slots_occupied.set(decoder.n_occupied)
            rm.decode_state_bytes.set(decoder.live_state_bytes())

        # Hard stop (drain=False): fail whatever is still in flight;
        # queued requests are failed by stop() after the join.
        self._abandon(rep, [], "replica set stopped")

    def _resolve(self, rep: Replica, rm, harvested) -> None:
        """Detokenize + cache + resolve futures for one harvest batch
        (identical semantics to ContinuousBatcher._resolve, plus the
        per-replica caption counter)."""
        t0 = time.monotonic()
        for p, tokens, score, steps in harvested:
            if p.future.done():
                # Hedge loser: the other replica's copy won the race
                # (identical tokens by construction) — discard.
                self.metrics.hedge_cancelled.inc()
                continue
            self.metrics.steps_per_caption.observe(steps)
            self.metrics.observe_stage("device", (t0 - p.t_admit) * 1e3)
            if p.trace is not None:
                self.tracer.record(
                    "decode", p.t_admit, t0,
                    trace_id=p.trace[0], parent_id=p.trace[1],
                    tags={"replica": rep.rid, "steps": steps},
                )
            td0 = time.monotonic()
            try:
                res = rep.engine.result_from_tokens(
                    p.prepared,
                    tokens,
                    {
                        "admission_ms": (p.t_admit - p.t_enqueue) * 1e3,
                        "device_ms": (t0 - p.t_admit) * 1e3,
                    },
                )
            except Exception as e:  # noqa: BLE001
                if _settle_exception(p, e):
                    self.metrics.requests_failed.inc()
                continue
            t1 = time.monotonic()
            if p.trace is not None:
                self.tracer.record(
                    "detok", td0, t1,
                    trace_id=p.trace[0], parent_id=p.trace[1],
                    tags={"replica": rep.rid},
                )
            self.metrics.observe_stage("detok", (t1 - t0) * 1e3)
            if _settle_result(p, {
                "caption": res.caption,
                "tokens": res.tokens,
                "cached": False,
                "score": score,
                "replica": rep.rid,
                "timings_ms": dict(
                    res.timings_ms,
                    detok_ms=(t1 - t0) * 1e3,
                    decode_steps=steps,
                ),
            }):
                self.metrics.requests_served.inc()
                rm.captions_total.inc()
            else:
                self.metrics.hedge_cancelled.inc()

    def _abandon(
        self, rep: Replica, admits: List[_Pending], why: str
    ) -> None:
        for p in admits:
            if _settle_exception(p, RuntimeError(why)):
                self.metrics.requests_failed.inc()
        for slot in list(rep.decoder.occupied):
            p = rep.decoder.evict(slot)
            if p is not None and _settle_exception(p, RuntimeError(why)):
                self.metrics.requests_failed.inc()
        self.metrics.replica(rep.rid).slots_occupied.set(0)

    # -------------------------------------------------------- failure path
    def _drain_replica(self, rep: Replica, why: str) -> None:
        """Mark ``rep`` unhealthy, drain it from routing, and requeue
        its queued + in-flight requests onto surviving replicas —
        bounded by each request's original deadline.  Runs on the dying
        worker's own thread (the decoder's single owner)."""
        requeued = expired = failed = overflowed = 0
        with self._cond:
            rep.healthy = False
            rm = self.metrics.replica(rep.rid)
            rm.healthy.set(0)
            pendings: List[Optional[_Pending]] = list(rep.q)
            rep.q.clear()
            rm.queue_depth.set(0)
            for slot in list(rep.decoder.occupied):
                pendings.append(rep.decoder.evict(slot))
            rm.slots_occupied.set(0)
            survivors = [r for r in self.replicas if r.healthy]
            now = time.monotonic()
            for p in pendings:
                if p is None or p.future.done():
                    continue
                if now > p.deadline:
                    # Shed, never served late: the ORIGINAL deadline
                    # rides through every requeue (the fuzzed
                    # requeue-deadline audit pins this).
                    self._expire(p, now, flight=rep.flight)
                    expired += 1
                elif not survivors:
                    if _settle_exception(p, RuntimeError(
                        f"{why}; no healthy replicas left"
                    )):
                        self.metrics.requests_failed.inc()
                    failed += 1
                elif p.requeues >= self.requeue_budget:
                    # Server-side retry budget: a request bounced across
                    # this many replica deaths fails outright instead of
                    # feeding a requeue storm.
                    self.metrics.requeue_overflow.inc()
                    self.metrics.shed(p.priority).inc()
                    rep.flight.event(
                        "shed", priority=p.priority,
                        reason="requeue_budget", requeues=p.requeues,
                    )
                    if _settle_exception(p, RuntimeError(
                        f"{why}; requeue budget "
                        f"({self.requeue_budget}) exhausted"
                    )):
                        self.metrics.requests_failed.inc()
                    overflowed += 1
                else:
                    # Accepted work is never dropped: requeue even past
                    # queue_depth (the bound gates NEW admissions only).
                    p.requeues += 1
                    self.metrics.requeues_total.inc()
                    r2 = self.router.pick(survivors)
                    p.rid = r2.rid
                    r2.q.append(p)
                    self.metrics.replica(r2.rid).queue_depth.set(
                        len(r2.q)
                    )
                    requeued += 1
            self.metrics.slots_total.set(
                sum(r.decoder.S for r in self.replicas if r.healthy)
            )
            self._cond.notify_all()
        # Post-mortem: the requeue outcome is part of the story an
        # operator needs to reconstruct, and the ring still holds the
        # replica's last ticks — dump it now, while both exist.
        rep.flight.event(
            "drain_requeue",
            requeued=requeued, expired=expired, failed=failed,
            overflowed=overflowed, survivors=self.healthy_replicas,
        )
        rep.flight.dump(why)
        _log.warning(
            "%s: drained from routing (%d requeued, %d expired, "
            "%d failed, %d over budget; %d healthy replicas remain)",
            why, requeued, expired, failed, overflowed,
            self.healthy_replicas,
        )

    # ----------------------------------------------------------------- info
    def describe(self) -> dict:
        return {
            "replicas": len(self.replicas),
            "healthy": self.healthy_replicas,
            "router": self.router.policy,
            "double_buffer": self.double_buffer,
            "devices": [
                str(getattr(r.engine, "device", None))
                for r in self.replicas
            ],
            "slots_per_replica": [r.decoder.S for r in self.replicas],
            # Mixed-provenance diagnosis (ISSUE 13): which replicas
            # booted from an AOT artifact ("v…") vs warm-compiled.
            "artifact_versions": [
                str(getattr(r.engine, "artifact_version", "warm"))
                for r in self.replicas
            ],
        }
