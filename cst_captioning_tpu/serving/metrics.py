"""Serving metrics: per-stage latency histograms + counters.

Stdlib-only and lock-per-object so the hot path (one ``observe`` per
stage per request) stays cheap under the threaded batcher/server.  The
histogram is fixed-bucket log-spaced: percentile estimates interpolate
inside the winning bucket, which is plenty for the p50/p99 split the
``/metrics`` endpoint and the bench sweep report (sub-bucket accuracy
does not change any serving decision).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

# Log-spaced bucket UPPER bounds in milliseconds, 50us .. 60s.  The tail
# bucket is open-ended (observations above 60s clamp into it).
DEFAULT_BUCKETS_MS: List[float] = [
    0.05, 0.1, 0.2, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0,
    100.0, 200.0, 500.0, 1_000.0, 2_000.0, 5_000.0, 10_000.0,
    30_000.0, 60_000.0,
]

# The serving pipeline's stage names, in request order.  ``queue`` is
# enqueue -> batch pop (scheduler wait), ``admission`` is enqueue ->
# decode-slot admission (continuous mode — the in-flight analogue of
# ``queue``), ``pad`` is batch assembly + shape-bucket padding,
# ``device`` is the jitted decode (including the H2D/D2H transfers it
# blocks on), ``detok`` is tokens -> text, and ``total`` is submit ->
# response.
STAGES = ("queue", "admission", "pad", "device", "detok", "total")

# Request priority classes, highest-value first.  Under overload the
# admission path sheds the LOWEST class present before touching anything
# above it (serving/batcher.py); `caption_shed_total{priority=...}`
# counts the decisions per class.
PRIORITIES = ("interactive", "batch", "best_effort")

# Bucket upper bounds for the steps-per-caption histogram (decode steps
# a caption actually paid before its slot freed — the continuous-mode
# win is this collapsing toward caption length instead of max_len).
STEP_BUCKETS = [
    1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 10.0, 12.0, 16.0, 20.0, 24.0,
    28.0, 32.0, 48.0, 64.0,
]

# The Prometheus name registry: every family this subsystem may emit,
# exactly once, as (name_pattern, type).  ``*`` stands for a computed
# segment (stage name, cache tier/stat key, histogram suffix).  The
# CST-MET analysis rules enforce that (a) every name emitted anywhere
# in serving/ matches a registered family, (b) every family is
# documented in docs/SERVING.md, and (c) no family is registered twice
# — so a new metric is added HERE and in the docs or tier-1 fails.
METRIC_FAMILIES = [
    ("caption_requests_total", "counter"),
    ("caption_requests_served_total", "counter"),
    ("caption_requests_rejected_total", "counter"),
    ("caption_requests_expired_total", "counter"),
    ("caption_requests_failed_total", "counter"),
    ("caption_batches_total", "counter"),
    ("caption_batch_rows_total", "counter"),
    ("caption_batch_pad_rows_total", "counter"),
    ("caption_slots_admitted_total", "counter"),
    ("caption_slot_device_steps_total", "counter"),
    ("caption_slot_bank_resizes_total", "counter"),
    ("caption_slots_total", "gauge"),
    ("caption_slots_occupied", "gauge"),
    ("caption_decode_state_bytes", "gauge"),
    ("caption_slot_bank_size", "gauge"),
    ("caption_replica_healthy", "gauge"),
    ("caption_replica_slots_occupied", "gauge"),
    ("caption_replica_queue_depth", "gauge"),
    ("caption_replica_captions_total", "counter"),
    ("caption_replica_admitted_total", "counter"),
    ("caption_replica_device_steps_total", "counter"),
    ("caption_replica_decode_state_bytes", "gauge"),
    ("caption_replica_slot_bank_size", "gauge"),
    ("caption_shed_total", "counter"),
    ("caption_hedges_total", "counter"),
    ("caption_hedge_cancelled_total", "counter"),
    ("caption_requeues_total", "counter"),
    ("caption_requeue_overflow_total", "counter"),
    ("caption_chaos_faults_total", "counter"),
    ("caption_autoscale_decisions_total", "counter"),
    ("caption_autoscale_scale_ups_total", "counter"),
    ("caption_autoscale_scale_downs_total", "counter"),
    ("caption_autoscale_target_replicas", "gauge"),
    ("caption_latency_*_ms", "histogram"),
    ("caption_steps_per_caption", "histogram"),
    ("caption_cache_*", "gauge"),
]

# One-line HELP text per family (Prometheus text-format audit, ISSUE
# 10): ``to_prometheus`` emits ``# HELP`` + ``# TYPE`` for EVERY
# exposed series from this table — a family without help text fails
# loudly at render time, and the parser-based test in
# tests/test_observability.py pins the exposition format instead of
# substring checks.  Keys are the registered family patterns above.
METRIC_HELP = {
    "caption_requests_total": "Requests accepted into the pipeline.",
    "caption_requests_served_total": "Requests resolved with a caption.",
    "caption_requests_rejected_total":
        "Requests rejected by queue-full backpressure (HTTP 429).",
    "caption_requests_expired_total":
        "Requests whose deadline passed before a result (HTTP 504).",
    "caption_requests_failed_total":
        "Requests failed by engine or input errors (HTTP 5xx).",
    "caption_batches_total": "Coalesced batches dispatched (ladder mode).",
    "caption_batch_rows_total": "Live request rows across batches.",
    "caption_batch_pad_rows_total":
        "Padding rows dispatched (wasted device rows).",
    "caption_slots_admitted_total":
        "Requests admitted into decode slots (continuous mode).",
    "caption_slot_device_steps_total": "Device decode steps dispatched.",
    "caption_slot_bank_resizes_total":
        "Elastic slot-bank grow/shrink transitions.",
    "caption_slots_total": "Configured decode slots (current bank).",
    "caption_slots_occupied": "Decode slots occupied right now.",
    "caption_decode_state_bytes":
        "Live bytes of the resident decode-slot pytree.",
    "caption_slot_bank_size": "Current elastic slot-bank size.",
    "caption_replica_healthy": "1 while the replica is routed, 0 drained.",
    "caption_replica_slots_occupied": "Occupied slots on this replica.",
    "caption_replica_queue_depth": "Queued requests on this replica.",
    "caption_replica_captions_total": "Captions served by this replica.",
    "caption_replica_admitted_total":
        "Requests admitted into this replica's slots.",
    "caption_replica_device_steps_total":
        "Device decode steps run by this replica.",
    "caption_replica_decode_state_bytes":
        "Live decode-state bytes on this replica.",
    "caption_replica_slot_bank_size":
        "This replica's current elastic slot-bank size.",
    "caption_shed_total":
        "Requests load-shed per priority class (overload eviction, "
        "deadline expiry, requeue-budget overflow).",
    "caption_hedges_total":
        "Hedged duplicate dispatches onto a second healthy replica.",
    "caption_hedge_cancelled_total":
        "Hedged duplicate copies discarded (queued skip or losing "
        "in-flight copy after first-result-wins).",
    "caption_requeues_total":
        "Requests requeued onto survivors after a replica drain.",
    "caption_requeue_overflow_total":
        "Requests failed because the server-side requeue budget was "
        "exhausted (requeue-storm cap).",
    "caption_chaos_faults_total":
        "Fault injections fired by the ChaosEngine (zero unless "
        "serving.chaos is configured).",
    "caption_autoscale_decisions_total":
        "Autoscaler signal-window evaluations (zero unless "
        "serving.autoscale is configured).",
    "caption_autoscale_scale_ups_total":
        "Applied scale-up decisions (replica added to the router).",
    "caption_autoscale_scale_downs_total":
        "Applied scale-down decisions (replica drained via the "
        "requeue path).",
    "caption_autoscale_target_replicas":
        "The autoscaler's current target healthy-replica count.",
    "caption_latency_*_ms":
        "Per-stage request latency in milliseconds.",
    "caption_steps_per_caption":
        "Device decode steps each caption paid before its slot freed.",
    "caption_cache_*": "Two-tier cache counters (hits/misses/bytes/...).",
}


class Counter:
    """Thread-safe monotonically-increasing counter."""

    def __init__(self) -> None:
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._v


class Gauge:
    """Thread-safe last-value gauge (slot occupancy, queue depth)."""

    def __init__(self) -> None:
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class LatencyHistogram:
    """Fixed-bucket latency histogram (milliseconds)."""

    def __init__(self, buckets_ms: Optional[List[float]] = None) -> None:
        self.bounds = list(buckets_ms or DEFAULT_BUCKETS_MS)
        if sorted(self.bounds) != self.bounds:
            raise ValueError("histogram buckets must be ascending")
        self._counts = [0] * (len(self.bounds) + 1)  # +1: open tail
        self._sum = 0.0
        self._count = 0
        self._max = 0.0
        # Exemplar-style anchor (ISSUE 10): the trace_id of the most
        # recent observation that carried one, with its value — /stats
        # surfaces it so an operator can jump from a histogram to the
        # exact /debug/trace timeline that produced a latency.
        self._exemplar: Optional[Dict[str, float]] = None
        self._lock = threading.Lock()

    def observe(self, ms: float, exemplar: Optional[str] = None) -> None:
        ms = float(ms)
        i = 0
        for i, b in enumerate(self.bounds):  # noqa: B007
            if ms <= b:
                break
        else:
            i = len(self.bounds)
        with self._lock:
            self._counts[i] += 1
            self._sum += ms
            self._count += 1
            if ms > self._max:
                self._max = ms
            if exemplar is not None:
                self._exemplar = {
                    "trace_id": exemplar, "value_ms": round(ms, 4)
                }

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def percentile(self, p: float) -> float:
        """p in [0, 100] -> estimated latency ms (linear interpolation
        inside the winning bucket; 0.0 when empty)."""
        if not 0.0 <= p <= 100.0:
            raise ValueError(f"percentile {p} outside [0, 100]")
        with self._lock:
            counts = list(self._counts)
            total = self._count
            mx = self._max
        if total == 0:
            return 0.0
        rank = p / 100.0 * total
        seen = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if seen + c >= rank:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) else mx
                frac = (rank - seen) / c if c else 0.0
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            seen += c
        return mx

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            total = self._count
            s = self._sum
            mx = self._max
            ex = dict(self._exemplar) if self._exemplar else None
        out = {
            "count": total,
            "mean_ms": round(s / total, 4) if total else 0.0,
            "p50_ms": round(self.percentile(50), 4),
            "p90_ms": round(self.percentile(90), 4),
            "p99_ms": round(self.percentile(99), 4),
            "max_ms": round(mx, 4),
        }
        if ex is not None:
            out["exemplar"] = ex
        return out

    def bucket_counts(self) -> List[int]:
        with self._lock:
            return list(self._counts)


class ReplicaMetrics:
    """Per-replica label set (serving/replicas.py): exported on
    ``/metrics`` as ``caption_replica_*{replica="<id>"}`` series."""

    def __init__(self) -> None:
        self.healthy = Gauge()           # 1 routed / 0 drained
        self.slots_occupied = Gauge()
        self.queue_depth = Gauge()
        self.captions_total = Counter()  # rate() -> captions/s
        self.admitted_total = Counter()
        self.steps_total = Counter()     # device decode steps run
        # Decode-state memory (PR 7): live bytes of the replica's slot
        # pytree (occupied slots only — freed rows are zeroed) and the
        # current elastic bank size.
        self.decode_state_bytes = Gauge()
        self.slot_bank_size = Gauge()


class ServingMetrics:
    """All serving-side observability in one object, shared by the
    batcher, the engine, and the HTTP front end."""

    def __init__(self) -> None:
        self.stages: Dict[str, LatencyHistogram] = {
            s: LatencyHistogram() for s in STAGES
        }
        self.requests_total = Counter()     # accepted into the pipeline
        self.requests_served = Counter()    # resolved with a caption
        self.requests_rejected = Counter()  # queue-full backpressure
        self.requests_expired = Counter()   # deadline exceeded
        self.requests_failed = Counter()    # engine/input errors
        self.batches_total = Counter()
        self.batch_rows_total = Counter()   # live rows across batches
        self.batch_pad_rows_total = Counter()  # padding rows (waste)
        # Continuous-mode (slot loop) observability:
        self.slots_total = Gauge()          # configured decode slots S
        self.slots_occupied = Gauge()       # live slots right now
        self.slots_admitted_total = Counter()   # admissions into slots
        self.slot_steps_total = Counter()   # device decode steps run
        # Decode-state memory (PR 7): live bytes of the resident slot
        # pytree(s) and the current elastic slot-bank size (summed /
        # single-replica; per-replica twins live on ReplicaMetrics).
        self.decode_state_bytes = Gauge()
        self.slot_bank_size = Gauge()
        self.slot_bank_resizes = Counter()  # elastic grow/shrink events
        # Degradation ladder (ISSUE 11): shed decisions per priority
        # class, hedge dispatch/cancel counts, requeue accounting after
        # replica drains, and chaos-injection hits.
        self.shed_total: Dict[str, Counter] = {
            p: Counter() for p in PRIORITIES
        }
        self.hedges_total = Counter()
        self.hedge_cancelled = Counter()
        self.requeues_total = Counter()
        self.requeue_overflow = Counter()
        self.chaos_faults = Counter()
        # Elastic autoscaler (ISSUE 13): window evaluations, applied
        # scale actions, and the current replica target — all zero
        # unless serving.autoscale is configured.
        self.autoscale_decisions = Counter()
        self.autoscale_ups = Counter()
        self.autoscale_downs = Counter()
        self.autoscale_target = Gauge()
        # Decode steps each caption actually paid before its slot freed.
        self.steps_per_caption = LatencyHistogram(STEP_BUCKETS)
        # Per-replica label sets, created on first use (replica ids are
        # small ints from ReplicaSet; str-keyed for label rendering).
        self._replicas: Dict[str, ReplicaMetrics] = {}
        self._replicas_lock = threading.Lock()
        self._t0 = time.monotonic()

    # ------------------------------------------------------------- views
    def replica(self, rid) -> ReplicaMetrics:
        """The label set for replica ``rid`` (created on first use)."""
        key = str(rid)
        with self._replicas_lock:
            if key not in self._replicas:
                self._replicas[key] = ReplicaMetrics()
            return self._replicas[key]

    def _replica_items(self):
        with self._replicas_lock:
            return sorted(self._replicas.items())

    def shed(self, priority: str) -> Counter:
        """The shed counter for one priority class (KeyError on an
        unknown class — priorities are a closed vocabulary)."""
        return self.shed_total[priority]

    def observe_stage(
        self, stage: str, ms: float, exemplar: Optional[str] = None
    ) -> None:
        self.stages[stage].observe(ms, exemplar=exemplar)

    def mean_batch_size(self) -> float:
        b = self.batches_total.value
        return self.batch_rows_total.value / b if b else 0.0

    def to_dict(self, cache_stats: Optional[Dict] = None) -> Dict:
        d = {
            "requests": {
                "total": self.requests_total.value,
                "served": self.requests_served.value,
                "rejected": self.requests_rejected.value,
                "expired": self.requests_expired.value,
                "failed": self.requests_failed.value,
            },
            "batches": {
                "total": self.batches_total.value,
                "mean_size": round(self.mean_batch_size(), 3),
                "pad_rows": self.batch_pad_rows_total.value,
            },
            "slots": {
                "total": self.slots_total.value,
                "occupied": self.slots_occupied.value,
                "admitted": self.slots_admitted_total.value,
                "device_steps": self.slot_steps_total.value,
                "steps_per_caption": self.steps_per_caption.snapshot(),
                "decode_state_bytes": self.decode_state_bytes.value,
                "bank_size": self.slot_bank_size.value,
                "bank_resizes": self.slot_bank_resizes.value,
            },
            "degradation": {
                "shed": {
                    p: c.value for p, c in self.shed_total.items()
                },
                "hedges": self.hedges_total.value,
                "hedge_cancelled": self.hedge_cancelled.value,
                "requeues": self.requeues_total.value,
                "requeue_overflow": self.requeue_overflow.value,
                "chaos_faults": self.chaos_faults.value,
            },
            "autoscale": {
                "decisions": self.autoscale_decisions.value,
                "scale_ups": self.autoscale_ups.value,
                "scale_downs": self.autoscale_downs.value,
                "target_replicas": self.autoscale_target.value,
            },
            "latency_ms": {s: h.snapshot() for s, h in self.stages.items()},
        }
        reps = self._replica_items()
        if reps:
            elapsed = max(time.monotonic() - self._t0, 1e-9)
            d["replicas"] = {
                rid: {
                    "healthy": rm.healthy.value,
                    "slots_occupied": rm.slots_occupied.value,
                    "queue_depth": rm.queue_depth.value,
                    "captions": rm.captions_total.value,
                    "captions_per_sec": round(
                        rm.captions_total.value / elapsed, 3
                    ),
                    "admitted": rm.admitted_total.value,
                    "device_steps": rm.steps_total.value,
                    "decode_state_bytes": rm.decode_state_bytes.value,
                    "slot_bank_size": rm.slot_bank_size.value,
                }
                for rid, rm in reps
            }
        if cache_stats is not None:
            d["cache"] = cache_stats
        return d

    @staticmethod
    def _header(lines: List[str], name: str, family: str, typ: str) -> None:
        """``# HELP`` + ``# TYPE`` for one exposed metric name.  Every
        sample family gets both lines, in that order, exactly once —
        the text-format contract the parser-based exposition test pins.
        ``family`` is the registered pattern the name belongs to (the
        METRIC_HELP key); a family without help text is a KeyError at
        render time, on purpose."""
        lines.append(f"# HELP {name} {METRIC_HELP[family]}")
        lines.append(f"# TYPE {name} {typ}")

    def to_prometheus(self, cache_stats: Optional[Dict] = None) -> str:
        """Prometheus text exposition of the same numbers (histograms as
        cumulative ``_bucket`` series, the standard encoding).  Serve it
        with content type ``text/plain; version=0.0.4; charset=utf-8``
        (the front end does)."""
        lines: List[str] = []
        counters = {
            "caption_requests_total": self.requests_total,
            "caption_requests_served_total": self.requests_served,
            "caption_requests_rejected_total": self.requests_rejected,
            "caption_requests_expired_total": self.requests_expired,
            "caption_requests_failed_total": self.requests_failed,
            "caption_batches_total": self.batches_total,
            "caption_batch_rows_total": self.batch_rows_total,
            "caption_batch_pad_rows_total": self.batch_pad_rows_total,
            "caption_slots_admitted_total": self.slots_admitted_total,
            "caption_slot_device_steps_total": self.slot_steps_total,
            "caption_slot_bank_resizes_total": self.slot_bank_resizes,
            "caption_hedges_total": self.hedges_total,
            "caption_hedge_cancelled_total": self.hedge_cancelled,
            "caption_requeues_total": self.requeues_total,
            "caption_requeue_overflow_total": self.requeue_overflow,
            "caption_chaos_faults_total": self.chaos_faults,
            "caption_autoscale_decisions_total": self.autoscale_decisions,
            "caption_autoscale_scale_ups_total": self.autoscale_ups,
            "caption_autoscale_scale_downs_total": self.autoscale_downs,
        }
        for name, c in counters.items():
            self._header(lines, name, name, "counter")
            lines.append(f"{name} {c.value}")
        self._header(
            lines, "caption_shed_total", "caption_shed_total", "counter"
        )
        for p in PRIORITIES:
            lines.append(
                f'caption_shed_total{{priority="{p}"}} '
                f"{self.shed_total[p].value}"
            )
        for name, g in (
            ("caption_slots_total", self.slots_total),
            ("caption_slots_occupied", self.slots_occupied),
            ("caption_decode_state_bytes", self.decode_state_bytes),
            ("caption_slot_bank_size", self.slot_bank_size),
            ("caption_autoscale_target_replicas", self.autoscale_target),
        ):
            self._header(lines, name, name, "gauge")
            lines.append(f"{name} {g.value}")
        reps = self._replica_items()
        if reps:
            families = (
                ("caption_replica_healthy", "gauge",
                 lambda rm: rm.healthy.value),
                ("caption_replica_slots_occupied", "gauge",
                 lambda rm: rm.slots_occupied.value),
                ("caption_replica_queue_depth", "gauge",
                 lambda rm: rm.queue_depth.value),
                ("caption_replica_captions_total", "counter",
                 lambda rm: rm.captions_total.value),
                ("caption_replica_admitted_total", "counter",
                 lambda rm: rm.admitted_total.value),
                ("caption_replica_device_steps_total", "counter",
                 lambda rm: rm.steps_total.value),
                ("caption_replica_decode_state_bytes", "gauge",
                 lambda rm: rm.decode_state_bytes.value),
                ("caption_replica_slot_bank_size", "gauge",
                 lambda rm: rm.slot_bank_size.value),
            )
            for name, typ, read in families:
                self._header(lines, name, name, typ)
                for rid, rm in reps:
                    lines.append(
                        f'{name}{{replica="{rid}"}} {read(rm)}'
                    )
        hists = {
            **{
                f"caption_latency_{s}_ms": ("caption_latency_*_ms", h)
                for s, h in self.stages.items()
            },
            "caption_steps_per_caption": (
                "caption_steps_per_caption", self.steps_per_caption
            ),
        }
        for name, (family, h) in hists.items():
            self._header(lines, name, family, "histogram")
            cum = 0
            counts = h.bucket_counts()
            for bound, c in zip(h.bounds, counts):
                cum += c
                lines.append(f'{name}_bucket{{le="{bound}"}} {cum}')
            cum += counts[-1]
            lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
            snap = h.snapshot()
            lines.append(f"{name}_count {snap['count']}")
            lines.append(
                f"{name}_sum {round(snap['mean_ms'] * snap['count'], 4)}"
            )
        if cache_stats:
            for tier, st in cache_stats.items():
                for k in (
                    "hits", "misses", "size", "capacity", "bytes",
                    "evictions",
                ):
                    if k in st:
                        name = f"caption_cache_{tier}_{k}"
                        self._header(lines, name, "caption_cache_*", "gauge")
                        lines.append(f"{name} {st[k]}")
        return "\n".join(lines) + "\n"
