"""Warm-model inference engine for online caption serving.

Loads a checkpoint ONCE, pre-jits decode at a small ladder of fixed
batch shapes, and exposes a synchronous ``decode_prepared`` the
micro-batcher (``serving/batcher.py``) calls with a coalesced batch.

Parity contract (the subsystem's correctness bar, pinned by
``tests/test_serving.py``): a served caption is token-exact with what
``evaluation.py`` produces offline for the same checkpoint and
features.  Three properties carry it:

* Per-request preprocessing is the OFFLINE preprocessing — the same
  ``subsample_frames`` + zero-pad + mask as ``BatchIterator._assemble``.
* The beam decode is dispatched through ``decoding/beam.py`` exactly as
  ``evaluation.py`` dispatches it (same beam size / max len / length
  normalization from ``EvalConfig``, same fused-kernel gate), and every
  decode math op is row-independent, so padding a request batch up to a
  ladder shape cannot change any live row's tokens.
* The feature-cache fast path (tier 2: pre-encoded
  :class:`~cst_captioning_tpu.models.captioner.DecodeCache` rows) feeds
  ``beam_search_from_state`` — the literal tail of ``beam_search`` —
  with encoder rows produced by the same jitted encode, and is pinned
  token-exact against the from-features path.

Shape-ladder rationale (docs/SERVING.md): every served batch pads up to
the smallest ladder shape that fits, so the engine compiles at most
``len(ladder)`` decode graphs per mode, ever — no recompiles under
traffic, bounded XLA cache, and the padded-batch discipline that keeps
TPU utilization high under the serving comparisons in PAPERS.md.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cst_captioning_tpu.config import Config
from cst_captioning_tpu.data.loader import subsample_frames
from cst_captioning_tpu.data.vocab import Vocabulary, decode_sequence
from cst_captioning_tpu.decoding.beam import (
    beam_search_from_state,
    fused_beam_engaged,
    make_beam_search_fn,
)
from cst_captioning_tpu.decoding.speculative import (
    load_draft_params,
    make_draft_params,
    spec_config,
)
from cst_captioning_tpu.models.captioner import (
    CaptionModel,
    DecodeCache,
    model_from_config,
)
from cst_captioning_tpu.serving.cache import TwoTierCache, content_key

_log = logging.getLogger("cst_captioning_tpu.serving")


class PreparedRequest(NamedTuple):
    """A validated, preprocessed request row (host numpy)."""

    feats: Optional[Dict[str, np.ndarray]]   # m -> (F, D_m) float32
    masks: Optional[Dict[str, np.ndarray]]   # m -> (F,) float32
    category: int
    feature_id: Optional[str]
    cache_key: str                           # tier-1 caption key
    enc_row: Optional[Tuple[np.ndarray, ...]]  # tier-2 DecodeCache row


class DecodedResult(NamedTuple):
    caption: str
    tokens: List[int]
    timings_ms: Dict[str, float]


def _default_ladder(max_batch: int) -> List[int]:
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


class InferenceEngine:
    """See module doc.  Thread-safety: ``decode_prepared`` is called
    from the single batcher thread; ``prepare`` and the cache are safe
    from any number of front-end threads."""

    def __init__(
        self,
        cfg: Config,
        params: Any = None,
        checkpoint: str = "",
        vocab: Optional[Vocabulary] = None,
        cache: Optional[TwoTierCache] = None,
        params_version: str = "0",
        random_init: bool = False,
        mesh=None,
        devices=None,
    ):
        self.cfg = cfg
        sv = cfg.serving
        self.vocab = self._resolve_vocab(vocab)
        if cfg.model.vocab_size == 0:
            cfg.model.vocab_size = len(self.vocab)
        # Model-sharded engine (serving.model_shards > 1): ONE logical
        # replica spans a (data=1, model=N) mesh — vocab-sized params
        # shard per parallel/partition.py, the slot decode's per-step
        # top-K merges per-shard candidates across the model axis
        # (serving.shard_fused_decode), slot/decode state is replicated
        # across the shard group (data axis is 1).  Composes with
        # `serving.replicas` into an (R, M) grid: ReplicaSet.from_engine
        # clones this engine onto deterministic per-replica submeshes
        # of M id-sorted devices each; `devices` pins THIS engine's
        # shard group (clone_for_submesh), defaulting to the first M
        # local devices.  model_shards == 1 leaves every code path
        # byte-identical to the pre-TP engine.
        self.tp_mesh = None
        model_shards = int(getattr(sv, "model_shards", 1) or 1)
        if model_shards > 1:
            if mesh is not None:
                raise ValueError(
                    "pass either an explicit mesh or "
                    "serving.model_shards > 1, not both"
                )
            devs = list(devices) if devices is not None else jax.devices()
            if len(devs) < model_shards:
                raise ValueError(
                    f"serving.model_shards={model_shards} needs that "
                    f"many devices, have {len(devs)}"
                )
            # (R, M) grid validation happens HERE, at the first engine,
            # so a mis-sized grid fails at boot, not at clone time —
            # clones (explicit `devices`) were validated by their
            # parent and see only their own M-device submesh.
            if devices is None:
                n_rep = int(sv.replicas)
                if n_rep == 0:
                    n_rep = max(1, len(devs) // model_shards)
                if n_rep < 0 or n_rep * model_shards > len(devs):
                    raise ValueError(
                        f"serving grid replicas={sv.replicas} x "
                        f"model_shards={model_shards} needs "
                        f"{max(n_rep, 0) * model_shards} local "
                        f"devices, have {len(devs)} — shrink an axis "
                        "(replicas*model_shards must fit the host)"
                    )
            from cst_captioning_tpu.parallel import make_mesh

            self.tp_mesh = make_mesh(
                {"data": 1, "model": model_shards},
                devices=devs[:model_shards],
            )
            mesh = self.tp_mesh
        elif mesh is not None and mesh.devices.size > 1:
            # Explicit multi-device serving mesh (tests / embedders):
            # the slot decoder reads tp_mesh for state placement — a
            # mesh that carries data > 1 activation-shards the slot
            # rows over it (serving/slots.py::_init_state).
            self.tp_mesh = mesh
        # Low-precision serving path (serving.dtype, ops/quant.py):
        # "f32" leaves the whole build byte-identical to the pre-knob
        # engine; "bf16"/"int8w" reshape the model via the serving_dtype
        # override of model_from_config.  Validated HERE so a typo'd
        # config fails at boot with the knob's name.
        self.serving_dtype = str(getattr(sv, "dtype", "f32") or "f32")
        if self.serving_dtype not in ("f32", "bf16", "int8w"):
            raise ValueError(
                f"unknown serving.dtype {self.serving_dtype!r}; expected "
                "'f32', 'bf16', or 'int8w'"
            )
        self.model: CaptionModel = model_from_config(
            cfg, mesh=mesh, serving_dtype=self.serving_dtype
        )
        if params is None:
            if checkpoint:
                params = self._restore(checkpoint)
            elif random_init:
                # Load-test / smoke server: fresh weights, noise captions.
                params = self._init_random()
            else:
                raise ValueError(
                    "InferenceEngine needs `params`, a `checkpoint` path, "
                    "or random_init=True"
                )
        if self.serving_dtype == "int8w":
            from cst_captioning_tpu.ops import quant

            # Quantize ONCE at boot (per-channel scales from the float
            # weights, calibrated per serving.quant_calibration) unless
            # the tree already carries int8 codes — an AOT artifact
            # restore or a clone of a quantized engine, for which
            # re-quantizing would be lossy double rounding (and for
            # which the original calibration already chose the scales).
            if not quant.is_quantized(params):
                params = quant.quantize_params(
                    params,
                    str(getattr(sv, "quant_calibration", "absmax")
                        or "absmax"),
                )
        if self.tp_mesh is not None:
            from cst_captioning_tpu.parallel import shard_params

            # Rule-table placement (vocab tensors over `model`); a vocab
            # that doesn't divide the axis falls back to replication per
            # tensor — correctness first, pad the vocab for the benefit.
            params = shard_params(params, self.tp_mesh)
        self.params = params
        self.decode_mode = sv.decode_mode
        if self.decode_mode not in ("beam", "greedy"):
            raise ValueError(f"unknown decode_mode {self.decode_mode!r}")
        # Speculative decode (serving.speculative; decoding/
        # speculative.py): the draft tree is DERIVED from the serving
        # params at boot — truncation init, or the distilled .npz the
        # draft_params knob names — so clones and artifact boots
        # rebuild the identical draft from the identical weights and
        # never ship extra state.  The draft only steers proposal
        # quality; decoded tokens are pinned to the full model by the
        # rejection rule, so it is NOT part of params_tag.
        self.draft_params = None
        spec = spec_config(sv)
        if spec is not None:
            if self.decode_mode != "greedy":
                raise ValueError(
                    "serving.speculative requires decode_mode='greedy'"
                )
            if spec.draft_params:
                dp = load_draft_params(spec.draft_params)
            else:
                dp = make_draft_params(params, spec.draft_hidden)
            self.draft_params = {
                k: jnp.asarray(v, jnp.float32) for k, v in dp.items()
            }
        self.max_batch = sv.max_batch_size or cfg.data.batch_size
        ladder = sorted(set(sv.batch_shapes or _default_ladder(self.max_batch)))
        if ladder[-1] != self.max_batch:
            raise ValueError(
                f"serving.batch_shapes top {ladder[-1]} != max_batch_size "
                f"{self.max_batch} — the coalescer would build unservable "
                "batches"
            )
        self.ladder = ladder
        self.cache = cache or TwoTierCache(
            sv.caption_cache_size,
            sv.feature_cache_size,
            sv.feature_cache_bytes,
        )
        # Everything that changes decoded tokens goes into the tier-1
        # key tag, so a reconfigured/reloaded engine can never serve a
        # stale caption for byte-identical features.
        self.params_tag = (
            f"{cfg.name}|{checkpoint or 'params'}|v{params_version}|"
            f"{self.decode_mode}|K{cfg.eval.beam_size}|"
            f"L{cfg.eval.max_decode_len}|ln{cfg.eval.length_normalize}"
        )
        if self.serving_dtype != "f32":
            # Low-precision decode can move tokens (relaxed-serving
            # tier), so the dtype is cache-key-relevant.  Appended only
            # off-f32: the f32 tag — like every other f32 byte — is
            # identical to the pre-knob engine.
            self.params_tag += f"|dt{self.serving_dtype}"
        self._feats_fns: Dict[int, Any] = {}
        self._encode_fns: Dict[int, Any] = {}
        self._state_fns: Dict[int, Any] = {}
        self._fused_at: Dict[int, bool] = {}
        self._slot_decoder = None
        # Admission-encode accounting (scheduler thread only): rows
        # admitted from tier-2 cached encoder state vs rows that paid
        # the encode — the zero-recompute contract is testable.
        self.admit_rows_cached = 0
        self.admit_rows_encoded = 0
        # Data-parallel replica identity (serving/replicas.py): the
        # device this engine's weights are committed to, or None for
        # the default single-engine placement.
        self.device = None
        self.replica_id: Optional[int] = None
        # Boot provenance (PR 13): "warm" for an engine that compiled
        # its own ladder, the artifact version string for one booted via
        # from_artifact (zero fresh tick compiles).  Surfaced in
        # fingerprint() so a mixed-provenance fleet is diagnosable from
        # /healthz and /debug/flight.
        self.artifact_version: str = "warm"
        if sv.warmup:
            self.warmup()

    @classmethod
    def from_artifact(cls, path: str, replica_id: Optional[int] = None):
        """Boot a replica from an AOT serving artifact
        (serving/artifact.py): manifest validated field-by-field against
        the live environment (refusal on any mismatch), params restored
        from the artifact's orbax item, and every tick-ladder variant
        installed as a pre-compiled executable — the returned engine's
        slot decoder has ``compile_count == 0`` and serves token-exact
        vs a warm-compiled engine (pinned in tests/test_artifact.py)."""
        from cst_captioning_tpu.serving.artifact import load_engine

        return load_engine(path, engine_cls=cls, replica_id=replica_id)

    # ------------------------------------------------------------ plumbing
    def _resolve_vocab(self, vocab: Optional[Vocabulary]) -> Vocabulary:
        if vocab is not None:
            return vocab
        d = self.cfg.data
        if d.vocab_file:
            return Vocabulary.load(d.vocab_file)
        if d.dataset == "synthetic":
            from cst_captioning_tpu.data.build import build_dataset

            _, vb = build_dataset(self.cfg, self.cfg.eval.eval_split)
            return vb
        raise ValueError(
            "no vocabulary: pass `vocab`, set data.vocab_file, or use the "
            "synthetic dataset"
        )

    def _template_inputs(self):
        cfg = self.cfg
        feats = {
            m: jnp.zeros((1, cfg.data.max_frames, dim))
            for m, dim in cfg.data.feature_dims.items()
        }
        masks = {m: jnp.ones((1, cfg.data.max_frames)) for m in feats}
        ids = jnp.zeros((1, 2), jnp.int32)
        cat = (
            jnp.zeros((1,), jnp.int32)
            if self.model.use_category
            else None
        )
        return feats, masks, ids, cat

    def _init_random(self):
        feats, masks, ids, cat = self._template_inputs()
        return self.model.init(
            jax.random.PRNGKey(self.cfg.train.seed), feats, masks, ids,
            category=cat,
        )

    def _restore(self, checkpoint: str):
        """Orbax params-only restore against an eval_shape template —
        the exact ``cli/test.py`` loading path.

        Under ``serving.dtype=int8w`` the checkpoint may hold EITHER a
        quantized tree (an AOT artifact's params item: int8 codes + f32
        scales) or an ordinary float training checkpoint.  Try the
        quantized template first — dtype-exact restore, no silent
        casting of int8 codes through a float template — and fall back
        to the float twin (the ctor quantizes the restored floats at
        boot)."""
        from cst_captioning_tpu.training.checkpoint import restore_params

        feats, masks, ids, cat = self._template_inputs()
        # The float twin: a weight_quant model's own init tree carries
        # scale leaves a training checkpoint doesn't have, so the float
        # template always comes from the unquantized clone.
        float_model = (
            self.model.clone(weight_quant=False)
            if getattr(self.model, "weight_quant", False)
            else self.model
        )
        template = jax.eval_shape(
            lambda: float_model.init(
                jax.random.PRNGKey(0), feats, masks, ids, category=cat
            )
        )
        template = jax.tree.map(
            lambda s: np.zeros(s.shape, s.dtype), template
        )
        if self.serving_dtype == "int8w":
            from cst_captioning_tpu.ops import quant

            try:
                return restore_params(
                    checkpoint, quant.quantize_template(template)
                )
            except Exception:
                # Not a quantized save — restore the float tree below;
                # the ctor quantizes it once at boot.
                pass
        return restore_params(checkpoint, template)

    def bucket(self, n: int) -> int:
        for b in self.ladder:
            if b >= n:
                return b
        raise ValueError(
            f"batch of {n} exceeds the ladder top {self.ladder[-1]}"
        )

    # ------------------------------------------------------- request prep
    def prepare(self, payload: Dict[str, Any]) -> PreparedRequest:
        """Validate + preprocess one request payload.

        ``payload``: ``{"features": {modality: (F_m, D_m) array-like},
        "feature_id": str?, "category": int?}``.  ``features`` may be
        omitted when ``feature_id`` names a previously-seen request
        (tier-2 hit).  Raises ``ValueError``/``KeyError`` on bad input —
        the front end maps those to 4xx before anything is enqueued.
        """
        d = self.cfg.data
        fid = payload.get("feature_id")
        category = int(payload.get("category", 0) or 0)
        raw = payload.get("features")
        if raw is None:
            if not fid:
                raise ValueError("request needs `features` or `feature_id`")
            entry = self.cache.features.get(fid)
            if entry is None:
                raise KeyError(
                    f"feature_id {fid!r} not cached — resend `features`"
                )
            return PreparedRequest(
                feats=entry["feats"],
                masks=entry["masks"],
                category=entry["category"],
                feature_id=fid,
                cache_key=entry["cache_key"],
                enc_row=entry.get("enc"),
            )
        missing = [m for m in d.feature_modalities if m not in raw]
        if missing:
            raise ValueError(f"missing feature modalities: {missing}")
        F = d.max_frames
        feats: Dict[str, np.ndarray] = {}
        masks: Dict[str, np.ndarray] = {}
        for m in d.feature_modalities:
            a = np.asarray(raw[m], np.float32)
            if a.ndim == 1:  # single frame vector
                a = a[None, :]
            dim = d.feature_dims[m]
            if a.ndim != 2 or a.shape[-1] != dim:
                raise ValueError(
                    f"modality {m!r}: expected (frames, {dim}), got "
                    f"{a.shape}"
                )
            if a.shape[0] == 0:
                raise ValueError(f"modality {m!r}: zero frames")
            # EXACTLY BatchIterator._assemble's per-video path: uniform
            # temporal subsample, zero-pad to max_frames, validity mask.
            a = subsample_frames(a, F)
            row = np.zeros((F, dim), np.float32)
            row[: a.shape[0]] = a
            mask = np.zeros((F,), np.float32)
            mask[: a.shape[0]] = 1.0
            feats[m] = row
            masks[m] = mask
        hash_input = dict(feats)
        hash_input.update({f"__mask_{m}": v for m, v in masks.items()})
        if self.model.use_category:
            hash_input["__category"] = np.float32([category])
        key = content_key(hash_input, self.params_tag)
        enc = None
        if fid:
            entry = self.cache.features.get(fid)
            if entry is not None:
                enc = entry.get("enc")
        req = PreparedRequest(
            feats=feats,
            masks=masks,
            category=category,
            feature_id=fid,
            cache_key=key,
            enc_row=enc,
        )
        if fid:
            self.cache.features.put(fid, {
                "feats": feats,
                "masks": masks,
                "category": category,
                "cache_key": key,
                "enc": req.enc_row,
            })
        return req

    def lookup_caption(self, key: str) -> Optional[Dict[str, Any]]:
        """Tier-1 probe (content hash -> finished result)."""
        return self.cache.captions.get(key)

    # ----------------------------------------------------------- jit cache
    def _feats_fn(self, B: int):
        if B not in self._feats_fns:
            if self.decode_mode == "beam":
                beam = make_beam_search_fn(
                    self.model,
                    beam_size=self.cfg.eval.beam_size,
                    max_len=self.cfg.eval.max_decode_len,
                    length_normalize=self.cfg.eval.length_normalize,
                )
                self._feats_fns[B] = (
                    lambda p, f, m, c: beam(p, f, m, c).tokens
                )
            else:
                from cst_captioning_tpu.training.steps import (
                    make_greedy_sample_fn,
                )

                self._feats_fns[B] = make_greedy_sample_fn(
                    self.model, self.cfg.eval.max_decode_len
                )
        return self._feats_fns[B]

    def _encode_fn(self, B: int):
        if B not in self._encode_fns:
            model = self.model

            @jax.jit
            def encode(params, feats, masks, category):
                _, cache = model.apply(
                    params, feats, masks, category, method="init_decode"
                )
                return cache

            self._encode_fns[B] = encode
        return self._encode_fns[B]

    # ----------------------------------------------- AOT encode ladder
    def encode_avals(self, B: int):
        """Shape/dtype templates of one admission-encode call at batch
        ``B`` — exactly what ``encode_prepared_rows`` assembles (float32
        feature/mask stacks, int32 categories), so an AOT-compiled
        encode executable accepts the live batches bit-for-bit."""
        d = self.cfg.data
        sds = jax.ShapeDtypeStruct
        feats = {
            m: sds((B, d.max_frames, d.feature_dims[m]), jnp.float32)
            for m in d.feature_modalities
        }
        masks = {
            m: sds((B, d.max_frames), jnp.float32)
            for m in d.feature_modalities
        }
        cat = sds((B,), jnp.int32) if self.model.use_category else None
        return feats, masks, cat

    def aot_lower_encode(self, buckets: Sequence[int]):
        """Builder half of the encode ladder: lower the jitted admission
        encode at every bucket.  ``[(key, lowered), ...]`` — the
        artifact builder compiles and serializes them; the loader
        installs via :meth:`aot_install_encode`."""
        p_avals = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self.params
        )
        out = []
        for B in buckets:
            feats, masks, cat = self.encode_avals(B)
            out.append((
                f"encode:B{B}",
                self._encode_fn(B).lower(p_avals, feats, masks, cat),
            ))
        return out

    def aot_install_encode(self, executables: Dict[str, Any]) -> None:
        """Loader half: install pre-compiled admission-encode
        executables under their batch buckets — no fresh trace, no
        fresh compile on the admission path."""
        for key, fn in executables.items():
            if not key.startswith("encode:B"):
                raise ValueError(f"unknown AOT encode key {key!r}")
            self._encode_fns[int(key[len("encode:B"):])] = fn

    def _state_fn(self, B: int):
        if B not in self._state_fns:
            model = self.model
            ev = self.cfg.eval

            @jax.jit
            def from_state(params, cache):
                from cst_captioning_tpu.models.captioner import DecodeState

                cdt = jnp.dtype(model.compute_dtype)
                n = cache.ctx_static.shape[0]
                state = DecodeState(
                    h=jnp.zeros((model.num_layers, n, model.rnn_size), cdt),
                    c=jnp.zeros(
                        (model.num_layers, n, model.rnn_size), jnp.float32
                    ),
                )
                return beam_search_from_state(
                    model, params, state, cache,
                    beam_size=ev.beam_size,
                    max_len=ev.max_decode_len,
                    length_normalize=ev.length_normalize,
                ).tokens

            self._state_fns[B] = from_state
        return self._state_fns[B]

    def _fused(self, B: int, feats: Dict[str, jnp.ndarray]) -> bool:
        if B not in self._fused_at:
            engaged, _ = fused_beam_engaged(
                self.model, feats, self.cfg.eval.beam_size
            )
            self._fused_at[B] = bool(engaged)
        return self._fused_at[B]

    def warmup(self) -> None:
        """Pre-jit the whole ladder — and, when continuous mode is
        configured, the slot loop's step/admit/extract fns — so the
        first real request never pays XLA compile latency."""
        t0 = time.perf_counter()
        for B in self.ladder:
            rows = [self.template_prepared()] * B
            self.decode_prepared(rows, store=False)
        if self.cfg.serving.continuous:
            self.slot_decoder().warmup()
        _log.info(
            "serving engine warm: ladder %s%s compiled in %.1fs",
            self.ladder,
            " + slot loop" if self.cfg.serving.continuous else "",
            time.perf_counter() - t0,
        )

    # --------------------------------------------------------------- decode
    def _assemble(
        self, reqs: Sequence[PreparedRequest], B: int
    ) -> Tuple[Dict, Dict, Optional[jnp.ndarray]]:
        """Stack request rows into a padded (B, ...) batch.  Padding rows
        replicate row 0 (the loader's wrap-around trick): every row is a
        valid decode input and row independence keeps live rows exact."""
        n = len(reqs)
        idx = list(range(n)) + [0] * (B - n)
        feats = {
            m: jnp.asarray(
                np.stack([reqs[i].feats[m] for i in idx])
            )
            for m in self.cfg.data.feature_modalities
        }
        masks = {
            m: jnp.asarray(
                np.stack([reqs[i].masks[m] for i in idx])
            )
            for m in self.cfg.data.feature_modalities
        }
        cat = (
            jnp.asarray(
                np.asarray([reqs[i].category for i in idx], np.int32)
            )
            if self.model.use_category
            else None
        )
        return feats, masks, cat

    def decode_prepared(
        self, reqs: Sequence[PreparedRequest], store: bool = True
    ) -> List[DecodedResult]:
        """Decode one coalesced batch (the batcher's unit of work).

        Chooses between three equivalent backends:
        * all rows carry cached encoder state and the scan beam path is
          active -> ``beam_search_from_state`` (tier-2 fast path, skips
          the encode GEMMs);
        * beam mode otherwise -> the ``decoding/beam.py`` dispatch (the
          offline path, fused kernel when its gate passes);
        * greedy mode -> the validation greedy sampler.
        """
        if not reqs:
            return []
        n = len(reqs)
        B = self.bucket(n)
        t0 = time.perf_counter()
        feats, masks, cat = self._assemble(reqs, B)
        t_pad = time.perf_counter()

        use_state_path = (
            self.decode_mode == "beam"
            and not self._fused(B, feats)
        )
        all_cached = use_state_path and all(
            r.enc_row is not None for r in reqs
        )
        if all_cached:
            idx = list(range(n)) + [0] * (B - n)
            cache = DecodeCache(*(
                jnp.asarray(np.stack([reqs[i].enc_row[f] for i in idx]))
                for f in range(len(reqs[0].enc_row))
            ))
            tokens = self._state_fn(B)(self.params, cache)
        elif use_state_path:
            cache = self._encode_fn(B)(self.params, feats, masks, cat)
            if store:
                self._store_enc_rows(reqs, cache)
            tokens = self._state_fn(B)(self.params, cache)
        else:
            tokens = self._feats_fn(B)(self.params, feats, masks, cat)
        tokens = np.asarray(jax.device_get(tokens))[:n]
        t_dev = time.perf_counter()
        captions = decode_sequence(self.vocab, tokens)
        t_detok = time.perf_counter()

        timings = {
            "pad_ms": (t_pad - t0) * 1e3,
            "device_ms": (t_dev - t_pad) * 1e3,
            "detok_ms": (t_detok - t_dev) * 1e3,
        }
        out = []
        for i, r in enumerate(reqs):
            res = DecodedResult(
                caption=captions[i],
                tokens=[int(t) for t in tokens[i]],
                timings_ms=timings,
            )
            if store and r.cache_key:
                self.cache.captions.put(
                    r.cache_key,
                    {"caption": res.caption, "tokens": res.tokens},
                )
            out.append(res)
        return out

    def _store_enc_rows(
        self, reqs: Sequence[PreparedRequest], cache: DecodeCache
    ) -> None:
        """Persist per-request projected encoder rows into tier 2 so the
        next request for the same ``feature_id`` skips the encode."""
        rows_np = None
        for i, r in enumerate(reqs):
            if not r.feature_id or r.enc_row is not None:
                continue
            if rows_np is None:
                rows_np = tuple(
                    np.asarray(jax.device_get(f)) for f in cache
                )
            enc = tuple(f[i] for f in rows_np)
            entry = self.cache.features.get(r.feature_id)
            if entry is not None:
                entry = dict(entry)
                entry["enc"] = enc
                self.cache.features.put(r.feature_id, entry)

    # ------------------------------------------- continuous-mode helpers
    def encode_prepared_rows(
        self, reqs: Sequence[PreparedRequest]
    ) -> DecodeCache:
        """The slot loop's admission encode: (B, ...) projected encoder
        rows for one admission batch, B = len(reqs) (the loop pads the
        batch to a compiled bucket itself).

        Tier-2 hits admit with ZERO encoder recompute: a request that
        carries cached ``DecodeCache`` rows contributes them directly
        (host stack + upload — no projection GEMMs), and only the MISS
        rows run the jitted ``init_decode`` — the same encode the
        offline paths run — at a padded power-of-two bucket.  Since the
        tier-2 cache is shared across replicas under one ``params_tag``,
        a row encoded by ANY replica admits hit-free on every other
        replica.  Fresh rows are stored back into tier 2 for requests
        with a ``feature_id``.  ``admit_rows_encoded`` /
        ``admit_rows_cached`` count both paths (scheduler thread only)."""
        miss = [i for i, r in enumerate(reqs) if r.enc_row is None]
        self.admit_rows_cached += len(reqs) - len(miss)
        self.admit_rows_encoded += len(miss)
        if not miss:
            return DecodeCache(*(
                jnp.asarray(np.stack([np.asarray(r.enc_row[f]) for r in reqs]))
                for f in range(len(reqs[0].enc_row))
            ))

        def encode(subset: Sequence[PreparedRequest]) -> DecodeCache:
            feats = {
                m: jnp.asarray(np.stack([r.feats[m] for r in subset]))
                for m in self.cfg.data.feature_modalities
            }
            masks = {
                m: jnp.asarray(np.stack([r.masks[m] for r in subset]))
                for m in self.cfg.data.feature_modalities
            }
            cat = (
                jnp.asarray(
                    np.asarray([r.category for r in subset], np.int32)
                )
                if self.model.use_category
                else None
            )
            cache = self._encode_fn(len(subset))(
                self.params, feats, masks, cat
            )
            self._store_enc_rows(subset, cache)
            return cache

        if len(miss) == len(reqs):
            return encode(reqs)
        # Mixed batch: encode only the misses, padded up to a
        # power-of-two bucket (replicating the last miss) so the jit
        # cache stays bounded, then splice encoded and cached rows back
        # into request order on the host — the tier-2 values are host
        # numpy by design, so the splice costs one fetch of the fresh
        # rows and no extra device compute.
        Bm = 1
        while Bm < len(miss):
            Bm *= 2
        subset = [reqs[i] for i in miss]
        subset += [subset[-1]] * (Bm - len(miss))
        fresh = tuple(
            np.asarray(jax.device_get(f)) for f in encode(subset)
        )
        pos = {ri: mi for mi, ri in enumerate(miss)}
        rows = []
        for i, r in enumerate(reqs):
            if r.enc_row is not None:
                rows.append(tuple(np.asarray(f) for f in r.enc_row))
            else:
                rows.append(tuple(f[pos[i]] for f in fresh))
        return DecodeCache(*(
            jnp.asarray(np.stack([row[f] for row in rows]))
            for f in range(len(rows[0]))
        ))

    def template_prepared(self) -> PreparedRequest:
        """A valid all-zeros request row (warmup traffic)."""
        d = self.cfg.data
        return PreparedRequest(
            feats={
                m: np.zeros((d.max_frames, d.feature_dims[m]), np.float32)
                for m in d.feature_modalities
            },
            masks={
                m: np.concatenate(
                    [np.ones((1,), np.float32),
                     np.zeros((d.max_frames - 1,), np.float32)]
                )
                for m in d.feature_modalities
            },
            category=0,
            feature_id=None,
            cache_key="",
            enc_row=None,
        )

    def result_from_tokens(
        self,
        req: PreparedRequest,
        tokens: np.ndarray,
        timings_ms: Dict[str, float],
        store: bool = True,
    ) -> DecodedResult:
        """Detokenize one decoded row and store it in tier 1 — the
        per-caption tail of ``decode_prepared``, shared with the slot
        loop's harvest path."""
        caption = decode_sequence(self.vocab, tokens[None])[0]
        res = DecodedResult(
            caption=caption,
            tokens=[int(t) for t in tokens],
            timings_ms=timings_ms,
        )
        if store and req.cache_key:
            self.cache.captions.put(
                req.cache_key,
                {"caption": res.caption, "tokens": res.tokens},
            )
        return res

    def clone_for_device(self, device, replica_id: Optional[int] = None):
        """A data-parallel replica of this engine on ``device``: the
        SAME weights ``device_put`` once onto the target device, the
        same vocabulary, and the SHARED two-tier cache — but its own
        jit caches and its own :class:`SlotDecoder`, so every replica's
        decode runs on its device with no cross-replica device sync.

        The clone inherits this engine's ``params_tag`` verbatim:
        replicas serve one logical model, so a tier-1 caption cached by
        any replica must hit for all of them.  ``device_put`` copies
        bytes — it cannot change any decoded token — which is why
        cross-replica serving stays token-exact vs the offline
        ``evaluation.py`` path (pinned in tests/test_replicas.py).

        With ``serving.warmup`` enabled the clone pre-jits its ladder
        and slot loop at construction ("one warm engine per device")."""
        import copy

        if self.tp_mesh is not None:
            raise ValueError(
                "a model-sharded engine (serving.model_shards > 1) spans "
                "its device group and cannot be cloned per-device — "
                "replica scaling of sharded engines goes through "
                "clone_for_submesh (one (1, M) submesh per replica)"
            )
        # Warm AFTER the replica identity lands: the slot decoder reads
        # ``engine.device`` (slot-matrix placement) and
        # ``engine.replica_id`` (span tags) at construction, and ctor
        # warmup would build it before either is set.
        cfg2 = copy.deepcopy(self.cfg)
        warm = cfg2.serving.warmup
        cfg2.serving.warmup = False
        eng = InferenceEngine(
            cfg2,
            params=jax.device_put(self.params, device),
            vocab=self.vocab,
            cache=self.cache,
        )
        eng.cfg.serving.warmup = warm
        eng.params_tag = self.params_tag
        # Weights provenance rides along (the clone's LADDER is
        # warm-compiled, but its params came from wherever this
        # engine's did — the fleet-diagnosis question).
        eng.artifact_version = self.artifact_version
        eng.device = device
        eng.replica_id = replica_id
        if warm:
            eng.warmup()
        return eng

    def clone_for_submesh(self, devices, replica_id: Optional[int] = None):
        """A model-sharded replica of this engine on its own (1, M)
        submesh — the tensor-parallel twin of :meth:`clone_for_device`
        and the unit the (R replicas) x (M shards) serving grid is
        built from (``ReplicaSet.from_engine``).  ``devices`` must be
        exactly this engine's shard count; weights are gathered to host
        once and committed to the new submesh by the same rule table,
        so — like ``clone_for_device`` — placement copies bytes and
        cannot change any decoded token.  The clone shares the two-tier
        cache and ``params_tag``; with ``serving.warmup`` it pre-jits
        its ladder and slot loop after the replica identity lands."""
        import copy

        if self.tp_mesh is None or self.tp_mesh.shape.get("model", 1) < 2:
            raise ValueError(
                "clone_for_submesh needs a model-sharded engine "
                "(serving.model_shards > 1) — use clone_for_device for "
                "single-device replicas"
            )
        M = self.tp_mesh.shape["model"]
        devices = list(devices)
        if len(devices) != M:
            raise ValueError(
                f"clone_for_submesh got {len(devices)} devices for a "
                f"{M}-way model-sharded engine — each replica submesh "
                "must hold exactly model_shards devices"
            )
        cfg2 = copy.deepcopy(self.cfg)
        warm = cfg2.serving.warmup
        cfg2.serving.warmup = False
        # Gather once to host, then the ctor re-commits by the rule
        # table onto the new submesh (a layout move, never arithmetic).
        host_params = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x)), self.params
        )
        eng = InferenceEngine(
            cfg2,
            params=host_params,
            vocab=self.vocab,
            cache=self.cache,
            devices=devices,
        )
        eng.cfg.serving.warmup = warm
        eng.params_tag = self.params_tag
        eng.artifact_version = self.artifact_version
        eng.replica_id = replica_id
        if warm:
            eng.warmup()
        return eng

    def slot_decoder(self):
        """The engine's persistent :class:`~cst_captioning_tpu.serving.
        slots.SlotDecoder` (continuous in-flight batching), built lazily
        — one slot matrix and one set of compiled slot fns per engine."""
        if self._slot_decoder is None:
            from cst_captioning_tpu.serving.slots import SlotDecoder

            self._slot_decoder = SlotDecoder(self)
        return self._slot_decoder

    # ----------------------------------------------------------- info
    def _mesh_shape_str(self) -> str:
        """"1x2"-style mesh string when model-sharded, "1x1" otherwise
        (the same ``*_mesh_shape`` format bench records use)."""
        if self.tp_mesh is None:
            return "1x1"
        return "x".join(
            str(self.tp_mesh.shape[a]) for a in self.tp_mesh.axis_names
        )

    def fingerprint(self) -> Dict[str, Any]:
        """The build/config fingerprint (ISSUE 10 satellite): the four
        identifiers that correlate a flight dump, a bench record, and a
        running deploy — surfaced on /healthz, /stats, and
        /debug/flight."""
        from cst_captioning_tpu import __version__

        return {
            "params_tag": self.params_tag,
            "mesh_shape": self._mesh_shape_str(),
            "preset": self.cfg.name,
            "version": __version__,
            # Low-precision serving path (f32 | bf16 | int8w): parity-
            # relevant, so artifacts refuse a mismatch field-by-field
            # (serving/artifact.py) and /healthz exposes it per replica.
            "serving_dtype": self.serving_dtype,
            # "warm" = self-compiled ladder; otherwise the AOT artifact
            # version this engine (or its clone source) booted from.
            "artifact_version": self.artifact_version,
        }

    def param_bytes_per_shard(self) -> int:
        """Resident weight bytes on ONE shard of this engine — measured
        off the live leaves (a model-sharded leaf counts its first
        addressable shard), so the int8w 0.25x vocab-tile claim is
        checked against reality, not arithmetic (the lowprec_* bench
        rows pair this with the ops/quant.py closed form)."""
        total = 0
        for leaf in jax.tree.leaves(self.params):
            shards = getattr(leaf, "addressable_shards", None)
            if shards and self.tp_mesh is not None:
                total += int(shards[0].data.nbytes)
            else:
                total += int(np.asarray(leaf).nbytes)
        return total

    def describe(self) -> Dict[str, Any]:
        return {
            "model": self.cfg.name,
            "decode_mode": self.decode_mode,
            "beam_size": self.cfg.eval.beam_size,
            "max_decode_len": self.cfg.eval.max_decode_len,
            "batch_ladder": self.ladder,
            "continuous": bool(self.cfg.serving.continuous),
            "num_slots": int(
                self.cfg.serving.num_slots or self.max_batch
            ),
            "dedup_cache": bool(self.cfg.serving.dedup_cache),
            "slot_bank_min": int(self.cfg.serving.slot_bank_min),
            "modalities": {
                m: self.cfg.data.feature_dims[m]
                for m in self.cfg.data.feature_modalities
            },
            "max_frames": self.cfg.data.max_frames,
            "vocab_size": len(self.vocab),
            "backend": jax.default_backend(),
            "mesh_shape": self._mesh_shape_str(),
            # Low-precision serving: the active dtype and the measured
            # resident weight bytes on one shard (int8w ~0.25x the f32
            # vocab tiles) — /healthz and /stats carry both.
            "serving_dtype": self.serving_dtype,
            "param_bytes_per_shard": self.param_bytes_per_shard(),
            # Deploy fingerprint: params_tag/mesh/preset/version —
            # /healthz carries it so dumps and bench records correlate.
            "build": self.fingerprint(),
        }
